"""Pipeline-parallel serving: stage-partitioned execution.

TPU-native re-design of the reference's inference pipeline parallelism
(stage assignment from transformer_layer_id, inference_manager.cc:91-133;
per-stage MachineViews with distinct start_device_id, graph.cc:2016-2024):

- layers partition into ``pp`` stages by transformer_layer_id (pre-block
  layers → stage 0, post-block layers → last stage);
- each stage's weights and KV caches live ONLY on that stage's device
  subset (a per-stage tp submesh) — the reference's reason for pp: a model
  larger than one device group's HBM;
- one jitted step per stage; activations crossing a stage boundary are
  device_put onto the next stage's submesh (the Legion region-move
  analogue).  Batches flow through stages sequentially per step; the
  4-deep in-flight overlap the reference gets from Legion futures maps to
  async dispatch across the disjoint per-stage device queues.

Paged KV (serving/kv_pager.py): pp-served rows take the shared
admission path — page leasing, admission blocking and pressure
preemption all apply — but their caches live on per-stage submeshes
the row fetch/restore transfers are not wired through
(``InferenceManager.supports_kv_spill`` is False for pp records), so a
preempted pp row always recovers by RECOMPUTE: the request re-enters
the pending queue with ``cached_len = 0`` and re-prefills chunk by
chunk, which is bit-exact (KV depends only on token values and
positions).  Lease accounting refreshes at every host sync via
``RequestManager._note_step`` — the pp decode block commits many
tokens per sync without touching ``prepare_next_batch``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..config import AXIS_MODEL, AXIS_SEQ
from ..ops.registry import OpContext, get_op


def _layer_slots(model):
    """Classify each layer into its pipeline slot: ``"pre"`` (before any
    transformer block → pinned to stage 0), a transformer_layer_id, or
    ``"post"`` (after the blocks → pinned to the last stage).  The single
    source of truth shared by :func:`partition_stages` (placement) and
    :func:`cost_balanced_stage_of_tid` (cost attribution)."""
    seen_block = False
    for layer in model.layers:
        tid = layer.transformer_layer_id
        if tid >= 0:
            seen_block = True
            yield layer, tid
        else:
            yield layer, ("post" if seen_block else "pre")


def cost_balanced_stage_of_tid(model, pp: int, tp: int,
                               machine=None) -> Dict[int, int]:
    """Assign transformer blocks to stages by forward cost, not count
    (the reference splits uniformly, inference_manager.cc:131; uniform and
    cost-balanced coincide for homogeneous blocks, but interleaved MoE or
    mixed-width blocks skew a count split).  ``machine`` defaults to the
    v5e :class:`SimpleMachineModel`; pass an ``EnhancedMachineModel`` for
    hardware with a different flops:bandwidth crossover."""
    from ..search.cost_model import SimpleMachineModel, estimate_op_cost
    from ..search.pcg import balanced_partition

    tids = sorted({l.transformer_layer_id for l in model.layers
                   if l.transformer_layer_id >= 0})
    if not tids:
        return {}
    machine = machine or SimpleMachineModel(tp)
    cost = {t: 0.0 for t in tids}
    pre = post = 0.0     # embedding → stage 0; final norm / head → last
    for layer, slot in _layer_slots(model):
        c = estimate_op_cost(
            layer, [o.spec.shape for o in layer.outputs], machine,
            tp=tp).forward_time            # serving runs forward only
        if slot == "pre":
            pre += c
        elif slot == "post":
            post += c
        else:
            cost[slot] += c
    costs = [cost[t] for t in tids]
    # pre/post-block layers are pinned to the first/last stage
    # (partition_stages), so their cost must weigh on those groups — an
    # lm_head over a 128k vocab streams as much as several blocks
    costs[0] += pre
    costs[-1] += post
    stages = balanced_partition(costs, pp)
    return dict(zip(tids, stages))


def partition_stages(model, pp: int,
                     stage_of_tid: Optional[Dict[int, int]] = None
                     ) -> List[List[Any]]:
    """Group layers into pp stages by transformer_layer_id
    (inference_manager.cc:131 layers_per_stage semantics); an explicit
    ``stage_of_tid`` (e.g. from :func:`cost_balanced_stage_of_tid`)
    overrides the uniform count split."""
    if stage_of_tid is None:
        tids = sorted({l.transformer_layer_id for l in model.layers
                       if l.transformer_layer_id >= 0})
        per_stage = -(-max(1, len(tids)) // pp)   # ceil
        stage_of_tid = {t: min(i // per_stage, pp - 1)
                        for i, t in enumerate(tids)}
    stages: List[List[Any]] = [[] for _ in range(pp)]
    for layer, slot in _layer_slots(model):
        if slot == "pre":
            stages[0].append(layer)           # embedding etc.
        elif slot == "post":
            stages[pp - 1].append(layer)      # final norm / head / sampler
        else:
            stages[stage_of_tid[slot]].append(layer)
    return stages


def stage_boundaries(model, stages) -> List[List[Tuple]]:
    """Per stage: the tensor keys it consumes from earlier stages."""
    from ..core.model import _tensor_key

    layer_stage = {}
    for s, ls in enumerate(stages):
        for l in ls:
            layer_stage[l.name] = s
    needed: List[List[Tuple]] = []
    for s, ls in enumerate(stages):
        keys = []
        for l in ls:
            for t in l.inputs:
                k = _tensor_key(t)
                if t.owner_layer is None:
                    continue               # graph inputs fed from batch
                if layer_stage[t.owner_layer.name] < s and k not in keys:
                    keys.append(k)
        needed.append(keys)
    return needed


def build_stage_meshes(config, pp: int, tp: int, sp: int = 1) -> List[Mesh]:
    """Disjoint per-stage device subsets; each stage's submesh carries the
    tp axis and, when sp > 1, an sp axis for the length-sharded KV cache
    (sp x pp composition)."""
    config.validate()   # informative dp x tp x pp > num_devices error
    devs = list(config.devices)
    per_stage = sp * tp
    if len(devs) < pp * per_stage:
        raise ValueError(
            f"pipeline serving needs pp({pp}) x sp({sp}) x tp({tp}) = "
            f"{pp * per_stage} devices, have {len(devs)}")
    meshes = []
    for s in range(pp):
        block = np.array(devs[s * per_stage:(s + 1) * per_stage])
        if sp > 1:
            meshes.append(Mesh(block.reshape(sp, tp),
                               (AXIS_SEQ, AXIS_MODEL)))
        else:
            meshes.append(Mesh(block, (AXIS_MODEL,)))
    return meshes


def pp_flash_ok(record, C: int) -> bool:
    """Host half of the flash kernel shape gates for a pipeline record:
    every stage's caches must pass the op-level path gate against that
    stage's submesh (the pp twin of inference_manager.record_flash_ok —
    r5: the Pallas kernels shard_map over each stage's tp/sp axes)."""
    from ..kernels.flash_decode import flash_path_ok
    from ..kernels.flash_prefill import prefill_path_ok

    gate = flash_path_ok if C == 1 else prefill_path_ok
    caches = record.get("caches") or {}
    if not caches:
        return False
    meshes = record["pp_meshes"]
    for s, ls in enumerate(record["pp_stages"]):
        for l in ls:
            if l.name in caches and not gate(C, caches[l.name]["k"],
                                             meshes[s]):
                return False
    return True


def make_stage_step(record, stage_idx: int, use_flash: bool = False):
    """Un-jitted step for one stage: (params, caches, boundary_vals,
    batch, rng) -> (boundary_outs_or_final, new_caches)."""
    model = record["model"]
    stages = record["pp_stages"]
    needed = record["pp_boundaries"]
    layers = stages[stage_idx]
    last_stage = stage_idx == len(stages) - 1
    input_names = [t.name for t in model.input_tensors]
    from ..core.model import _tensor_key

    # keys this stage must export to later stages: anything produced at or
    # before this stage that a later stage consumes — an edge spanning >1
    # stage boundary (e.g. a long skip connection) is forwarded stage by
    # stage through the boundary dict
    producer_stage = {l.name: s for s, ls in enumerate(stages) for l in ls}
    exports: List[Tuple] = []
    for later in needed[stage_idx + 1:]:
        for k in later:
            if producer_stage.get(k[0], 1 << 30) <= stage_idx \
                    and k not in exports:
                exports.append(k)

    def step(params, caches, boundary, batch, rng):
        ctx = OpContext(training=False, rng=rng, batch_config=batch,
                        kv_cache=caches, kv_cache_out={},
                        mesh=record["pp_meshes"][stage_idx],
                        use_flash=use_flash,
                        w8a8=model.config.int8_native_matmul,
                        extra_outputs={})
        feeds = {}
        C = batch["token_ids"].shape[1]
        for name in input_names:
            if name == "tokens":
                feeds[name] = batch["token_ids"]
            elif name == "positions":
                feeds[name] = (batch["first_depth"][:, None]
                               + jnp.arange(C)[None, :])
            else:
                raise ValueError(f"unknown serving input {name!r}")
        # the shared layer-graph executor, restricted to this stage
        vals = model.run_layers(params, feeds, ctx, inference=True,
                                layers=layers, seed_vals=boundary)
        new_caches = {**caches, **ctx.kv_cache_out}
        from .inference_manager import pin_cache_layout

        new_caches = pin_cache_layout(new_caches,
                                      record["pp_meshes"][stage_idx],
                                      record["pp_cache_spec"])
        if last_stage:
            final = model.layers[-1]
            outs = [vals[(final.name, i)]
                    for i in range(len(final.outputs))]
            return outs, new_caches
        return {k: vals[k] for k in exports}, new_caches

    return step


def compile_pipeline(im, record, model, cfg, cache_dtype, rows, alloc_len):
    """Set up per-stage meshes/params/caches/step slots on the record."""
    from .inference_manager import SERVING_ATTENTION_OPS, _param_pspecs

    pp = cfg.pipeline_parallelism_degree
    tp = cfg.tensor_parallelism_degree
    sp = cfg.sequence_parallelism_degree
    stages = partition_stages(model, pp,
                              cost_balanced_stage_of_tid(model, pp, tp))
    meshes = build_stage_meshes(cfg, pp, tp, sp)
    record["pp_stages"] = stages
    record["pp_meshes"] = meshes
    record["pp_boundaries"] = stage_boundaries(model, stages)
    record["pp_steps"] = {}
    # sp x pp: the cache's length axis shards over each stage's sp axis
    from ..quantization import extend_quantized_pspecs
    from .inference_manager import _device_put_preserving, cache_pspec

    cache_spec = cache_pspec(sp, tp)
    record["pp_cache_spec"] = cache_spec
    # set by _compile_pipeline_model from the same cache_dtype — read,
    # don't recompute, so the flag cannot desynchronize from the layout
    kv_quantized = record["kv_quantized"]

    pspecs = extend_quantized_pspecs(_param_pspecs(model), model.params)
    for s, ls in enumerate(stages):
        for layer in ls:
            lp = model.params.get(layer.name)
            if lp is None:
                continue
            model.params[layer.name] = {
                pn: _device_put_preserving(
                    v, meshes[s],
                    pspecs[layer.name][pn] if tp > 1 else PartitionSpec())
                for pn, v in lp.items()}
            if layer.op_type in SERVING_ATTENTION_OPS:
                a = layer.attrs
                kv = a["num_kv_heads"]
                d = a.get("head_dim") or a["embed_dim"] // a["num_q_heads"]
                shape = (rows, kv, alloc_len, d)
                csh = NamedSharding(meshes[s], cache_spec)
                record["caches"][layer.name] = {
                    "k": jax.device_put(jnp.zeros(shape, cache_dtype), csh),
                    "v": jax.device_put(jnp.zeros(shape, cache_dtype), csh),
                }
                if kv_quantized:
                    from .inference_manager import scale_pspec

                    ssh = NamedSharding(meshes[s], scale_pspec(cache_spec))
                    for part in ("k_scale", "v_scale"):
                        record["caches"][layer.name][part] = \
                            jax.device_put(
                                jnp.zeros((rows, kv, alloc_len),
                                          jnp.float32), ssh)


def _group_count(rows: int, pp: int) -> int:
    """Micro-batch groups for pipelined decode: the largest M <= pp that
    divides the row count (pp groups keep every stage busy in steady
    state, the reference's <=4-in-flight-batch overlap,
    request_manager.cc:1946-1977)."""
    m = min(pp, rows)
    while rows % m:
        m -= 1
    return m


def pipeline_decode_block(im, record, model_id: int, bc, k: int, rng,
                          init_tokens=None):
    """``k`` decode steps through the stage pipeline with device-resident
    token feedback and micro-batched rows — ONE host sync for the whole
    block.

    The per-token pp path costs a host round trip per token (the 17x
    cost decode blocks were built to kill) and walks stages sequentially.
    Here the request rows split into M groups; each step dispatches
    stage s of group g before stage s of group g+1, so stage s computes
    group g+1 while stage s+1 computes group g (the reference's in-flight
    batch overlap on Legion futures, request_manager.cc:1946-1977 — here
    the overlap comes from async dispatch onto disjoint per-stage device
    queues).  The sampled token of a group's last stage feeds its next
    step's first stage as a device array (ICI/device-to-device move, no
    host).

    Group cache rows are sliced out of the full cache arrays once per
    block and written back once at the end — O(cache) twice per block,
    amortized over k tokens.

    Returns sampled ids [k(+1 with init_tokens), R] as one host array.
    """
    stages = record["pp_stages"]
    meshes = record["pp_meshes"]
    model = record["model"]
    pp = len(stages)
    batch_np = bc.pack()
    R = batch_np["token_ids"].shape[0]
    M = _group_count(R, pp)
    Rg = R // M

    # per-stage attention layers (cache owners), stage params
    stage_cache_names = [[l.name for l in ls if l.name in record["caches"]]
                         for ls in stages]
    stage_params = [{l.name: model.params[l.name] for l in ls
                     if l.name in model.params} for ls in stages]

    # ragged/deep decode batches dispatch to the sharded flash kernel
    # (r5): each stage's attention shard_maps over its submesh
    from .inference_manager import _record_flash_tile, flash_wins

    gate_ok = pp_flash_ok(record, 1)
    use_flash = (gate_ok
                 and flash_wins(bc, k + 1, record["alloc_len"],
                                _record_flash_tile(record)))
    im.count_kernel_path(record, 1, gate_ok, use_flash)
    im.recorder.record_event("decode-step", block=k, pp=pp, groups=M)
    im.ledger.note_event("decode-step", block=k, pp=pp, groups=M)

    # jitted per-stage chunk-1 steps (shared with the per-token path
    # except for the group row count)
    steps = []
    for s in range(pp):
        key = ("pp_step", s, 1, Rg, use_flash)
        if key not in record["pp_steps"]:
            record["pp_steps"][key] = jax.jit(
                make_stage_step(record, s, use_flash),
                donate_argnums=(1,))
        steps.append(record["pp_steps"][key])

    # slice each group's cache rows out of the full arrays (one dispatch
    # per array; async).  M == 1 passes the originals straight through —
    # they are donated by the stage steps and replaced at the end (a
    # full-range slice can alias its input, and donating an alias would
    # delete the parent).  Partial slices (M > 1) are always fresh
    # buffers.
    group_caches: List[Dict] = []
    for g in range(M):
        gc = {}
        for s in range(pp):
            for name in stage_cache_names[s]:
                kv = record["caches"][name]
                # generic over parts: int8 caches carry k_scale/v_scale
                # [R, KV, S] rows that slice and ride exactly like K/V
                if M == 1:
                    gc[name] = dict(kv)
                else:
                    gc[name] = {part: arr[g * Rg:(g + 1) * Rg]
                                for part, arr in kv.items()}
        group_caches.append(gc)

    include_init = init_tokens is not None
    toks: List[List[Any]] = [[] for _ in range(M)]
    tok_g: List[Any] = []
    depth_g: List[np.ndarray] = []
    active_g: List[np.ndarray] = []
    reps = [NamedSharding(m, PartitionSpec()) for m in meshes]
    for g in range(M):
        lo, hi = g * Rg, (g + 1) * Rg
        if include_init:
            init = jnp.asarray(init_tokens[lo:hi], jnp.int32)[:, None]
            toks[g].append(init[:, 0])
        else:
            init = jnp.asarray(batch_np["token_ids"][lo:hi, :1], jnp.int32)
        tok_g.append(init)
        depth_g.append(batch_np["first_depth"][lo:hi].copy())
        active_g.append(batch_np["active"][lo:hi].astype(np.int64))

    # block-invariant batch fields: committed to every stage mesh ONCE
    # (a per-step device_put of each would double the dispatch count)
    static_sg = [[{kk: jax.device_put(batch_np[kk][g * Rg:(g + 1) * Rg],
                                      reps[s])
                   for kk in ("row_tokens", "active")}
                  for g in range(M)] for s in range(pp)]
    # per-stage dispatch odometer (r5, VERDICT weak #6): the virtual-mesh
    # dryrun/CI can assert the schedule's shape (k * M dispatches per
    # stage per block) so a scheduling regression is visible even where
    # wall clock is unmeasurable
    disp = record.setdefault("pp_dispatches", [0] * pp)
    for t in range(k):
        rng, step_rng = jax.random.split(rng)
        # dispatch order: (stage, group) so stage s's queue holds every
        # group back-to-back while later stages consume earlier groups
        bounds: List[Dict] = [dict() for _ in range(M)]
        outs_g: List[Any] = [None] * M
        for s in range(pp):
            disp[s] += M
            for g in range(M):
                sbatch = dict(
                    static_sg[s][g],
                    token_ids=jax.device_put(tok_g[g], reps[s]),
                    first_depth=jax.device_put(depth_g[g], reps[s]))
                boundary = {kk: jax.device_put(v, reps[s])
                            for kk, v in bounds[g].items()}
                stage_caches = {n: group_caches[g][n]
                                for n in stage_cache_names[s]}
                # per-group key: sharing step_rng across groups would give
                # equal in-group row indices identical Gumbel noise under
                # do_sample (rows r and r+Rg correlated)
                out, new_caches = steps[s](stage_params[s], stage_caches,
                                           boundary, sbatch,
                                           jax.random.fold_in(step_rng, g))
                group_caches[g].update(new_caches)
                if s == pp - 1:
                    outs_g[g] = out
                else:
                    bounds[g] = out
        for g in range(M):
            new_tok = outs_g[g][0].astype(jnp.int32)   # [Rg, 1]
            tok_g[g] = new_tok
            toks[g].append(new_tok[:, 0])
            # NEW array, never `+=`: device_put of a numpy array can be
            # zero-copy on the CPU backend, so mutating it in place
            # corrupts batches already dispatched but not yet executed
            depth_g[g] = depth_g[g] + active_g[g]

    # re-emit the per-stage dispatch odometer through the registry (one
    # bulk inc per stage per block, via the manager's cached handle —
    # the snapshot twin of pp_dispatches)
    for s in range(pp):
        im.note_pp_dispatches(s, k * M)

    # write group cache rows back into the full arrays (in-place row
    # update; one dispatch per array).  M == 1 ran on the originals
    # (donated through the steps) — just adopt the final buffers.
    for name in (n for ns in stage_cache_names for n in ns):
        kv = record["caches"][name]
        for part in tuple(kv):
            if M == 1:
                kv[part] = group_caches[0][name][part]
                continue
            full = kv[part]
            for g in range(M):
                full = jax.lax.dynamic_update_slice_in_dim(
                    full, group_caches[g][name][part], g * Rg, axis=0)
            kv[part] = full

    # ONE sync: stack per group + concat across groups on device (the
    # token arrays all live on the last stage's mesh), single fetch
    # (the fetch itself happens at the caller's np.asarray)
    return jnp.concatenate([jnp.stack(ts) for ts in toks],
                           axis=1)                   # [k(+1), R]


def pipeline_inference(im, record, model_id: int, batch, rng) -> List[Any]:
    """Run one step through all stages (sequential per batch; dispatches
    overlap across batches because stages own disjoint devices)."""
    stages = record["pp_stages"]
    meshes = record["pp_meshes"]
    model = record["model"]
    caches = record["caches"]
    boundary: Dict[Tuple, Any] = {}
    outs: List[Any] = []
    chunk = int(batch["token_ids"].shape[1])
    # flash dispatch (r5): the host cost models run on the packed batch
    # the caller already built, so reconstruct the two fields they read
    from .inference_manager import (_record_flash_tile,
                                    flash_prefill_wins, flash_wins)

    class _BCView:
        request_available = np.asarray(batch["active"])
        first_token_depth = np.asarray(batch["first_depth"])

    gate_ok = pp_flash_ok(record, chunk)
    use_flash = (
        (chunk == 1 and gate_ok
         and flash_wins(_BCView, 1, record["alloc_len"],
                        _record_flash_tile(record)))
        or (chunk > 1 and gate_ok
            and flash_prefill_wins(_BCView, chunk,
                                   record["alloc_len"])))
    im.count_kernel_path(record, chunk, gate_ok, use_flash)
    if chunk > 1:
        im.recorder.record_event("prefill-chunk", chunk=chunk,
                                 pp=len(stages))
        im.ledger.note_event("prefill-chunk", chunk=chunk,
                             pp=len(stages))
    else:
        im.recorder.record_event("decode-step", chunk=1, pp=len(stages))
        im.ledger.note_event("decode-step", chunk=1, pp=len(stages))
    for s in range(len(stages)):
        key = ("pp_step", s, chunk, use_flash)
        if key not in record["pp_steps"]:
            record["pp_steps"][key] = jax.jit(
                make_stage_step(record, s, use_flash),
                donate_argnums=(1,))
        stage_params = {l.name: model.params[l.name] for l in stages[s]
                        if l.name in model.params}
        stage_caches = {l.name: caches[l.name] for l in stages[s]
                        if l.name in caches}
        # move boundary activations + batch onto this stage's devices
        rep = NamedSharding(meshes[s], PartitionSpec())
        boundary = {k: jax.device_put(v, rep) for k, v in boundary.items()}
        sbatch = {k: jax.device_put(v, rep) for k, v in batch.items()}
        out, new_caches = record["pp_steps"][key](
            stage_params, stage_caches, boundary, sbatch, rng)
        caches.update(new_caches)
        if s == len(stages) - 1:
            outs = out
        else:
            boundary = out
    return outs
