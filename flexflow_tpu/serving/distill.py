"""In-repo SSM distillation for speculative decoding (r5, VERDICT #2).

The reference specs with a real 160M draft model downloaded from HF
(tests/inference/python_test_configs/generate_configs.py pairs
llama-7b with llama-160m).  This container has no weight egress, so the
rebuild trains its OWN draft: a small LM distilled against the target
LLM's greedy outputs.  The resulting SSM genuinely disagrees with the
LLM (acceptance < 1 is measured, not assumed), closing the r4 gap where
every chip-measured spec number used a synthetic token-map SSM aligned
to the LLM by construction.

Pipeline (all on-device, no external data):

1. ``synthetic_corpus``  — an order-k Markov corpus with tunable
   determinism: the learnable structure acceptance comes from in real
   text (a random-weights LLM's greedy map is an unlearnable hash; a
   TRAINED LLM on structured text is the honest stand-in).
2. ``train_lm``          — next-token training via
   models/llama_train.LLaMATrainer (the flagship training path).
3. ``llm_generate_corpus`` — the trained LLM greedy-continues corpus
   seeds; the SSM trains on THESE tokens, i.e. on the LLM's own greedy
   outputs (distillation without external weights).
4. ``trainer_params_to_serving`` — map the trainer's param tree onto
   the serving graph's layer names so both models serve through the
   production stack (InferenceManager + spec_infer).

Measured acceptance then comes from the REAL spec loop's per-request
profiles, and the tree shape (W, D) is tuned at that acceptance —
bench.py bench_distill_spec drives this on chip.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def synthetic_corpus(vocab_size: int, n_tokens: int, order: int = 2,
                     determinism: float = 0.85, seed: int = 0,
                     reserved: int = 4) -> np.ndarray:
    """Order-``order`` Markov corpus: each state (the last ``order``
    tokens) has one fixed successor taken with probability
    ``determinism``; otherwise the next token is uniform noise.  Two
    models that learn the chain agree on the deterministic transitions
    and disagree on the noise — acceptance between them approaches the
    predictable fraction, which is what makes it a tunable stand-in for
    natural text.  Tokens < ``reserved`` are kept out (BOS/EOS/pad)."""
    rng = np.random.default_rng(seed)
    usable = vocab_size - reserved
    assert usable > 8, vocab_size
    # deterministic successor per state via a fixed random hash
    a = rng.integers(1, 1 << 30)
    b = rng.integers(1, 1 << 30)

    def successor(state: Tuple[int, ...]) -> int:
        h = 0
        for t in state:
            h = (h * a + t + b) % (1 << 31)
        return reserved + h % usable

    out = np.empty(n_tokens, np.int32)
    state = tuple(rng.integers(reserved, vocab_size, order).tolist())
    noise = rng.random(n_tokens)
    noise_tok = rng.integers(reserved, vocab_size, n_tokens)
    for i in range(n_tokens):
        t = successor(state) if noise[i] < determinism else int(noise_tok[i])
        out[i] = t
        state = state[1:] + (t,)
    return out


def train_lm(cfg, ffcfg, corpus: np.ndarray, steps: int, batch: int,
             seq_len: int, lr: float = 3e-4, seed: int = 0,
             log_every: int = 0):
    """Train a LLaMA-architecture LM on ``corpus`` with the flagship
    trainer; returns (trainer, params, losses)."""
    import jax

    from ..models.llama_train import LLaMATrainer
    from ..training.optimizer import AdamOptimizer

    trainer = LLaMATrainer(cfg, ffcfg, optimizer=AdamOptimizer(alpha=lr))
    params = trainer.init_params(jax.random.PRNGKey(seed))
    opt_state = trainer.optimizer.init(params)
    rng = np.random.default_rng(seed)
    n_windows = len(corpus) - seq_len - 1
    assert steps > 0 and n_windows > 0, (steps, len(corpus), seq_len)
    losses: List[float] = []
    for step in range(steps):
        starts = rng.integers(0, n_windows, batch)
        tokens = np.stack([corpus[s:s + seq_len + 1] for s in starts])
        params, opt_state, loss = trainer.fit_batch(params, opt_state,
                                                    tokens)
        if log_every and step % log_every == 0 and step != steps - 1:
            losses.append(float(loss))
    losses.append(float(loss))   # final loss exactly once
    return trainer, params, losses


def _unstack_blocks(blocks) -> List[Dict[str, Any]]:
    """Trainer blocks are ONE pytree with leading [stages, layers/stage]
    dims (parallel/pipeline.stack_stage_params); flatten back to one
    dict per layer, stage-major (= original layer order)."""
    import jax

    leaves = jax.tree.leaves(blocks)
    S, Lps = leaves[0].shape[:2]
    return [jax.tree.map(lambda v: v[s, i], blocks)
            for s in range(S) for i in range(Lps)]


def trainer_params_to_serving(params, cfg) -> Dict[str, Dict[str, Any]]:
    """Map LLaMATrainer params onto the serving builder's layer names
    (models/llama.py create_llama_model) — both use the HF-derived
    [E,H,D]/[H,D,E] layouts (llama_train.py docstring), so this is pure
    renaming, no transposes."""
    out: Dict[str, Dict[str, Any]] = {
        "embed_tokens": {"embedding": params["embed"]},
        "norm": {"weight": params["norm"]},
        "lm_head": {"kernel": params["lm_head"]},
    }
    for i, bp in enumerate(_unstack_blocks(params["blocks"])):
        pfx = f"layers_{i}"
        out[f"{pfx}_input_layernorm"] = {"weight": bp["attn_norm"]}
        out[f"{pfx}_attention"] = {k: bp[k]
                                   for k in ("wq", "wk", "wv", "wo")}
        out[f"{pfx}_post_attention_layernorm"] = {"weight": bp["ffn_norm"]}
        out[f"{pfx}_mlp_gate_proj"] = {"kernel": bp["w1"]}
        out[f"{pfx}_mlp_up_proj"] = {"kernel": bp["w3"]}
        out[f"{pfx}_mlp_down_proj"] = {"kernel": bp["w2"]}
    return out


def serving_model_from_trainer(cfg, params, mode, max_requests: int,
                               name: str, computation_dtype="float32"):
    """Build a serving Model for ``cfg`` and load the trained params."""
    from .. import FFConfig, Model
    from ..fftype import DataType
    from ..models.llama import create_llama_model

    model = Model(FFConfig(computation_dtype=computation_dtype), name=name)
    create_llama_model(model, cfg, mode=mode, max_requests=max_requests,
                       dtype=(DataType.HALF
                              if computation_dtype == "bfloat16"
                              else DataType.FLOAT))
    dt = np.dtype(computation_dtype) if computation_dtype != "bfloat16" \
        else None
    conv = trainer_params_to_serving(params, cfg)
    model.params = {
        ln: {pn: (np.asarray(v, dt) if dt is not None else np.asarray(v))
             for pn, v in lp.items()}
        for ln, lp in conv.items()}
    return model


def llm_generate_corpus(im, mid, rm_factory, seeds: Sequence[Sequence[int]],
                        n_new: int) -> List[List[int]]:
    """Greedy-continue each seed with the compiled LLM through the
    production serving stack; returns full token lists (the SSM's
    distillation corpus — the LLM's own greedy outputs)."""
    outs: List[List[int]] = []
    for chunk_start in range(0, len(seeds), 8):
        group = seeds[chunk_start:chunk_start + 8]
        rm = rm_factory()
        reqs = [rm.register_new_request(list(s), max_new_tokens=n_new)
                for s in group]
        rm.generate_incr_decoding(im, mid, reqs)
        outs.extend([list(r.tokens) for r in reqs])
    return outs


def measured_acceptance(reqs) -> float:
    """Per-proposal acceptance from the spec loop's per-request
    profiles (accepted/speculated — the bench_spec_infer convention)."""
    spec = sum(r.profile.speculated_tokens for r in reqs)
    if spec == 0:
        return 0.0
    return sum(r.profile.accepted_tokens for r in reqs) / spec
