"""Paged KV cache: block-granular allocator, host-RAM spill, preemption.

The serving stack sizes every cache row to the full allocation up front
(``compile_model_and_allocate_buffer``: ``rows = max_requests *
beam_width`` dense ``[R, KV, alloc_len, D]`` slabs — mirroring the
reference's statically-sized per-request KV, src/runtime/
request_manager.cc / inference_manager.cc), so the resident batch is
hard-capped by worst-case row HBM even though short requests never
touch most of their slab.  This module is the allocator half of the
fix (vLLM's PagedAttention block tables / the reference's planned
paged-KV direction, adapted to this stack's row-oriented caches):

- Cache rows LEASE refcounted, fixed-length **pages** of the KV length
  axis instead of owning a full-length slab: a row's page count tracks
  its committed KV (``ceil(len / page_len)``), and the pager enforces a
  process-level page budget — the HBM accounting a scheduler needs to
  admit more rows than worst-case sizing would allow.
- Under pressure, victim rows **spill** their committed KV to host RAM
  (``InferenceManager.fetch_row`` — a bucketed device->host fetch
  outside any jitted step) or are dropped for **recompute**, releasing
  their pages; a preempted request re-enters the pending queue with
  resume priority and, at re-admission, either **restores** its KV
  (``InferenceManager.restore_row`` — ``device_put`` + a jitted,
  donated row write) or re-prefills it chunk by chunk.  Both paths are
  bit-exact: KV depends only on token values and absolute positions
  (the prefix-cache correctness argument, prefix_cache.py).
- The restore-vs-recompute decision is **priced** by the search cost
  model (:class:`RecoveryPolicy`): restore = bytes / host-link
  bandwidth, recompute = a roofline over ``cached_len`` tokens of
  chunked prefill (``search/cost_model.MachineModel`` — the
  BENCH_r04-validated scaling model's machine description).
- Admission is **pressure-aware** (:class:`PressureScheduler`): when
  the pending queue's head has waited long enough to threaten the
  installed :class:`~flexflow_tpu.observability.SLOPolicy` TTFT
  target, the scheduler preempts the lowest-priority (most recently
  admitted) row to free pages/rows — trading one row's TPOT for the
  queue's TTFT, which is the balance FCFS admission cannot express.

Alignment invariants (shared with the prefix cache and the Pallas
kernels): ``page_len`` must be a multiple of ``PREFIX_ALIGN`` (16, the
flash-prefill append-window contract) AND of 32 (the int8 sublane RMW
window, docs/STATIC_ANALYSIS.md pallas-tiling table), so page
boundaries are always legal chunk-start depths for every cache dtype.
Restore lengths align DOWN to 16 like prefix matches — the resumed
prefill recomputes the unaligned tail.

Shape stability (the zero-recompile contract): paging lives entirely
in the allocator and the admission path.  The jitted decode/prefill
steps never see a page table — rows stay dense device slabs, and
spill/restore are separate bucketed transfers outside the decode
loop, so ``TestRetraceGuard`` pins a warmed decode loop to ZERO
compiles with the pager enabled.  The page budget is therefore an
*accounting* bound over committed-KV bytes (what admission control
and preemption need); physically freeing dense frames awaits a paged
Mosaic attend kernel (docs/INTERNALS.md "Paged KV cache" notes the
boundary honestly).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..observability import get_flight_recorder, get_registry
from .prefix_cache import PREFIX_ALIGN, align_down

#: smallest legal page length: lcm(16, 32) — 16-aligned chunk starts
#: for bf16 flash prefill AND 32-wide int8 RMW append windows, so page
#: boundaries are valid start depths for every cache dtype.
PAGE_ALIGN = 32

#: default page length (tokens of KV per page).  64 = two int8 RMW
#: windows; small enough that short requests strand < one chunk of HBM.
DEFAULT_PAGE_LEN = 64


def pages_for(length: int, page_len: int) -> int:
    """Pages needed to hold ``length`` committed KV positions."""
    if length <= 0:
        return 0
    return -(-int(length) // int(page_len))


class PageLease:
    """One slot's page holding: a running request's row or a resident
    prefix-pool entry (a slot is owned by exactly one of those at a
    time, so leases key by slot).  ``refs`` counts borrowers beyond the
    owner — a pooled entry pinned by in-flight admissions keeps its
    pages until released (the prefix pool's refcount rule, extended to
    pages)."""

    __slots__ = ("slot", "pages", "length", "owner", "guid", "refs",
                 "last_use")

    def __init__(self, slot: int, pages: int, length: int, owner: str,
                 guid: Optional[int]):
        self.slot = slot
        self.pages = pages
        self.length = length
        self.owner = owner          # "req" | "pool"
        self.guid = guid
        self.refs = 0
        self.last_use = 0.0


class RecoveryPolicy:
    """Prices restore-from-host against recompute-by-prefill for a
    preempted request with ``cached_len`` committed KV positions.

    - restore cost  = spilled bytes / ``host_bandwidth`` (the
      host<->device link; defaults to the machine model's DCN figure —
      the conservative off-chip link in the BENCH_r04-validated
      scaling model).
    - recompute cost = ``cached_len`` tokens of chunked prefill under
      the same machine's roofline: ``max(flops/peak_flops,
      weight_bytes/hbm_bandwidth)`` per token — prefill streams the
      weights once per chunk, so the per-token weight stream divides
      by ``chunk``.

    ``mode``: "auto" prices per decision; "restore"/"recompute" pin it
    (tests and the bench A/B arms use the pins).
    """

    def __init__(self, machine=None, flops_per_token: float = 0.0,
                 weight_bytes: float = 0.0,
                 kv_bytes_per_token: float = 0.0,
                 prefill_chunk: int = 256,
                 host_bandwidth: Optional[float] = None,
                 mode: str = "auto"):
        if machine is None:
            from ..search.cost_model import SimpleMachineModel

            machine = SimpleMachineModel(1)
        assert mode in ("auto", "restore", "recompute"), mode
        self.machine = machine
        self.flops_per_token = float(flops_per_token)
        self.weight_bytes = float(weight_bytes)
        self.kv_bytes_per_token = float(kv_bytes_per_token)
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.host_bandwidth = float(host_bandwidth
                                    or machine.dcn_bandwidth)
        self.mode = mode

    def restore_s(self, nbytes: int) -> float:
        return float(nbytes) / self.host_bandwidth

    def recompute_s(self, cached_len: int) -> float:
        per_tok = max(
            self.flops_per_token / self.machine.peak_flops,
            (self.weight_bytes / self.prefill_chunk
             + self.kv_bytes_per_token) / self.machine.hbm_bandwidth)
        return float(cached_len) * per_tok

    def choose(self, cached_len: int, nbytes: int) -> str:
        """"restore" | "recompute" for a spilled span of ``cached_len``
        tokens occupying ``nbytes`` of host RAM."""
        if self.mode != "auto":
            return self.mode
        if nbytes <= 0 or cached_len <= 0:
            return "recompute"
        return ("restore" if self.restore_s(nbytes)
                <= self.recompute_s(cached_len) else "recompute")

    @classmethod
    def for_record(cls, im, model_id: int, machine=None,
                   mode: str = "auto",
                   host_bandwidth: Optional[float] = None
                   ) -> "RecoveryPolicy":
        """Policy parameterized from a compiled record: decode flops ~
        2 * params per token, weight stream = param bytes, KV stream
        from KVCacheStats."""
        record = im.models[model_id]
        n_params = im.model_param_bytes(model_id)
        stats = im.kv_cache_stats(model_id)
        return cls(machine=machine,
                   flops_per_token=2.0 * n_params["elements"],
                   weight_bytes=n_params["bytes"],
                   kv_bytes_per_token=stats.bytes_per_token,
                   prefill_chunk=record.get("prefill_chunk", 256),
                   host_bandwidth=host_bandwidth, mode=mode)


class PressureScheduler:
    """Preemption policy: WHEN to preempt for admission and WHOM.

    - ``should_admit_preempt``: True when the pending queue's head has
      waited longer than the pressure threshold — ``queue_pressure_s``
      (the operator's knob), TIGHTENED to half the installed SLO TTFT
      target when that is smaller (preemption must fire before queue
      wait alone consumes the TTFT budget, leaving the other half for
      the prefill itself; a loose SLO never slackens the knob, which
      keeps preemption timing deterministic for tests and benches).
    - ``pick_victim``: the lowest-priority running request — most
      recently admitted first (LIFO preemption preserves FCFS
      fairness: the newest arrival re-queues, the oldest keeps its
      TPOT), tie-broken toward the most pages held.  Forward progress
      is the CALLER's contract: every call passes ``protect_guids``
      (the earliest-admitted request, RequestManager._protected_guids)
      so at least one row always runs to completion.
    """

    def __init__(self, queue_pressure_s: float = 0.25,
                 preempt_for_admission: bool = True):
        self.queue_pressure_s = float(queue_pressure_s)
        self.preempt_for_admission = bool(preempt_for_admission)

    def _threshold_s(self) -> float:
        from ..observability import get_ledger

        pol = get_ledger().slo_policy()
        if pol is not None and pol.ttft_s is not None:
            return min(self.queue_pressure_s, 0.5 * pol.ttft_s)
        return self.queue_pressure_s

    def should_admit_preempt(self, queue_wait_s: float) -> bool:
        # strict >: a zero threshold must not let a request whose wait
        # clock was JUST reset (preemption thrash guard) re-trigger
        return (self.preempt_for_admission
                and queue_wait_s > self._threshold_s())

    @staticmethod
    def pick_victim(running: Dict[int, Any],
                    protect_guids: Tuple[int, ...] = ()) -> Optional[Any]:
        cands = [r for r in running.values()
                 if r.guid not in protect_guids]
        if not cands:
            return None
        cands.sort(key=lambda r: (-r.profile.admit_mono,
                                  -(len(r.tokens))))
        return cands[0]


#: live pagers (weak — bench A/B arms and tests create several per
#: process); the watchdog embeds every live pager's snapshot in stall
#: bundles so ffstat can print pages free/leased + spilled GUIDs.
_LIVE_PAGERS: "weakref.WeakSet[KVPager]" = weakref.WeakSet()


def pager_snapshots() -> List[Dict[str, Any]]:
    """Snapshots of every live pager (the watchdog-bundle feed)."""
    return [p.snapshot() for p in list(_LIVE_PAGERS)]


class KVPager:
    """Block/page-granular KV accounting + host-RAM spill buffers.

    Pure host bookkeeping — the KV bytes live in the
    InferenceManager's dense cache rows; this class decides how many
    committed-KV pages each slot may hold against ``total_pages``, and
    keeps the host-side spill store for preempted rows and spilled
    prefix-pool entries.  Thread-safe (snapshots run from the
    watchdog's signal path).
    """

    def __init__(self, total_pages: int, page_len: int = DEFAULT_PAGE_LEN,
                 policy: Optional[RecoveryPolicy] = None,
                 scheduler: Optional[PressureScheduler] = None,
                 bytes_per_token: int = 0,
                 host_budget_bytes: Optional[int] = None):
        if page_len % PAGE_ALIGN:
            raise ValueError(
                f"page_len={page_len} must be a multiple of {PAGE_ALIGN} "
                f"(lcm of the {PREFIX_ALIGN}-aligned flash-prefill chunk "
                f"starts and the 32-wide int8 RMW append window)")
        self.total_pages = max(1, int(total_pages))
        self.page_len = int(page_len)
        self.policy = policy or RecoveryPolicy()
        self.scheduler = scheduler or PressureScheduler()
        #: bytes of committed KV per position (for budget<->bytes
        #: conversions in snapshots/bench; 0 = unknown)
        self.bytes_per_token = int(bytes_per_token)
        self.host_budget_bytes = host_budget_bytes
        self.leases: Dict[int, PageLease] = {}       # slot -> lease
        self.leased_pages = 0
        #: guid -> {"models": {mid: {"layers": {...}, "len": L}},
        #:          "bytes": n, "tokens": committed tokens at spill}
        self.spilled: Dict[int, Dict[str, Any]] = {}
        self.spilled_bytes = 0
        # lifetime odometers (the registry counters' local twins, so
        # tests and bench read them without a registry diff)
        self.spill_bytes_total = 0
        self.restore_bytes_total = 0
        self.preemptions = {"pages": 0, "admission": 0, "pool": 0}
        self.spill_drops = 0
        # RLock, not Lock: snapshot() is reachable from the watchdog's
        # SIGTERM/SIGUSR1 bundle path, which runs at an arbitrary
        # bytecode boundary of the main thread — if that thread is
        # mid-lease() when the signal lands, a plain Lock would
        # self-deadlock the dump (the PR-6 lock-discipline class)
        self._lock = threading.RLock()
        m = get_registry()
        self._recorder = get_flight_recorder()
        self._g_pages_total = m.gauge("serving_kv_pages_total")
        self._g_pages_free = m.gauge("serving_kv_pages_free")
        self._c_spill = m.counter("serving_kv_spill_bytes_total")
        self._c_restore = m.counter("serving_kv_restore_bytes_total")
        self._c_preempt = m.counter("serving_preemptions_total")
        self._g_pages_total.set(self.total_pages)
        self._g_pages_free.set(self.total_pages)
        _LIVE_PAGERS.add(self)

    # ------------------------------------------------------------ leases
    @property
    def free_pages(self) -> int:
        with self._lock:
            return max(0, self.total_pages - self.leased_pages)

    @property
    def overcommitted_pages(self) -> int:
        with self._lock:
            return max(0, self.leased_pages - self.total_pages)

    def pages_for(self, length: int) -> int:
        return pages_for(length, self.page_len)

    def lease_of(self, slot: int) -> Optional[PageLease]:
        with self._lock:
            return self.leases.get(slot)

    def shortfall(self, slot: Optional[int], length: int) -> int:
        """Extra pages a lease-to-``length`` on ``slot`` would need
        beyond the free pool (0 = satisfiable now)."""
        with self._lock:
            have = self.leases[slot].pages if slot in self.leases else 0
            need = pages_for(length, self.page_len) - have
            free = self.total_pages - self.leased_pages
            return max(0, need - max(0, free))

    def lease(self, slot: int, length: int, owner: str = "req",
              guid: Optional[int] = None, force: bool = False) -> bool:
        """Adjust ``slot``'s page count to cover ``length`` positions.
        Returns False (state unchanged) when growth exceeds the free
        pool and ``force`` is not set; ``force=True`` books the overage
        anyway (forward-progress guarantee mid-decode-block — the dense
        allocation physically has the space; the overcommit is counted
        and trued up by preemption at the next fold boundary)."""
        with self._lock:
            lease = self.leases.get(slot)
            have = lease.pages if lease is not None else 0
            want = pages_for(length, self.page_len)
            grow = want - have
            if grow > 0 and not force and (
                    self.leased_pages + grow > self.total_pages):
                return False
            if lease is None:
                lease = self.leases[slot] = PageLease(
                    slot, 0, 0, owner, guid)
            lease.pages = want
            lease.length = int(length)
            lease.owner = owner
            lease.guid = guid
            lease.last_use = time.monotonic()
            self.leased_pages += grow
            self._g_pages_free.set(
                max(0, self.total_pages - self.leased_pages))
            return True

    def release(self, slot: int) -> int:
        """Free a slot's pages; returns the page count released."""
        with self._lock:
            lease = self.leases.pop(slot, None)
            if lease is None:
                return 0
            self.leased_pages -= lease.pages
            self._g_pages_free.set(
                max(0, self.total_pages - self.leased_pages))
            return lease.pages

    def acquire(self, slot: int):
        with self._lock:
            if slot in self.leases:
                self.leases[slot].refs += 1

    def release_ref(self, slot: int):
        with self._lock:
            if slot in self.leases and self.leases[slot].refs > 0:
                self.leases[slot].refs -= 1

    # ------------------------------------------------------------- spill
    def store_spill(self, guid: int, models: Dict[int, Dict[str, Any]],
                    tokens: int, nbytes: int) -> None:
        """Keep a preempted request's fetched KV in host RAM.  Over the
        host budget, the LRU spill is dropped (its request silently
        degrades to recompute — counted in ``spill_drops``)."""
        with self._lock:
            self.spilled[guid] = {"models": models, "tokens": int(tokens),
                                  "bytes": int(nbytes)}
            self.spilled_bytes += int(nbytes)
            self.spill_bytes_total += int(nbytes)
            while (self.host_budget_bytes is not None
                   and self.spilled_bytes > self.host_budget_bytes
                   and len(self.spilled) > 1):
                old_guid = next(iter(self.spilled))
                if old_guid == guid:
                    break
                dropped = self.spilled.pop(old_guid)
                self.spilled_bytes -= dropped["bytes"]
                self.spill_drops += 1
        self._c_spill.inc(nbytes)

    def peek_spill(self, guid: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self.spilled.get(guid)

    def take_spill(self, guid: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            sp = self.spilled.pop(guid, None)
            if sp is not None:
                self.spilled_bytes -= sp["bytes"]
            return sp

    def drop_spill(self, guid: int) -> None:
        self.take_spill(guid)

    def count_spill(self, nbytes: int) -> None:
        """Count spill bytes that bypass the per-guid store (prefix-
        pool page spills keep their payload on the PrefixEntry)."""
        with self._lock:
            self.spill_bytes_total += int(nbytes)
        self._c_spill.inc(nbytes)

    def count_restore(self, nbytes: int) -> None:
        with self._lock:
            self.restore_bytes_total += int(nbytes)
        self._c_restore.inc(nbytes)

    def count_preemption(self, reason: str) -> None:
        with self._lock:
            self.preemptions[reason] = self.preemptions.get(reason, 0) + 1
        self._c_preempt.inc(reason=reason)

    # ---------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable state (the watchdog-bundle / ffstat feed):
        budget, per-slot leases, spilled GUIDs and the odometers."""
        with self._lock:
            return {
                "page_len": self.page_len,
                "total_pages": self.total_pages,
                "leased_pages": self.leased_pages,
                "free_pages": max(0,
                                  self.total_pages - self.leased_pages),
                "overcommitted_pages": max(
                    0, self.leased_pages - self.total_pages),
                "bytes_per_token": self.bytes_per_token,
                "budget_bytes": (self.total_pages * self.page_len
                                 * self.bytes_per_token),
                "leases": [
                    {"slot": l.slot, "pages": l.pages,
                     "length": l.length, "owner": l.owner,
                     "guid": l.guid, "refs": l.refs}
                    for l in self.leases.values()],
                "spilled_guids": {g: {"tokens": s["tokens"],
                                      "bytes": s["bytes"]}
                                  for g, s in self.spilled.items()},
                "spilled_bytes": self.spilled_bytes,
                "spill_bytes_total": self.spill_bytes_total,
                "restore_bytes_total": self.restore_bytes_total,
                "spill_drops": self.spill_drops,
                "preemptions": dict(self.preemptions),
            }

    def config(self) -> Dict[str, Any]:
        """The bench-record ``kv_pager`` stamp (page size, budget,
        spill policy) — stable fields only."""
        return {
            "enabled": True,
            "page_len": self.page_len,
            "total_pages": self.total_pages,
            "budget_bytes": (self.total_pages * self.page_len
                             * self.bytes_per_token),
            "spill_policy": self.policy.mode,
            "host_budget_bytes": self.host_budget_bytes,
        }


def pager_for_budget(budget_bytes: int, bytes_per_token: int,
                     page_len: int = DEFAULT_PAGE_LEN,
                     **kwargs) -> KVPager:
    """A pager whose page budget covers ``budget_bytes`` of committed
    KV at ``bytes_per_token`` (KVCacheStats.bytes_per_token of the
    served record) — the bench A/B's fixed-HBM-budget constructor."""
    page_bytes = max(1, page_len * int(bytes_per_token))
    return KVPager(max(1, int(budget_bytes) // page_bytes),
                   page_len=page_len, bytes_per_token=bytes_per_token,
                   **kwargs)


def _selftest() -> int:
    """Pure-host allocator smoke (the run_tier1.sh pager gate): lease /
    release / refcount accounting, alignment validation, spill-store
    budgeting and policy pricing — no model, no device."""
    import numpy as np

    ok = True

    def check(cond, msg):
        nonlocal ok
        if not cond:
            ok = False
            print(f"kv_pager selftest FAILED: {msg}")

    try:
        KVPager(4, page_len=48)
        check(False, "page_len=48 accepted")
    except ValueError:
        pass
    p = KVPager(8, page_len=64, bytes_per_token=128)
    check(p.pages_for(1) == 1 and p.pages_for(64) == 1
          and p.pages_for(65) == 2, "pages_for math")
    check(p.lease(0, 100) and p.free_pages == 6, "lease grow")
    check(p.lease(0, 30) and p.free_pages == 7, "lease shrink")
    check(not p.lease(1, 8 * 64) and p.free_pages == 7,
          "over-budget lease must fail atomically")
    check(p.lease(1, 8 * 64, force=True) and p.free_pages == 0
          and p.overcommitted_pages == 1, "forced overcommit books")
    check(p.release(1) == 8 and p.free_pages == 7, "release")
    check(p.shortfall(None, 64 * 7) == 0
          and p.shortfall(None, 64 * 8) == 1, "shortfall")
    payload = {0: {"layers": {"l0": {"k": np.zeros((1, 2, 64, 4))}},
                   "len": 64}}
    p.store_spill(7, payload, tokens=90, nbytes=4096)
    check(p.peek_spill(7) is not None and p.spilled_bytes == 4096,
          "spill store")
    check(p.take_spill(7)["tokens"] == 90 and p.spilled_bytes == 0,
          "spill take")
    pol = RecoveryPolicy(flops_per_token=2e9, weight_bytes=1e9,
                         kv_bytes_per_token=1e5, prefill_chunk=256)
    check(pol.choose(4096, 64) == "restore",
          "tiny spill vs long recompute must restore")
    check(pol.choose(16, 10 ** 12) == "recompute",
          "huge spill vs short recompute must recompute")
    check(RecoveryPolicy(mode="recompute").choose(4096, 64)
          == "recompute", "pinned mode wins")
    snap = p.snapshot()
    check(snap["total_pages"] == 8 and snap["leases"][0]["slot"] == 0,
          "snapshot shape")
    if ok:
        print("kv_pager selftest OK")
    return 0 if ok else 1


if __name__ == "__main__":  # pragma: no cover - CLI smoke entry
    import sys

    sys.exit(_selftest())
