"""Paged KV cache: block-granular allocator, host-RAM spill, preemption.

The serving stack sizes every cache row to the full allocation up front
(``compile_model_and_allocate_buffer``: ``rows = max_requests *
beam_width`` dense ``[R, KV, alloc_len, D]`` slabs — mirroring the
reference's statically-sized per-request KV, src/runtime/
request_manager.cc / inference_manager.cc), so the resident batch is
hard-capped by worst-case row HBM even though short requests never
touch most of their slab.  This module is the allocator half of the
fix (vLLM's PagedAttention block tables / the reference's planned
paged-KV direction, adapted to this stack's row-oriented caches):

- Cache rows LEASE refcounted, fixed-length **pages** of the KV length
  axis instead of owning a full-length slab: a row's page count tracks
  its committed KV (``ceil(len / page_len)``), and the pager enforces a
  process-level page budget — the HBM accounting a scheduler needs to
  admit more rows than worst-case sizing would allow.
- Under pressure, victim rows **spill** their committed KV to host RAM
  (``InferenceManager.fetch_row`` — a bucketed device->host fetch
  outside any jitted step) or are dropped for **recompute**, releasing
  their pages; a preempted request re-enters the pending queue with
  resume priority and, at re-admission, either **restores** its KV
  (``InferenceManager.restore_row`` — ``device_put`` + a jitted,
  donated row write) or re-prefills it chunk by chunk.  Both paths are
  bit-exact: KV depends only on token values and absolute positions
  (the prefix-cache correctness argument, prefix_cache.py).
- The restore-vs-recompute decision is **priced** by the search cost
  model (:class:`RecoveryPolicy`): restore = bytes / host-link
  bandwidth, recompute = a roofline over ``cached_len`` tokens of
  chunked prefill (``search/cost_model.MachineModel`` — the
  BENCH_r04-validated scaling model's machine description).
- Admission is **pressure-aware** (:class:`PressureScheduler`): when
  the pending queue's head has waited long enough to threaten the
  installed :class:`~flexflow_tpu.observability.SLOPolicy` TTFT
  target, the scheduler preempts the lowest-priority (most recently
  admitted) row to free pages/rows — trading one row's TPOT for the
  queue's TTFT, which is the balance FCFS admission cannot express.

Alignment invariants (shared with the prefix cache and the Pallas
kernels): ``page_len`` must be a multiple of ``PREFIX_ALIGN`` (16, the
flash-prefill append-window contract) AND of 32 (the int8 sublane RMW
window, docs/STATIC_ANALYSIS.md pallas-tiling table), so page
boundaries are always legal chunk-start depths for every cache dtype.
Restore lengths align DOWN to 16 like prefix matches — the resumed
prefill recomputes the unaligned tail.

Shape stability (the zero-recompile contract): paging lives entirely
in the allocator and the admission path.  Against a DENSE record the
jitted decode/prefill steps never see a page table and the page budget
is an *accounting* bound over committed-KV bytes; against a PAGED
record (PR 10, ``kv_layout="paged"``) the pager additionally owns
CONCRETE frame ids of the record's global frame pool
(``num_frames``), and the per-row page table the jitted steps consume
is pure int32 DATA of a fixed ``[rows, max_pages]`` shape — either
way ``TestRetraceGuard``/``TestPagedRetraceGuard`` pin a warmed
decode loop to ZERO compiles with the pager enabled.  Physical mode
makes the budget real: HBM residency is ``leased_frames x
frame_bytes``, spill/restore move whole frames, and a prefix-pool hit
LEASES the donor's frames by refcount instead of copying rows
(docs/INTERNALS.md "Paged KV cache — the page lifecycle").
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..observability import get_flight_recorder, get_registry
from .prefix_cache import PREFIX_ALIGN, align_down

#: smallest legal page length: lcm(16, 32) — 16-aligned chunk starts
#: for bf16 flash prefill AND 32-wide int8 RMW append windows, so page
#: boundaries are valid start depths for every cache dtype.
PAGE_ALIGN = 32

#: default page length (tokens of KV per page).  64 = two int8 RMW
#: windows; small enough that short requests strand < one chunk of HBM.
DEFAULT_PAGE_LEN = 64


def pages_for(length: int, page_len: int) -> int:
    """Pages needed to hold ``length`` committed KV positions."""
    if length <= 0:
        return 0
    return -(-int(length) // int(page_len))


class PageLease:
    """One slot's page holding: a running request's row or a resident
    prefix-pool entry (a slot is owned by exactly one of those at a
    time, so leases key by slot).  ``refs`` counts borrowers beyond the
    owner — a pooled entry pinned by in-flight admissions keeps its
    pages until released (the prefix pool's refcount rule, extended to
    pages).  ``frames`` (physical pagers only) is the ordered list of
    CONCRETE frame ids backing logical pages 0..pages-1 — frame ids
    need not be contiguous or monotone (the free list fragments under
    churn; the page-table kernels only ever see data)."""

    __slots__ = ("slot", "pages", "length", "owner", "guid", "refs",
                 "last_use", "frames")

    def __init__(self, slot: int, pages: int, length: int, owner: str,
                 guid: Optional[int]):
        self.slot = slot
        self.pages = pages
        self.length = length
        self.owner = owner          # "req" | "pool"
        self.guid = guid
        self.refs = 0
        self.last_use = 0.0
        self.frames: List[int] = []


class RecoveryPolicy:
    """Prices restore-from-host against recompute-by-prefill for a
    preempted request with ``cached_len`` committed KV positions.

    - restore cost  = spilled bytes / ``host_bandwidth`` (the
      host<->device link; defaults to the machine model's DCN figure —
      the conservative off-chip link in the BENCH_r04-validated
      scaling model).
    - recompute cost = ``cached_len`` tokens of chunked prefill under
      the same machine's roofline: ``max(flops/peak_flops,
      weight_bytes/hbm_bandwidth)`` per token — prefill streams the
      weights once per chunk, so the per-token weight stream divides
      by ``chunk``.
    - migrate cost = spilled bytes / ``device_bandwidth`` (the direct
      device-to-device link, ``MachineModel.device_link_bandwidth``):
      single-device slices transfer committed device arrays via
      jax.device_put without host staging (FrameMigrator's direct
      path), which is what this term prices — distinct from restore's
      host link.  Sharded submesh slices fall back to the host-staged
      spill payload, where this price is optimistic (two host-link
      crossings) until a sharded d2d transport lands.

    ``mode``: "auto" prices per decision; "restore"/"recompute" pin it
    (tests and the bench A/B arms use the pins).  ``migrate_mode``
    plays the same role for the disaggregated migrate-vs-recompute
    decision ("auto" | "migrate" | "recompute").
    """

    def __init__(self, machine=None, flops_per_token: float = 0.0,
                 weight_bytes: float = 0.0,
                 kv_bytes_per_token: float = 0.0,
                 prefill_chunk: int = 256,
                 host_bandwidth: Optional[float] = None,
                 mode: str = "auto",
                 device_bandwidth: Optional[float] = None,
                 migrate_mode: str = "auto",
                 wire_bandwidth: Optional[float] = None):
        if machine is None:
            # default_machine honors a calibrated FF_MACHINE_PROFILE
            # (tools/ffprof.py --calibrate) — measured hbm/link rates
            # price restore/recompute/migrate instead of the datasheet
            from ..search.cost_model import default_machine

            machine = default_machine()
        assert mode in ("auto", "restore", "recompute"), mode
        assert migrate_mode in ("auto", "migrate", "recompute"), \
            migrate_mode
        self.machine = machine
        self.flops_per_token = float(flops_per_token)
        self.weight_bytes = float(weight_bytes)
        self.kv_bytes_per_token = float(kv_bytes_per_token)
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.host_bandwidth = float(host_bandwidth
                                    or machine.dcn_bandwidth)
        self.device_bandwidth = float(
            device_bandwidth
            or getattr(machine, "device_link_bandwidth", None)
            or machine.ici_bandwidth)
        self.wire_bandwidth = float(
            wire_bandwidth
            or getattr(machine, "wire_bandwidth", None)
            or machine.dcn_bandwidth)
        self.mode = mode
        self.migrate_mode = migrate_mode

    def restore_s(self, nbytes: int) -> float:
        return float(nbytes) / self.host_bandwidth

    def migrate_s(self, nbytes: int) -> float:
        """Whole-payload device-to-device transfer time over the
        migration link (+ one link latency)."""
        return (float(nbytes) / self.device_bandwidth
                + self.machine.ici_latency)

    def wire_migrate_s(self, nbytes: int) -> float:
        """Cross-replica KV bundle over the datacenter wire (the
        router's ``/v1/kv/export`` -> ``/v1/kv/import`` pair): one
        network crossing + a device hop on each end."""
        return (float(nbytes) / self.wire_bandwidth
                + 2.0 * self.machine.ici_latency)

    def recompute_s(self, cached_len: int) -> float:
        per_tok = max(
            self.flops_per_token / self.machine.peak_flops,
            (self.weight_bytes / self.prefill_chunk
             + self.kv_bytes_per_token) / self.machine.hbm_bandwidth)
        return float(cached_len) * per_tok

    def choose(self, cached_len: int, nbytes: int) -> str:
        """"restore" | "recompute" for a spilled span of ``cached_len``
        tokens occupying ``nbytes`` of host RAM."""
        if self.mode != "auto":
            return self.mode
        if nbytes <= 0 or cached_len <= 0:
            return "recompute"
        return ("restore" if self.restore_s(nbytes)
                <= self.recompute_s(cached_len) else "recompute")

    def choose_migrate(self, cached_len: int, nbytes: int) -> str:
        """"migrate" | "recompute" for a prefilled span of
        ``cached_len`` KV positions (``nbytes`` of cache bytes) whose
        request is leaving the prefill slice: ship the frames over the
        device link, or re-prefill on the decode slice (the
        DistServe-style transfer-vs-recompute decision)."""
        if self.migrate_mode != "auto":
            return self.migrate_mode
        if nbytes <= 0 or cached_len <= 0:
            return "recompute"
        return ("migrate" if self.migrate_s(nbytes)
                <= self.recompute_s(cached_len) else "recompute")

    def choose_wire(self, cached_len: int, nbytes: int) -> str:
        """"migrate" | "recompute" for a prefix of ``cached_len``
        committed KV positions a PEER replica holds (``nbytes`` of
        cache bytes on the wire): ship the bundle across the network
        into the local pager, or re-prefill the prefix locally — the
        fleet-KV-economy pricing the router runs before routing a
        request whose prefix lives elsewhere.  Honors ``migrate_mode``
        pins the same way :meth:`choose_migrate` does."""
        if self.migrate_mode != "auto":
            return self.migrate_mode
        if nbytes <= 0 or cached_len <= 0:
            return "recompute"
        return ("migrate" if self.wire_migrate_s(nbytes)
                <= self.recompute_s(cached_len) else "recompute")

    @classmethod
    def for_record(cls, im, model_id: int, machine=None,
                   mode: str = "auto",
                   host_bandwidth: Optional[float] = None,
                   migrate_mode: str = "auto"
                   ) -> "RecoveryPolicy":
        """Policy parameterized from a compiled record: decode flops ~
        2 * params per token, weight stream = param bytes, KV stream
        from KVCacheStats."""
        record = im.models[model_id]
        n_params = im.model_param_bytes(model_id)
        stats = im.kv_cache_stats(model_id)
        return cls(machine=machine,
                   flops_per_token=2.0 * n_params["elements"],
                   weight_bytes=n_params["bytes"],
                   kv_bytes_per_token=stats.bytes_per_token,
                   prefill_chunk=record.get("prefill_chunk", 256),
                   host_bandwidth=host_bandwidth, mode=mode,
                   migrate_mode=migrate_mode)


class PressureScheduler:
    """Preemption policy: WHEN to preempt for admission and WHOM.

    - ``should_admit_preempt``: True when the pending queue's head has
      waited longer than the pressure threshold — ``queue_pressure_s``
      (the operator's knob), TIGHTENED to half the installed SLO TTFT
      target when that is smaller (preemption must fire before queue
      wait alone consumes the TTFT budget, leaving the other half for
      the prefill itself; a loose SLO never slackens the knob, which
      keeps preemption timing deterministic for tests and benches).
    - ``pick_victim``: the lowest-priority running request — most
      recently admitted first (LIFO preemption preserves FCFS
      fairness: the newest arrival re-queues, the oldest keeps its
      TPOT), tie-broken toward the most pages held.  Forward progress
      is the CALLER's contract: every call passes ``protect_guids``
      (the earliest-admitted request, RequestManager._protected_guids)
      so at least one row always runs to completion.
    """

    def __init__(self, queue_pressure_s: float = 0.25,
                 preempt_for_admission: bool = True):
        self.queue_pressure_s = float(queue_pressure_s)
        self.preempt_for_admission = bool(preempt_for_admission)

    def _threshold_s(self) -> float:
        from ..observability import get_ledger

        pol = get_ledger().slo_policy()
        if pol is not None and pol.ttft_s is not None:
            return min(self.queue_pressure_s, 0.5 * pol.ttft_s)
        return self.queue_pressure_s

    def should_admit_preempt(self, queue_wait_s: float) -> bool:
        # strict >: a zero threshold must not let a request whose wait
        # clock was JUST reset (preemption thrash guard) re-trigger
        return (self.preempt_for_admission
                and queue_wait_s > self._threshold_s())

    @staticmethod
    def pick_victim(running: Dict[int, Any],
                    protect_guids: Tuple[int, ...] = ()) -> Optional[Any]:
        cands = [r for r in running.values()
                 if r.guid not in protect_guids]
        if not cands:
            return None
        cands.sort(key=lambda r: (-r.profile.admit_mono,
                                  -(len(r.tokens))))
        return cands[0]


#: live pagers (weak — bench A/B arms and tests create several per
#: process); the watchdog embeds every live pager's snapshot in stall
#: bundles so ffstat can print pages free/leased + spilled GUIDs.
_LIVE_PAGERS: "weakref.WeakSet[KVPager]" = weakref.WeakSet()


def pager_snapshots() -> List[Dict[str, Any]]:
    """Snapshots of every live pager (the watchdog-bundle feed)."""
    return [p.snapshot() for p in list(_LIVE_PAGERS)]


class KVPager:
    """Block/page-granular KV accounting + host-RAM spill buffers.

    Pure host bookkeeping — the KV bytes live in the
    InferenceManager's dense cache rows; this class decides how many
    committed-KV pages each slot may hold against ``total_pages``, and
    keeps the host-side spill store for preempted rows and spilled
    prefix-pool entries.  Thread-safe (snapshots run from the
    watchdog's signal path).
    """

    def __init__(self, total_pages: int, page_len: int = DEFAULT_PAGE_LEN,
                 policy: Optional[RecoveryPolicy] = None,
                 scheduler: Optional[PressureScheduler] = None,
                 bytes_per_token: int = 0,
                 host_budget_bytes: Optional[int] = None,
                 num_frames: Optional[int] = None,
                 frame_order: Optional[List[int]] = None,
                 slice_label: Optional[str] = None):
        if page_len % PAGE_ALIGN:
            raise ValueError(
                f"page_len={page_len} must be a multiple of {PAGE_ALIGN} "
                f"(lcm of the {PREFIX_ALIGN}-aligned flash-prefill chunk "
                f"starts and the 32-wide int8 RMW append window)")
        self.total_pages = max(1, int(total_pages))
        self.page_len = int(page_len)
        #: PHYSICAL mode (PR 10): when set, leases own concrete frame
        #: ids of an InferenceManager frame pool instead of a pure page
        #: count — ``total_pages`` stays the admission BUDGET while
        #: ``num_frames`` is the pool's physical capacity (>= budget;
        #: the surplus is the forced-overcommit headroom that replaces
        #: the dense slabs' implicit slack).  ``frame_order`` seeds the
        #: free list (tests use it to force fragmented, out-of-order
        #: frame ids; default ascending).
        self.num_frames = int(num_frames) if num_frames else None
        self._free_frames: List[int] = []
        self._frame_refs: Dict[int, int] = {}
        if self.num_frames is not None:
            if self.num_frames < self.total_pages:
                raise ValueError(
                    f"num_frames={self.num_frames} < total_pages="
                    f"{self.total_pages}: the physical pool must cover "
                    f"the page budget")
            order = (list(frame_order) if frame_order is not None
                     else list(range(self.num_frames)))
            assert sorted(order) == list(range(self.num_frames)), (
                "frame_order must be a permutation of range(num_frames)")
            # popped from the END: reversed so default allocation starts
            # at frame 0 (pure convention — ids are opaque to kernels)
            self._free_frames = list(reversed(order))
        self.policy = policy or RecoveryPolicy()
        self.scheduler = scheduler or PressureScheduler()
        #: bytes of committed KV per position (for budget<->bytes
        #: conversions in snapshots/bench; 0 = unknown)
        self.bytes_per_token = int(bytes_per_token)
        self.host_budget_bytes = host_budget_bytes
        self.leases: Dict[int, PageLease] = {}       # slot -> lease
        self.leased_pages = 0
        #: guid -> {"models": {mid: {"layers": {...}, "len": L}},
        #:          "bytes": n, "tokens": committed tokens at spill}
        self.spilled: Dict[int, Dict[str, Any]] = {}
        self.spilled_bytes = 0
        # lifetime odometers (the registry counters' local twins, so
        # tests and bench read them without a registry diff)
        self.spill_bytes_total = 0
        self.restore_bytes_total = 0
        self.preemptions = {"pages": 0, "admission": 0, "pool": 0}
        self.spill_drops = 0
        # RLock, not Lock: snapshot() is reachable from the watchdog's
        # SIGTERM/SIGUSR1 bundle path, which runs at an arbitrary
        # bytecode boundary of the main thread — if that thread is
        # mid-lease() when the signal lands, a plain Lock would
        # self-deadlock the dump (the PR-6 lock-discipline class)
        self._lock = threading.RLock()
        #: disaggregated serving (serving/disagg.py) runs one pager per
        #: mesh slice — the label keys this pager's gauge series (e.g.
        #: {slice="prefill"} vs {slice="decode"}) and rides snapshots
        #: so ffstat's stall diagnosis prints per-slice frame gauges.
        #: None keeps the unlabeled single-pool series (bit-identical
        #: to the pre-disagg exposition).
        self.slice_label = slice_label
        self._slice_kw = ({"slice": slice_label} if slice_label else {})
        m = get_registry()
        self._recorder = get_flight_recorder()
        self._g_pages_total = m.gauge("serving_kv_pages_total")
        self._g_pages_free = m.gauge("serving_kv_pages_free")
        self._g_frames_total = m.gauge("serving_kv_frames_total")
        self._g_frames_free = m.gauge("serving_kv_frames_free")
        self._c_spill = m.counter("serving_kv_spill_bytes_total")
        self._c_restore = m.counter("serving_kv_restore_bytes_total")
        self._c_preempt = m.counter("serving_preemptions_total")
        self._c_shared = m.counter("serving_prefix_frames_shared_total")
        self._g_pages_total.set(self.total_pages, **self._slice_kw)
        self._g_pages_free.set(self.total_pages, **self._slice_kw)
        if self.num_frames is not None:
            self._g_frames_total.set(self.num_frames, **self._slice_kw)
            self._g_frames_free.set(len(self._free_frames),
                                    **self._slice_kw)
        _LIVE_PAGERS.add(self)

    # ------------------------------------------------------------ leases
    @property
    def free_pages(self) -> int:
        with self._lock:
            return max(0, self.total_pages - self.leased_pages)

    @property
    def overcommitted_pages(self) -> int:
        with self._lock:
            return max(0, self.leased_pages - self.total_pages)

    def pages_for(self, length: int) -> int:
        return pages_for(length, self.page_len)

    def lease_of(self, slot: int) -> Optional[PageLease]:
        with self._lock:
            return self.leases.get(slot)

    def shortfall(self, slot: Optional[int], length: int) -> int:
        """Extra pages a lease-to-``length`` on ``slot`` would need
        beyond the free pool (0 = satisfiable now)."""
        with self._lock:
            have = self.leases[slot].pages if slot in self.leases else 0
            need = pages_for(length, self.page_len) - have
            free = self.total_pages - self.leased_pages
            if self.num_frames is not None:
                # physical mode: the free LIST is the hard bound (the
                # budget may be overcommitted by forced bookings)
                free = min(free, len(self._free_frames))
            return max(0, need - max(0, free))

    def lease(self, slot: int, length: int, owner: str = "req",
              guid: Optional[int] = None, force: bool = False) -> bool:
        """Adjust ``slot``'s page count to cover ``length`` positions.
        Returns False (state unchanged) when growth exceeds the free
        pool and ``force`` is not set; ``force=True`` books the overage
        anyway (forward-progress guarantee mid-decode-block: accounting
        pagers have the dense slabs' physical space behind them, and
        physical pagers carry ``num_frames - total_pages`` headroom
        frames for exactly this).  A PHYSICAL pager additionally fails
        even under ``force`` when the frame free list itself runs dry —
        there is no byte of HBM left to book; the caller must preempt
        (``RequestManager.pager_sync_leases`` does)."""
        with self._lock:
            lease = self.leases.get(slot)
            have = lease.pages if lease is not None else 0
            want = pages_for(length, self.page_len)
            grow = want - have
            if grow > 0 and not force and (
                    self.leased_pages + grow > self.total_pages):
                return False
            if self.num_frames is not None and grow > len(
                    self._free_frames):
                return False           # physically out of frames
            if lease is None:
                lease = self.leases[slot] = PageLease(
                    slot, 0, 0, owner, guid)
            if self.num_frames is not None:
                if grow > 0:
                    for _ in range(grow):
                        f = self._free_frames.pop()
                        self._frame_refs[f] = 1
                        lease.frames.append(f)
                elif grow < 0:
                    for _ in range(-grow):
                        self._unref_frame(lease.frames.pop())
                self.leased_pages = len(self._frame_refs)
            else:
                self.leased_pages += grow
            lease.pages = want
            lease.length = int(length)
            lease.owner = owner
            lease.guid = guid
            lease.last_use = time.monotonic()
            self._set_free_gauges()
            return True

    def _unref_frame(self, f: int) -> None:
        """Drop one reference on frame ``f``; a frame nobody references
        returns to the free list.  Callers already hold ``_lock`` —
        re-acquiring the RLock here keeps the helper safe standalone."""
        with self._lock:
            rc = self._frame_refs.get(f, 0) - 1
            if rc <= 0:
                self._frame_refs.pop(f, None)
                self._free_frames.append(f)
            else:
                self._frame_refs[f] = rc

    def _set_free_gauges(self) -> None:
        with self._lock:
            self._g_pages_free.set(
                max(0, self.total_pages - self.leased_pages),
                **self._slice_kw)
            if self.num_frames is not None:
                self._g_frames_free.set(len(self._free_frames),
                                        **self._slice_kw)

    def release(self, slot: int) -> int:
        """Free a slot's pages; returns the page count released."""
        with self._lock:
            lease = self.leases.pop(slot, None)
            if lease is None:
                return 0
            if self.num_frames is not None:
                for f in lease.frames:
                    self._unref_frame(f)
                self.leased_pages = len(self._frame_refs)
            else:
                self.leased_pages -= lease.pages
            self._set_free_gauges()
            return lease.pages

    # ------------------------------------------------------------- frames
    def frames_of(self, slot: int) -> List[int]:
        """The ordered concrete frame ids backing ``slot``'s logical
        pages (physical pagers; empty otherwise)."""
        with self._lock:
            lease = self.leases.get(slot)
            return list(lease.frames) if lease is not None else []

    def adopt_prefix(self, dst_slot: int, src_slot: int,
                     n_pages: int) -> int:
        """Frame-sharing prefix hit (the physical twin of the device
        ``copy_prefix``): ``dst_slot``'s logical pages [0, n) become
        refcounted borrows of ``src_slot``'s frames — no device copy,
        no new frames, the donor's bytes serve both rows.  Only WHOLE
        donor pages share (a partially-matched tail page would be
        written by the borrower's resumed prefill, corrupting the
        donor); the caller aligns the match down to a page boundary.
        Returns the pages shared (0 when the source cannot serve).
        ``dst_slot`` must not hold a lease yet (admission calls this
        before the row's own lease)."""
        with self._lock:
            if self.num_frames is None:
                return 0
            src = self.leases.get(src_slot)
            if src is None or n_pages <= 0:
                return 0
            n = min(int(n_pages), len(src.frames))
            if n <= 0:
                return 0
            assert dst_slot not in self.leases, (
                "adopt_prefix: destination slot already holds a lease",
                dst_slot)
            dst = self.leases[dst_slot] = PageLease(
                dst_slot, n, n * self.page_len, "req", None)
            for f in src.frames[:n]:
                self._frame_refs[f] = self._frame_refs.get(f, 0) + 1
                dst.frames.append(f)
            dst.last_use = time.monotonic()
            self.leased_pages = len(self._frame_refs)
            self._set_free_gauges()
        self._c_shared.inc(n)
        return n

    def frame_table(self, rows: int, max_pages: int,
                    fill: Optional[int] = None) -> "Any":
        """Pack every slot's lease into an int32 ``[rows, max_pages]``
        page table (the device feed — tables are DATA, not shapes).
        Slots without a lease, and pages past a lease's count, hold
        ``fill`` — default ``num_frames``, the OUT-OF-RANGE sentinel:
        reads there clip to a real frame but are masked by the
        attend's depth guard, while writes are dropped by the scatter
        guards (a row that outruns its lease corrupts nobody)."""
        import numpy as np

        if fill is None:
            fill = self.num_frames or 0
        with self._lock:
            table = np.full((rows, max_pages), int(fill), np.int32)
            for slot, lease in self.leases.items():
                if 0 <= slot < rows and lease.frames:
                    n = min(len(lease.frames), max_pages)
                    table[slot, :n] = lease.frames[:n]
            return table

    def acquire(self, slot: int):
        with self._lock:
            if slot in self.leases:
                self.leases[slot].refs += 1

    def release_ref(self, slot: int):
        with self._lock:
            if slot in self.leases and self.leases[slot].refs > 0:
                self.leases[slot].refs -= 1

    # ------------------------------------------------------------- spill
    def store_spill(self, guid: int, models: Dict[int, Dict[str, Any]],
                    tokens: int, nbytes: int) -> None:
        """Keep a preempted request's fetched KV in host RAM.  Over the
        host budget, the LRU spill is dropped (its request silently
        degrades to recompute — counted in ``spill_drops``)."""
        with self._lock:
            self.spilled[guid] = {"models": models, "tokens": int(tokens),
                                  "bytes": int(nbytes)}
            self.spilled_bytes += int(nbytes)
            self.spill_bytes_total += int(nbytes)
            while (self.host_budget_bytes is not None
                   and self.spilled_bytes > self.host_budget_bytes
                   and len(self.spilled) > 1):
                old_guid = next(iter(self.spilled))
                if old_guid == guid:
                    break
                dropped = self.spilled.pop(old_guid)
                self.spilled_bytes -= dropped["bytes"]
                self.spill_drops += 1
        self._c_spill.inc(nbytes)

    def peek_spill(self, guid: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self.spilled.get(guid)

    def take_spill(self, guid: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            sp = self.spilled.pop(guid, None)
            if sp is not None:
                self.spilled_bytes -= sp["bytes"]
            return sp

    def drop_spill(self, guid: int) -> None:
        self.take_spill(guid)

    def count_spill(self, nbytes: int) -> None:
        """Count spill bytes that bypass the per-guid store (prefix-
        pool page spills keep their payload on the PrefixEntry)."""
        with self._lock:
            self.spill_bytes_total += int(nbytes)
        self._c_spill.inc(nbytes)

    def count_restore(self, nbytes: int) -> None:
        with self._lock:
            self.restore_bytes_total += int(nbytes)
        self._c_restore.inc(nbytes)

    def count_preemption(self, reason: str) -> None:
        with self._lock:
            self.preemptions[reason] = self.preemptions.get(reason, 0) + 1
        self._c_preempt.inc(reason=reason)

    # ---------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable state (the watchdog-bundle / ffstat feed):
        budget, per-slot leases, spilled GUIDs and the odometers."""
        with self._lock:
            return {
                "slice": self.slice_label,
                "page_len": self.page_len,
                "total_pages": self.total_pages,
                "leased_pages": self.leased_pages,
                "free_pages": max(0,
                                  self.total_pages - self.leased_pages),
                "overcommitted_pages": max(
                    0, self.leased_pages - self.total_pages),
                "bytes_per_token": self.bytes_per_token,
                "budget_bytes": (self.total_pages * self.page_len
                                 * self.bytes_per_token),
                "num_frames": self.num_frames,
                "free_frames": (len(self._free_frames)
                                if self.num_frames is not None else None),
                "leases": [
                    {"slot": l.slot, "pages": l.pages,
                     "length": l.length, "owner": l.owner,
                     "guid": l.guid, "refs": l.refs,
                     "frames": list(l.frames)}
                    for l in self.leases.values()],
                "spilled_guids": {g: {"tokens": s["tokens"],
                                      "bytes": s["bytes"]}
                                  for g, s in self.spilled.items()},
                "spilled_bytes": self.spilled_bytes,
                "spill_bytes_total": self.spill_bytes_total,
                "restore_bytes_total": self.restore_bytes_total,
                "spill_drops": self.spill_drops,
                "preemptions": dict(self.preemptions),
            }

    def config(self) -> Dict[str, Any]:
        """The bench-record ``kv_pager`` stamp (page size, budget,
        spill policy) — stable fields only."""
        return {
            "enabled": True,
            "page_len": self.page_len,
            "total_pages": self.total_pages,
            "num_frames": self.num_frames,
            "budget_bytes": (self.total_pages * self.page_len
                             * self.bytes_per_token),
            "spill_policy": self.policy.mode,
            "host_budget_bytes": self.host_budget_bytes,
        }


def pager_for_budget(budget_bytes: int, bytes_per_token: int,
                     page_len: int = DEFAULT_PAGE_LEN,
                     **kwargs) -> KVPager:
    """A pager whose page budget covers ``budget_bytes`` of committed
    KV at ``bytes_per_token`` (KVCacheStats.bytes_per_token of the
    served record) — the bench A/B's fixed-HBM-budget constructor."""
    page_bytes = max(1, page_len * int(bytes_per_token))
    return KVPager(max(1, int(budget_bytes) // page_bytes),
                   page_len=page_len, bytes_per_token=bytes_per_token,
                   **kwargs)


def pager_for_record(im, model_id: int, mode: str = "auto",
                     scheduler: Optional[PressureScheduler] = None,
                     host_budget_bytes: Optional[int] = None,
                     total_pages: Optional[int] = None,
                     slice_label: Optional[str] = None,
                     migrate_mode: str = "auto") -> KVPager:
    """The PHYSICAL pager matching a paged record: owns the record's
    ``num_frames`` concrete frame ids (budget == the allocated pool
    unless ``total_pages`` caps it lower), with the byte accounting
    and recovery policy parameterized from the compiled record — the
    ONE record->pager wiring, shared by serve.LLM.compile and the
    bench's physical arm so their knobs cannot diverge."""
    record = im.models[model_id]
    assert record.get("paged"), (
        "pager_for_record: record is dense — use pager_for_budget")
    return KVPager(
        total_pages or record["num_frames"],
        page_len=record["page_len"],
        num_frames=record["num_frames"],
        bytes_per_token=im.kv_cache_stats(model_id).bytes_per_token,
        policy=RecoveryPolicy.for_record(im, model_id, mode=mode,
                                         migrate_mode=migrate_mode),
        scheduler=scheduler, host_budget_bytes=host_budget_bytes,
        slice_label=slice_label)


def _selftest() -> int:
    """Pure-host allocator smoke (the run_tier1.sh pager gate): lease /
    release / refcount accounting, alignment validation, spill-store
    budgeting and policy pricing — no model, no device."""
    import numpy as np

    ok = True

    def check(cond, msg):
        nonlocal ok
        if not cond:
            ok = False
            print(f"kv_pager selftest FAILED: {msg}")

    try:
        # fflint: disable=pallas-tiling  the misalignment IS the test
        KVPager(4, page_len=48)
        check(False, "page_len=48 accepted")
    except ValueError:
        pass
    p = KVPager(8, page_len=64, bytes_per_token=128)
    check(p.pages_for(1) == 1 and p.pages_for(64) == 1
          and p.pages_for(65) == 2, "pages_for math")
    check(p.lease(0, 100) and p.free_pages == 6, "lease grow")
    check(p.lease(0, 30) and p.free_pages == 7, "lease shrink")
    check(not p.lease(1, 8 * 64) and p.free_pages == 7,
          "over-budget lease must fail atomically")
    check(p.lease(1, 8 * 64, force=True) and p.free_pages == 0
          and p.overcommitted_pages == 1, "forced overcommit books")
    check(p.release(1) == 8 and p.free_pages == 7, "release")
    check(p.shortfall(None, 64 * 7) == 0
          and p.shortfall(None, 64 * 8) == 1, "shortfall")
    payload = {0: {"layers": {"l0": {"k": np.zeros((1, 2, 64, 4))}},
                   "len": 64}}
    p.store_spill(7, payload, tokens=90, nbytes=4096)
    check(p.peek_spill(7) is not None and p.spilled_bytes == 4096,
          "spill store")
    check(p.take_spill(7)["tokens"] == 90 and p.spilled_bytes == 0,
          "spill take")
    pol = RecoveryPolicy(flops_per_token=2e9, weight_bytes=1e9,
                         kv_bytes_per_token=1e5, prefill_chunk=256)
    check(pol.choose(4096, 64) == "restore",
          "tiny spill vs long recompute must restore")
    check(pol.choose(16, 10 ** 12) == "recompute",
          "huge spill vs short recompute must recompute")
    check(RecoveryPolicy(mode="recompute").choose(4096, 64)
          == "recompute", "pinned mode wins")
    # the migrate arm (disaggregated prefill->decode): the device link
    # is faster than the host link, so a payload that would lose as a
    # host restore can still win as a device-to-device migration
    check(pol.choose_migrate(4096, 64) == "migrate",
          "tiny payload vs long recompute must migrate")
    check(pol.choose_migrate(16, 10 ** 13) == "recompute",
          "huge payload vs short recompute must recompute")
    check(pol.migrate_s(10 ** 6) < pol.restore_s(10 ** 6),
          "device link must price below the host link by default")
    check(RecoveryPolicy(migrate_mode="recompute")
          .choose_migrate(4096, 64) == "recompute",
          "pinned migrate_mode wins")
    snap = p.snapshot()
    check(snap["total_pages"] == 8 and snap["leases"][0]["slot"] == 0,
          "snapshot shape")
    # physical frame mode: concrete ids, refcounted sharing, hard cap
    f = KVPager(4, page_len=64, num_frames=6,
                frame_order=[5, 3, 1, 0, 2, 4])
    check(f.lease(0, 130) and f.frames_of(0) == [5, 3, 1],
          "frame alloc follows the seeded order")
    check(f.leased_pages == 3 and f.free_pages == 1, "frame accounting")
    check(f.adopt_prefix(2, 0, 2) == 2
          and f.frames_of(2) == [5, 3]
          and f.leased_pages == 3, "adopt shares without new frames")
    check(f.lease(2, 3 * 64) and f.frames_of(2)[:2] == [5, 3]
          and len(f.frames_of(2)) == 3, "borrower grows with own frames")
    check(f.release(0) == 3 and f.leased_pages == 3,
          "shared frames survive the donor release")
    check(f.release(2) == 3 and f.leased_pages == 0
          and f.free_pages == 4, "last ref frees")
    check(f.lease(1, 6 * 64, force=True) and not f.lease(3, 64,
                                                         force=True),
          "force stops at the physical frame pool")
    tab = f.frame_table(4, 8)
    check(tab.shape == (4, 8) and list(tab[1][:6]) == f.frames_of(1)
          and tab[0, 0] == f.num_frames, "frame_table packs leases "
          "(unleased slots hold the out-of-range sentinel)")
    try:
        KVPager(8, page_len=64, num_frames=4)
        check(False, "num_frames < total_pages accepted")
    except ValueError:
        pass
    if ok:
        print("kv_pager selftest OK")
    return 0 if ok else 1


if __name__ == "__main__":  # pragma: no cover - CLI smoke entry
    import sys

    sys.exit(_selftest())
