"""Serving stack: continuous batching + speculative decoding on TPU.

TPU-native re-design of the reference's inference subsystem
(src/runtime/request_manager.cc, inference_manager.cc, batch_config.cc —
SURVEY.md §2.1 layers 6-7).
"""

from .batch_config import (BatchConfig, BeamInferenceResult,
                           BeamSearchBatchConfig, InferenceResult,
                           TreeVerifyBatchConfig)
from .inference_manager import InferenceManager
from .kv_pager import (KVPager, PressureScheduler, RecoveryPolicy,
                       pager_for_budget, pager_snapshots)
from .prefix_cache import PrefixCache, PrefixEntry
from .request_manager import (GenerationConfig, GenerationResult, ProfileInfo,
                              Request, RequestManager, get_request_manager,
                              reset_request_manager)
from .tokenizer import (ByteTokenizer, GPT2BPETokenizer, HFTokenizersBackend,
                        TransformersBackend, load_tokenizer)
