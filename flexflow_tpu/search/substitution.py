"""Substitution-based strategy search (the Unity analogue).

TPU-native re-design of src/runtime/substitution.cc: the reference rewrites
the PCG with TASO-style GraphXfers (wrapping ops in Partition/Combine or
Replicate/Combine pairs per degree, substitution.cc:1368-1382) and drives a
best-first backtracking search with budget + alpha pruning
(base_optimize, substitution.cc:2245-2327) inside a DP over sequence splits
(generic_sequence_optimize, substitution.cc:2588).

Here a "xfer" changes one node's :class:`ShardAssignment` — because under
GSPMD the Partition/Combine/Replicate ops are *implied* by the sharding
annotations (the mechanical insertion the reference does explicitly is done
by the XLA partitioner), the search space collapses to per-node degree
choices while remaining exactly as expressive for dp x tp hybrid
strategies.  The explicit parallel-op IR (parallel/parallel_ops.py) is the
lowering target when a strategy is applied manually via shard_map.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Dict, List, Optional, Tuple

from .cost_model import MachineModel
from .pcg import (EP_CAPABLE, PCG, SP_CAPABLE, ShardAssignment,
                  TP_CAPABLE, data_parallel_strategy)


def _factor_pairs(n: int) -> List[Tuple[int, int]]:
    """All (dp, tp) with dp*tp == n."""
    out = []
    for dp in range(1, n + 1):
        if n % dp == 0:
            out.append((dp, n // dp))
    return out


def _batch_extent(layer) -> Optional[int]:
    """Leading-dim extent of the layer's first input (bounds dp: you
    cannot batch-shard past the batch)."""
    for t in layer.inputs:
        if t.spec.shape:
            return int(t.spec.shape[0])
    return None


def node_choices(layer, num_devices: int) -> List[ShardAssignment]:
    """Legal assignments for one node (reference create_xfers,
    substitution.cc:1675: partition/replicate wrappers per degree).

    This space is already MAXIMAL over (dp, tp) degree combinations for
    every op with a tp lowering, which is why a loaded substitution-rule
    collection (--substitution-json analogue) does not alter it: the
    reference appends JSON xfers to an always-generated base set
    (substitution.cc:1787-1800), and in the sharding-collapsed search the
    base set subsumes any degree a rule could license, while the rules'
    algebraic parallel-op identities are rewrites GSPMD performs
    mechanically (see search.graph_optimize / substitution_loader).

    Beyond the reference's space: attention nodes also offer sp (ring
    sequence parallelism) degrees — dp is capped by the batch extent (a
    batch of 1 long sequence cannot data-shard; the reference has no
    dimension to offer there, SURVEY §5)."""
    batch = _batch_extent(layer)

    def dp_ok(dp: int) -> bool:
        return batch is None or dp <= batch and batch % dp == 0

    choices = [ShardAssignment(dp=d)
               for d in _divisors(num_devices) if dp_ok(d)]
    if not choices:
        choices = [ShardAssignment()]
    if layer.op_type in TP_CAPABLE and layer.param_specs:
        for total in _divisors(num_devices):
            for dp, tp in _factor_pairs(total):
                if tp > 1 and dp_ok(dp):
                    choices.append(ShardAssignment(dp=dp, tp=tp))
    if layer.op_type in SP_CAPABLE:
        for total in _divisors(num_devices):
            for rest, sp in _factor_pairs(total):
                if sp <= 1:
                    continue
                for dp, tp in _factor_pairs(rest):
                    if dp_ok(dp) and (tp == 1 or (
                            layer.op_type in TP_CAPABLE
                            and layer.param_specs)):
                        choices.append(
                            ShardAssignment(dp=dp, tp=tp, sp=sp))
    if layer.op_type in EP_CAPABLE and layer.param_specs:
        # expert-parallel degrees for MoE nodes: ep must divide the
        # expert count (whole experts per shard); composes with dp
        n_exp = layer.attrs.get("num_experts") or layer.attrs.get("n")
        for total in _divisors(num_devices):
            for dp, ep in _factor_pairs(total):
                if (ep > 1 and dp_ok(dp)
                        and (n_exp is None or
                             (ep <= n_exp and n_exp % ep == 0))):
                    choices.append(ShardAssignment(dp=dp, ep=ep))
    return choices


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def feasible_dp_strategy(pcg: PCG, num_devices: int
                         ) -> Dict[str, ShardAssignment]:
    """Data-parallel start point clamped to each node's batch extent —
    dp=num_devices on a batch-1 node is not a real strategy, and an
    infeasible start would anchor the search on a cost the hardware
    cannot realize."""
    out = {}
    for l in pcg.nodes:
        batch = _batch_extent(l)
        dp = num_devices
        if batch is not None:
            dp = max(d for d in _divisors(num_devices)
                     if d <= batch and batch % d == 0)
        out[l.name] = ShardAssignment(dp=dp)
    return out


def _lambda_cost(metrics, mem_factor: float) -> float:
    """Run-time/memory tradeoff objective (reference MemoryOptimConfig's
    run_time_cost_factor, memory_optimization.h:25-60): factor 1.0 = pure
    run time, 0.0 = pure memory."""
    return (mem_factor * metrics.total_time
            + (1.0 - mem_factor) * metrics.memory * 1e-12)


def base_optimize(pcg: PCG, machine: MachineModel, num_devices: int,
                  budget: int = 2000, alpha: float = 1.05,
                  mem_factor: float = 1.0,
                  start: Optional[Dict[str, ShardAssignment]] = None,
                  est=None
                  ) -> Tuple[Dict[str, ShardAssignment], float]:
    """Best-first search over single-node assignment rewrites
    (reference base_optimize, substitution.cc:2245-2327; memory-aware
    variant :2337 via ``mem_factor``).

    Starts from pure data parallelism (the reference starts from the user
    graph, which its manual path also maps to DP) and explores changing one
    node's assignment at a time; candidates costing more than
    ``alpha * best`` are pruned, at most ``budget`` states are expanded.
    """
    names = [l.name for l in pcg.nodes]
    choices = {l.name: node_choices(l, num_devices) for l in pcg.nodes}
    start = start or feasible_dp_strategy(pcg, num_devices)

    def key(strategy):
        return tuple(strategy[n] for n in names)

    def cost(strategy):
        return _lambda_cost(pcg.strategy_cost(strategy, machine, est=est),
                            mem_factor)

    best, best_cost = dict(start), cost(start)
    seen = {key(start)}
    counter = itertools.count()          # FIFO tiebreak for equal costs
    frontier = [(best_cost, next(counter), dict(start))]
    expanded = 0
    while frontier and expanded < budget:
        c, _, strat = heapq.heappop(frontier)
        if c > alpha * best_cost:        # alpha pruning
            continue
        expanded += 1
        for n in names:
            cur = strat[n]
            for ch in choices[n]:
                if ch == cur:
                    continue
                cand = dict(strat)
                cand[n] = ch
                k = key(cand)
                if k in seen:
                    continue
                seen.add(k)
                cc = cost(cand)
                if cc < best_cost:
                    best, best_cost = cand, cc
                if cc <= alpha * best_cost:
                    heapq.heappush(frontier, (cc, next(counter), cand))
    return best, best_cost


def generic_sequence_optimize(pcg: PCG, machine: MachineModel,
                              num_devices: int, budget: int = 2000,
                              alpha: float = 1.05, mem_factor: float = 1.0,
                              est=None
                              ) -> Tuple[Dict[str, ShardAssignment], float]:
    """DP over sequence splits at bottleneck nodes (reference
    generic_sequence_optimize, substitution.cc:2588): optimize each
    segment independently with base_optimize, then stitch — sound because
    resharding cost at a single-tensor cut point is already charged by the
    edge term."""
    cuts = pcg.bottleneck_nodes()
    if not cuts or len(pcg.nodes) <= 8:
        return base_optimize(pcg, machine, num_devices, budget, alpha,
                             mem_factor, est=est)
    # split node list into segments at cut points
    order = pcg.topo_order()
    cut_set = set(cuts)
    segments: List[List[str]] = [[]]
    for n in order:
        segments[-1].append(n)
        if n in cut_set:
            segments.append([])
    if not segments[-1]:
        segments.pop()
    per_seg_budget = max(50, budget // max(1, len(segments)))
    strategy: Dict[str, ShardAssignment] = {}
    for seg in segments:
        # earlier segments are frozen: the boundary edge into this segment
        # charges resharding against their fixed assignments, so the DP
        # split stays sound (cross-cut cost is seen during the search, not
        # only at the final stitch)
        sub = _SubPCG(pcg, seg, frozen=strategy)
        s, _ = base_optimize(sub, machine, num_devices, per_seg_budget,
                             alpha, mem_factor, est=est)
        strategy.update({n: s[n] for n in seg})
    full = pcg.strategy_cost(strategy, machine, est=est)
    return strategy, _lambda_cost(full, mem_factor)


class _SubPCG(PCG):
    """Segment view sharing the parent's nodes (reference
    Graph::split_at_node, graph.cc:972).  ``frozen`` carries assignments
    already fixed for earlier segments; edges from frozen nodes into this
    segment are kept so their resharding cost participates."""

    def __init__(self, parent: PCG, names: List[str],
                 frozen: Optional[Dict[str, ShardAssignment]] = None):
        keep = set(names)
        self.frozen = dict(frozen or {})
        self.model = parent.model
        self.nodes = [parent.by_name[n] for n in names]
        self.by_name = {n: parent.by_name[n] for n in names}
        self.edges = [e for e in parent.edges
                      if e.dst in keep
                      and (e.src in keep or e.src in self.frozen)]
        self.in_edges = {n: [e for e in parent.in_edges[n]
                             if e.src in keep or e.src in self.frozen]
                         for n in names}
        self.out_edges = {n: [e for e in parent.out_edges[n]
                              if e.dst in keep] for n in names}

    def strategy_cost(self, strategy, machine, est=None):
        return super().strategy_cost({**self.frozen, **strategy}, machine,
                                     est=est)


def mcmc_optimize(pcg: PCG, machine: MachineModel, num_devices: int,
                  iterations: int = 2000, temperature: float = 1e-4,
                  seed: int = 0, mem_factor: float = 1.0, est=None
                  ) -> Tuple[Dict[str, ShardAssignment], float]:
    """MCMC fallback search (reference FFModel::mcmc_optimize,
    model.cc:3791): propose a random single-node assignment flip, accept
    with Metropolis probability."""
    import math
    import random

    rng = random.Random(seed)
    names = [l.name for l in pcg.nodes]
    choices = {l.name: node_choices(l, num_devices) for l in pcg.nodes}

    def cost(strategy):
        return _lambda_cost(pcg.strategy_cost(strategy, machine, est=est),
                            mem_factor)

    cur = feasible_dp_strategy(pcg, num_devices)
    cur_cost = cost(cur)
    best, best_cost = dict(cur), cur_cost
    for _ in range(iterations):
        n = rng.choice(names)
        ch = rng.choice(choices[n])
        if ch == cur[n]:
            continue
        cand = dict(cur)
        cand[n] = ch
        cc = cost(cand)
        if cc < cur_cost or rng.random() < math.exp(
                (cur_cost - cc) / max(temperature, 1e-30)):
            cur, cur_cost = cand, cc
            if cc < best_cost:
                best, best_cost = dict(cand), cc
    return best, best_cost
