"""Cost model for the auto-parallelization search.

TPU-native re-design of the reference's simulator stack:
- ``CostMetrics`` mirrors simulator.h:55-89;
- :class:`SimpleMachineModel` / :class:`EnhancedMachineModel` mirror
  src/runtime/machine_model.cc (NVLink/NIC bandwidths become ICI/DCN);
- :func:`estimate_op_cost` plays ``Simulator::measure_operator_cost``
  (simulator.cc:519) in analytic mode: a roofline over MXU flops and HBM
  bytes instead of running CUDA kernels — XLA fusion makes isolated kernel
  timing misleading on TPU (SURVEY.md §7 hard part 4), so the analytic
  roofline is the default and :class:`MeasuredCostModel` refines it with
  real on-chip timings of jitted blocks, cached by (op-params, sharding)
  exactly like simulator.cc:523-537.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from ..fftype import OpType


@dataclasses.dataclass
class CostMetrics:
    """Per-(op, parallelization) cost record (reference simulator.h:55-89)."""

    forward_time: float = 0.0     # seconds
    backward_time: float = 0.0
    sync_time: float = 0.0        # collective time (gradient or activation)
    memory: int = 0               # bytes resident per device (weights+acts)

    @property
    def total_time(self) -> float:
        return self.forward_time + self.backward_time + self.sync_time

    def __add__(self, other: "CostMetrics") -> "CostMetrics":
        return CostMetrics(self.forward_time + other.forward_time,
                           self.backward_time + other.backward_time,
                           self.sync_time + other.sync_time,
                           self.memory + other.memory)


class MachineModel:
    """Hardware description (reference: simulator.h:213-380).

    Bandwidths in bytes/s, latency in seconds, flops in FLOP/s.
    """

    def __init__(self, num_devices: int, peak_flops: float,
                 hbm_bandwidth: float, ici_bandwidth: float,
                 ici_latency: float, dcn_bandwidth: float,
                 devices_per_host: int = 0, hbm_per_device: int = 0,
                 device_link_bandwidth: Optional[float] = None,
                 wire_bandwidth: Optional[float] = None):
        self.num_devices = num_devices
        self.peak_flops = peak_flops
        self.hbm_bandwidth = hbm_bandwidth
        self.ici_bandwidth = ici_bandwidth
        self.ici_latency = ici_latency
        self.dcn_bandwidth = dcn_bandwidth
        self.devices_per_host = devices_per_host or num_devices
        self.hbm_per_device = hbm_per_device
        # direct device-to-device payload link (whole-frame KV
        # migration between mesh slices, serving/disagg.py): a single
        # p2p hop, so it defaults to the per-direction ICI figure —
        # distinct from dcn_bandwidth, which prices the HOST link the
        # spill/restore path crosses.
        self.device_link_bandwidth = float(device_link_bandwidth
                                           or ici_bandwidth)
        # cross-replica wire link (router-directed prefix-frame
        # migration over /v1/kv/export+import): a KV bundle crosses
        # process boundaries over the datacenter network, so it
        # defaults to the DCN figure — distinct from the device link,
        # which never leaves the host.
        self.wire_bandwidth = float(wire_bandwidth or dcn_bandwidth)

    # -------------------------------------------------------- collectives
    def _link_bw(self, group: int) -> float:
        # groups within one ICI domain ride ICI; larger ride DCN
        return (self.ici_bandwidth if group <= self.devices_per_host
                else self.dcn_bandwidth)

    def allreduce_time(self, bytes_: int, group: int) -> float:
        """Ring allreduce: 2(n-1)/n * bytes over the slowest link
        (reference estimate via machine_model.cc bandwidths)."""
        if group <= 1 or bytes_ == 0:
            return 0.0
        bw = self._link_bw(group)
        return 2.0 * (group - 1) / group * bytes_ / bw \
            + 2.0 * (group - 1) * self.ici_latency

    def allgather_time(self, bytes_out: int, group: int) -> float:
        if group <= 1 or bytes_out == 0:
            return 0.0
        bw = self._link_bw(group)
        return (group - 1) / group * bytes_out / bw \
            + (group - 1) * self.ici_latency

    def reducescatter_time(self, bytes_in: int, group: int) -> float:
        return self.allgather_time(bytes_in, group)

    def p2p_time(self, bytes_: int) -> float:
        if bytes_ == 0:
            return 0.0
        return bytes_ / self.ici_bandwidth + self.ici_latency

    def alltoall_time(self, bytes_: int, group: int) -> float:
        """All-to-all token exchange (MoE dispatch/combine over ep): each
        device ships (group-1)/group of its bytes across the group."""
        if group <= 1 or bytes_ == 0:
            return 0.0
        return ((group - 1) / group * bytes_ / self._link_bw(group)
                + (group - 1) * self.ici_latency)

    def migrate_time(self, bytes_: int) -> float:
        """One whole-payload device-to-device KV handoff (the
        disaggregated prefill->decode frame migration): a single p2p
        transfer over the device link — what RecoveryPolicy's
        ``migrate`` arm prices against recompute-on-the-decode-slice."""
        if bytes_ <= 0:
            return 0.0
        return bytes_ / self.device_link_bandwidth + self.ici_latency

    def wire_migrate_time(self, bytes_: int) -> float:
        """One cross-replica KV bundle over the datacenter wire (the
        router-directed ``/v1/kv/export`` -> ``/v1/kv/import`` path):
        the bytes cross the network once plus a device hop on each
        end, so one DCN crossing + two link latencies is the model —
        what the router's migrate-vs-recompute pricing uses."""
        if bytes_ <= 0:
            return 0.0
        return bytes_ / self.wire_bandwidth + 2.0 * self.ici_latency

    # ------------------------------------------------- calibrated profiles
    @classmethod
    def from_json(cls, source,
                  num_devices: Optional[int] = None) -> "MachineModel":
        """Build a machine model from a machine-profile JSON (a path or
        an already-parsed dict) — the artifact ``tools/ffprof.py
        --calibrate`` fits from devprof's sampled dispatch timings.
        Keys follow :meth:`EnhancedMachineModel.from_file`'s vocabulary
        (``hbm_gbps``, ``peak_tflops``, ``dcn_gbps``,
        ``device_link_gbps``, ...); absent keys keep the
        SimpleMachineModel v5e defaults, so a partial calibration (say,
        only hbm_gbps measured) still loads.  ``num_devices`` passed
        explicitly overrides the profile's own value (None defers to
        the profile)."""
        import json

        if isinstance(source, dict):
            kv = source
        else:
            with open(source) as f:
                kv = json.load(f)
        return cls(
            num_devices=int(num_devices or kv.get("num_devices", 1)),
            peak_flops=float(kv.get("peak_tflops", 197.0)) * 1e12,
            hbm_bandwidth=float(kv.get("hbm_gbps", 819.0)) * 1e9,
            ici_bandwidth=float(kv.get("ici_gbps", 45.0)) * 1e9,
            ici_latency=float(kv.get("ici_latency_us", 1.0)) * 1e-6,
            dcn_bandwidth=float(kv.get("dcn_gbps", 25.0)) * 1e9,
            devices_per_host=int(kv.get("devices_per_host", 0)),
            hbm_per_device=int(float(kv.get("hbm_gb", 16)) * 1024**3),
            device_link_bandwidth=(float(kv["device_link_gbps"]) * 1e9
                                   if "device_link_gbps" in kv else None),
            wire_bandwidth=(float(kv["wire_gbps"]) * 1e9
                            if "wire_gbps" in kv else None),
        )


class SimpleMachineModel(MachineModel):
    """One-knob model (reference SimpleMachineModel: intra-node + NIC bw).

    Defaults describe one TPU v5e chip: 197 TFLOP/s bf16 MXU, 819 GB/s HBM,
    ~45 GB/s/link ICI (3D torus per-direction), 16 GB HBM.
    """

    def __init__(self, num_devices: int, peak_flops: float = 197e12,
                 hbm_bandwidth: float = 819e9, ici_bandwidth: float = 45e9,
                 ici_latency: float = 1e-6, dcn_bandwidth: float = 25e9,
                 devices_per_host: int = 0,
                 hbm_per_device: int = 16 * 1024**3,
                 device_link_bandwidth: Optional[float] = None):
        super().__init__(num_devices, peak_flops, hbm_bandwidth,
                         ici_bandwidth, ici_latency, dcn_bandwidth,
                         devices_per_host, hbm_per_device,
                         device_link_bandwidth=device_link_bandwidth)


class EnhancedMachineModel(MachineModel):
    """File-configured model (reference EnhancedMachineModel parsed from
    machine_config_example:1-40).  Config lines: ``key = value`` with keys
    num_devices, devices_per_host, peak_tflops, hbm_gbps, ici_gbps,
    ici_latency_us, dcn_gbps, hbm_gb; '#' comments."""

    @classmethod
    def from_file(cls, path: str) -> "EnhancedMachineModel":
        kv: Dict[str, float] = {}
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                k, _, v = line.partition("=")
                kv[k.strip()] = float(v.strip())
        return cls(
            num_devices=int(kv.get("num_devices", 1)),
            peak_flops=kv.get("peak_tflops", 197.0) * 1e12,
            hbm_bandwidth=kv.get("hbm_gbps", 819.0) * 1e9,
            ici_bandwidth=kv.get("ici_gbps", 45.0) * 1e9,
            ici_latency=kv.get("ici_latency_us", 1.0) * 1e-6,
            dcn_bandwidth=kv.get("dcn_gbps", 25.0) * 1e9,
            devices_per_host=int(kv.get("devices_per_host", 0)),
            hbm_per_device=int(kv.get("hbm_gb", 16) * 1024**3),
            device_link_bandwidth=(kv["device_link_gbps"] * 1e9
                                   if "device_link_gbps" in kv else None),
        )


def default_machine(num_devices: Optional[int] = None) -> MachineModel:
    """The machine description serving pricing uses when none is
    passed explicitly: a calibrated machine-profile JSON from
    ``FF_MACHINE_PROFILE`` (written by ``tools/ffprof.py --calibrate``
    from devprof's sampled dispatch timings) when the env var is set
    and loadable, else the hand-set :class:`SimpleMachineModel` v5e
    defaults.  This is the feedback edge that makes the KV pager's
    RecoveryPolicy, the disaggregated migrate-vs-recompute decision,
    the hybrid rider budget and devprof's own drift gauges price the
    MEASURED machine instead of the datasheet.  ``num_devices`` left
    None defers to the profile's own (calibrated-box) value; pass it
    only to model a different topology."""
    import logging
    import os

    path = os.environ.get("FF_MACHINE_PROFILE")
    if path:
        try:
            return MachineModel.from_json(path, num_devices=num_devices)
        except Exception as e:
            logging.getLogger(__name__).warning(
                "FF_MACHINE_PROFILE=%s failed to load (%s); falling "
                "back to SimpleMachineModel defaults", path, e)
    return SimpleMachineModel(num_devices or 1)


# --------------------------------------------------------------- op math
def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def op_flops_bytes(layer, out_shapes) -> Tuple[int, int, int]:
    """(forward flops, activation bytes moved, weight bytes) for one layer
    at full (unsharded) size.  4 bytes/elt f32 accounting (the relative
    costs the search compares are dtype-independent)."""
    a = layer.attrs
    ins = [t.spec.shape for t in layer.inputs]
    outs = [tuple(s) for s in out_shapes]
    elt = 4
    in_bytes = sum(_prod(s) for s in ins) * elt
    out_bytes = sum(_prod(s) for s in outs) * elt
    t = layer.op_type
    weight_bytes = sum(_prod(p.shape) for p in layer.param_specs) * elt
    if t == OpType.LINEAR:
        batch = _prod(ins[0][:-1])
        flops = 2 * batch * ins[0][-1] * outs[0][-1]
    elif t == OpType.CONV2D:
        # NHWC out * (kh*kw*cin) MACs
        kh, kw = a.get("kernel_h", 1), a.get("kernel_w", 1)
        cin = ins[0][-1]
        flops = 2 * _prod(outs[0]) * kh * kw * cin
    elif t == OpType.BATCH_MATMUL:
        b = _prod(ins[0][:-2])
        flops = 2 * b * ins[0][-2] * ins[0][-1] * outs[0][-1]
    elif t in (OpType.MULTIHEAD_ATTENTION,
               OpType.INC_MULTIHEAD_SELF_ATTENTION,
               OpType.SPEC_INC_MULTIHEAD_SELF_ATTENTION,
               OpType.TREE_INC_MULTIHEAD_SELF_ATTENTION):
        embed = a.get("embed_dim", ins[0][-1])
        tokens = _prod(ins[0][:-1])
        # per-sequence quadratic term: seq is the second-to-last dim (not
        # tokens=batch*seq — that would overcount by a factor of batch)
        seq = ins[0][-2] if len(ins[0]) >= 2 else 1
        # qkv+o projections + 2 seq^2 matmuls (seq bounded by input len)
        flops = 8 * tokens * embed * embed + 4 * tokens * seq * embed
    elif t == OpType.EMBEDDING:
        flops = 0  # gather, bandwidth-bound
    elif t == OpType.EXPERTS:
        k = a.get("num_selected", a.get("k", 1))
        experts_dim = a.get("experts_internal_dim_size", outs[0][-1])
        tokens = _prod(ins[0][:-1])
        flops = 2 * tokens * k * ins[0][-1] * experts_dim
    else:
        # elementwise / norm / movement: ~O(bytes)
        flops = 2 * _prod(outs[0]) if outs else 0
    return flops, in_bytes + out_bytes, weight_bytes


def estimate_op_cost(layer, out_shapes, machine: MachineModel,
                     dp: int = 1, tp: int = 1, sp: int = 1, ep: int = 1,
                     batch_dim_size: Optional[int] = None) -> CostMetrics:
    """Roofline cost of one layer under (dp, tp, sp, ep) sharding.

    - dp shards the batch dim: per-device flops/bytes divide by dp; gradient
      sync adds an allreduce of the weights over dp (the reference's NCCL
      optimizer path, optimizer.h:59-76).
    - tp shards weights/heads: flops and weight memory divide by tp; one
      activation allreduce of the output over tp (the reference's inserted
      AllReduce, model.cc:3292).
    - sp shards the sequence dim (ring attention, ops/ring_attention.py):
      compute divides like dp (weights replicate) but attention pays
      (sp-1) ring hops of its K/V shards over ICI.
    - ep shards the expert dim (MoE, ops/moe_ops.py): expert weights AND
      compute divide by ep, and the tokens pay two all-to-alls (dispatch
      + combine) across the ep group — the searched form of the
      reference's sample/parameter/attribute-dim flags
      (config.h:148-150).
    """
    flops, act_bytes, w_bytes = op_flops_bytes(layer, out_shapes)
    shard = dp * tp * sp * ep
    # weights stream from HBM every step and shard over tp and (for MoE
    # experts) ep — replicated across dp/sp; at small batch (serving
    # decode) this term dominates.  Gather-style ops (embedding:
    # flops == 0) touch only the rows used, already counted in act_bytes.
    w_stream = w_bytes / (tp * ep) if flops else 0.0
    compute = max(flops / shard / machine.peak_flops,
                  (act_bytes / shard + w_stream) / machine.hbm_bandwidth)
    fwd = compute
    bwd = 2 * compute if w_bytes else compute  # dX and dW matmuls
    sync = 0.0
    if tp > 1 and w_bytes:
        out_act = sum(_prod(s) for s in out_shapes) * 4 // (dp * sp * ep)
        sync += machine.allreduce_time(out_act, tp)          # fwd activations
        sync += machine.allreduce_time(out_act, tp)          # bwd d(input)
    if dp > 1 and w_bytes:
        sync += machine.allreduce_time(w_bytes // (tp * ep), dp)  # grads
    if sp > 1:
        # ring attention: each device forwards its K/V shard sp-1 times
        # (ppermute); K+V together ~ input activation bytes
        kv_shard = act_bytes // shard
        sync += (sp - 1) * machine.p2p_time(kv_shard)
        if w_bytes:   # grads of replicated weights also sum over sp
            sync += machine.allreduce_time(w_bytes // tp, sp)
    if ep > 1:
        # MoE all-to-all: the routed token activations cross the ep group
        # twice per direction (dispatch + combine, fwd + bwd)
        tok_bytes = act_bytes // shard
        sync += 4 * machine.alltoall_time(tok_bytes, ep)
    mem = w_bytes // (tp * ep) + act_bytes // shard
    return CostMetrics(fwd, bwd, sync, mem)


def hybrid_rider_budget(machine: MachineModel, weight_bytes: int,
                        weight_elements: int, decode_rows: int,
                        kv_stream_bytes: int = 0,
                        slack: float = 1.0) -> int:
    """Rider-token knee for the stall-free hybrid step (ROADMAP "fuse
    chunked prefill into decode steps"; the serving twin of
    :func:`estimate_op_cost`'s compute/bandwidth max): the largest
    prefill chunk whose sub-pass stays BANDWIDTH-bound.

    A decode step at serving batch sizes is bandwidth-bound: its floor
    is streaming the weights (plus the KV it attends) from HBM, during
    which the MXU idles.  The fused hybrid step runs the rider chunk
    as its own full-model sub-pass, so a mixed step pays roughly one
    EXTRA weight stream (~+t_mem) over the pure-decode floor — rider
    tokens are not free, they are flat-priced: any chunk whose FLOPs
    fit inside that stream's MXU idle time costs the same +t_mem, so
    the budget is the knee where the sub-pass would flip
    compute-bound and start scaling with chunk size:

        t_mem   = (weight_bytes + kv_stream_bytes) / hbm_bw
        free    = t_mem * peak_flops - 2 * weight_elements * decode_rows
        budget  = slack * free / (2 * weight_elements)

    (2 flops per weight element per token — the same accounting the
    KV pager's RecoveryPolicy uses.)  Versus the separate-dispatch
    arm's chunk-wide COMPUTE-bound stall this bounds bystander TPOT at
    ~2x the decode floor during mixed phases instead of ~chunk/x;
    compacting rider rows into the decode pass (ROADMAP follow-up)
    is what would make riders genuinely free.  ``slack`` derates the
    headroom (<1 trades rider throughput for bystander TPOT margin;
    >1 accepts measured TPOT degradation for faster victim TTFT).
    Returns whole tokens, >= 0; the caller still clamps to chunk
    floors/alignment and the compiled cache slack
    (batch_config.budgeted_chunk)."""
    per_tok_flops = 2.0 * max(1, weight_elements)
    t_mem = (max(0, weight_bytes) + max(0, kv_stream_bytes)) \
        / machine.hbm_bandwidth
    free = t_mem * machine.peak_flops - per_tok_flops * max(0, decode_rows)
    return max(0, int(slack * free / per_tok_flops))


def resharding_cost(tensor_bytes: int, src: Tuple[int, ...],
                    dst: Tuple[int, ...], machine: MachineModel) -> float:
    """Cost of moving a tensor between (dp, tp[, sp[, ep]]) layouts
    (reference: Simulator::estimate_xfer_cost, simulator.cc:604 +
    repartition cost :562-600).  Identical layouts are free; otherwise
    approximate as an allgather out of the finer layout plus a
    repartition into the new one.  (dp=2,sp=1) vs (dp=1,sp=2) differ —
    batch- vs sequence-sharded — so layouts compare by the full tuple,
    not the partition product.
    """
    src = tuple(src) + (1,) * (4 - len(src))
    dst = tuple(dst) + (1,) * (4 - len(dst))
    if src == dst:
        return 0.0
    src_parts = src[0] * src[1] * src[2] * src[3]
    dst_parts = dst[0] * dst[1] * dst[2] * dst[3]
    t = 0.0
    if src_parts > 1:
        t += machine.allgather_time(tensor_bytes, src_parts)
    if dst_parts > 1:
        t += machine.p2p_time(tensor_bytes // dst_parts)
    return t


class MeasuredCostModel:
    """Refines the roofline with real on-chip timings.

    Times a jitted forward block per (op-params, shard degrees) — the
    TPU analogue of ``Op::inner_measure_operator_cost`` (operator.h:152-155)
    — with the same memoization as simulator.cc:523-537.
    """

    def __init__(self, machine: MachineModel, repeats: int = 3,
                 auto_measure: bool = False):
        self.machine = machine
        self.repeats = repeats
        self.cache: Dict[Tuple, float] = {}
        # auto_measure: build + time a jitted per-shard forward for ops
        # the runner supports (compute ops with plain forward()); serving
        # attention needs cache/batch plumbing and falls back to the
        # roofline
        self.auto_measure = auto_measure

    def _key(self, layer, out_shapes, dp, tp, sp=1, ep=1):
        return (layer.op_type.value,
                tuple(tuple(t.spec.shape) for t in layer.inputs),
                tuple(tuple(s) for s in out_shapes), dp, tp, sp, ep)

    def measure(self, layer, out_shapes, dp: int = 1, tp: int = 1,
                sp: int = 1, ep: int = 1,
                run: Optional[Callable[[], None]] = None) -> CostMetrics:
        est = estimate_op_cost(layer, out_shapes, self.machine, dp, tp,
                               sp, ep)
        key = self._key(layer, out_shapes, dp, tp, sp, ep)
        if key in self.cache:
            # None is the 'unmeasurable' sentinel (stored below when
            # make_op_runner declines) — fall back to the roofline instead
            # of treating it as a timing
            fwd = self.cache[key]
            if fwd is None:
                fwd = est.forward_time
        elif run is not None:
            fwd = self.cache[key] = self._time(run)
        elif self.auto_measure:
            # the runner shards only the batch dims (one chip cannot run
            # a tp/ep-sharded op in isolation), so time the
            # (dp, sp, tp=1, ep=1) shape and scale by the analytic ratio —
            # measuring the full shapes directly would make tp/ep look
            # like zero gain
            k1 = self._key(layer, out_shapes, dp, 1, sp, 1)
            if k1 not in self.cache:
                run1 = make_op_runner(layer, dp, sp)
                if run1 is None:
                    self.cache[k1] = None     # unmeasurable: roofline
                else:
                    self.cache[k1] = self._time(run1)
            base = self.cache[k1]
            if base is None:
                fwd = est.forward_time
            else:
                est1 = estimate_op_cost(layer, out_shapes, self.machine,
                                        dp, 1, sp, 1)
                ratio = (est.forward_time / est1.forward_time
                         if est1.forward_time > 0 else 1.0)
                fwd = self.cache[key] = base * ratio
        else:
            fwd = est.forward_time
        scale = fwd / est.forward_time if est.forward_time > 0 else 1.0
        return CostMetrics(fwd, est.backward_time * scale, est.sync_time,
                           est.memory)

    def _time(self, run: Callable[[], None]) -> float:
        run()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(self.repeats):
            run()
        return (time.perf_counter() - t0) / self.repeats

    def est(self, layer, out_shapes, machine, dp: int = 1, tp: int = 1,
            sp: int = 1, ep: int = 1) -> CostMetrics:
        """Drop-in estimator for PCG.strategy_cost(est=...): routes the
        search's per-node cost queries through the measurement cache —
        the reference's measured search mode (simulator.cc:519-560)."""
        return self.measure(layer, out_shapes, dp, tp, sp, ep)


def make_op_runner(layer, dp: int = 1,
                   sp: int = 1) -> Optional[Callable[[], None]]:
    """Build a timed per-shard forward for one layer (the reference's
    Op::inner_measure_operator_cost, operator.h:152-155): random inputs at
    the batch shard size (dp*sp divides the leading dim), zero-init
    params, one jitted call per invocation.  Returns None for ops whose
    forward needs serving plumbing (KV caches / batch configs) — the
    caller falls back to the roofline for those."""
    import jax
    import jax.numpy as jnp

    from ..fftype import OpType
    from ..ops.registry import OpContext, get_op

    if layer.op_type in (OpType.INC_MULTIHEAD_SELF_ATTENTION,
                         OpType.SPEC_INC_MULTIHEAD_SELF_ATTENTION,
                         OpType.TREE_INC_MULTIHEAD_SELF_ATTENTION,
                         OpType.INPUT, OpType.NOOP):
        return None
    op = get_op(layer.op_type)
    div = max(1, dp * sp)
    if any(t.spec.shape and t.spec.shape[0] % div
           for t in layer.inputs):
        return None   # shard doesn't divide the batch: roofline fallback
    try:
        key = jax.random.PRNGKey(0)
        ins = []
        for t in layer.inputs:
            shape = list(t.spec.shape)
            if shape:
                shape[0] //= div
            dt = t.spec.dtype.to_jnp()
            if jnp.issubdtype(dt, jnp.integer):
                ins.append(jnp.zeros(shape, dt))
            else:
                key, sub = jax.random.split(key)
                ins.append(jax.random.normal(sub, shape, dt))
        params = {p.name: jnp.zeros(p.shape, p.dtype.to_jnp())
                  for p in layer.param_specs}

        fn = jax.jit(lambda pr, xs: op.forward(
            pr, xs, layer.attrs, OpContext(training=False)))
        fn(params, ins)  # tracing succeeds -> runnable

        def run():
            jax.block_until_ready(fn(params, ins))

        return run
    except Exception:
        return None
