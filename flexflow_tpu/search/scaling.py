"""Analytic multi-chip scaling model for the BASELINE configs.

Single-chip hardware is all this container has, so the 1→16-chip
scaling-efficiency metric BASELINE.md asks for cannot be *measured*
here.  This module produces the honest substitute the r3 verdict asked
for (missing #7): a per-step collective-bytes + ICI-latency model,
computed from the same :class:`~flexflow_tpu.search.cost_model.MachineModel`
collective formulas the auto-parallelization search uses — the role the
reference's simulator plays for unmeasurable clusters
(/root/reference/src/runtime/simulator.cc:900-1010 estimates xfer +
queueing cost over a machine model instead of running the hardware).

Every formula input is emitted alongside the result so the numbers are
auditable: no hidden constants, no measured curve pretending to be one.

The three modeled workloads are BASELINE.md's measurement configs:
  2. ResNet-50 data-parallel training (gradient ring-allreduce per step)
  4. LLaMA-7B int8 incremental decoding under tp×pp
  5. LLaMA-7B + 160M SSM speculative decoding under tp×pp (per
     macro-iteration: D SSM steps + one tree-verify LLM step)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .cost_model import MachineModel, SimpleMachineModel

# tp×pp decomposition per chip count for the serving configs: tp first
# (intra-ICI-domain, highest-bandwidth axis), then pp — the layout the
# reference's CI matrix uses for spec_infer (TP×PP degrees,
# tests/inference/python_inference_tests.sh:1-55)
DEFAULT_MESHES: Dict[int, Tuple[int, int]] = {
    1: (1, 1), 2: (2, 1), 4: (4, 1), 8: (4, 2), 16: (8, 2),
}


def resnet50_dp_scaling(machine: Optional[MachineModel] = None,
                        grad_bytes: int = 25_557_032 * 4,
                        step_compute_s: float = 0.082,
                        chips=(1, 2, 4, 8, 16)) -> Dict:
    """Weak-scaling efficiency of data-parallel training (BASELINE
    config 2): per-device batch fixed, each step adds one ring
    all-reduce of the f32 gradients over the dp group.

    ``step_compute_s`` defaults to the single-chip bench's measured step
    time (BENCH resnet50 config: batch 32, 390.8 samples/s → 82 ms);
    pass the current bench value to keep the model honest.
    eff(n) = t_compute / (t_compute + t_allreduce(n)) — no
    compute/communication overlap assumed (conservative; XLA overlaps
    grad all-reduces with backprop in practice).
    """
    m = machine or SimpleMachineModel(max(chips))
    rows = []
    for n in chips:
        ar = m.allreduce_time(grad_bytes, n)
        rows.append({
            "chips": n,
            "allreduce_ms": round(ar * 1e3, 3),
            "efficiency": round(step_compute_s / (step_compute_s + ar), 3),
        })
    return {
        "workload": "resnet50_dp_training (BASELINE config 2)",
        "model": "weak scaling; eff = t_step / (t_step + ring_allreduce)",
        "inputs": {
            "grad_bytes": grad_bytes,
            "step_compute_s": step_compute_s,
            "ici_gbps": m.ici_bandwidth / 1e9,
            "ici_latency_us": m.ici_latency * 1e6,
            "allreduce": "2(n-1)/n * bytes / bw + 2(n-1) * lat",
        },
        "per_chip": rows,
    }


def llama_decode_scaling(machine: Optional[MachineModel] = None,
                         weight_bytes: int = 6_869_286_912,
                         layers: int = 32, hidden: int = 4096,
                         rows: int = 16, act_bytes_per_elt: int = 2,
                         step_overhead_s: float = 0.0,
                         meshes: Optional[Dict[int, Tuple[int, int]]] = None,
                         chips=(1, 2, 4, 8, 16)) -> Dict:
    """Strong-scaling model of weight-bound incremental decoding
    (BASELINE config 4: LLaMA-7B int8, tp×pp).

    Per decode step and chip:
      t_weights(n)   = weight_bytes / (tp*pp) / hbm_bw   (weights shard
                       over tp; pp holds layers/pp per stage)
      t_tp_coll      = 2 * (layers/pp) * allreduce(rows*hidden*elt, tp)
                       (the reference's inserted AllReduce after
                       attention and after the FFN, model.cc:3292)
      t_pp_handoff   = (pp-1) * p2p(rows*hidden*elt)  (per-token stage
                       handoff; decode pipelines steps back-to-back so
                       the handoff rides the step's critical path once)
    tokens/s/chip ∝ 1 / (n * t_step(n)); efficiency(n) =
    t_step(1) / (n * t_step(n)).
    ``step_overhead_s``: measured single-chip non-weight time (attention
    + floors), assumed to shard with tp*pp like the weights.
    """
    m = machine or SimpleMachineModel(max(chips))
    meshes = meshes or DEFAULT_MESHES
    act = rows * hidden * act_bytes_per_elt
    t1 = weight_bytes / m.hbm_bandwidth + step_overhead_s
    out = []
    for n in chips:
        tp, pp = meshes[n]
        assert tp * pp == n, (n, tp, pp)
        t_w = (weight_bytes / m.hbm_bandwidth + step_overhead_s) / (tp * pp)
        t_tp = 2 * (layers // pp) * m.allreduce_time(act, tp)
        t_pp = (pp - 1) * m.p2p_time(act)
        t_step = t_w + t_tp + t_pp
        out.append({
            "chips": n, "tp": tp, "pp": pp,
            "step_ms": round(t_step * 1e3, 3),
            "collective_ms": round((t_tp + t_pp) * 1e3, 3),
            "collective_bytes": int(2 * (layers // pp) * act * 2 * (tp - 1)
                                    / max(tp, 1) + (pp - 1) * act),
            "efficiency": round(t1 / (n * t_step), 3),
            "tokens_s_batch": round(rows / t_step, 1),
        })
    return {
        "workload": "llama7b_int8_incr_decoding tp*pp (BASELINE config 4)",
        "model": ("strong scaling; t = weights/(tp*pp)/hbm + "
                  "2*layers/pp*allreduce(act, tp) + (pp-1)*p2p(act)"),
        "inputs": {
            "weight_bytes": weight_bytes, "layers": layers,
            "hidden": hidden, "batch_rows": rows,
            "act_bytes": act, "hbm_gbps": m.hbm_bandwidth / 1e9,
            "ici_gbps": m.ici_bandwidth / 1e9,
            "ici_latency_us": m.ici_latency * 1e6,
            "step_overhead_s": step_overhead_s,
        },
        "per_chip": out,
    }


def spec_infer_scaling(machine: Optional[MachineModel] = None,
                       llm_weight_bytes: int = 6_869_286_912,
                       ssm_weight_bytes: int = 2 * 160_000_000,
                       layers: int = 32, hidden: int = 4096,
                       rows: int = 16, beam_depth: int = 7,
                       tree_tokens: int = 8,
                       commit_per_iter: float = 8.0,
                       meshes: Optional[Dict[int, Tuple[int, int]]] = None,
                       chips=(1, 2, 4, 8, 16)) -> Dict:
    """Speculative decoding macro-iteration under tp×pp (BASELINE
    config 5: 7B LLM + 160M SSM).

    Per macro-iteration: ``beam_depth`` SSM expansion steps (SSM small
    enough that only the LLM shards; SSM replicates per pp stage 0) +
    one LLM tree-verify step streaming the full LLM weights with
    ``tree_tokens`` queries (weight-bound, same bytes as decode) + the
    same tp/pp collectives as decode.  tokens/s uses the measured-or-
    assumed committed tokens per iteration (acceptance-dependent — see
    the spec acceptance-curve bench for the chip-measured relation).
    """
    m = machine or SimpleMachineModel(max(chips))
    meshes = meshes or DEFAULT_MESHES
    act = rows * hidden * 2
    tree_act = rows * tree_tokens * hidden * 2

    def iter_time(tp: int, pp: int) -> float:
        t_ssm = beam_depth * (ssm_weight_bytes / m.hbm_bandwidth)
        t_llm = llm_weight_bytes / (tp * pp) / m.hbm_bandwidth
        t_tp = 2 * (layers // pp) * m.allreduce_time(tree_act, tp)
        t_pp = (pp - 1) * m.p2p_time(tree_act)
        return t_ssm + t_llm + t_tp + t_pp

    t1 = iter_time(1, 1)
    out = []
    for n in chips:
        tp, pp = meshes[n]
        t = iter_time(tp, pp)
        out.append({
            "chips": n, "tp": tp, "pp": pp,
            "iter_ms": round(t * 1e3, 3),
            "efficiency": round(t1 / (n * t), 3),
            "tokens_s_batch": round(rows * commit_per_iter / t, 1),
        })
    return {
        "workload": ("llama7b+160M spec_infer tp*pp (BASELINE config 5, "
                     "the north star)"),
        "model": ("t_iter = D*ssm_w/hbm + llm_w/(tp*pp)/hbm + "
                  "2*layers/pp*allreduce(tree_act, tp) + "
                  "(pp-1)*p2p(tree_act); throughput uses commit_per_iter "
                  "committed tokens (acceptance-dependent)"),
        "inputs": {
            "llm_weight_bytes": llm_weight_bytes,
            "ssm_weight_bytes": ssm_weight_bytes,
            "beam_depth": beam_depth, "tree_tokens": tree_tokens,
            "commit_per_iter": commit_per_iter,
            "hbm_gbps": m.hbm_bandwidth / 1e9,
            "ici_gbps": m.ici_bandwidth / 1e9,
            "ici_latency_us": m.ici_latency * 1e6,
        },
        "per_chip": out,
    }


def scaling_model(resnet_step_s: Optional[float] = None,
                  llama_step_overhead_s: float = 0.0,
                  spec_commit_per_iter: float = 8.0) -> List[Dict]:
    """The three BASELINE-config scaling statements, formula inputs
    included (bench.py embeds this as the ``scaling_model`` block)."""
    kw = {}
    if resnet_step_s is not None:
        kw["step_compute_s"] = resnet_step_s
    return [
        resnet50_dp_scaling(**kw),
        llama_decode_scaling(step_overhead_s=llama_step_overhead_s),
        spec_infer_scaling(commit_per_iter=spec_commit_per_iter),
    ]
