"""Loader for TASO-style substitution rule collections (JSON).

TPU-native equivalent of the reference's substitution loader
(src/runtime/substitution_loader.cc; schema exemplified by
substitutions/test_subst.json, shipped collection
substitutions/graph_subst_3_v2.json with 640 generated rules; unit test
tests/unit/test_substitution_loader.cc).

Schema (reference substitution_loader.h):
    RuleCollection { "_t": "RuleCollection", "rule": [Rule] }
    Rule   { "_t": "Rule", "name", "srcOp": [Operator], "dstOp": [Operator],
             "mappedOutput": [MapOutput] }
    Operator { "_t": "Operator", "type": "OP_*", "para": [Parameter],
               "input": [Tensor] }
    Tensor { "_t": "Tensor", "opId", "tsId" }   # opId < 0: external input
    Parameter { "_t": "Parameter", "key": "PM_*", "value": int }

How the rules act here: the reference applies a matched rule by literally
rewriting the PCG — inserting Repartition/Combine/Replicate/Reduction ops
(GraphXfer::run, substitution.cc:791) — and a provided --substitution-json
REPLACES the manually coded xfers (the else-branch at
substitution.cc:1803 skips them when a JSON file is given).  Under GSPMD
those parallel ops are implied
by sharding annotations and the sharding-collapsed search space is
already maximal over (dp, tp) degrees, so a loaded collection cannot add
choices the base lacks, and its algebraic parallel-op identities are
rewrites the XLA partitioner performs mechanically.  graph_optimize
therefore loads + validates the collection (schema errors surface like
the reference loader's) and WARNS about licenses it cannot lower —
strategies are unchanged by design, an invariant the tests pin.
:func:`collection_choice_hints` distills the licenses;
:func:`find_matches` embeds src patterns into a PCG.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Set, Tuple

from ..fftype import OpType

# reference ffconst op-type names -> our OpType (subset that appears in
# rule files).  Unmapped types are kept as raw strings for inspection but
# match nothing in find_matches (matching requires a mapped op type)
_OP_TYPE_MAP = {
    "OP_LINEAR": OpType.LINEAR,
    "OP_CONV2D": OpType.CONV2D,
    "OP_EW_ADD": OpType.EW_ADD,
    "OP_EW_MUL": OpType.EW_MUL,
    "OP_RELU": OpType.RELU,
    "OP_CONCAT": OpType.CONCAT,
    "OP_SPLIT": OpType.SPLIT,
    "OP_RESHAPE": OpType.RESHAPE,
    "OP_TRANSPOSE": OpType.TRANSPOSE,
    "OP_SOFTMAX": OpType.SOFTMAX,
    "OP_MULTIHEAD_ATTENTION": OpType.MULTIHEAD_ATTENTION,
    "OP_EMBEDDING": OpType.EMBEDDING,
    "OP_MATMUL": OpType.BATCH_MATMUL,
    "OP_BATCHMATMUL": OpType.BATCH_MATMUL,
    "OP_PARTITION": OpType.REPARTITION,
    "OP_REPARTITION": OpType.REPARTITION,
    "OP_COMBINE": OpType.COMBINE,
    "OP_REPLICATE": OpType.REPLICATE,
    "OP_REDUCE": OpType.REDUCTION,
    "OP_REDUCTION": OpType.REDUCTION,
    "OP_PIPELINE": None,
    "OP_NOOP": OpType.NOOP,
}

PARALLEL_TYPES = {"OP_PARTITION", "OP_REPARTITION", "OP_COMBINE",
                  "OP_REPLICATE", "OP_REDUCE", "OP_REDUCTION"}


@dataclasses.dataclass(frozen=True)
class TensorRef:
    """reference substitution_loader.h Tensor: opId < 0 names the
    (-opId)-th external input; opId >= 0 indexes the pattern's op list."""

    op_id: int
    ts_id: int


@dataclasses.dataclass
class PatternOp:
    type_name: str                       # raw "OP_*" string
    op_type: Optional[OpType]            # mapped, if known
    inputs: List[TensorRef]
    params: Dict[str, int]               # "PM_*" -> value


@dataclasses.dataclass
class MapOutput:
    src_op_id: int
    src_ts_id: int
    dst_op_id: int
    dst_ts_id: int


@dataclasses.dataclass
class Rule:
    name: str
    src_ops: List[PatternOp]
    dst_ops: List[PatternOp]
    mapped_outputs: List[MapOutput]


@dataclasses.dataclass
class RuleCollection:
    rules: List[Rule]


class RuleSchemaError(ValueError):
    pass


def _parse_op(d: dict) -> PatternOp:
    if d.get("_t") != "Operator":
        raise RuleSchemaError(f"expected Operator, got {d.get('_t')!r}")
    t = d["type"]
    params = {}
    for p in d.get("para", []):
        if p.get("_t") != "Parameter":
            raise RuleSchemaError(f"expected Parameter, got {p.get('_t')!r}")
        params[p["key"]] = int(p["value"])
    inputs = []
    for i in d.get("input", []):
        if i.get("_t") != "Tensor":
            raise RuleSchemaError(f"expected Tensor, got {i.get('_t')!r}")
        inputs.append(TensorRef(int(i["opId"]), int(i["tsId"])))
    return PatternOp(t, _OP_TYPE_MAP.get(t), inputs, params)


def _validate_pattern(ops: List[PatternOp], where: str) -> None:
    """Mirror of the reference loader's sanity checks
    (tests/unit/test_substitution_loader.cc): every non-external input
    must reference an EARLIER op in the same pattern (patterns are
    topologically ordered DAGs)."""
    for idx, op in enumerate(ops):
        for ref in op.inputs:
            if ref.op_id >= idx:
                raise RuleSchemaError(
                    f"{where}: op {idx} input references op {ref.op_id} "
                    f"(patterns must be topologically ordered)")


def parse_rule(d: dict) -> Rule:
    if d.get("_t") != "Rule":
        raise RuleSchemaError(f"expected Rule, got {d.get('_t')!r}")
    src = [_parse_op(o) for o in d["srcOp"]]
    dst = [_parse_op(o) for o in d["dstOp"]]
    _validate_pattern(src, f"rule {d.get('name')!r} srcOp")
    _validate_pattern(dst, f"rule {d.get('name')!r} dstOp")
    mapped = []
    for m in d.get("mappedOutput", []):
        if m.get("_t") != "MapOutput":
            raise RuleSchemaError(f"expected MapOutput, got {m.get('_t')!r}")
        mo = MapOutput(int(m["srcOpId"]), int(m["srcTsId"]),
                       int(m["dstOpId"]), int(m["dstTsId"]))
        if not (0 <= mo.src_op_id < len(src)):
            raise RuleSchemaError(
                f"rule {d.get('name')!r}: mappedOutput srcOpId "
                f"{mo.src_op_id} out of range")
        if not (0 <= mo.dst_op_id < len(dst)):
            raise RuleSchemaError(
                f"rule {d.get('name')!r}: mappedOutput dstOpId "
                f"{mo.dst_op_id} out of range")
        mapped.append(mo)
    return Rule(d.get("name", "<unnamed>"), src, dst, mapped)


def load_rule_collection(path: str) -> RuleCollection:
    """Load + validate a rule collection JSON (reference
    load_rule_collection, substitution_loader.cc; CLI flag
    --substitution-json).  All schema problems — including missing
    required keys — surface as :class:`RuleSchemaError`."""
    with open(path) as f:
        d = json.load(f)
    if d.get("_t") != "RuleCollection":
        raise RuleSchemaError(
            f"expected RuleCollection, got {d.get('_t')!r}")
    try:
        return RuleCollection([parse_rule(r) for r in d.get("rule", [])])
    except KeyError as e:
        raise RuleSchemaError(f"missing required key {e}") from e


# ------------------------------------------------------------------ match
def find_matches(rule: Rule, pcg) -> List[Dict[int, str]]:
    """All embeddings of ``rule.src_ops`` into the PCG: maps pattern op
    index -> node name.  Structural matching on op type + dataflow edges
    (the reference's GraphXfer::create_operator_from_pb + match,
    substitution.cc:791+); parallel-op pattern nodes have no PCG
    counterpart here (shardings are implicit) so rules containing them in
    src match nothing — they act through :func:`collection_choice_hints`.
    """
    n_pat = len(rule.src_ops)
    if any(op.type_name in PARALLEL_TYPES for op in rule.src_ops):
        return []
    out: List[Dict[int, str]] = []
    nodes = pcg.nodes

    def _src_key(tensor):
        """Identity of a tensor: (producer, output index) for internal
        edges, ("__input__", name) for graph inputs."""
        if tensor.owner_layer is None:
            return ("__input__", tensor.name)
        return (tensor.owner_layer.name, tensor.owner_idx)

    def compatible(p_idx: int, node, assign: Dict[int, str],
                   ext: Dict[int, tuple]) -> Optional[Dict[int, tuple]]:
        """None if incompatible; else the external-input bindings this
        node adds (a pattern reusing opId -1 twice must see the SAME
        actual tensor both times)."""
        pat = rule.src_ops[p_idx]
        if pat.op_type is None or node.op_type is not pat.op_type:
            return None
        if len(pat.inputs) > len(node.inputs):
            return None
        commutative = pat.op_type in (OpType.EW_ADD, OpType.EW_MUL)
        orders = ([list(range(len(pat.inputs)))] if not commutative
                  else [[0, 1], [1, 0]])
        for order in orders:
            new_ext: Dict[int, tuple] = {}
            ok = True
            for slot, ref in zip(order, pat.inputs):
                actual = _src_key(node.inputs[slot])  # positional (like
                if ref.op_id < 0:                     # the reference's
                    bound = ext.get(ref.op_id,        # Operator inputs),
                                    new_ext.get(ref.op_id))
                    if bound is not None and bound != actual:
                        ok = False                    # plus the swapped
                        break                         # order for
                    new_ext[ref.op_id] = actual       # commutative ops
                else:
                    want = assign.get(ref.op_id)
                    if want is None or actual != (want, ref.ts_id):
                        ok = False
                        break
            if ok:
                return new_ext
        return None

    def backtrack(p_idx: int, assign: Dict[int, str], used: Set[str],
                  ext: Dict[int, tuple]):
        if p_idx == n_pat:
            out.append(dict(assign))
            return
        for node in nodes:
            if node.name in used:
                continue
            new_ext = compatible(p_idx, node, assign, ext)
            if new_ext is not None:
                assign[p_idx] = node.name
                used.add(node.name)
                backtrack(p_idx + 1, assign, used, {**ext, **new_ext})
                used.remove(node.name)
                del assign[p_idx]

    backtrack(0, {}, set(), {})
    return out


# ------------------------------------------------------------ integration
def collection_choice_hints(collection: RuleCollection
                            ) -> Dict[OpType, Set[Tuple[str, int, int]]]:
    """Distill a collection into per-op-type parallelization licenses.

    A rule whose dst pattern wraps an op O with OP_PARTITION (dim k,
    degree d) / OP_REPLICATE producers asserts "O admits that
    parallelization" — what the reference's xfers encode (create_xfers,
    substitution.cc:1368-1382).  Returns {op_type: {(kind, dim, degree)}}
    with kind in {"partition", "replicate"}; dim 0 is the batch dim (a
    data-parallel rewrite), dim > 0 licenses weight/feature sharding (tp).
    In the reference a supplied --substitution-json REPLACES the manually
    coded xfers (substitution.cc:1803 else-branch skips them).  Here the
    sharding-collapsed strategy space already subsumes every rule in the
    reference's shipped collections, so graph_optimize only loads +
    validates a provided collection and WARNS about licenses the space
    cannot express — strategies are unchanged (an invariant the tests
    pin, tests/test_substitution_loader.py).
    """
    hints: Dict[OpType, Set[Tuple[str, int, int]]] = {}
    for rule in collection.rules:
        # dataflow: a tensor is partitioned once it passes OP_PARTITION
        # and stays partitioned through compute ops until OP_COMBINE /
        # OP_REDUCE — so an op deep in the dst pattern (e.g. a LINEAR fed
        # by another LINEAR fed by the partition) is licensed too, which
        # is how the reference's multi-op rules express it
        state: Dict[int, Optional[Tuple[str, int, int]]] = {}
        for i, op in enumerate(rule.dst_ops):
            deg = op.params.get("PM_PARALLEL_DEGREE", 0)
            dim = op.params.get("PM_PARALLEL_DIM", 0)
            if op.type_name in ("OP_PARTITION", "OP_REPARTITION"):
                state[i] = ("partition", dim, deg) if deg > 1 else None
                continue
            if op.type_name == "OP_REPLICATE":
                state[i] = ("replicate", 0, deg) if deg > 1 else None
                continue
            if op.type_name in PARALLEL_TYPES:   # combine/reduce: undone
                state[i] = None
                continue
            inherited = next(
                (state.get(r.op_id) for r in op.inputs
                 if r.op_id >= 0 and state.get(r.op_id) is not None),
                None)
            state[i] = inherited
            if inherited is not None and op.op_type is not None:
                hints.setdefault(op.op_type, set()).add(inherited)
    return hints
