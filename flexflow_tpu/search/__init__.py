"""Auto-parallelization search (the Unity analogue).

Top entry mirrors the reference's ``Graph::graph_optimize_task``
(graph.cc:2108): a memory-constrained lambda binary search
(try_one_lambda, graph.cc:2117-2192) around the substitution search, with
the only_data_parallel manual fast path (graph.cc:1969-2025) and the MCMC
fallback (model.cc:3791).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from .cost_model import (CostMetrics, EnhancedMachineModel, MachineModel,
                         MeasuredCostModel, SimpleMachineModel,
                         estimate_op_cost, op_flops_bytes, resharding_cost)
from .pcg import (PCG, Edge, ShardAssignment, assign_pipeline_stages,
                  data_parallel_strategy, export_strategy_dot,
                  strategy_from_json, strategy_to_json)
from .substitution import (base_optimize, generic_sequence_optimize,
                           mcmc_optimize, node_choices)
from .substitution_loader import (Rule, RuleCollection, RuleSchemaError,
                                  collection_choice_hints, find_matches,
                                  load_rule_collection)

__all__ = [
    "CostMetrics", "MachineModel", "SimpleMachineModel",
    "EnhancedMachineModel", "MeasuredCostModel", "estimate_op_cost",
    "op_flops_bytes", "resharding_cost", "PCG", "Edge", "ShardAssignment",
    "assign_pipeline_stages", "data_parallel_strategy",
    "export_strategy_dot", "strategy_to_json", "strategy_from_json",
    "base_optimize", "generic_sequence_optimize", "mcmc_optimize",
    "node_choices", "graph_optimize", "Rule", "RuleCollection",
    "RuleSchemaError", "collection_choice_hints", "find_matches",
    "load_rule_collection",
]


def graph_optimize(model, machine: Optional[MachineModel] = None,
                   num_devices: Optional[int] = None,
                   budget: int = 2000, alpha: float = 1.05,
                   memory_limit: Optional[int] = None,
                   only_data_parallel: bool = False,
                   use_mcmc: bool = False, seed: int = 0,
                   substitution_json: Optional[str] = None,
                   cost_model: Optional[MeasuredCostModel] = None,
                   max_pipeline: int = 1
                   ) -> Tuple[Dict[str, ShardAssignment], CostMetrics]:
    """Find a per-layer sharding strategy (reference graph_optimize_task,
    graph.cc:2108).

    Returns ``(strategy, cost)``.  If ``memory_limit`` (bytes per device)
    is set and the unconstrained optimum exceeds it, re-searches with
    decreasing run-time weight lambda until the strategy fits — a binary
    search exactly like try_one_lambda (graph.cc:2117-2192).

    ``cost_model``: a :class:`MeasuredCostModel` routes every per-node
    cost query through its on-chip timing cache (the reference's measured
    search, simulator.cc:519-560) instead of the analytic roofline.

    ``max_pipeline`` > 1 additionally searches pipeline-stage splits: for
    each stage count pp dividing the device count, the per-node (dp, tp,
    sp) search runs with num_devices/pp devices per stage, blocks are
    cost-balanced into stages (balanced_partition), and candidates
    compare on steady-state pipeline cost (bottleneck stage + boundary
    p2p, PCG.pipeline_cost) — the analogue of the reference searching
    MachineViews with per-stage start_device_id (graph.cc:1993-2024).
    """
    pcg = PCG(model)
    est = cost_model.est if cost_model is not None else None
    # a supplied MachineModel's scale wins over the local device count —
    # searching for a machine you don't have is the normal use
    num_devices = (num_devices
                   or (machine.num_devices if machine is not None else 0)
                   or model.config.num_devices or 1)
    machine = machine or SimpleMachineModel(num_devices)
    if only_data_parallel:
        # manual fast path (graph.cc:1969-1992; DefaultConfig model.cc:3995)
        strategy = data_parallel_strategy(pcg, num_devices)
        cost = pcg.strategy_cost(strategy, machine)
        if memory_limit is not None and cost.memory > memory_limit:
            raise MemoryError(
                f"pure data parallelism needs {cost.memory} bytes/device, "
                f"over memory_limit={memory_limit}; rerun without "
                f"only_data_parallel to search sharded strategies")
        return strategy, cost

    search = mcmc_optimize if use_mcmc else generic_sequence_optimize
    kwargs = (dict(iterations=budget, seed=seed, est=est) if use_mcmc
              else dict(budget=budget, alpha=alpha, est=est))
    if substitution_json:
        # the reference's --substitution-json appends JSON xfers to an
        # always-generated base set (substitution.cc:1787-1800).  In the
        # sharding-collapsed search the base set is already maximal over
        # (dp, tp) degrees and the rules' algebraic parallel-op
        # identities are rewrites GSPMD performs mechanically — so the
        # collection is loaded and validated (schema errors surface
        # here, like the reference loader's), and licenses referencing
        # op types with no tp lowering are reported
        import warnings

        hints = collection_choice_hints(
            load_rule_collection(substitution_json))
        from .pcg import TP_CAPABLE

        unlowerable = sorted(
            t.value for t, hs in hints.items()
            if t not in TP_CAPABLE
            and any(k == "partition" and dim > 0 for k, dim, _ in hs))
        if unlowerable:
            warnings.warn(
                f"substitution rules license partitioning for op types "
                f"without a tensor-parallel lowering (ignored): "
                f"{unlowerable}")

    def run_at_pp(pp: int, mem_factor: float = 1.0):
        """Search with num_devices/pp per stage; pp > 1 balances blocks
        into stages and costs at the pipeline bottleneck."""
        nd = num_devices // pp
        s, _ = search(pcg, machine, nd, **(
            dict(kwargs, mem_factor=mem_factor) if mem_factor != 1.0
            else kwargs))
        if pp > 1:
            s = assign_pipeline_stages(pcg, pp, machine, s, est=est)
            return s, pcg.pipeline_cost(s, machine, est=est)
        return s, pcg.strategy_cost(s, machine, est=est)

    pps = [p for p in range(1, max_pipeline + 1)
           if num_devices % p == 0] or [1]

    def best_over_pp(mem_factor: float = 1.0):
        cands = [run_at_pp(p, mem_factor) for p in pps]
        if memory_limit is not None:
            fitting = [sc for sc in cands
                       if sc[1].memory <= memory_limit]
            if fitting:   # deeper pipelines trade speed for capacity
                return min(fitting, key=lambda sc: sc[1].total_time)
        return min(cands, key=lambda sc: sc[1].total_time)

    strategy, cost = best_over_pp()
    if memory_limit is None or cost.memory <= memory_limit:
        return strategy, cost

    # lambda binary search: weight memory ever harder until it fits
    lo, hi = 0.0, 1.0    # mem_factor: 1 = pure runtime, 0 = pure memory
    best_fit: Optional[Tuple[Dict[str, ShardAssignment], CostMetrics]] = None
    c = cost
    for _ in range(8):
        lam = (lo + hi) / 2
        s, c = best_over_pp(mem_factor=lam)
        if c.memory <= memory_limit:
            best_fit = (s, c)
            lo = lam          # fits: try weighting runtime more again
        else:
            hi = lam          # too big: weight memory harder
    if best_fit is None:
        raise MemoryError(
            f"no strategy fits memory_limit={memory_limit} "
            f"(best found needs {c.memory} bytes/device)")
    return best_fit
