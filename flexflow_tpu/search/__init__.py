"""Auto-parallelization search (the Unity analogue).

Top entry mirrors the reference's ``Graph::graph_optimize_task``
(graph.cc:2108): a memory-constrained lambda binary search
(try_one_lambda, graph.cc:2117-2192) around the substitution search, with
the only_data_parallel manual fast path (graph.cc:1969-2025) and the MCMC
fallback (model.cc:3791).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from .cost_model import (CostMetrics, EnhancedMachineModel, MachineModel,
                         MeasuredCostModel, SimpleMachineModel,
                         estimate_op_cost, op_flops_bytes, resharding_cost)
from .pcg import (PCG, Edge, ShardAssignment, assign_pipeline_stages,
                  data_parallel_strategy, export_strategy_dot,
                  strategy_from_json, strategy_to_json)
from .substitution import (base_optimize, generic_sequence_optimize,
                           mcmc_optimize, node_choices)
from .substitution_loader import (Rule, RuleCollection, RuleSchemaError,
                                  collection_choice_hints, find_matches,
                                  load_rule_collection)

__all__ = [
    "CostMetrics", "MachineModel", "SimpleMachineModel",
    "EnhancedMachineModel", "MeasuredCostModel", "estimate_op_cost",
    "op_flops_bytes", "resharding_cost", "PCG", "Edge", "ShardAssignment",
    "assign_pipeline_stages", "data_parallel_strategy",
    "export_strategy_dot", "strategy_to_json", "strategy_from_json",
    "base_optimize", "generic_sequence_optimize", "mcmc_optimize",
    "node_choices", "graph_optimize", "Rule", "RuleCollection",
    "RuleSchemaError", "collection_choice_hints", "find_matches",
    "load_rule_collection",
]


def graph_optimize(model, machine: Optional[MachineModel] = None,
                   num_devices: Optional[int] = None,
                   budget: int = 2000, alpha: float = 1.05,
                   memory_limit: Optional[int] = None,
                   only_data_parallel: bool = False,
                   use_mcmc: bool = False, seed: int = 0,
                   substitution_json: Optional[str] = None
                   ) -> Tuple[Dict[str, ShardAssignment], CostMetrics]:
    """Find a per-layer sharding strategy (reference graph_optimize_task,
    graph.cc:2108).

    Returns ``(strategy, cost)``.  If ``memory_limit`` (bytes per device)
    is set and the unconstrained optimum exceeds it, re-searches with
    decreasing run-time weight lambda until the strategy fits — a binary
    search exactly like try_one_lambda (graph.cc:2117-2192).
    """
    pcg = PCG(model)
    # a supplied MachineModel's scale wins over the local device count —
    # searching for a machine you don't have is the normal use
    num_devices = (num_devices
                   or (machine.num_devices if machine is not None else 0)
                   or model.config.num_devices or 1)
    machine = machine or SimpleMachineModel(num_devices)
    if only_data_parallel:
        # manual fast path (graph.cc:1969-1992; DefaultConfig model.cc:3995)
        strategy = data_parallel_strategy(pcg, num_devices)
        cost = pcg.strategy_cost(strategy, machine)
        if memory_limit is not None and cost.memory > memory_limit:
            raise MemoryError(
                f"pure data parallelism needs {cost.memory} bytes/device, "
                f"over memory_limit={memory_limit}; rerun without "
                f"only_data_parallel to search sharded strategies")
        return strategy, cost

    search = mcmc_optimize if use_mcmc else generic_sequence_optimize
    kwargs = (dict(iterations=budget, seed=seed) if use_mcmc
              else dict(budget=budget, alpha=alpha))
    if substitution_json:
        # the reference's --substitution-json appends JSON xfers to an
        # always-generated base set (substitution.cc:1787-1800).  In the
        # sharding-collapsed search the base set is already maximal over
        # (dp, tp) degrees and the rules' algebraic parallel-op
        # identities are rewrites GSPMD performs mechanically — so the
        # collection is loaded and validated (schema errors surface
        # here, like the reference loader's), and licenses referencing
        # op types with no tp lowering are reported
        import warnings

        hints = collection_choice_hints(
            load_rule_collection(substitution_json))
        from .pcg import TP_CAPABLE

        unlowerable = sorted(
            t.value for t, hs in hints.items()
            if t not in TP_CAPABLE
            and any(k == "partition" and dim > 0 for k, dim, _ in hs))
        if unlowerable:
            warnings.warn(
                f"substitution rules license partitioning for op types "
                f"without a tensor-parallel lowering (ignored): "
                f"{unlowerable}")

    strategy, _ = search(pcg, machine, num_devices, **kwargs)
    cost = pcg.strategy_cost(strategy, machine)
    if memory_limit is None or cost.memory <= memory_limit:
        return strategy, cost

    # lambda binary search: weight memory ever harder until it fits
    lo, hi = 0.0, 1.0    # mem_factor: 1 = pure runtime, 0 = pure memory
    best_fit: Optional[Tuple[Dict[str, ShardAssignment], CostMetrics]] = None
    c = cost
    for _ in range(8):
        lam = (lo + hi) / 2
        s, _ = search(pcg, machine, num_devices, mem_factor=lam, **kwargs)
        c = pcg.strategy_cost(s, machine)
        if c.memory <= memory_limit:
            best_fit = (s, c)
            lo = lam          # fits: try weighting runtime more again
        else:
            hi = lam          # too big: weight memory harder
    if best_fit is None:
        raise MemoryError(
            f"no strategy fits memory_limit={memory_limit} "
            f"(best found needs {c.memory} bytes/device)")
    return best_fit
