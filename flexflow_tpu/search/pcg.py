"""Parallel Computation Graph over the layer list.

TPU-native re-design of the reference PCG (src/runtime/graph.cc): nodes are
layers, edges are tensor flows (Edge{srcOp,dstOp,srcIdx,dstIdx}, graph.h:31).
Where the reference assigns each node a MachineView, we assign a
:class:`ShardAssignment` — per-node (dp, tp, pp_stage) degrees over the
global mesh — which lowers to `NamedSharding` annotations instead of Legion
partitions.  Strategy export mirrors the reference's dot/json strategy dump
(graph.cc:460-480, config.h:160-163).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple

from ..fftype import OpType
from .cost_model import (CostMetrics, MachineModel, _prod, estimate_op_cost,
                         resharding_cost)

# ops whose weights can be sharded tensor-parallel (the reference's
# partitionable ops: Linear/Conv/Attention/Experts, substitution.cc:70-127)
TP_CAPABLE = {
    OpType.LINEAR, OpType.CONV2D, OpType.MULTIHEAD_ATTENTION,
    OpType.INC_MULTIHEAD_SELF_ATTENTION, OpType.EXPERTS,
    OpType.EMBEDDING,
}

# ops that admit expert parallelism: the expert dim shards over an 'ep'
# mesh axis with all-to-all token dispatch/combine (ops/moe_ops.py; the
# reference's sample/parameter/attribute-dim parallelizable flags,
# config.h:148-150, collapse to this one searched degree)
EP_CAPABLE = {
    OpType.EXPERTS,
}

# ops that admit sequence parallelism (ring attention over ppermute,
# ops/ring_attention.py — a dimension the reference cannot search at all,
# SURVEY §5 "sequence parallelism: absent")
SP_CAPABLE = {
    OpType.MULTIHEAD_ATTENTION,
    OpType.INC_MULTIHEAD_SELF_ATTENTION,
    OpType.SPEC_INC_MULTIHEAD_SELF_ATTENTION,
    OpType.TREE_INC_MULTIHEAD_SELF_ATTENTION,
}


@dataclasses.dataclass(frozen=True)
class ShardAssignment:
    """Per-node parallelization choice (reference MachineView,
    machine_view.h:18-39: here degrees over named mesh axes instead of
    device-id strides).  ``sp`` is the sequence-parallel degree (ring
    attention) — a search dimension the reference lacks."""

    dp: int = 1
    tp: int = 1
    pp_stage: int = 0
    sp: int = 1
    ep: int = 1   # expert-parallel degree (MoE expert dim)

    def degree(self) -> int:
        return self.dp * self.tp * self.sp * self.ep


@dataclasses.dataclass
class Edge:
    """reference: PCG::Edge (graph.h:31)."""

    src: str           # producer layer name
    dst: str           # consumer layer name
    src_idx: int
    dst_idx: int
    tensor_bytes: int


class PCG:
    """Graph view of a Model's layers (reference PCG::Graph)."""

    def __init__(self, model):
        self.model = model
        self.nodes: List = list(model.layers)
        self.by_name = {l.name: l for l in self.nodes}
        self.edges: List[Edge] = []
        self.in_edges: Dict[str, List[Edge]] = {l.name: [] for l in self.nodes}
        self.out_edges: Dict[str, List[Edge]] = {l.name: []
                                                 for l in self.nodes}
        for layer in self.nodes:
            for dst_idx, t in enumerate(layer.inputs):
                if t.owner_layer is None:
                    continue
                e = Edge(t.owner_layer.name, layer.name, t.owner_idx,
                         dst_idx, _prod(t.spec.shape) * 4)
                self.edges.append(e)
                self.in_edges[layer.name].append(e)
                self.out_edges[t.owner_layer.name].append(e)

    # ------------------------------------------------------------- topology
    def topo_order(self) -> List[str]:
        return [l.name for l in self.nodes]  # build order is topological

    def bottleneck_nodes(self) -> List[str]:
        """Sequence-split candidates (reference find_split_node,
        substitution.cc:2640): node i is a cut point iff every edge from an
        earlier node lands at or before i — then the only tensors crossing
        the cut are i's own outputs (a residual edge skipping over i
        disqualifies it)."""
        order = self.topo_order()
        idx = {n: i for i, n in enumerate(order)}
        max_reach = [0] * len(order)
        for e in self.edges:
            max_reach[idx[e.src]] = max(max_reach[idx[e.src]], idx[e.dst])
        out: List[str] = []
        frontier = 0   # max reach over nodes j < i
        for i, n in enumerate(order):
            if i > 0 and frontier <= i and i + 1 < len(order):
                out.append(n)
            frontier = max(frontier, max_reach[i])
        return out

    # ----------------------------------------------------------------- cost
    def strategy_cost(self, strategy: Dict[str, ShardAssignment],
                      machine: MachineModel, est=None) -> CostMetrics:
        """Graph cost under a strategy: per-node roofline + edge resharding
        (reference SearchHelper DP composition, graph.cc:1206-1281).

        ``est`` overrides the per-node estimator — pass
        ``MeasuredCostModel.est`` to run the search on real on-chip
        timings (the reference's simulator.cc:519 measured mode)."""
        est = est or estimate_op_cost
        total = CostMetrics()
        per_dev_mem = 0
        for layer in self.nodes:
            a = strategy.get(layer.name, ShardAssignment())
            c = est(layer, [o.spec.shape for o in layer.outputs], machine,
                    dp=a.dp, tp=a.tp, sp=a.sp, ep=a.ep)
            total = total + CostMetrics(c.forward_time, c.backward_time,
                                        c.sync_time, 0)
            per_dev_mem += c.memory
        xfer = 0.0
        for e in self.edges:
            sa = strategy.get(e.src, ShardAssignment())
            da = strategy.get(e.dst, ShardAssignment())
            xfer += resharding_cost(e.tensor_bytes,
                                    (sa.dp, sa.tp, sa.sp, sa.ep),
                                    (da.dp, da.tp, da.sp, da.ep), machine)
            if sa.pp_stage != da.pp_stage:  # stage boundary: p2p activation
                xfer += machine.p2p_time(e.tensor_bytes // sa.degree())
        total.sync_time += xfer
        total.memory = per_dev_mem
        return total

    def pipeline_cost(self, strategy: Dict[str, ShardAssignment],
                      machine: MachineModel, est=None) -> CostMetrics:
        """Steady-state cost of a staged strategy: the bottleneck stage
        bounds throughput once batches pipeline through the stages
        (serving/pipeline_serving.py micro-batch overlap; the reference
        gets the same overlap from its <=4 in-flight batches,
        request_manager.cc:1946-1977).  Memory is the largest stage's
        per-device footprint — the pp capacity win."""
        est = est or estimate_op_cost
        stage_time: Dict[int, float] = {}
        stage_mem: Dict[int, int] = {}
        for layer in self.nodes:
            a = strategy.get(layer.name, ShardAssignment())
            c = est(layer, [o.spec.shape for o in layer.outputs], machine,
                    dp=a.dp, tp=a.tp, sp=a.sp, ep=a.ep)
            stage_time[a.pp_stage] = (stage_time.get(a.pp_stage, 0.0)
                                      + c.total_time)
            stage_mem[a.pp_stage] = stage_mem.get(a.pp_stage, 0) + c.memory
        xfer = 0.0
        for e in self.edges:
            sa = strategy.get(e.src, ShardAssignment())
            da = strategy.get(e.dst, ShardAssignment())
            xfer += resharding_cost(e.tensor_bytes,
                                    (sa.dp, sa.tp, sa.sp, sa.ep),
                                    (da.dp, da.tp, da.sp, da.ep), machine)
            if sa.pp_stage != da.pp_stage:
                xfer += machine.p2p_time(e.tensor_bytes // sa.degree())
        bottleneck = max(stage_time.values()) if stage_time else 0.0
        return CostMetrics(bottleneck, 0.0, xfer,
                           max(stage_mem.values()) if stage_mem else 0)


# ------------------------------------------------------------- strategies
def data_parallel_strategy(pcg: PCG, num_devices: int
                           ) -> Dict[str, ShardAssignment]:
    """The only_data_parallel fast path (reference graph.cc:1969-1992)."""
    return {l.name: ShardAssignment(dp=num_devices) for l in pcg.nodes}


def balanced_partition(costs: List[float], k: int) -> List[int]:
    """Split a cost sequence into ``k`` contiguous groups minimizing the
    max group sum (linear-partition DP) — the stage-balancing objective the
    reference approximates with its uniform layers_per_stage split
    (inference_manager.cc:131).  Returns the group index per item."""
    n = len(costs)
    if n == 0:
        return []
    k = min(k, n)
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)
    INF = float("inf")
    # best[j][i]: minimal max-sum splitting the first i items into j groups
    best = [[INF] * (n + 1) for _ in range(k + 1)]
    cut = [[0] * (n + 1) for _ in range(k + 1)]
    best[0][0] = 0.0
    for j in range(1, k + 1):
        for i in range(j, n + 1):
            for m in range(j - 1, i):
                cand = max(best[j - 1][m], prefix[i] - prefix[m])
                if cand < best[j][i]:
                    best[j][i] = cand
                    cut[j][i] = m
    out = [0] * n
    i = n
    for j in range(k, 0, -1):
        m = cut[j][i]
        for t in range(m, i):
            out[t] = j - 1
        i = m
    return out


def assign_pipeline_stages(pcg: PCG, num_stages: int,
                           machine: MachineModel,
                           strategy: Optional[Dict[str, ShardAssignment]]
                           = None, est=None) -> Dict[str, ShardAssignment]:
    """Balance transformer layers across stages by cost, not just count
    (refines the reference's layers_per_stage split,
    inference_manager.cc:131, graph.cc:2016-2024).  Balancing uses the
    SAME estimator (incl. sp degrees and measured timings) that
    pipeline_cost scores the result with — a split computed from
    different costs than its score would be systematically skewed."""
    est = est or estimate_op_cost
    strategy = dict(strategy or
                    {l.name: ShardAssignment() for l in pcg.nodes})
    costs = []
    for l in pcg.nodes:
        a = strategy[l.name]
        c = est(l, [o.spec.shape for o in l.outputs], machine,
                dp=a.dp, tp=a.tp, sp=a.sp)
        costs.append(c.total_time)
    stages = balanced_partition(costs, num_stages)
    for l, s in zip(pcg.nodes, stages):
        a = strategy[l.name]
        strategy[l.name] = ShardAssignment(a.dp, a.tp, s, a.sp)
    return strategy


# ------------------------------------------------------- (de)serialization
def strategy_to_json(strategy: Dict[str, ShardAssignment]) -> str:
    return json.dumps({k: {"dp": v.dp, "tp": v.tp, "pp_stage": v.pp_stage,
                           "sp": v.sp}
                       for k, v in strategy.items()}, indent=2)


def strategy_from_json(s: str) -> Dict[str, ShardAssignment]:
    return {k: ShardAssignment(v["dp"], v["tp"], v["pp_stage"],
                               v.get("sp", 1))   # pre-sp exports load fine
            for k, v in json.loads(s).items()}


def export_strategy_dot(pcg: PCG, strategy: Dict[str, ShardAssignment]
                        ) -> str:
    """Dot export (reference export_strategy_computation_graph_file,
    graph.cc:460-480)."""
    lines = ["digraph strategy {"]
    for l in pcg.nodes:
        a = strategy.get(l.name, ShardAssignment())
        lines.append(
            f'  "{l.name}" [label="{l.name}\\n{l.op_type.value}\\n'
            f'dp={a.dp} tp={a.tp} pp={a.pp_stage}"];')
    for e in pcg.edges:
        lines.append(f'  "{e.src}" -> "{e.dst}";')
    lines.append("}")
    return "\n".join(lines)
