"""Weight-only quantization (int8 / int4).

TPU-native re-design of the reference's quantization support
(``--4bit-quantization``/``--8bit-quantization``: FileDataLoader's
``load_attention_weights_quantized`` / ``load_quantization_weight``
inference/file_loader.cc:400-651 + on-GPU decompression
src/ops/kernels/decompress_kernels.cu).  There the quantized weights are
decompressed by hand-written kernels before each GEMM; here the dequant is
expressed in jnp inside the op's forward and XLA fuses it into the matmul's
operand load — weights stay int8/int4-packed in HBM, halving/quartering
weight bandwidth, which is what matters for serving (decode is
weight-bandwidth-bound).

Layouts:
- int8: symmetric per-output-channel. kernel_q int8 [in, out],
  kernel_scale f32 [out].
- int4: symmetric group-wise along the in dim (group=64 like the
  reference's GROUP_SIZE). Two values pack per int8 byte: kernel_q int8
  [in//2, out] (low nibble = even row, high nibble = odd row),
  kernel_scale f32 [in//group, out].
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from .fftype import OpType

INT4_GROUP = 64


# ------------------------------------------------------------------- int8
def quantize_int8(w: np.ndarray):
    """w [in, out] -> (q int8 [in, out], scale f32 [out])."""
    w = np.asarray(w, np.float32)
    scale = np.abs(w).max(axis=0) / 127.0
    scale = np.where(scale == 0, 1.0, scale).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize_int8(q, scale, dtype):
    return (q.astype(jnp.float32) * scale[None, :]).astype(dtype)


# ------------------------------------------------------------------- int4
def quantize_int4(w: np.ndarray, group: int = INT4_GROUP):
    """w [in, out] -> (packed int8 [in//2, out], scale f32 [in//g, out]).
    The 2-D linear-kernel layout: packs along the in dim (axis 0)."""
    return quantize_int4_nd(w, 0, group)


def dequantize_int4(packed, scale, dtype, in_dim: int):
    assert in_dim == packed.shape[0] * 2, (in_dim, packed.shape)
    return dequantize_int4_nd(packed, scale, dtype, 0)


# --------------------------------------------------------------- param tree
def quantize_linear_params(lparams: Dict[str, Any], mode: str
                           ) -> Dict[str, Any]:
    """Quantize one linear layer's params in-place-style (bias untouched)."""
    w = np.asarray(lparams["kernel"], np.float32)
    out = {k: v for k, v in lparams.items() if k != "kernel"}
    if mode == "int8":
        q, s = quantize_int8(w)
    elif mode == "int4":
        q, s = quantize_int4(w)
    else:
        raise ValueError(f"unknown quantization mode {mode!r}")
    out["kernel_q"] = q
    out["kernel_scale"] = s
    return out


def dequantize_kernel(params: Dict[str, Any], dtype):
    """Used by the Linear op when it sees quantized params; the layout
    (int8 vs packed int4) is recovered from static shapes so this traces
    cleanly under jit."""
    scale = params["kernel_scale"]
    q = params["kernel_q"]
    if scale.ndim == 1:
        return dequantize_int8(q, scale, dtype)
    return dequantize_int4(q, scale, dtype, q.shape[0] * 2)


# --------------------------------------------- N-d int4 (attention)
def quantize_int4_nd(w: np.ndarray, axis: int, group: int = INT4_GROUP):
    """Group-wise int4 along one reduction ``axis``; all other axes keep
    independent scales (finer than the int8_nd per-output-channel scale).
    Returns (packed int8 with axis halved, scale f32 with axis/group).
    The pack axis must be even-sized and must NOT be a tp-sharded axis
    (nibble pairs may not straddle shards): wq/wk/wv pack E, wo packs D
    (heads shard, tp_specs.ATTN_WEIGHT_SPECS)."""
    w = np.asarray(w, np.float32)
    n = w.shape[axis]
    assert n % 2 == 0, "int4 packing needs an even pack-axis size"
    g = min(group, n)
    while n % g:
        g //= 2
    wm = np.moveaxis(w, axis, 0)
    rest = wm.shape[1:]
    wg = wm.reshape(n // g, g, *rest)
    scale = np.abs(wg).max(axis=1) / 7.0
    scale = np.where(scale == 0, 1.0, scale).astype(np.float32)
    q = np.clip(np.rint(wg / scale[:, None]), -8, 7).astype(np.int8)
    q = q.reshape(n, *rest)
    packed = ((q[0::2] & 0x0F) | ((q[1::2] & 0x0F) << 4)).astype(np.int8)
    return (np.moveaxis(packed, 0, axis),
            np.moveaxis(scale, 0, axis))


def dequantize_int4_nd(packed, scale, dtype, axis: int):
    pm = jnp.moveaxis(packed, axis, 0)
    sm = jnp.moveaxis(scale, axis, 0)
    lo = (pm << 4).astype(jnp.int8) >> 4               # sign-extend low
    hi = pm.astype(jnp.int8) >> 4                      # arithmetic shift
    n = pm.shape[0] * 2
    rest = pm.shape[1:]
    q = jnp.stack([lo, hi], axis=1).reshape(n, *rest)
    g = n // sm.shape[0]
    deq = (q.reshape(sm.shape[0], g, *rest).astype(jnp.float32)
           * sm[:, None])
    return jnp.moveaxis(deq.reshape(n, *rest), 0, axis).astype(dtype)


# ------------------------------------------- W8A8 native-int8 matmuls
def quantize_activation_rows(x):
    """Dynamic symmetric per-row int8 quantization of activations
    ([..., K] float -> (int8 [..., K], f32 scale [..., 1])).  The TPU
    twin of runtime activation quantization in W8A8 serving stacks: one
    scale per token row keeps the MXU contraction purely int8."""
    import jax.numpy as jnp

    xs = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    xs = jnp.maximum(xs / 127.0, 1e-10)
    xq = jnp.clip(jnp.rint(x.astype(jnp.float32) / xs),
                  -127, 127).astype(jnp.int8)
    return xq, xs


def native_int8_matmul(x, w_q, scale, contract_rhs_dims=(0,)):
    """x [..., K...] @ int8 weight, MXU-NATIVE: the contraction runs
    int8 x int8 -> int32 (no int8->bf16 convert on the VPU — the
    convert, not HBM, bounds the convert-dot path on v5e), then the
    per-row activation scale and per-channel weight ``scale`` apply to
    the int32 result.

    ``contract_rhs_dims``: the weight's LEADING dims to contract with
    x's trailing dims — only (0,) ([K, N] linear kernels / [E, H, D]
    qkv) and (0, 1) ([H, D, E] wo) are supported; the dims must be
    exactly (0..n-1).  Exactness: int8 weights ARE exact; the only
    approximation is the activation rounding (~0.4% rms), measured as a
    greedy-token match rate in the bench methodology."""
    import jax
    import jax.numpy as jnp

    assert tuple(contract_rhs_dims) in ((0,), (0, 1)), contract_rhs_dims
    n = len(contract_rhs_dims)
    x2 = x
    if n > 1:   # fold x's trailing contraction dims into one
        x2 = x.reshape(x.shape[:-n] + (-1,))
        wshape = w_q.shape
        k = 1
        for dim in contract_rhs_dims:
            k *= wshape[dim]
        w_q = w_q.reshape((k,) + wshape[n:])
    xq, xs = quantize_activation_rows(x2)
    y = jax.lax.dot_general(
        xq, w_q, (((x2.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out_extra = y.ndim - x2.ndim + 1        # rhs out dims
    scale_b = scale[(None,) * (y.ndim - scale.ndim)] if scale.ndim \
        else scale
    xs_b = xs.reshape(xs.shape[:-1] + (1,) * out_extra)
    return (y.astype(jnp.float32) * xs_b * scale_b).astype(x.dtype)


# ------------------------------------------------------- int8 KV cache
def quantize_kv(x):
    """Symmetric per-slice int8 quantization of KV-cache entries: float
    ``[..., D]`` -> (q int8 ``[..., D]``, scale f32 ``[...]``), one scale
    per head-dim slice (per row, per position, per kv head — the
    granularity the serving caches store, ``[R, KV, S]`` beside the
    ``[R, KV, S, D]`` int8 K/V).  The single quantizer for BOTH the jnp
    scatter path and the Pallas append wrappers, so the two paths write
    bit-identical cache contents."""
    m = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(m == 0, 1.0, m / 127.0).astype(jnp.float32)
    q = jnp.clip(jnp.rint(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale, dtype):
    """int8 ``[..., D]`` + scale ``[...]`` -> ``dtype``.  Expressed in
    jnp so XLA fuses the dequant into the attend's operand load — the
    HBM stream stays int8 (the same fusion argument as the weight
    convert-dot above)."""
    return (q.astype(jnp.float32)
            * scale[..., None].astype(jnp.float32)).astype(dtype)


def scatter_kv_scales(scales, chunk, start, active):
    """``scales [R, KV, S] <- chunk [R, C, KV]`` at per-row offset
    ``start`` (the scale twin of serving_attention._scatter_chunk).

    ``start`` may be SIGNED (sharded callers pass shard-local offsets):
    positions outside ``[0, S)`` and inactive rows redirect past the
    array end and DROP.  No sorted/unique hints — the clamp can break
    monotonicity and the array is tiny (4 bytes/position)."""
    S = scales.shape[2]
    R, C = chunk.shape[:2]
    pos = start[:, None].astype(jnp.int32) + jnp.arange(C,
                                                        dtype=jnp.int32)
    ok = active[:, None].astype(bool) & (pos >= 0) & (pos < S)
    pos = jnp.where(ok, pos, S)
    rows = jnp.broadcast_to(jnp.arange(R)[:, None], (R, C))
    return scales.at[rows, :, pos].set(chunk.astype(scales.dtype),
                                       mode="drop")


def scatter_kv_scales_paged(scales, chunk, start, active, table):
    """``scales [F, KV, page_len] <- chunk [R, C, KV]`` through the
    per-row page table (the paged twin of :func:`scatter_kv_scales`):
    position ``start[r] + c`` lands in frame ``table[r, pos //
    page_len]`` at in-frame offset ``pos % page_len``.  Positions past
    the table and inactive rows redirect to the out-of-range frame
    sentinel and DROP."""
    F, KV, L = scales.shape
    R, C = chunk.shape[:2]
    P = table.shape[1]
    pos = start[:, None].astype(jnp.int32) + jnp.arange(C,
                                                       dtype=jnp.int32)
    page = pos // L
    ok = active[:, None].astype(bool) & (pos >= 0) & (page < P)
    fr = jnp.take_along_axis(jnp.asarray(table, jnp.int32),
                             jnp.clip(page, 0, P - 1), axis=1)
    fr = jnp.where(ok, fr, F)
    return scales.at[fr, :, pos % L].set(chunk.astype(scales.dtype),
                                         mode="drop")


# ------------------------------------------------- int4 packed KV cache
# Carrier layout (the serving caches' "int4" dtype): the K/V arrays stay
# int8-TYPED but hold 2 codes/byte along the SEQUENCE axis at half width
# — dense ``[R, KV, S//2, D]`` / paged ``[F, KV, L//2, D]`` — so every
# dtype-generic layer (sharding pspecs, pager frame pool, whole-frame
# migration, prefix-pool keys) sees an ordinary int8 array and needs no
# new cases.  Byte at carrier row ``s2`` holds logical position ``2*s2``
# in the LOW nibble and ``2*s2 + 1`` in the HIGH nibble (the
# file-loader's weight-pack convention, quantize_int4_nd above).  Scale
# frames keep the FULL logical length (f32 ``[R, KV, S]``), which also
# makes the pack factor recoverable from static shapes alone
# (:func:`kv_pack_factor`).

def quantize_kv_int4(x):
    """Symmetric per-slice int4 quantization: float ``[..., D]`` ->
    (codes int8 ``[..., D]`` in [-7, 7], scale f32 ``[...]``).  Codes
    come back UNPACKED (one per byte) — the jnp scatter packs them via
    :func:`scatter_kv_packed` and the Pallas chunk append packs them
    in-kernel, both from the same exact integers, so the two paths
    write bit-identical carrier bytes.  Symmetric around 0 at +-7 (not
    -8) so negation symmetry holds like the int8 KV quantizer's."""
    m = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(m == 0, 1.0, m / 7.0).astype(jnp.float32)
    q = jnp.clip(jnp.rint(x.astype(jnp.float32) / scale[..., None]),
                 -7, 7).astype(jnp.int8)
    return q, scale


def pack_kv_int4(q, axis: int = 2):
    """Codes int8 (values in [-8, 7]) -> packed carrier int8 with
    ``axis`` halved.  Even positions land in low nibbles."""
    qm = jnp.moveaxis(q, axis, 0)
    packed = ((qm[0::2] & 0x0F) | ((qm[1::2] & 0x0F) << 4))
    return jnp.moveaxis(packed.astype(jnp.int8), 0, axis)


def unpack_kv_int4(p, axis: int = 2):
    """Packed carrier int8 -> sign-extended codes int8 with ``axis``
    doubled (low nibble first, interleaved back to logical order)."""
    pm = jnp.moveaxis(p, axis, 0)
    lo = (pm << 4).astype(jnp.int8) >> 4               # sign-extend low
    hi = pm.astype(jnp.int8) >> 4                      # arithmetic shift
    n = pm.shape[0] * 2
    q = jnp.stack([lo, hi], axis=1).reshape((n,) + pm.shape[1:])
    return jnp.moveaxis(q, 0, axis)


def dequantize_kv_packed(packed, scale, dtype, axis: int = 2):
    """Packed carrier + full-length scale -> ``dtype``; the unpack is
    pure shifts/masks so XLA fuses it (with the dequant multiply) into
    the attend's operand load — the HBM stream stays at 0.5 byte per
    cached value."""
    return dequantize_kv(unpack_kv_int4(packed, axis), scale, dtype)


def kv_pack_factor(cache, scales) -> int:
    """Codes per carrier byte, recovered from static shapes: the scale
    frame keeps full logical length on axis 2 while the int4 carrier
    halves it.  1 for bf16 (no scales) and int8, 2 for int4; works for
    dense ``[R, KV, S(,D)]`` and paged ``[F, KV, L(,D)]`` layouts."""
    if scales is None:
        return 1
    return scales.shape[2] // cache.shape[2]


def _merge_nibbles(carrier, rows, byte, ok, codes, odd):
    """One parity pass of the packed scatter: gather the target bytes,
    merge ``codes`` into the ``odd`` (high) or even (low) nibble, and
    scatter back with out-of-range/inactive entries redirected past the
    end (DROP).  Within one parity class consecutive logical positions
    hit DISTINCT bytes, so the scatter is collision-free."""
    S2 = carrier.shape[2]
    old = carrier[rows, :, jnp.clip(byte, 0, S2 - 1)].astype(jnp.int32)
    c4 = codes.astype(jnp.int32) & 0x0F
    new = jnp.where(odd[..., None, None],
                    (old & 0x0F) | (c4 << 4),
                    (old & ~0x0F) | c4).astype(carrier.dtype)
    tgt = jnp.where(ok, byte, S2)
    return carrier.at[rows, :, tgt].set(new, mode="drop")


def scatter_kv_packed(carrier, codes, start, active):
    """``carrier [R, KV, S//2, D] <- codes [R, C, KV, D]`` (int4 values,
    unpacked) at per-row LOGICAL offset ``start`` — the packed twin of
    serving_attention._scatter_chunk.  Read-modify-write in two
    parity-sequenced passes (even logical positions merge low nibbles,
    then odd positions merge highs on the pass-A result) so a chunk
    boundary splitting a byte never loses the neighbouring nibble.
    ``start`` may be signed (sharded callers pass shard-local offsets);
    out-of-range positions and inactive rows DROP."""
    S2 = carrier.shape[2]
    R, C = codes.shape[:2]
    pos = start[:, None].astype(jnp.int32) + jnp.arange(C,
                                                        dtype=jnp.int32)
    ok = active[:, None].astype(bool) & (pos >= 0) & (pos < S2 * 2)
    byte, odd = pos // 2, (pos % 2).astype(bool)
    rows = jnp.broadcast_to(jnp.arange(R)[:, None], (R, C))
    carrier = _merge_nibbles(carrier, rows, byte, ok & ~odd, codes, odd)
    return _merge_nibbles(carrier, rows, byte, ok & odd, codes, odd)


def scatter_kv_packed_paged(pool, codes, start, active, table):
    """``pool [F, KV, page_len//2, D] <- codes [R, C, KV, D]`` through
    the per-row page table (the packed twin of _scatter_chunk_paged):
    logical position ``start[r] + c`` lands in frame ``table[r, pos //
    L]`` at carrier byte ``(pos % L) // 2``.  Same two-pass parity
    merge; positions past the table, unleased (negative) frames and
    inactive rows redirect to the frame sentinel and DROP."""
    F, KV, L2, D = pool.shape
    L = L2 * 2
    R, C = codes.shape[:2]
    P = table.shape[1]
    pos = start[:, None].astype(jnp.int32) + jnp.arange(C,
                                                        dtype=jnp.int32)
    page = pos // L
    fr = jnp.take_along_axis(jnp.asarray(table, jnp.int32),
                             jnp.clip(page, 0, P - 1), axis=1)
    ok = (active[:, None].astype(bool) & (pos >= 0) & (page < P)
          & (fr >= 0) & (fr < F))
    fr = jnp.where(ok, fr, 0)           # safe gather index; DROP via tgt
    byte, odd = (pos % L) // 2, (pos % 2).astype(bool)
    for parity in (False, True):
        m = ok & (odd == parity)
        old = pool[fr, :, jnp.clip(byte, 0, L2 - 1)].astype(jnp.int32)
        c4 = codes.astype(jnp.int32) & 0x0F
        new = jnp.where(odd[..., None, None],
                        (old & 0x0F) | (c4 << 4),
                        (old & ~0x0F) | c4).astype(pool.dtype)
        f_tgt = jnp.where(m, fr, F)
        pool = pool.at[f_tgt, :, byte].set(new, mode="drop")
    return pool


def commit_kv_packed(carrier, count, src, dst):
    """Tree-verify commit on a packed carrier ``[R, KV, S//2, D]``: per
    row, gather the int4 codes at LOGICAL positions ``src[r, i]`` and
    rewrite them at ``dst[r, i]`` for ``i < count[r]`` (the packed twin
    of TreeIncMultiHeadSelfAttention's slot-compaction gather).  The
    gather sign-extends whichever nibble ``src`` selects; the rewrite
    runs the two-pass parity merge so committed neighbours sharing a
    destination byte compose instead of clobbering."""
    def row_fn(car, n, s_idx, d_idx):
        S2 = car.shape[1]
        N = s_idx.shape[0]
        valid = jnp.arange(N, dtype=jnp.int32) < n
        v = car[:, jnp.clip(s_idx // 2, 0, S2 - 1)].astype(jnp.int32)
        code = jnp.where((s_idx % 2).astype(bool)[None, :, None],
                         v >> 4, (v << 28) >> 28)      # sign-extended
        db, odd = d_idx // 2, (d_idx % 2).astype(bool)
        for parity in (False, True):
            m = valid & (odd == parity)
            old = car[:, jnp.clip(db, 0, S2 - 1)].astype(jnp.int32)
            c4 = code & 0x0F
            new = jnp.where(odd[None, :, None],
                            (old & 0x0F) | (c4 << 4),
                            (old & ~0x0F) | c4).astype(car.dtype)
            car = car.at[:, jnp.where(m, db, S2)].set(new, mode="drop")
        return car

    import jax
    return jax.vmap(row_fn)(carrier, count, src, dst)


# ------------------------------------------------- N-d int8 (attention)
def quantize_int8_nd(w: np.ndarray, reduce_axes):
    """Symmetric int8 with scale over the non-reduced (output) axes; q
    keeps w's shape so existing shardings apply unchanged."""
    w = np.asarray(w, np.float32)
    scale = np.abs(w).max(axis=tuple(reduce_axes)) / 127.0
    scale = np.where(scale == 0, 1.0, scale).astype(np.float32)
    expand = scale[(np.newaxis,) * len(reduce_axes)]
    q = np.clip(np.rint(w / expand), -127, 127).astype(np.int8)
    return q, scale


def dequantize_int8_nd(q, scale, dtype):
    expand = scale[(None,) * (q.ndim - scale.ndim)]
    return (q.astype(jnp.float32) * expand).astype(dtype)


def resolve_weight(params: Dict[str, Any], name: str, dtype):
    """Fetch a (possibly quantized) weight for an op forward: dequantizes
    if ``<name>_q`` is present, else returns the plain weight.  Layout is
    recovered from static shapes (traces cleanly under jit): group-wise
    int4 carries a scale of the same rank as q; int8_nd's scale drops the
    reduced leading axes."""
    if name + "_q" in params:
        q = params[name + "_q"]
        scale = params[name + "_scale"]
        if scale.ndim == q.ndim:
            return dequantize_int4_nd(q, scale, dtype,
                                      ATTENTION_INT4_PACK_AXIS[name])
        return dequantize_int8_nd(q, scale, dtype)
    return params[name].astype(dtype)


# attention projections and their input (reduction) axes: wq/wk/wv are
# [E, H, D] (in = E), wo is [H, D, E] (in = H, D) — reference scope
# load_attention_weights_quantized, file_loader.cc:400
ATTENTION_WEIGHTS = {"wq": (0,), "wk": (0,), "wv": (0,), "wo": (0, 1)}
# int4 nibble pairs pack along an unsharded reduction axis (heads shard)
ATTENTION_INT4_PACK_AXIS = {"wq": 0, "wk": 0, "wv": 0, "wo": 1}

SERVING_ATTENTION_TYPES = frozenset({
    OpType.INC_MULTIHEAD_SELF_ATTENTION,
    OpType.SPEC_INC_MULTIHEAD_SELF_ATTENTION,
    OpType.TREE_INC_MULTIHEAD_SELF_ATTENTION,
})


def quantize_model_params(model, mode: Optional[str],
                          skip_layers=()) -> None:
    """Quantize Linear kernels AND attention projections in ``model.params``
    (reference scope: file_loader.cc:400-651 covers both).  Embeddings,
    norms and biases stay full precision.  Attention's 3-D projections
    honor the mode like linear kernels: int8 per-output-channel or int4
    group-wise packed along an unsharded reduction axis.
    """
    if not mode:
        return
    skip = set(skip_layers)
    for layer in model.layers:
        if layer.name in skip:
            continue
        lp = model.params.get(layer.name)
        if lp is None:
            continue
        if layer.op_type is OpType.LINEAR and "kernel" in lp:
            model.params[layer.name] = quantize_linear_params(lp, mode)
        elif layer.op_type in SERVING_ATTENTION_TYPES:
            out = dict(lp)
            for wname, axes in ATTENTION_WEIGHTS.items():
                if wname not in out:
                    continue
                if mode == "int4":
                    q, s = quantize_int4_nd(
                        out.pop(wname), ATTENTION_INT4_PACK_AXIS[wname])
                else:
                    q, s = quantize_int8_nd(out.pop(wname), axes)
                out[wname + "_q"] = q
                out[wname + "_scale"] = s
            model.params[layer.name] = out


def _quantize_int8_nd_device(w, reduce_axes):
    """jnp twin of :func:`quantize_int8_nd` — runs where ``w`` lives (no
    host round trip; essential when init streams a 7B model layer by
    layer over a network-attached chip)."""
    scale = jnp.abs(w).max(axis=tuple(reduce_axes)) / 127.0
    scale = jnp.where(scale == 0, 1.0, scale).astype(jnp.float32)
    expand = scale[(jnp.newaxis,) * len(reduce_axes)]
    q = jnp.clip(jnp.rint(w / expand), -127, 127).astype(jnp.int8)
    return q, scale


def init_quantized_params(model, mode: str = "int8", seed: int = 0,
                          dtype=None) -> None:
    """Random-init ``model.params`` directly in int8, one layer at a
    time, entirely ON DEVICE: the full-precision tensor exists only
    transiently per layer, so models whose f32 weights exceed HBM (e.g.
    7B on one 16 GB chip) can still be built for benchmarking/serving
    without a checkpoint.  Non-quantizable params (norms, biases,
    embeddings) init at ``dtype`` (default: the model's computation
    dtype)."""
    import jax

    assert mode == "int8", "on-device init supports int8 (int4 packing " \
                           "is a host-side checkpoint-load path)"
    cdt = jnp.dtype(dtype or model.config.computation_dtype)
    rng = jax.random.PRNGKey(seed)
    model.params = {}
    for layer in model.layers:
        if not layer.param_specs:
            continue
        lp = {}
        for ps in layer.param_specs:
            rng, sub = jax.random.split(rng)
            lp[ps.name] = ps.initializer(sub, ps.shape, jnp.float32,
                                         fans=ps.fans)
        if layer.op_type is OpType.LINEAR and "kernel" in lp:
            q, s = _quantize_int8_nd_device(lp.pop("kernel"), (0,))
            lp["kernel_q"], lp["kernel_scale"] = q, s
        elif layer.op_type in SERVING_ATTENTION_TYPES:
            for wname, axes in ATTENTION_WEIGHTS.items():
                if wname not in lp:
                    continue
                q, s = _quantize_int8_nd_device(lp.pop(wname), axes)
                lp[wname + "_q"], lp[wname + "_scale"] = q, s
        # cast the leftovers (norm weights, biases, embeddings; scales
        # stay f32 by the quantizers' convention)
        lp = {n: (v if n.endswith(("_q", "_scale")) else v.astype(cdt))
              for n, v in lp.items()}
        # materialize now so the transient f32 frees before the next layer
        lp = {n: jax.block_until_ready(v) for n, v in lp.items()}
        model.params[layer.name] = lp


def extend_quantized_pspecs(pspecs, params):
    """Give quantized params the shardings of the weights they replace
    (``x_q`` inherits x's spec; ``x_scale`` takes the trailing axes of x's
    spec matching its rank — the reduced leading axes are gone)."""
    from jax.sharding import PartitionSpec

    out = {}
    for ln, lspec in pspecs.items():
        lp = params.get(ln, {})
        new = dict(lspec)
        for pname, arr in lp.items():
            if pname in new:
                continue
            if pname.endswith("_q"):
                new[pname] = lspec[pname[:-2]]
            elif pname.endswith("_scale"):
                base = tuple(lspec[pname[:-6]])
                nd = getattr(arr, "ndim", len(np.shape(arr)))
                new[pname] = PartitionSpec(*base[len(base) - nd:])
        out[ln] = new
    return out
