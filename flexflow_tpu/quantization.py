"""Weight-only quantization (int8 / int4).

TPU-native re-design of the reference's quantization support
(``--4bit-quantization``/``--8bit-quantization``: FileDataLoader's
``load_attention_weights_quantized`` / ``load_quantization_weight``
inference/file_loader.cc:400-651 + on-GPU decompression
src/ops/kernels/decompress_kernels.cu).  There the quantized weights are
decompressed by hand-written kernels before each GEMM; here the dequant is
expressed in jnp inside the op's forward and XLA fuses it into the matmul's
operand load — weights stay int8/int4-packed in HBM, halving/quartering
weight bandwidth, which is what matters for serving (decode is
weight-bandwidth-bound).

Layouts:
- int8: symmetric per-output-channel. kernel_q int8 [in, out],
  kernel_scale f32 [out].
- int4: symmetric group-wise along the in dim (group=64 like the
  reference's GROUP_SIZE). Two values pack per int8 byte: kernel_q int8
  [in//2, out] (low nibble = even row, high nibble = odd row),
  kernel_scale f32 [in//group, out].
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from .fftype import OpType

INT4_GROUP = 64


# ------------------------------------------------------------------- int8
def quantize_int8(w: np.ndarray):
    """w [in, out] -> (q int8 [in, out], scale f32 [out])."""
    w = np.asarray(w, np.float32)
    scale = np.abs(w).max(axis=0) / 127.0
    scale = np.where(scale == 0, 1.0, scale).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize_int8(q, scale, dtype):
    return (q.astype(jnp.float32) * scale[None, :]).astype(dtype)


# ------------------------------------------------------------------- int4
def quantize_int4(w: np.ndarray, group: int = INT4_GROUP):
    """w [in, out] -> (packed int8 [in//2, out], scale f32 [in//g, out])."""
    w = np.asarray(w, np.float32)
    in_dim, out = w.shape
    assert in_dim % 2 == 0, "int4 packing needs an even in_dim"
    g = min(group, in_dim)
    while in_dim % g:
        g //= 2
    wg = w.reshape(in_dim // g, g, out)
    scale = np.abs(wg).max(axis=1) / 7.0
    scale = np.where(scale == 0, 1.0, scale).astype(np.float32)
    q = np.clip(np.rint(wg / scale[:, None, :]), -8, 7).astype(np.int8)
    q = q.reshape(in_dim, out)
    lo = q[0::2] & 0x0F
    hi = (q[1::2] & 0x0F) << 4
    return (lo | hi).astype(np.int8), scale


def dequantize_int4(packed, scale, dtype, in_dim: int):
    lo = (packed << 4).astype(jnp.int8) >> 4           # sign-extend low
    hi = packed.astype(jnp.int8) >> 4                  # arithmetic shift
    q = jnp.stack([lo, hi], axis=1).reshape(in_dim, packed.shape[-1])
    g = in_dim // scale.shape[0]
    deq = (q.reshape(scale.shape[0], g, -1).astype(jnp.float32)
           * scale[:, None, :])
    return deq.reshape(in_dim, -1).astype(dtype)


# --------------------------------------------------------------- param tree
def quantize_linear_params(lparams: Dict[str, Any], mode: str
                           ) -> Dict[str, Any]:
    """Quantize one linear layer's params in-place-style (bias untouched)."""
    w = np.asarray(lparams["kernel"], np.float32)
    out = {k: v for k, v in lparams.items() if k != "kernel"}
    if mode == "int8":
        q, s = quantize_int8(w)
    elif mode == "int4":
        q, s = quantize_int4(w)
    else:
        raise ValueError(f"unknown quantization mode {mode!r}")
    out["kernel_q"] = q
    out["kernel_scale"] = s
    return out


def dequantize_kernel(params: Dict[str, Any], dtype):
    """Used by the Linear op when it sees quantized params; the layout
    (int8 vs packed int4) is recovered from static shapes so this traces
    cleanly under jit."""
    scale = params["kernel_scale"]
    q = params["kernel_q"]
    if scale.ndim == 1:
        return dequantize_int8(q, scale, dtype)
    return dequantize_int4(q, scale, dtype, q.shape[0] * 2)


# ------------------------------------------------- N-d int8 (attention)
def quantize_int8_nd(w: np.ndarray, reduce_axes):
    """Symmetric int8 with scale over the non-reduced (output) axes; q
    keeps w's shape so existing shardings apply unchanged."""
    w = np.asarray(w, np.float32)
    scale = np.abs(w).max(axis=tuple(reduce_axes)) / 127.0
    scale = np.where(scale == 0, 1.0, scale).astype(np.float32)
    expand = scale[(np.newaxis,) * len(reduce_axes)]
    q = np.clip(np.rint(w / expand), -127, 127).astype(np.int8)
    return q, scale


def dequantize_int8_nd(q, scale, dtype):
    expand = scale[(None,) * (q.ndim - scale.ndim)]
    return (q.astype(jnp.float32) * expand).astype(dtype)


def resolve_weight(params: Dict[str, Any], name: str, dtype):
    """Fetch a (possibly quantized) weight for an op forward: dequantizes
    if ``<name>_q`` is present, else returns the plain weight."""
    if name + "_q" in params:
        return dequantize_int8_nd(params[name + "_q"],
                                  params[name + "_scale"], dtype)
    return params[name].astype(dtype)


# attention projections and their input (reduction) axes: wq/wk/wv are
# [E, H, D] (in = E), wo is [H, D, E] (in = H, D) — reference scope
# load_attention_weights_quantized, file_loader.cc:400
ATTENTION_WEIGHTS = {"wq": (0,), "wk": (0,), "wv": (0,), "wo": (0, 1)}

SERVING_ATTENTION_TYPES = frozenset({
    OpType.INC_MULTIHEAD_SELF_ATTENTION,
    OpType.SPEC_INC_MULTIHEAD_SELF_ATTENTION,
    OpType.TREE_INC_MULTIHEAD_SELF_ATTENTION,
})


def quantize_model_params(model, mode: Optional[str],
                          skip_layers=()) -> None:
    """Quantize Linear kernels AND attention projections in ``model.params``
    (reference scope: file_loader.cc:400-651 covers both).  Embeddings,
    norms and biases stay full precision.  Attention's 3-D projections use
    per-output-channel int8 even under mode="int4" (nibble packing is
    defined on the 2-D linear layout); linear kernels honor the mode.
    """
    if not mode:
        return
    skip = set(skip_layers)
    for layer in model.layers:
        if layer.name in skip:
            continue
        lp = model.params.get(layer.name)
        if lp is None:
            continue
        if layer.op_type is OpType.LINEAR and "kernel" in lp:
            model.params[layer.name] = quantize_linear_params(lp, mode)
        elif layer.op_type in SERVING_ATTENTION_TYPES:
            out = dict(lp)
            for wname, axes in ATTENTION_WEIGHTS.items():
                if wname not in out:
                    continue
                q, s = quantize_int8_nd(out.pop(wname), axes)
                out[wname + "_q"] = q
                out[wname + "_scale"] = s
            model.params[layer.name] = out


def extend_quantized_pspecs(pspecs, params):
    """Give quantized params the shardings of the weights they replace
    (``x_q`` inherits x's spec; ``x_scale`` takes the trailing axes of x's
    spec matching its rank — the reduced leading axes are gone)."""
    from jax.sharding import PartitionSpec

    out = {}
    for ln, lspec in pspecs.items():
        lp = params.get(ln, {})
        new = dict(lspec)
        for pname, arr in lp.items():
            if pname in new:
                continue
            if pname.endswith("_q"):
                new[pname] = lspec[pname[:-2]]
            elif pname.endswith("_scale"):
                base = tuple(lspec[pname[:-6]])
                nd = getattr(arr, "ndim", len(np.shape(arr)))
                new[pname] = PartitionSpec(*base[len(base) - nd:])
        out[ln] = new
    return out
