"""flexflow_tpu: a TPU-native distributed DL framework.

Brand-new implementation of the capabilities of the reference FlexFlow
(Legion/CUDA auto-parallelizing training + SpecInfer LLM serving), designed
TPU-first: JAX/XLA/Pallas for compute, GSPMD sharding over `jax.sharding.Mesh`
for parallelism, ICI collectives instead of NCCL.  See SURVEY.md at the repo
root for the structural map of the reference this build follows.
"""

from .config import (AXIS_DATA, AXIS_EXPERT, AXIS_MODEL, AXIS_PIPE, AXIS_SEQ,
                     FFConfig)
from .core.initializers import (ConstantInitializer, GlorotUniform,
                                NormInitializer, UniformInitializer,
                                ZeroInitializer)
from .core.model import FFModel, Model
from .core.tensor import ParallelDim, ParallelTensorShape, Tensor, TensorSpec
from .fftype import (ActiMode, AggrMode, DataType, InferenceMode, LossType,
                     MetricsType, OpType, ParameterSyncType, PoolType)
from .training.checkpoint import CheckpointManager
from .training.dataloader import DataLoaderGroup, SingleDataLoader
from .training.losses import compute_loss
from .training.metrics import PerfMetrics
from .training.optimizer import AdamOptimizer, Optimizer, SGDOptimizer

__version__ = "0.1.0"
