"""StarCoder (GPT-BigCode) graph builder for serving.

TPU-native re-design of the reference's StarCoder builder
(inference/models/starcoder.cc:40-220 create_starcoder_model; Python twin
python/flexflow/serve/models/starcoder.py).  Layer recipe:

  wte + wpe -> N x [ ln_1 -> mqa(1 kv head, qkv bias) -> ln_2 ->
                     c_fc -> gelu -> c_proj ]
  -> ln_f -> lm_head (tied) -> sampling head

Divergence from the reference: the attention out-projection bias
(c_proj.bias) is kept (final_bias=True) — the reference drops it
(starcoder.cc passes final_bias=false), which misaligns with HF by a
constant per layer; we match HF `GPTBigCodeForCausalLM` exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np

from ..core.model import Model
from ..fftype import DataType, InferenceMode
from ..serving.request_manager import GenerationConfig
from .llama import _finish_serving_graph, _np_of, hf_get


@dataclasses.dataclass
class STARCODERConfig:
    """Mirrors inference/models/starcoder.h startcoder_config."""

    vocab_size: int = 49152
    hidden_size: int = 6144
    num_attention_heads: int = 48
    num_hidden_layers: int = 40
    intermediate_size: int = 24576
    max_position_embeddings: int = 8192
    layer_norm_epsilon: float = 1e-5
    dropout_p: float = 0.0
    bos_token_id: int = 0
    eos_token_id: int = 0

    @classmethod
    def from_hf(cls, hf) -> "STARCODERConfig":
        get = hf_get(hf)
        # builder/converter assume the GPTBigCode MQA layout (1 KV head,
        # c_attn packed [E + 2*D, E]); reject the multi-head variant early
        # rather than failing with an opaque reshape error mid-convert
        if get("multi_query", True) is False:
            raise NotImplementedError(
                "GPTBigCode multi_query=False checkpoints are not supported")
        hidden = get("n_embd", None) or get("hidden_size", 6144)
        return cls(
            vocab_size=get("vocab_size", 49152),
            hidden_size=hidden,
            num_attention_heads=get("n_head", None)
            or get("num_attention_heads", 48),
            num_hidden_layers=get("n_layer", None)
            or get("num_hidden_layers", 40),
            intermediate_size=get("n_inner", None) or 4 * hidden,
            max_position_embeddings=get("n_positions", None)
            or get("max_position_embeddings", 8192),
            layer_norm_epsilon=get("layer_norm_epsilon", 1e-5),
            dropout_p=get("attn_pdrop", 0.0),
            bos_token_id=get("bos_token_id", 0),
            eos_token_id=get("eos_token_id", 0),
        )


def create_starcoder_model(
        model: Model, config: STARCODERConfig,
        mode: InferenceMode = InferenceMode.INC_DECODING,
        generation_config: Optional[GenerationConfig] = None,
        max_requests: int = 8, chunk: int = 1,
        dtype: DataType = DataType.FLOAT) -> Model:
    """Build the serving graph (reference: inference/models/starcoder.cc:40).

    The reference only wires INC_DECODING for StarCoder (starcoder.cc mode
    switch has a single case); we do the same.
    """
    c = config
    if mode is not InferenceMode.INC_DECODING:
        raise NotImplementedError(
            "StarCoder supports incremental decoding only (the reference's "
            "mode switch is identical, starcoder.cc:100-130)")

    tokens = model.create_tensor((max_requests, chunk), DataType.INT32,
                                 name="tokens")
    positions = model.create_tensor((max_requests, chunk), DataType.INT32,
                                    name="positions")
    token = model.embedding(tokens, c.vocab_size, c.hidden_size, dtype=dtype,
                            name="transformer_wte")
    pos_emb = model.embedding(positions, c.max_position_embeddings,
                              c.hidden_size, dtype=dtype,
                              name="transformer_wpe")

    hidden_states, c_proj = token, pos_emb
    for i in range(c.num_hidden_layers):
        model.current_transformer_layer_id = i
        pfx = f"layers_{i}"
        ln_1, hidden_states = model.residual_layer_norm(
            hidden_states, c_proj, eps=c.layer_norm_epsilon,
            name=f"{pfx}_ln_1")

        mha = model.inc_multiquery_self_attention(
            ln_1, c.hidden_size, c.num_attention_heads, 1,
            dropout=c.dropout_p, qkv_bias=True, final_bias=True,
            apply_rotary_embedding=False, name=f"{pfx}_attention")

        ln_2, hidden_states = model.residual_layer_norm(
            hidden_states, mha, eps=c.layer_norm_epsilon,
            name=f"{pfx}_ln_2")

        c_fc = model.dense(ln_2, c.intermediate_size, name=f"{pfx}_mlp_c_fc")
        model.layers[-1].attrs["shard"] = "col"
        act = model.gelu(c_fc, name=f"{pfx}_mlp_gelu")
        c_proj = model.dense(act, c.hidden_size, name=f"{pfx}_mlp_c_proj")
        model.layers[-1].attrs["shard"] = "row"

    model.current_transformer_layer_id = -1
    final_norm, _ = model.residual_layer_norm(
        hidden_states, c_proj, eps=c.layer_norm_epsilon, name="ln_f")
    _finish_serving_graph(model, final_norm, c.vocab_size, mode,
                          generation_config)
    return model


def convert_hf_state_dict(state_dict: Dict[str, Any],
                          config: STARCODERConfig
                          ) -> Dict[str, Dict[str, np.ndarray]]:
    """HF GPTBigCodeForCausalLM state dict -> framework params.  c_attn is
    fused [E + 2*D, E] (q heads then one shared k and v head)."""
    c = config
    H = c.num_attention_heads
    D = c.hidden_size // H
    E = c.hidden_size
    sd = state_dict
    pre = "transformer."

    p: Dict[str, Dict[str, np.ndarray]] = {}
    p["transformer_wte"] = {"embedding": _np_of(sd[pre + "wte.weight"])}
    p["transformer_wpe"] = {"embedding": _np_of(sd[pre + "wpe.weight"])}
    for i in range(c.num_hidden_layers):
        hf = f"{pre}h.{i}."
        pfx = f"layers_{i}"
        p[f"{pfx}_ln_1"] = {"weight": _np_of(sd[hf + "ln_1.weight"]),
                            "bias": _np_of(sd[hf + "ln_1.bias"])}
        w = _np_of(sd[hf + "attn.c_attn.weight"])  # [E + 2D, E]
        b = _np_of(sd[hf + "attn.c_attn.bias"])
        wo = _np_of(sd[hf + "attn.c_proj.weight"])  # [E, E]
        p[f"{pfx}_attention"] = {
            "wq": w[:E].reshape(H, D, E).transpose(2, 0, 1),
            "wk": w[E:E + D].reshape(1, D, E).transpose(2, 0, 1),
            "wv": w[E + D:].reshape(1, D, E).transpose(2, 0, 1),
            "wo": wo.reshape(E, H, D).transpose(1, 2, 0),
            "bq": b[:E].reshape(H, D),
            "bk": b[E:E + D].reshape(1, D),
            "bv": b[E + D:].reshape(1, D),
            "bo": _np_of(sd[hf + "attn.c_proj.bias"])}
        p[f"{pfx}_ln_2"] = {"weight": _np_of(sd[hf + "ln_2.weight"]),
                            "bias": _np_of(sd[hf + "ln_2.bias"])}
        p[f"{pfx}_mlp_c_fc"] = {"kernel": _np_of(sd[hf + "mlp.c_fc.weight"]).T,
                                "bias": _np_of(sd[hf + "mlp.c_fc.bias"])}
        p[f"{pfx}_mlp_c_proj"] = {
            "kernel": _np_of(sd[hf + "mlp.c_proj.weight"]).T,
            "bias": _np_of(sd[hf + "mlp.c_proj.bias"])}
    p["ln_f"] = {"weight": _np_of(sd[pre + "ln_f.weight"]),
                 "bias": _np_of(sd[pre + "ln_f.bias"])}
    lm = sd.get("lm_head.weight", sd[pre + "wte.weight"])  # tied
    p["lm_head"] = {"kernel": _np_of(lm).T}
    return p
