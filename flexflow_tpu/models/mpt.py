"""MPT-family graph builder for serving.

TPU-native re-design of the reference's MPT builder
(inference/models/mpt.cc:40-250 create_mpt_model; Python twin
python/flexflow/serve/models/mpt.py).  Layer recipe:

  wte -> N x [ norm_1 (bias-free LN) -> mha(ALiBi position bias, q scaled
          d^-0.5, no qk-prod scaling, no biases) -> norm_2 -> up_proj ->
          gelu -> down_proj ]
  -> norm_f -> lm_head (tied to wte) -> sampling head

MPT has no positional embeddings — attention carries ALiBi bias
(position_bias=True; slopes per inc_multihead_self_attention.cu:304-325).
Covers HF `MptForCausalLM` with no_bias=True.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np

from ..core.model import Model
from ..fftype import DataType, InferenceMode
from ..serving.request_manager import GenerationConfig
from .llama import _finish_serving_graph, _np_of, hf_get


@dataclasses.dataclass
class MPTConfig:
    """Mirrors inference/models/mpt.h mpt_config."""

    vocab_size: int = 50368
    hidden_size: int = 4096
    n_heads: int = 32
    n_layers: int = 32
    bos_token_id: int = 0
    eos_token_id: int = 0

    @classmethod
    def from_hf(cls, hf) -> "MPTConfig":
        get = hf_get(hf)
        # the builder/converter hardcode the bias-free default MPT layout
        # (reference inference/models/mpt.cc likewise only handles it);
        # reject variants that would silently convert to wrong logits
        if get("no_bias", True) is False:
            raise NotImplementedError(
                "MPT variants with biases (no_bias=False) are not supported")
        attn_cfg = get("attn_config", None) or {}
        aget = hf_get(attn_cfg)
        if aget("alibi", True) is False or aget("clip_qkv", None) or \
                aget("qk_ln", False):
            raise NotImplementedError(
                f"unsupported MPT attn_config variant: {attn_cfg}")
        return cls(
            vocab_size=get("vocab_size", 50368),
            hidden_size=get("d_model", None) or get("hidden_size", 4096),
            n_heads=get("n_heads", 32),
            n_layers=get("n_layers", 32),
            bos_token_id=get("bos_token_id", None) or 0,
            eos_token_id=get("eos_token_id", None) or 0,
        )


def create_mpt_model(model: Model, config: MPTConfig,
                     mode: InferenceMode = InferenceMode.INC_DECODING,
                     generation_config: Optional[GenerationConfig] = None,
                     max_requests: int = 8, chunk: int = 1,
                     dtype: DataType = DataType.FLOAT) -> Model:
    """Build the serving graph (reference: inference/models/mpt.cc:40)."""
    c = config
    head_dim = c.hidden_size // c.n_heads

    tokens = model.create_tensor((max_requests, chunk), DataType.INT32,
                                 name="tokens")
    hidden_states = model.embedding(tokens, c.vocab_size, c.hidden_size,
                                    dtype=dtype, name="transformer_wte")

    intermediate_output = None
    for i in range(c.n_layers):
        model.current_transformer_layer_id = i
        pfx = f"layers_{i}"
        if i == 0:
            layernorm_output = model.layer_norm(
                hidden_states, eps=1e-5, use_bias=False,
                name=f"{pfx}_norm_1")
        else:
            layernorm_output, hidden_states = model.residual_layer_norm(
                intermediate_output, hidden_states, eps=1e-5, use_bias=False,
                name=f"{pfx}_norm_1")

        attn_kw = dict(kdim=head_dim, vdim=head_dim, qkv_bias=False,
                       final_bias=False, apply_rotary_embedding=False,
                       scaling_query=True, scaling_factor=head_dim ** -0.5,
                       qk_prod_scaling=False, position_bias=True,
                       name=f"{pfx}_attention")
        attn_outputs = model.serving_self_attention(
            mode, layernorm_output, c.hidden_size, c.n_heads, **attn_kw)

        layernorm_output, hidden_states = model.residual_layer_norm(
            attn_outputs, hidden_states, eps=1e-5, use_bias=False,
            name=f"{pfx}_norm_2")

        up = model.dense(layernorm_output, 4 * c.hidden_size, use_bias=False,
                         name=f"{pfx}_ffn_up_proj")
        model.layers[-1].attrs["shard"] = "col"
        act = model.gelu(up, name=f"{pfx}_ffn_gelu")
        intermediate_output = model.dense(act, c.hidden_size, use_bias=False,
                                          name=f"{pfx}_ffn_down_proj")
        model.layers[-1].attrs["shard"] = "row"

    model.current_transformer_layer_id = -1
    final_norm, _ = model.residual_layer_norm(
        intermediate_output, hidden_states, eps=1e-5, use_bias=False,
        name="transformer_norm_f")
    _finish_serving_graph(model, final_norm, c.vocab_size, mode,
                          generation_config)
    return model


def convert_hf_state_dict(state_dict: Dict[str, Any],
                          config: MPTConfig) -> Dict[str, Dict[str, np.ndarray]]:
    """HF MptForCausalLM state dict -> framework params.  MPT packs qkv as
    fused Wqkv [3*E, E]."""
    c = config
    H = c.n_heads
    D = c.hidden_size // H
    E = c.hidden_size
    sd = state_dict
    pre = "transformer."

    p: Dict[str, Dict[str, np.ndarray]] = {}
    p["transformer_wte"] = {"embedding": _np_of(sd[pre + "wte.weight"])}
    for i in range(c.n_layers):
        hf = f"{pre}blocks.{i}."
        pfx = f"layers_{i}"
        p[f"{pfx}_norm_1"] = {"weight": _np_of(sd[hf + "norm_1.weight"])}
        qkv = _np_of(sd[hf + "attn.Wqkv.weight"])  # [3E, E]
        wq, wk, wv = qkv[:E], qkv[E:2 * E], qkv[2 * E:]
        wo = _np_of(sd[hf + "attn.out_proj.weight"])  # [E, E]
        p[f"{pfx}_attention"] = {
            "wq": wq.reshape(H, D, E).transpose(2, 0, 1),
            "wk": wk.reshape(H, D, E).transpose(2, 0, 1),
            "wv": wv.reshape(H, D, E).transpose(2, 0, 1),
            "wo": wo.reshape(E, H, D).transpose(1, 2, 0)}
        p[f"{pfx}_norm_2"] = {"weight": _np_of(sd[hf + "norm_2.weight"])}
        p[f"{pfx}_ffn_up_proj"] = {
            "kernel": _np_of(sd[hf + "ffn.up_proj.weight"]).T}
        p[f"{pfx}_ffn_down_proj"] = {
            "kernel": _np_of(sd[hf + "ffn.down_proj.weight"]).T}
    p["transformer_norm_f"] = {"weight": _np_of(sd[pre + "norm_f.weight"])}
    # MPT always ties lm_head to wte
    p["lm_head"] = {"kernel": _np_of(sd[pre + "wte.weight"]).T}
    return p
