"""Falcon-family graph builder for serving.

TPU-native re-design of the reference's Falcon builder
(inference/models/falcon.cc:40-240 create_falcon_model; Python twin
python/flexflow/serve/models/falcon.py).  Layer recipe (parallel-attention
decoder):

  word_embeddings
  -> N x [ input_layernorm (folding in the PREVIOUS block's mha+mlp
           residuals, falcon.cc:78-92) -> { mqa(+RoPE) || dense_h_to_4h
           -> gelu -> dense_4h_to_h } ]   (attention and MLP both read the
           norm output — Falcon's parallel_attn block)
  -> final residual_layer_norm(token, mha, mlp) -> lm_head -> sampling

Covers HF `FalconForCausalLM` with parallel_attn=True (7B-style MQA and
grouped-KV variants via n_head_kv).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np

from ..core.model import Model
from ..fftype import DataType, InferenceMode
from ..serving.request_manager import GenerationConfig
from .llama import _finish_serving_graph, _np_of, hf_get


@dataclasses.dataclass
class FalconConfig:
    """Mirrors inference/models/falcon.h falcon_config."""

    vocab_size: int = 65024
    hidden_size: int = 4544
    n_head: int = 71
    n_head_kv: int = 1
    n_layer: int = 32
    layer_norm_epsilon: float = 1e-5
    rope_theta: float = 10000.0
    # Falcon-40B/180B style: separate ln_attn/ln_mlp per block (HF
    # new_decoder_architecture).  The reference builder only covers the
    # single-input_layernorm 7B form; we support both.
    new_decoder_architecture: bool = False
    # fused-qkv layout discriminator (old architecture): True = flat
    # [q-heads | k | v] MQA packing, False = per-head-interleaved MHA
    multi_query: bool = True
    bos_token_id: int = 11
    eos_token_id: int = 11

    @classmethod
    def from_hf(cls, hf) -> "FalconConfig":
        get = hf_get(hf)
        if get("alibi", False):
            raise NotImplementedError(
                "ALiBi Falcon variants (falcon-rw) are not supported — the "
                "reference builder likewise hardcodes RoPE "
                "(falcon.cc apply_rotary_embedding=true)")
        if not get("parallel_attn", True):
            raise NotImplementedError(
                "sequential-attention Falcon variants (parallel_attn=False) "
                "are not supported — the reference builds the parallel "
                "block only (falcon.cc:78-205)")
        n_head = get("num_attention_heads", None) or get("n_head", 71)
        # HF encodes MQA as multi_query=True (new_decoder_architecture
        # uses num_kv_heads); the reference reads n_head_kv the same way
        if get("new_decoder_architecture", False):
            n_head_kv = get("num_kv_heads", None) or get("n_head_kv", n_head)
        elif get("multi_query", True):
            n_head_kv = 1
        else:
            n_head_kv = n_head
        return cls(
            multi_query=get("multi_query", True),
            vocab_size=get("vocab_size", 65024),
            hidden_size=get("hidden_size", 4544),
            n_head=n_head,
            n_head_kv=n_head_kv,
            n_layer=get("num_hidden_layers", None) or get("n_layer", 32),
            layer_norm_epsilon=get("layer_norm_epsilon", 1e-5),
            rope_theta=get("rope_theta", 10000.0),
            new_decoder_architecture=get("new_decoder_architecture", False),
            bos_token_id=get("bos_token_id", 11),
            eos_token_id=get("eos_token_id", 11),
        )


def create_falcon_model(model: Model, config: FalconConfig,
                        mode: InferenceMode = InferenceMode.INC_DECODING,
                        generation_config: Optional[GenerationConfig] = None,
                        max_requests: int = 8, chunk: int = 1,
                        dtype: DataType = DataType.FLOAT) -> Model:
    """Build the serving graph (reference: inference/models/falcon.cc:40)."""
    c = config
    head_dim = c.hidden_size // c.n_head

    tokens = model.create_tensor((max_requests, chunk), DataType.INT32,
                                 name="tokens")
    token = model.embedding(tokens, c.vocab_size, c.hidden_size, dtype=dtype,
                            name="word_embeddings")

    mha = mlp_output = None
    for i in range(c.n_layer):
        model.current_transformer_layer_id = i
        pfx = f"layers_{i}"
        if i == 0:
            pass  # token is already the residual stream
        elif c.new_decoder_architecture:
            token = model.add(model.add(token, mha, name=f"{pfx}_res_attn"),
                              mlp_output, name=f"{pfx}_res_mlp")
        if c.new_decoder_architecture:
            att_norm = model.layer_norm(token, eps=c.layer_norm_epsilon,
                                        name=f"{pfx}_ln_attn")
            mlp_norm = model.layer_norm(token, eps=c.layer_norm_epsilon,
                                        name=f"{pfx}_ln_mlp")
        elif i == 0:
            att_norm = model.layer_norm(token, eps=c.layer_norm_epsilon,
                                        name=f"{pfx}_input_layernorm")
            mlp_norm = att_norm
        else:
            # (normed, residual_sum): norm feeds attention+MLP, the sum is
            # the running residual stream (falcon.cc:78-92)
            att_norm, token = model.residual_layer_norm(
                token, mha, mlp_output, use_two_residuals=True,
                eps=c.layer_norm_epsilon, name=f"{pfx}_input_layernorm")
            mlp_norm = att_norm

        attn_kw = dict(kdim=head_dim, vdim=head_dim, qkv_bias=False,
                       final_bias=False, apply_rotary_embedding=True,
                       rope_theta=c.rope_theta, name=f"{pfx}_attention")
        mha = model.serving_self_attention(
            mode, att_norm, c.hidden_size, c.n_head, c.n_head_kv,
            **attn_kw)

        h4 = model.dense(mlp_norm, 4 * c.hidden_size, use_bias=False,
                         name=f"{pfx}_mlp_dense_h_to_4h")
        model.layers[-1].attrs["shard"] = "col"
        act = model.gelu(h4, name=f"{pfx}_mlp_gelu")
        mlp_output = model.dense(act, c.hidden_size, use_bias=False,
                                 name=f"{pfx}_mlp_dense_4h_to_h")
        model.layers[-1].attrs["shard"] = "row"

    model.current_transformer_layer_id = -1
    if c.n_layer == 0:
        final_norm = model.layer_norm(token, eps=c.layer_norm_epsilon,
                                      name="ln_f")
    else:
        final_norm, _ = model.residual_layer_norm(
            token, mha, mlp_output, use_two_residuals=True,
            eps=c.layer_norm_epsilon, name="ln_f")
    _finish_serving_graph(model, final_norm, c.vocab_size, mode,
                          generation_config)
    return model


def convert_hf_state_dict(state_dict: Dict[str, Any],
                          config: FalconConfig) -> Dict[str, Dict[str, np.ndarray]]:
    """HF FalconForCausalLM state dict -> framework params.

    Falcon packs qkv as fused query_key_value [(H + 2*KV) * D, E]; the
    reference unpacks per-head in FileDataLoader (file_loader.cc:81
    multi-query variant) — here we slice the same layout in numpy.
    """
    c = config
    H, KV = c.n_head, c.n_head_kv
    D = c.hidden_size // H
    E = c.hidden_size
    sd = state_dict
    pre = "transformer."

    p: Dict[str, Dict[str, np.ndarray]] = {}
    p["word_embeddings"] = {
        "embedding": _np_of(sd[pre + "word_embeddings.weight"])}
    for i in range(c.n_layer):
        hf = f"{pre}h.{i}."
        pfx = f"layers_{i}"
        if c.new_decoder_architecture:
            p[f"{pfx}_ln_attn"] = {
                "weight": _np_of(sd[hf + "ln_attn.weight"]),
                "bias": _np_of(sd[hf + "ln_attn.bias"])}
            p[f"{pfx}_ln_mlp"] = {
                "weight": _np_of(sd[hf + "ln_mlp.weight"]),
                "bias": _np_of(sd[hf + "ln_mlp.bias"])}
        else:
            p[f"{pfx}_input_layernorm"] = {
                "weight": _np_of(sd[hf + "input_layernorm.weight"]),
                "bias": _np_of(sd[hf + "input_layernorm.bias"])}
        qkv = _np_of(sd[hf + "self_attention.query_key_value.weight"])
        # layout determined by CONFIG, never by shape (KV == H checkpoints
        # exist in both packings and would silently mis-slice)
        if c.new_decoder_architecture:
            # grouped layout [KV groups x (H/KV q heads + k + v), D, E]
            g = H // KV
            qkv = qkv.reshape(KV, g + 2, D, E)
            wq = qkv[:, :g].reshape(H, D, E)
            wk = qkv[:, g].reshape(KV, D, E)
            wv = qkv[:, g + 1].reshape(KV, D, E)
        elif c.multi_query:
            # flat [q heads | one k | one v]
            wq = qkv[: H * D].reshape(H, D, E)
            wk = qkv[H * D: (H + KV) * D].reshape(KV, D, E)
            wv = qkv[(H + KV) * D:].reshape(KV, D, E)
        else:
            # old MHA: per-head interleaved [H, (q,k,v), D, E]
            qkv = qkv.reshape(H, 3, D, E)
            wq, wk, wv = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        wo = _np_of(sd[hf + "self_attention.dense.weight"])  # [E, H*D]
        p[f"{pfx}_attention"] = {
            "wq": wq.transpose(2, 0, 1), "wk": wk.transpose(2, 0, 1),
            "wv": wv.transpose(2, 0, 1),
            "wo": wo.reshape(E, H, D).transpose(1, 2, 0)}
        p[f"{pfx}_mlp_dense_h_to_4h"] = {
            "kernel": _np_of(sd[hf + "mlp.dense_h_to_4h.weight"]).T}
        p[f"{pfx}_mlp_dense_4h_to_h"] = {
            "kernel": _np_of(sd[hf + "mlp.dense_4h_to_h.weight"]).T}
    p["ln_f"] = {"weight": _np_of(sd[pre + "ln_f.weight"]),
                 "bias": _np_of(sd[pre + "ln_f.bias"])}
    lm = sd.get("lm_head.weight", sd[pre + "word_embeddings.weight"])  # tied
    p["lm_head"] = {"kernel": _np_of(lm).T}
    return p
