"""OPT-family graph builder for serving.

TPU-native re-design of the reference's OPT model builder
(inference/models/opt.cc:23-280 create_opt_model; Python twin
python/flexflow/serve/models/opt.py).  Layer recipe:

  embed_tokens + embed_positions(+2 offset)
  -> N x [ residual_layer_norm -> inc_mha(qkv_bias, q-scaled d^-0.5,
           no qk-prod scaling) -> add_bias_residual_layer_norm
           -> fc1 -> relu -> fc2 ]
  -> final residual_layer_norm -> lm_head (tied) -> sampling head

The out-projection bias lives in the add_bias_residual_layer_norm layer,
exactly like the reference (opt.cc add_bias_residual_layer_norm call).
Covers HF `OPTForCausalLM` with do_layer_norm_before=True (125M..66B).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np

from ..core.model import Model
from ..fftype import DataType, InferenceMode
from ..serving.request_manager import GenerationConfig
from .llama import _finish_serving_graph, _np_of, hf_get


@dataclasses.dataclass
class OPTConfig:
    """Mirrors inference/models/opt.h opt_config (HF config.json fields)."""

    vocab_size: int = 50272
    hidden_size: int = 768
    ffn_dim: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 2048
    layer_norm_elementwise_affine: bool = True
    word_embed_proj_dim: int = 768
    bos_token_id: int = 2
    eos_token_id: int = 2

    @classmethod
    def from_hf(cls, hf) -> "OPTConfig":
        get = hf_get(hf)
        return cls(
            vocab_size=get("vocab_size", 50272),
            hidden_size=get("hidden_size", 768),
            ffn_dim=get("ffn_dim", 3072),
            num_hidden_layers=get("num_hidden_layers", 12),
            num_attention_heads=get("num_attention_heads", 12),
            max_position_embeddings=get("max_position_embeddings", 2048),
            layer_norm_elementwise_affine=get(
                "layer_norm_elementwise_affine", True),
            word_embed_proj_dim=get("word_embed_proj_dim",
                                    get("hidden_size", 768)),
            bos_token_id=get("bos_token_id", 2),
            eos_token_id=get("eos_token_id", 2),
        )


def create_opt_model(model: Model, config: OPTConfig,
                     mode: InferenceMode = InferenceMode.INC_DECODING,
                     generation_config: Optional[GenerationConfig] = None,
                     max_requests: int = 8, chunk: int = 1,
                     dtype: DataType = DataType.FLOAT) -> Model:
    """Build the serving graph (reference: inference/models/opt.cc:23)."""
    c = config
    assert c.word_embed_proj_dim == c.hidden_size, (
        "word_embed_proj_dim != hidden_size (OPT-350M's project_in/out) is "
        "not supported — the reference has the same restriction "
        "(opt.cc adds token and positional embeddings directly)")
    head_dim = c.hidden_size // c.num_attention_heads
    affine = c.layer_norm_elementwise_affine

    tokens = model.create_tensor((max_requests, chunk), DataType.INT32,
                                 name="tokens")
    positions = model.create_tensor((max_requests, chunk), DataType.INT32,
                                    name="positions")
    token = model.embedding(tokens, c.vocab_size, c.hidden_size, dtype=dtype,
                            name="embed_tokens")
    # reference: ff.set_position_offset(2) — HF OPT looks positions up at +2
    pos_emb = model.embedding(positions, c.max_position_embeddings + 2,
                              c.hidden_size, dtype=dtype, input_offset=2,
                              name="embed_positions")

    added, fc2 = token, pos_emb
    for i in range(c.num_hidden_layers):
        model.current_transformer_layer_id = i
        pfx = f"layers_{i}"
        hidden, residual = model.residual_layer_norm(
            added, fc2, elementwise_affine=affine, eps=1e-5,
            name=f"{pfx}_attention_layer_norm")

        mha = model.serving_self_attention(
            mode, hidden, c.hidden_size, c.num_attention_heads,
            qkv_bias=True, final_bias=False, apply_rotary_embedding=False,
            scaling_query=True, scaling_factor=head_dim ** -0.5,
            qk_prod_scaling=False, name=f"{pfx}_attention")

        # (normed, sum): norm feeds the FFN, the bias+residual sum is the
        # running stream (reference opt.cc: added=outputs[0]=sum there)
        ffn_in, added = model.add_bias_residual_layer_norm(
            mha, residual, elementwise_affine=affine, eps=1e-5,
            name=f"{pfx}_add_bias_residual_layer_norm")
        fc1 = model.dense(ffn_in, c.ffn_dim, name=f"{pfx}_fc1")
        act = model.relu(fc1, name=f"{pfx}_relu")
        fc2 = model.dense(act, c.hidden_size, name=f"{pfx}_fc2")
        model.layers[-1].attrs["shard"] = "row"
        model.layers[-3].attrs["shard"] = "col"

    model.current_transformer_layer_id = -1
    final_norm, _ = model.residual_layer_norm(
        added, fc2, elementwise_affine=affine, eps=1e-5,
        name="final_layer_norm")
    _finish_serving_graph(model, final_norm, c.vocab_size, mode,
                          generation_config)
    return model


def convert_hf_state_dict(state_dict: Dict[str, Any],
                          config: OPTConfig) -> Dict[str, Dict[str, np.ndarray]]:
    """HF OPTForCausalLM state dict -> framework params (reference analogue:
    serve/models/opt.py convert_hf_model)."""
    c = config
    H = c.num_attention_heads
    D = c.hidden_size // H
    E = c.hidden_size
    sd = state_dict
    pre = "model.decoder."

    p: Dict[str, Dict[str, np.ndarray]] = {}
    p["embed_tokens"] = {"embedding": _np_of(sd[pre + "embed_tokens.weight"])}
    p["embed_positions"] = {
        "embedding": _np_of(sd[pre + "embed_positions.weight"])}
    for i in range(c.num_hidden_layers):
        hf = f"{pre}layers.{i}."
        pfx = f"layers_{i}"
        p[f"{pfx}_attention_layer_norm"] = {
            "weight": _np_of(sd[hf + "self_attn_layer_norm.weight"]),
            "bias": _np_of(sd[hf + "self_attn_layer_norm.bias"])}
        wq = _np_of(sd[hf + "self_attn.q_proj.weight"])  # [H*D, E]
        wk = _np_of(sd[hf + "self_attn.k_proj.weight"])
        wv = _np_of(sd[hf + "self_attn.v_proj.weight"])
        wo = _np_of(sd[hf + "self_attn.out_proj.weight"])  # [E, H*D]
        p[f"{pfx}_attention"] = {
            "wq": wq.reshape(H, D, E).transpose(2, 0, 1),
            "wk": wk.reshape(H, D, E).transpose(2, 0, 1),
            "wv": wv.reshape(H, D, E).transpose(2, 0, 1),
            "wo": wo.reshape(E, H, D).transpose(1, 2, 0),
            "bq": _np_of(sd[hf + "self_attn.q_proj.bias"]).reshape(H, D),
            "bk": _np_of(sd[hf + "self_attn.k_proj.bias"]).reshape(H, D),
            "bv": _np_of(sd[hf + "self_attn.v_proj.bias"]).reshape(H, D),
        }
        # out_proj bias folds into the fused add+norm (opt.cc semantics)
        p[f"{pfx}_add_bias_residual_layer_norm"] = {
            "attn_bias": _np_of(sd[hf + "self_attn.out_proj.bias"]),
            "weight": _np_of(sd[hf + "final_layer_norm.weight"]),
            "bias": _np_of(sd[hf + "final_layer_norm.bias"])}
        p[f"{pfx}_fc1"] = {"kernel": _np_of(sd[hf + "fc1.weight"]).T,
                           "bias": _np_of(sd[hf + "fc1.bias"])}
        p[f"{pfx}_fc2"] = {"kernel": _np_of(sd[hf + "fc2.weight"]).T,
                           "bias": _np_of(sd[hf + "fc2.bias"])}
    p["final_layer_norm"] = {
        "weight": _np_of(sd[pre + "final_layer_norm.weight"]),
        "bias": _np_of(sd[pre + "final_layer_norm.bias"])}
    lm = sd.get("lm_head.weight", sd[pre + "embed_tokens.weight"])  # tied
    p["lm_head"] = {"kernel": _np_of(lm).T}
    return p
