"""Serving model zoo (reference: inference/models/ + python/flexflow/serve/models/)."""

from . import falcon  # noqa: F401
from . import llama  # noqa: F401
from . import mpt  # noqa: F401
from . import opt  # noqa: F401
from . import starcoder  # noqa: F401
