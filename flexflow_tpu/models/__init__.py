"""Serving model zoo (reference: inference/models/ + python/flexflow/serve/models/)."""

from . import llama  # noqa: F401
