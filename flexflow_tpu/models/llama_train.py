"""LLaMA training path with dp x pp x sp x tp sharding — the flagship
multi-chip training configuration.

The reference trains transformers through the generic FFModel path with
Unity-searched or data-parallel MachineViews (SURVEY.md §2.3); pipeline
parallelism exists only for inference and sequence parallelism not at all
(SURVEY.md §5).  This module is the TPU-native superset: one jitted train
step over a (dp, pp, sp, tp) `jax.sharding.Mesh` where

- dp  shards the (micro)batch dim — gradient psum inserted by GSPMD
  (replacing the reference's NCCL optimizer path, optimizer.h:59-76);
- pp  runs the stacked decoder blocks through the GPipe shard_map schedule
  (flexflow_tpu/parallel/pipeline.py — replacing per-stage MachineViews,
  graph.cc:2016);
- tp  shards attention heads and FFN hidden dim, Megatron-style, via
  NamedShardings on the weights (replacing the Replicate/AllReduce insertion
  rules, model.cc:3243-3296);
- sp  shards the sequence dim of activations between blocks (new vs the
  reference) — norms/residuals run sequence-sharded; attention gathers
  heads-first (ring attention supersedes this on the long-context path,
  flexflow_tpu/ops/ring_attention.py).

Weights use the same [E, H, D] / [H, D, E] layouts as the serving builder
(models/llama.py convert_hf_state_dict), so HF checkpoints load into either.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..config import (AXIS_DATA, AXIS_MODEL, AXIS_PIPE, AXIS_SEQ, FFConfig)
from ..ops.attention_ops import apply_rotary_embedding
from ..ops.norm_ops import _rms as _rms_norm
from ..ops.ring_attention import manual_axis_active, ring_attention
from ..parallel.pipeline import (microbatch, spmd_pipeline,
                                 stack_stage_params, stage_fn_from_blocks,
                                 unmicrobatch)
from ..training.optimizer import AdamOptimizer, Optimizer
from .llama import LLAMAConfig

P = PartitionSpec


@dataclasses.dataclass
class LLaMATrainer:
    """Sharded next-token-prediction training on a LLaMA architecture.

    Not a Model-graph path: this is the hand-sharded flagship configuration
    (the analogue of the reference's examples/cpp/Transformer manual
    strategy), kept separate from the generic layer-graph `Model` the way
    the reference keeps examples' manual parallel strategies separate from
    the Unity search.
    """

    config: LLAMAConfig
    ffconfig: FFConfig
    num_microbatches: int = 1
    optimizer: Optional[Optimizer] = None
    param_dtype: Any = jnp.float32
    # sequence-parallel attention strategy: "ring" keeps the sequence dim
    # sharded through attention (KV blocks rotate over ICI,
    # ops/ring_attention.py); "gather" all-gathers the sequence
    # (Megatron-style) and shards heads instead.  Ring is the long-context
    # path; gather can win at short T where the ring bubble dominates.
    attention_mode: str = "ring"

    def __post_init__(self):
        c, f = self.config, self.ffconfig
        self.dp = f.data_parallelism_degree
        self.pp = f.pipeline_parallelism_degree
        self.sp = f.sequence_parallelism_degree
        self.tp = f.tensor_parallelism_degree
        assert c.num_hidden_layers % self.pp == 0, (
            f"layers {c.num_hidden_layers} % pp {self.pp} != 0")
        assert c.num_attention_heads % self.tp == 0
        assert c.num_key_value_heads % self.tp == 0
        if self.attention_mode not in ("ring", "gather"):
            raise ValueError(f"attention_mode must be 'ring' or 'gather', "
                             f"got {self.attention_mode!r}")
        if self.num_microbatches < 1:
            raise ValueError(f"num_microbatches must be >= 1, got "
                             f"{self.num_microbatches}")
        if f.batch_size % (self.num_microbatches * self.dp):
            raise ValueError(
                f"batch_size {f.batch_size} must divide into "
                f"num_microbatches {self.num_microbatches} x dp {self.dp}")
        self.mesh = f.make_mesh([AXIS_DATA, AXIS_PIPE, AXIS_SEQ, AXIS_MODEL])
        self.optimizer = self.optimizer or AdamOptimizer(alpha=1e-3)
        self._train_step = None
        self.head_dim = c.hidden_size // c.num_attention_heads

    # ------------------------------------------------------------- params
    def param_specs(self) -> Dict[str, Any]:
        tp, pp = AXIS_MODEL, AXIS_PIPE
        block = {
            "attn_norm": P(pp, None, None),
            "wq": P(pp, None, None, tp, None),
            "wk": P(pp, None, None, tp, None),
            "wv": P(pp, None, None, tp, None),
            "wo": P(pp, None, tp, None, None),
            "ffn_norm": P(pp, None, None),
            "w1": P(pp, None, None, tp),
            "w3": P(pp, None, None, tp),
            "w2": P(pp, None, tp, None),
        }
        return {
            "embed": P(None, tp),
            "blocks": block,
            "norm": P(None),
            "lm_head": P(None, tp),
        }

    def init_params(self, rng) -> Dict[str, Any]:
        c = self.config
        E, F, V = c.hidden_size, c.intermediate_size, c.vocab_size
        H, KV, D = c.num_attention_heads, c.num_key_value_heads, self.head_dim
        L = c.num_hidden_layers
        dt = self.param_dtype

        keys = jax.random.split(rng, 8)
        scale = lambda fan_in: 1.0 / np.sqrt(fan_in)

        def init(k, shape, fan_in):
            return (jax.random.normal(k, shape, jnp.float32)
                    * scale(fan_in)).astype(dt)

        layer_params = []
        lkeys = jax.random.split(keys[0], L)
        for i in range(L):
            ks = jax.random.split(lkeys[i], 6)
            layer_params.append({
                "attn_norm": jnp.ones((E,), dt),
                "wq": init(ks[0], (E, H, D), E),
                "wk": init(ks[1], (E, KV, D), E),
                "wv": init(ks[2], (E, KV, D), E),
                "wo": init(ks[3], (H, D, E), H * D),
                "ffn_norm": jnp.ones((E,), dt),
                "w1": init(ks[4], (E, F), E),
                "w3": init(ks[5], (E, F), E),
                "w2": init(jax.random.fold_in(ks[5], 1), (F, E), F),
            })
        params = {
            "embed": init(keys[1], (V, E), E),
            "blocks": stack_stage_params(layer_params, self.pp),
            "norm": jnp.ones((E,), dt),
            "lm_head": init(keys[2], (E, V), E),
        }
        specs = self.param_specs()
        return jax.tree.map(
            lambda v, s: jax.device_put(v, NamedSharding(self.mesh, s)),
            params, specs,
            is_leaf=lambda v: isinstance(v, jnp.ndarray))

    # -------------------------------------------------------------- block
    def _wsc(self, x, spec):
        # inside a shard_map, entries naming manually-bound axes must be
        # dropped (those dims are already local); constraints on the
        # remaining auto axes still apply
        m = jax.sharding.get_abstract_mesh()
        manual = set(getattr(m, "manual_axes", ())) if not m.empty else set()
        if manual:
            spec = P(*[None if e in manual else e for e in spec])
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, spec))

    def _block_fn(self, bp, h):
        """One decoder block; h [mb, T, E] (sp-sharded on T between
        blocks)."""
        c = self.config
        D = self.head_dim
        groups = c.num_attention_heads // c.num_key_value_heads
        T = h.shape[1]  # LOCAL seq block when sp is manually bound (ring)
        if manual_axis_active(AXIS_SEQ):
            pos = jax.lax.axis_index(AXIS_SEQ) * T + jnp.arange(T)
        else:
            pos = jnp.arange(T)

        x = _rms_norm(h, bp["attn_norm"], c.rms_norm_eps)
        q = jnp.einsum("bte,ehd->bthd", x, bp["wq"])
        k = jnp.einsum("bte,ehd->bthd", x, bp["wk"])
        v = jnp.einsum("bte,ehd->bthd", x, bp["wv"])
        # positions [t, 1] broadcast over the heads dim of [b, t, h, d]
        q = apply_rotary_embedding(q, pos[:, None], c.rope_theta)
        k = apply_rotary_embedding(k, pos[:, None], c.rope_theta)
        if self.attention_mode == "ring" and self.sp > 1:
            # sequence stays sharded; KV blocks ride the sp ring (GQA
            # grouping handled inside — kv heads are NOT repeated, so ring
            # traffic is per-kv-head)
            ctxv = ring_attention(q, k, v, mesh=self.mesh, causal=True)
        else:
            if groups > 1:
                k = jnp.repeat(k, groups, axis=2)
                v = jnp.repeat(v, groups, axis=2)
            # heads-sharded attention (sp gathers T here)
            scores = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(D)
            mask = jnp.tril(jnp.ones((T, T), bool))
            scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
            probs = jax.nn.softmax(scores.astype(jnp.float32),
                                   axis=-1).astype(h.dtype)
            ctxv = jnp.einsum("bhts,bshd->bthd", probs, v)
        attn_out = jnp.einsum("bthd,hde->bte", ctxv, bp["wo"])
        h = self._wsc(h + attn_out, P(AXIS_DATA, AXIS_SEQ, None))

        x = _rms_norm(h, bp["ffn_norm"], c.rms_norm_eps)
        gate = jax.nn.silu(jnp.einsum("bte,ef->btf", x, bp["w1"]))
        up = jnp.einsum("bte,ef->btf", x, bp["w3"])
        y = jnp.einsum("btf,fe->bte", gate * up, bp["w2"])
        return self._wsc(h + y, P(AXIS_DATA, AXIS_SEQ, None))

    # --------------------------------------------------------------- step
    def loss_fn(self, params, tokens):
        """Next-token CE over [B, T] int32 tokens."""
        c = self.config
        M = self.num_microbatches
        h = jnp.take(params["embed"], tokens, axis=0)
        h = self._wsc(h, P(AXIS_DATA, AXIS_SEQ, None))
        # the sp ring inside the blocks needs sp bound by the SAME shard_map
        # as pp (shardy forbids nested re-binding)
        ring = self.attention_mode == "ring" and self.sp > 1
        pipe = spmd_pipeline(stage_fn_from_blocks(self._block_fn),
                             num_stages=self.pp, num_microbatches=M,
                             mesh=self.mesh,
                             extra_manual_axes=(AXIS_SEQ,) if ring else (),
                             xs_spec=(P(None, None, AXIS_SEQ, None)
                                      if ring else P()))
        h = unmicrobatch(pipe(params["blocks"], microbatch(h, M)))
        h = _rms_norm(h, params["norm"], c.rms_norm_eps)
        logits = jnp.einsum("bte,ev->btv", h, params["lm_head"])
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        tgt = tokens[:, 1:]
        nll = -jnp.take_along_axis(lp, tgt[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    def train_step(self):
        if self._train_step is not None:
            return self._train_step

        def step(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(self.loss_fn)(params, tokens)
            new_params, new_opt = self.optimizer.update(params, grads,
                                                        opt_state)
            return new_params, new_opt, loss

        self._train_step = jax.jit(step, donate_argnums=(0, 1))
        return self._train_step

    def fit_batch(self, params, opt_state, tokens):
        step = self.train_step()
        tokens = np.asarray(tokens, np.int32)
        if jax.process_count() > 1:
            # multi-controller (DCN) path: every process holds the same
            # full batch; serve each process's addressable shards of the
            # dp-sharded global array from it (a plain jnp.asarray would
            # be a process-local array, which jit over a multi-process
            # mesh rejects) — the reference reaches the same state via
            # mpirun + GASNet bootstrap (MULTI-NODE.md)
            sh = NamedSharding(self.mesh, P(AXIS_DATA))
            arr = jax.make_array_from_callback(
                tokens.shape, sh, lambda idx: tokens[idx])
            return step(params, opt_state, arr)
        return step(params, opt_state, jnp.asarray(tokens, jnp.int32))
