"""LLaMA-family graph builder for serving.

TPU-native re-design of the reference's LLaMA model builder
(inference/models/llama.cc:23-259 create_llama_model) and its Python twin
(python/flexflow/serve/models/llama.py).  Same layer recipe:

  embed -> N x [ (residual_)rms_norm -> {inc|spec|tree}_mqa(+RoPE)
                 -> residual_rms_norm -> w1/w3 -> sigmoid_silu_multi -> w2 ]
  -> final residual norm -> lm_head -> sampling head per mode

plus the HF-checkpoint weight conversion the reference does offline in
python/flexflow/serve/models/llama.py (convert_hf_model) + C++ FileDataLoader
(inference/file_loader.cc:209 TP head sharding — here sharding is a
NamedSharding on the converted arrays, so no layout surgery is needed).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np

from ..core.model import Model
from ..fftype import DataType, InferenceMode
from ..serving.request_manager import GenerationConfig


@dataclasses.dataclass
class LLAMAConfig:
    """Mirrors inference/models/llama.h llama_config (read from HF
    config.json)."""

    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    max_position_embeddings: int = 2048
    bos_token_id: int = 1
    eos_token_id: int = 2

    @classmethod
    def from_hf(cls, hf) -> "LLAMAConfig":
        get = hf_get(hf)
        return cls(
            vocab_size=get("vocab_size", 32000),
            hidden_size=get("hidden_size", 4096),
            intermediate_size=get("intermediate_size", 11008),
            num_hidden_layers=get("num_hidden_layers", 32),
            num_attention_heads=get("num_attention_heads", 32),
            num_key_value_heads=get("num_key_value_heads", None)
            or get("num_attention_heads", 32),
            rms_norm_eps=get("rms_norm_eps", 1e-6),
            rope_theta=get("rope_theta", 10000.0),
            max_position_embeddings=get("max_position_embeddings", 2048),
            bos_token_id=get("bos_token_id", 1),
            eos_token_id=get("eos_token_id", 2),
        )


def hf_get(hf):
    """Accessor over an HF config given as either a dict (parsed
    config.json) or a transformers PretrainedConfig object — shared by every
    model family's ``from_hf``."""
    return (hf.get if isinstance(hf, dict)
            else lambda k, d=None: getattr(hf, k, d))


def create_llama_model(model: Model, config: LLAMAConfig,
                       mode: InferenceMode = InferenceMode.INC_DECODING,
                       generation_config: Optional[GenerationConfig] = None,
                       max_requests: int = 8, chunk: int = 1,
                       dtype: DataType = DataType.FLOAT) -> Model:
    """Build the serving graph (reference: inference/models/llama.cc:23)."""
    c = config
    gen = generation_config or GenerationConfig()
    head_dim = c.hidden_size // c.num_attention_heads

    tokens = model.create_tensor((max_requests, chunk), DataType.INT32,
                                 name="tokens")
    t = model.embedding(tokens, c.vocab_size, c.hidden_size, dtype=dtype,
                        name="embed_tokens")

    for i in range(c.num_hidden_layers):
        model.current_transformer_layer_id = i
        pfx = f"layers_{i}"
        if i == 0:
            attn_in = model.rms_norm(t, eps=c.rms_norm_eps,
                                     name=f"{pfx}_input_layernorm")
            residual = t
        else:
            # fused add+norm (reference llama.cc residual_rms_norm)
            attn_in, residual = model.residual_rms_norm(
                t, residual, eps=c.rms_norm_eps,
                name=f"{pfx}_input_layernorm")

        attn_kw = dict(
            embed_dim=c.hidden_size, num_q_heads=c.num_attention_heads,
            num_kv_heads=c.num_key_value_heads, kdim=head_dim, vdim=head_dim,
            qkv_bias=False, final_bias=False, apply_rotary_embedding=True,
            rope_theta=c.rope_theta, name=f"{pfx}_attention")
        mha = model.serving_self_attention(
            mode, attn_in, attn_kw.pop("embed_dim"),
            attn_kw.pop("num_q_heads"), attn_kw.pop("num_kv_heads"),
            **attn_kw)

        ffn_in, residual = model.residual_rms_norm(
            mha, residual, eps=c.rms_norm_eps,
            name=f"{pfx}_post_attention_layernorm")
        w1 = model.dense(ffn_in, c.intermediate_size, use_bias=False,
                         name=f"{pfx}_mlp_gate_proj")
        w3 = model.dense(ffn_in, c.intermediate_size, use_bias=False,
                         name=f"{pfx}_mlp_up_proj")
        ssm = model.sigmoid_silu_multi(w1, w3, name=f"{pfx}_mlp_act")
        t = model.dense(ssm, c.hidden_size, use_bias=False,
                        name=f"{pfx}_mlp_down_proj")
        # TP annotations (reference AllReduce-insertion rules model.cc:3292)
        model.layers[-1].attrs["shard"] = "row"
        model.layers[-3].attrs["shard"] = "col"  # up_proj
        model.layers[-4].attrs["shard"] = "col"  # gate_proj

    model.current_transformer_layer_id = -1
    final_norm, _ = model.residual_rms_norm(t, residual, eps=c.rms_norm_eps,
                                            name="norm")
    _finish_serving_graph(model, final_norm, c.vocab_size, mode, gen)
    return model


def _finish_serving_graph(model: Model, final_hidden, vocab_size: int,
                          mode: InferenceMode,
                          generation_config: Optional[GenerationConfig]):
    """Shared serving-graph tail: lm_head + per-mode sampling head
    (reference: the common epilogue of every inference/models/*.cc builder,
    e.g. llama.cc:232-259)."""
    gen = generation_config or GenerationConfig()
    lm_head = model.dense(final_hidden, vocab_size, use_bias=False,
                          name="lm_head")
    model.layers[-1].attrs["shard"] = "col"
    if mode is InferenceMode.BEAM_SEARCH:
        from ..serving.batch_config import BeamSearchBatchConfig
        softmax = model.softmax(lm_head, name="softmax")
        model.beam_top_k(softmax, BeamSearchBatchConfig.MAX_BEAM_WIDTH,
                         name="beam_topk")
    elif gen.do_sample:
        scaled = model.scalar_true_divide(lm_head, max(gen.temperature, 1e-6),
                                          name="temp_scale")
        model.sampling(scaled, top_p=gen.topp, top_k=gen.topk,
                       name="sampling")
    else:
        model.arg_max(lm_head, name="argmax")
    return model


# ---------------------------------------------------------------- weights
def _np_of(v) -> np.ndarray:
    """torch tensor / array-like -> float32 numpy (shared by all model
    converters)."""
    return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach")
                      else v, np.float32)


def convert_hf_state_dict(state_dict: Dict[str, Any],
                          config: LLAMAConfig) -> Dict[str, Dict[str, np.ndarray]]:
    """HF LlamaForCausalLM state dict -> framework params.

    reference analogue: serve/models/llama.py convert_hf_model +
    file_loader.cc:209 load_attention_weights_v2 (qkv head splitting).
    torch Linear stores [out, in]; our Linear kernel is [in, out] and
    attention weights are [E, H, D] / wo [H, D, E].
    """
    c = config
    H, KV = c.num_attention_heads, c.num_key_value_heads
    D = c.hidden_size // H
    E = c.hidden_size

    p: Dict[str, Dict[str, np.ndarray]] = {}
    p["embed_tokens"] = {"embedding": _np_of(state_dict["model.embed_tokens.weight"])}
    for i in range(c.num_hidden_layers):
        hf = f"model.layers.{i}."
        pfx = f"layers_{i}"
        p[f"{pfx}_input_layernorm"] = {
            "weight": _np_of(state_dict[hf + "input_layernorm.weight"])}
        p[f"{pfx}_post_attention_layernorm"] = {
            "weight": _np_of(state_dict[hf + "post_attention_layernorm.weight"])}
        wq = _np_of(state_dict[hf + "self_attn.q_proj.weight"])  # [H*D, E]
        wk = _np_of(state_dict[hf + "self_attn.k_proj.weight"])  # [KV*D, E]
        wv = _np_of(state_dict[hf + "self_attn.v_proj.weight"])
        wo = _np_of(state_dict[hf + "self_attn.o_proj.weight"])  # [E, H*D]
        p[f"{pfx}_attention"] = {
            "wq": wq.reshape(H, D, E).transpose(2, 0, 1),
            "wk": wk.reshape(KV, D, E).transpose(2, 0, 1),
            "wv": wv.reshape(KV, D, E).transpose(2, 0, 1),
            "wo": wo.reshape(E, H, D).transpose(1, 2, 0),
        }
        p[f"{pfx}_mlp_gate_proj"] = {
            "kernel": _np_of(state_dict[hf + "mlp.gate_proj.weight"]).T}
        p[f"{pfx}_mlp_up_proj"] = {
            "kernel": _np_of(state_dict[hf + "mlp.up_proj.weight"]).T}
        p[f"{pfx}_mlp_down_proj"] = {
            "kernel": _np_of(state_dict[hf + "mlp.down_proj.weight"]).T}
    p["norm"] = {"weight": _np_of(state_dict["model.norm.weight"])}
    lm = state_dict.get("lm_head.weight",
                        state_dict["model.embed_tokens.weight"])  # tied
    p["lm_head"] = {"kernel": _np_of(lm).T}
    return p
