"""Shared eager layer-graph walk for the debug/profile utilities.

One walk, two consumers (utils/profiling.py, utils/debugging.py) — the
jitted execution path stays in Model.run_layers; this is the host-visible
twin used when per-layer host work (timing, file dumps) is needed.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

from ..ops.registry import OpContext, get_op


def eager_layer_walk(model, params, input_values: Dict[str, Any],
                     visit: Callable, inference: bool = False,
                     rng=None) -> Dict[Any, Any]:
    """Walk the layer graph eagerly, delegating each op's execution to
    ``visit(layer, run, lparams, ins) -> outs`` where ``run()`` executes
    the op.  ``visit`` may run it several times (profiling) or dump
    tensors around it (debugging); it must return the op's outputs."""
    from ..core.model import _tensor_key

    ctx = OpContext(training=False, rng=rng, mesh=model.mesh)
    vals: Dict[Any, Any] = {}
    for t in model.input_tensors:
        if t.name in input_values:
            vals[("__input__", t.name)] = input_values[t.name]
    for layer in model.layers:
        ins = [vals[_tensor_key(t)] for t in layer.inputs]
        op = get_op(layer.op_type)
        lparams = params.get(layer.name, {})
        fn = op.inference if inference and hasattr(op, "inference") \
            else op.forward

        def run(fn=fn, lparams=lparams, ins=ins, layer=layer):
            return fn(lparams, ins, layer.attrs, ctx)

        outs = visit(layer, run, lparams, ins)
        for i, o in enumerate(outs):
            vals[(layer.name, i)] = o
    return vals
