"""Inference debugging: per-op tensor dumps + the retrace guard.

TPU-native equivalent of the reference's ``--inference-debugging`` mode
(``Op::save_inference_tensors_to_file``, src/runtime/operator.cc:29, call
sites like linear.cc:663-673): every op's inputs, weights and outputs are
written to files for offline diffing against another implementation.

``retrace_guard`` is the DYNAMIC oracle for fflint's static
``retrace-hazard`` rule (docs/STATIC_ANALYSIS.md): it counts actual XLA
compilations via ``jax.monitoring`` events, so a test can pin a warmed
decode loop to ZERO recompiles — the invariant the static rule
approximates at the AST level.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from .eager import eager_layer_walk


class RetraceCounter:
    """Mutable compile counter a ``retrace_guard`` block exposes."""

    def __init__(self):
        self.compiles = 0
        self.events: List[str] = []
        self.active = True


@contextlib.contextmanager
def retrace_guard(max_compiles: Optional[int] = 0):
    """Count XLA compilations inside the block; raise if they exceed
    ``max_compiles`` (None = count only, never raise).

    Test-only: registers a ``jax.monitoring`` duration listener and
    counts ``backend_compile`` events — a jit cache HIT emits nothing,
    a miss (first trace or a RETRACE from an unbucketed shape / weak
    Python scalar in the cache key) emits one per compiled program.
    This is compilation-cache-miss counting, not wall clock, so the pin
    is exact and deterministic.

    Usage::

        with retrace_guard() as g:      # pins 0 compiles
            run_warmed_decode_loop()
        assert g.compiles == 0          # already enforced on exit

    Callers must warm the loop first (the first call legitimately
    compiles).  If the installed JAX emits no monitoring events at all,
    ``g.compiles`` stays 0 — tests should first prove signal with a
    fresh compile under ``retrace_guard(max_compiles=None)`` and skip
    when none is seen.
    """
    try:
        from jax import monitoring
    except ImportError:                              # very old JAX
        from jax._src import monitoring  # type: ignore
    # the public module re-exports register but (on some versions) not
    # the private unregister — resolve the latter where it lives, or the
    # guard would leak one dead listener per use into JAX's global list
    try:
        from jax._src import monitoring as _monitoring_impl
    except ImportError:
        _monitoring_impl = monitoring

    guard = RetraceCounter()

    def _on_event(name: str, duration: float = 0.0, **kw):
        if guard.active and "backend_compile" in name:
            guard.compiles += 1
            guard.events.append(name)

    monitoring.register_event_duration_secs_listener(_on_event)
    try:
        yield guard
    finally:
        guard.active = False
        unregister = getattr(
            _monitoring_impl,
            "_unregister_event_duration_listener_by_callback", None)
        if unregister is not None:
            try:
                unregister(_on_event)
            except Exception:
                pass                     # inert: guard.active gates it
    if max_compiles is not None and guard.compiles > max_compiles:
        raise AssertionError(
            f"retrace_guard: {guard.compiles} XLA compilation(s) inside "
            f"a block pinned to {max_compiles} — a jit cache key is "
            f"unstable (unbucketed shape, weak Python scalar, or a "
            f"Python branch on a traced value; see fflint "
            f"retrace-hazard in docs/STATIC_ANALYSIS.md). Events: "
            f"{guard.events}")


def save_inference_tensors(model, params, input_values: Dict[str, Any],
                           outdir: str, inference: bool = True,
                           rng=None) -> List[str]:
    """Run the graph eagerly, dumping ``<layer>.{input_i,param_*,output_i}
    .npy`` per op (reference file naming: model-id_decoding-step_layer-name
    _shard-id; here one dir per call).  Returns the written paths."""
    os.makedirs(outdir, exist_ok=True)
    written: List[str] = []

    def dump(name: str, arr):
        a = np.asarray(jax.device_get(arr))
        if a.dtype.kind == "V" or str(a.dtype) == "bfloat16":
            # np.save writes bf16 as raw void and the dtype is lost on
            # load — widen so dumps stay diffable offline
            a = np.asarray(jax.device_get(jax.numpy.asarray(arr)
                                          .astype(jax.numpy.float32)))
        p = os.path.join(outdir, name + ".npy")
        np.save(p, a)
        written.append(p)

    def visit(layer, run, lparams, ins):
        for i, x in enumerate(ins):
            dump(f"{layer.name}.input_{i}", x)
        for pname, pv in lparams.items():
            dump(f"{layer.name}.param_{pname}", pv)
        outs = run()
        for i, o in enumerate(outs):
            dump(f"{layer.name}.output_{i}", o)
        return outs

    eager_layer_walk(model, params, input_values, visit,
                     inference=inference, rng=rng)
    return written
