"""Inference debugging: per-op tensor dumps.

TPU-native equivalent of the reference's ``--inference-debugging`` mode
(``Op::save_inference_tensors_to_file``, src/runtime/operator.cc:29, call
sites like linear.cc:663-673): every op's inputs, weights and outputs are
written to files for offline diffing against another implementation.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List

import jax
import numpy as np

from .eager import eager_layer_walk


def save_inference_tensors(model, params, input_values: Dict[str, Any],
                           outdir: str, inference: bool = True,
                           rng=None) -> List[str]:
    """Run the graph eagerly, dumping ``<layer>.{input_i,param_*,output_i}
    .npy`` per op (reference file naming: model-id_decoding-step_layer-name
    _shard-id; here one dir per call).  Returns the written paths."""
    os.makedirs(outdir, exist_ok=True)
    written: List[str] = []

    def dump(name: str, arr):
        a = np.asarray(jax.device_get(arr))
        if a.dtype.kind == "V" or str(a.dtype) == "bfloat16":
            # np.save writes bf16 as raw void and the dtype is lost on
            # load — widen so dumps stay diffable offline
            a = np.asarray(jax.device_get(jax.numpy.asarray(arr)
                                          .astype(jax.numpy.float32)))
        p = os.path.join(outdir, name + ".npy")
        np.save(p, a)
        written.append(p)

    def visit(layer, run, lparams, ins):
        for i, x in enumerate(ins):
            dump(f"{layer.name}.input_{i}", x)
        for pname, pv in lparams.items():
            dump(f"{layer.name}.param_{pname}", pv)
        outs = run()
        for i, o in enumerate(outs):
            dump(f"{layer.name}.output_{i}", o)
        return outs

    eager_layer_walk(model, params, input_values, visit,
                     inference=inference, rng=rng)
    return written
