"""Quantization quality accounting (r5, VERDICT #7).

The reference gates quantized serving on OUTPUT equivalence, not just
speed (its CI token-matches spec vs incremental runs regardless of the
weight path, tests/inference/python_inference_tests.sh:30-55; the
quantized loader feeds the same gates, inference/file_loader.cc:651).
This module is the rebuild's equivalent: a teacher-forced logits probe
on the SERVING graph that turns "int8 is fast" into "int8 is fast and
costs X nats of logprob error / diverges from bf16 greedy at step Y".

Metrics (all vs a full-precision reference model over the same prompts):

- ``top1_agreement``   fraction of next-token argmaxes that agree.
- ``mean/max_logprob_err``  |log p_q - log p_fp| on the reference
  model's greedy token at each position (softmax-shift invariant, and
  weighted toward the tokens that matter — the ones actually decoded).
- ``ppl_ratio``        exp(mean NLL_q - mean NLL_fp) on the reference
  greedy continuation: how much likelier the fp model finds its own
  output than the quantized model does.  1.0 = no quality loss.
- ``greedy_divergence_step``  first decode step where greedy outputs
  differ (None = never within the horizon).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


def teacher_forced_logprobs(im, model_id: int, tokens: Sequence[int],
                            layer_name: str = "lm_head"):
    """Run one prefill chunk over ``tokens`` through the compiled
    serving record and return the next-token log-softmax
    [len(tokens), vocab] (float32 numpy): position i holds the
    distribution over token i+1.

    Uses the record's own step-function machinery (same params/caches/
    sharding as production serving) but reads the ``layer_name`` dense
    output instead of the sampling head, via a dedicated jitted probe
    that does NOT donate the caches (quality probes must not disturb a
    live serving record).
    """
    import jax
    import jax.numpy as jnp

    from ..ops.registry import OpContext

    record = im.models[model_id]
    model = record["model"]
    L = len(tokens)
    assert L <= record["prefill_chunk"], (
        f"probe prompt {L} exceeds the compiled prefill chunk "
        f"{record['prefill_chunk']}")
    key = ("logits_probe", L, layer_name)
    if key not in record["steps"]:
        input_names = [t.name for t in model.input_tensors]

        def probe(params, caches, token_ids, row_tokens, active):
            batch = {"token_ids": token_ids,
                     "first_depth": jnp.zeros((token_ids.shape[0],),
                                              jnp.int32),
                     "row_tokens": row_tokens, "active": active}
            ctx = OpContext(training=False, rng=jax.random.PRNGKey(0),
                            batch_config=batch, kv_cache=caches,
                            kv_cache_out={}, attend_len=None,
                            w8a8=model.config.int8_native_matmul,
                            mesh=record["mesh"], extra_outputs={})
            feeds = {}
            C = token_ids.shape[1]
            for name in input_names:
                if name == "tokens":
                    feeds[name] = token_ids
                elif name == "positions":
                    feeds[name] = jnp.broadcast_to(
                        jnp.arange(C, dtype=jnp.int32)[None, :],
                        token_ids.shape)
                else:
                    raise ValueError(f"unknown serving input {name!r}")
            vals = model.run_layers(params, feeds, ctx, inference=True)
            logits = vals[(layer_name, 0)]          # [R, C, V]
            return jax.nn.log_softmax(
                logits[0].astype(jnp.float32), axis=-1)

        record["steps"][key] = jax.jit(probe)
    R = record["rows"]
    C = record["prefill_chunk"]
    token_ids = np.zeros((R, C), np.int32)
    token_ids[0, :L] = tokens
    row_tokens = np.zeros((R,), np.int32)
    row_tokens[0] = L
    active = np.zeros((R,), bool)
    active[0] = True
    lp = record["steps"][key](model.params, record["caches"],
                              np.asarray(token_ids),
                              np.asarray(row_tokens), np.asarray(active))
    return np.asarray(lp[:L])


def quality_report(im_ref, mid_ref, im_q, mid_q,
                   prompts: Sequence[Sequence[int]],
                   ref_tokens: Optional[List[List[int]]] = None,
                   q_tokens: Optional[List[List[int]]] = None,
                   layer_name: str = "lm_head") -> Dict[str, float]:
    """Compare a quantized serving record against a full-precision one.

    ``prompts``: token sequences to teacher-force (each is prompt +
    reference-greedy continuation, so the probe weighs the positions a
    real decode visits).  ``ref_tokens``/``q_tokens``: optional greedy
    generations from each model for the divergence-step metric.
    """
    agree = total = 0
    errs: List[np.ndarray] = []
    nll_ref_all: List[np.ndarray] = []
    nll_q_all: List[np.ndarray] = []
    for toks in prompts:
        toks = list(toks)
        lp_ref = teacher_forced_logprobs(im_ref, mid_ref, toks, layer_name)
        lp_q = teacher_forced_logprobs(im_q, mid_q, toks, layer_name)
        nxt = np.asarray(toks[1:])                  # teacher-forced targets
        pos = np.arange(len(nxt))
        agree += int((lp_ref[:-1].argmax(-1) == lp_q[:-1].argmax(-1)).sum())
        total += len(nxt)
        # logprob error on the path actually taken
        errs.append(np.abs(lp_q[pos, nxt] - lp_ref[pos, nxt]))
        nll_ref_all.append(-lp_ref[pos, nxt])
        nll_q_all.append(-lp_q[pos, nxt])
    errs_c = np.concatenate(errs)
    nll_ref = float(np.concatenate(nll_ref_all).mean())
    nll_q = float(np.concatenate(nll_q_all).mean())
    report = {
        "top1_agreement": round(agree / max(1, total), 4),
        "mean_logprob_err": round(float(errs_c.mean()), 5),
        "max_logprob_err": round(float(errs_c.max()), 4),
        "ppl_ref": round(float(np.exp(nll_ref)), 3),
        "ppl_q": round(float(np.exp(nll_q)), 3),
        "ppl_ratio": round(float(np.exp(nll_q - nll_ref)), 4),
    }
    if ref_tokens is not None and q_tokens is not None:
        div = None
        for rt, qt in zip(ref_tokens, q_tokens):
            for i, (a, b) in enumerate(zip(rt, qt)):
                if a != b:
                    div = i if div is None else min(div, i)
                    break
        report["greedy_divergence_step"] = div
    return report
