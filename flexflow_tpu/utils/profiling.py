"""Profiling utilities.

TPU-native equivalents of the reference's profiling aids (SURVEY.md §5):
- per-op kernel timing behind ``--profiling`` (cudaEvent timing in every
  kernel wrapper, src/ops/kernels/linear_kernels.cu:130-164) →
  :func:`profile_per_op` runs each layer eagerly with block_until_ready;
- NVTX ranges (deps/nvtx) → :func:`annotate` wraps
  ``jax.profiler.TraceAnnotation``;
- Legion ``-lg:prof`` → :func:`trace` wraps the XLA/TensorBoard profiler
  (``jax.profiler.trace``), capturing device timelines.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, List

import jax

from .eager import eager_layer_walk


def annotate(name: str):
    """Named range visible in the profiler timeline (reference
    nvtxRangePushA, request_manager.cc:2030)."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace viewable in TensorBoard/XProf (the Legion
    ``-lg:prof`` analogue)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def profile_per_op(model, params, input_values: Dict[str, Any],
                   repeats: int = 5, inference: bool = False,
                   rng=None) -> List[Dict[str, Any]]:
    """Time each layer's forward individually (reference --profiling).

    Runs the graph layer by layer eagerly — numbers include dispatch
    overhead and exclude XLA fusion, so they are for *relative* hot-spot
    hunting exactly like the reference's per-kernel prints; end-to-end time
    comes from timing the jitted step.
    """
    report: List[Dict[str, Any]] = []

    def visit(layer, run, lparams, ins):
        outs = run()                     # warm / build
        jax.block_until_ready(outs)
        t0 = time.perf_counter()
        for _ in range(repeats):
            outs = run()
            jax.block_until_ready(outs)
        ms = (time.perf_counter() - t0) / repeats * 1e3
        report.append({"layer": layer.name, "op": layer.op_type.value,
                       "ms": ms})
        return outs

    eager_layer_walk(model, params, input_values, visit,
                     inference=inference, rng=rng)
    return report


def format_profile(report: List[Dict[str, Any]]) -> str:
    total = sum(r["ms"] for r in report)
    lines = [f"{'layer':<40} {'op':<28} {'ms':>9} {'%':>6}"]
    for r in sorted(report, key=lambda r: -r["ms"]):
        lines.append(f"{r['layer']:<40} {r['op']:<28} {r['ms']:>9.3f} "
                     f"{100 * r['ms'] / max(total, 1e-12):>5.1f}%")
    lines.append(f"{'TOTAL':<40} {'':<28} {total:>9.3f}")
    return "\n".join(lines)
