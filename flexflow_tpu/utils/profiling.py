"""Profiling utilities.

TPU-native equivalents of the reference's profiling aids (SURVEY.md §5):
- per-op kernel timing behind ``--profiling`` (cudaEvent timing in every
  kernel wrapper, src/ops/kernels/linear_kernels.cu:130-164) →
  :func:`profile_per_op` runs each layer eagerly with block_until_ready;
- NVTX ranges (deps/nvtx) → :func:`annotate` wraps
  ``jax.profiler.TraceAnnotation``;
- Legion ``-lg:prof`` → :func:`trace` wraps the XLA/TensorBoard profiler
  (``jax.profiler.trace``), capturing device timelines.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from .eager import eager_layer_walk


def annotate(name: str):
    """Named range visible in the profiler timeline (reference
    nvtxRangePushA, request_manager.cc:2030)."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace viewable in TensorBoard/XProf (the Legion
    ``-lg:prof`` analogue)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def profile_per_op(model, params, input_values: Dict[str, Any],
                   repeats: int = 5, inference: bool = False,
                   rng=None) -> List[Dict[str, Any]]:
    """Time each layer's forward individually (reference --profiling).

    Runs the graph layer by layer eagerly — numbers include dispatch
    overhead and exclude XLA fusion, so they are for *relative* hot-spot
    hunting exactly like the reference's per-kernel prints; end-to-end time
    comes from timing the jitted step.
    """
    report: List[Dict[str, Any]] = []

    def visit(layer, run, lparams, ins):
        outs = run()                     # warm / build
        jax.block_until_ready(outs)
        t0 = time.perf_counter()
        for _ in range(repeats):
            outs = run()
            jax.block_until_ready(outs)
        ms = (time.perf_counter() - t0) / repeats * 1e3
        report.append({"layer": layer.name, "op": layer.op_type.value,
                       "ms": ms})
        return outs

    eager_layer_walk(model, params, input_values, visit,
                     inference=inference, rng=rng)
    return report


@dataclasses.dataclass
class PrefixCacheStats:
    """Prefix-KV-cache effectiveness counters (serving/prefix_cache.py).

    ``tokens_matched`` is the KV the pool actually supplied (prefill
    FLOPs + HBM writes skipped); ``tokens_prompt`` is the total prompt
    token mass admitted while the cache was on — their ratio is the
    tokens-saved fraction, the cache's headline win alongside warm-TTFT.
    """

    lookups: int = 0
    hits: int = 0
    tokens_matched: int = 0
    tokens_prompt: int = 0
    donations: int = 0
    donations_rejected: int = 0
    evictions: int = 0

    def note_lookup(self, matched: int, prompt_len: int):
        self.lookups += 1
        self.tokens_prompt += prompt_len
        if matched > 0:
            self.hits += 1
            self.tokens_matched += matched

    def hit_rate(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0

    def tokens_saved_frac(self) -> float:
        return (self.tokens_matched / self.tokens_prompt
                if self.tokens_prompt else 0.0)

    def snapshot(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["hit_rate"] = round(self.hit_rate(), 4)
        d["tokens_saved_frac"] = round(self.tokens_saved_frac(), 4)
        return d


@dataclasses.dataclass
class KVCacheStats:
    """KV-cache memory/bandwidth accounting for one compiled serving
    record (``InferenceManager.kv_cache_stats``).

    ``bytes_resident`` is everything the record's caches pin in HBM
    (K + V + scale tensors across layers, at the padded allocation);
    ``bytes_per_token`` is the per-attended-position stream cost across
    layers — what one decode step reads per position of context — so
    ``bytes_streamed_step`` for a batch is sum over active rows of
    (depth_r + 1) * bytes_per_token.  The int8 win is visible directly:
    int8 K/V (1 byte) + f32 scales (4 bytes / head / position) lands at
    ~0.52x the bf16 bytes at head_dim 128, which is why the acceptance
    gate asks for <= 0.55x.  Int4 packs two positions per carrier byte
    (0.5 bytes / element + the same f32 scales) and lands at ~0.28x,
    gated at <= 0.35x."""

    kv_cache_dtype: str
    layers: int
    rows: int
    alloc_len: int
    bytes_resident: int
    bytes_per_token: int
    #: physical paging (kv_layout="paged"): K/V live in a global
    #: [num_frames, KV, page_len, D] pool per layer, so residency is
    #: ``frames_leased * frame_bytes`` (what the leases pin) rather
    #: than the dense rows x alloc_len formula; ``pool_bytes`` is the
    #: pool's full allocation (the hard HBM ceiling the operator sized)
    paged: bool = False
    page_len: int = 0
    frames_total: int = 0
    frames_leased: int = 0
    frame_bytes: int = 0
    pool_bytes: int = 0

    @classmethod
    def of_record(cls, record) -> "KVCacheStats":
        caches = record.get("caches") or {}
        pack = record.get("kv_pack", 1)
        resident = 0
        per_token = 0
        frame_bytes = 0
        dtype = "none"
        for kv in caches.values():
            dtype = "int4" if pack == 2 else str(kv["k"].dtype)
            for part, arr in kv.items():
                resident += int(arr.size) * arr.dtype.itemsize
                # per attended position: a 4-D [R, KV, S, D] part
                # streams KV*D elements per position, a 3-D scale
                # [R, KV, S] streams KV.  Int4 carriers hold ``pack``
                # logical positions per stored byte, so a position
                # streams KV*D//pack carrier bytes
                per_pos = int(np.prod(arr.shape[1:2]
                                      + arr.shape[3:]))
                nb = per_pos * arr.dtype.itemsize
                if arr.ndim == 4:
                    nb //= pack
                per_token += nb
                # paged pools: one frame of this part = everything
                # past the leading frame axis
                frame_bytes += (int(np.prod(arr.shape[1:]))
                                * arr.dtype.itemsize)
        if record.get("paged"):
            leased = int(record.get("leased_frames", 0))
            return cls(kv_cache_dtype=dtype, layers=len(caches),
                       rows=record.get("rows", 0),
                       alloc_len=record.get("alloc_len", 0),
                       bytes_resident=leased * frame_bytes,
                       bytes_per_token=per_token, paged=True,
                       page_len=record.get("page_len", 0),
                       frames_total=record.get("num_frames", 0),
                       frames_leased=leased, frame_bytes=frame_bytes,
                       pool_bytes=resident)
        return cls(kv_cache_dtype=dtype, layers=len(caches),
                   rows=record.get("rows", 0),
                   alloc_len=record.get("alloc_len", 0),
                   bytes_resident=resident, bytes_per_token=per_token)

    def bytes_streamed_step(self, depths: Sequence[int],
                            active: Optional[Sequence[bool]] = None
                            ) -> int:
        """Decode-step HBM read estimate for a batch at the given
        per-row depths: each active row streams its attended prefix
        (depth + 1 positions) across every layer.  The jnp path reads
        the batch-max bucket instead of each row's own depth, and the
        flash kernel reads whole tiles — both bounded below by this
        number, which is the dtype comparison that matters."""
        d = np.asarray(depths, np.int64)
        if active is not None:
            d = d[np.asarray(active, bool)]
        return int((d + 1).sum()) * self.bytes_per_token

    def snapshot(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def ttft_percentiles(requests: Sequence[Any],
                     ps: Sequence[int] = (50, 90),
                     ledger: Any = None) -> Dict[str, float]:
    """Host-observed time-to-first-token percentiles (seconds) over a
    batch of finished Requests.

    Per-request TTFTs come from the request LEDGER
    (observability/ledger.py) — the PR-7 reconciliation: the ledger's
    retire feed carries the authoritative ``ProfileInfo.ttft_s()``
    stamp, so both paths agree exactly (pinned by
    tests/test_ledger.py); requests the ledger never saw
    (``FF_TELEMETRY=0``, ring-evicted) fall back to their profile
    stamps, monotonic-clock deltas either way (NTP-jump immune).

    TTFT measures ADMISSION -> first token (``ProfileInfo.admit_mono``):
    a warm prefix-cache hit is credited for the prefill it skipped, not
    penalized for queue wait — the wait is its own ``queue_wait_s``
    component.  Requests that never produced a token are skipped.
    ``ledger``: explicit RequestLedger (defaults to the process-wide
    one)."""
    import numpy as np

    if ledger is None:
        try:
            from ..observability import get_ledger
            ledger = get_ledger()
        except ImportError:         # pragma: no cover - partial install
            ledger = None
    ttfts = []
    for r in requests:
        t = ledger.ttft_of(r.guid) if ledger is not None else None
        if t is None:
            t = r.profile.ttft_s()
        if t is not None:
            ttfts.append(t)
    if not ttfts:
        return {f"p{p}": 0.0 for p in ps}
    return {f"p{p}": float(np.percentile(ttfts, p)) for p in ps}


def format_profile(report: List[Dict[str, Any]]) -> str:
    total = sum(r["ms"] for r in report)
    lines = [f"{'layer':<40} {'op':<28} {'ms':>9} {'%':>6}"]
    for r in sorted(report, key=lambda r: -r["ms"]):
        lines.append(f"{r['layer']:<40} {r['op']:<28} {r['ms']:>9.3f} "
                     f"{100 * r['ms'] / max(total, 1e-12):>5.1f}%")
    lines.append(f"{'TOTAL':<40} {'':<28} {total:>9.3f}")
    return "\n".join(lines)
