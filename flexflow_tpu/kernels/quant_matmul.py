"""Pallas TPU kernel: fused int8-dequant matmul for weight-only-quantized
serving.

Decode is weight-HBM-bandwidth-bound (BASELINE.md serving configs), so the
win from int8 quantization is streaming HALF the weight bytes — which only
materializes if the dequant fuses into the matmul's operand load.  The XLA
lowering of ``(q.astype(f32) * scale) @ x`` materializes the dequantized
matrix in HBM (and compiles pathologically inside lax.scan), recreating the
full-precision traffic; this kernel keeps weights int8 in HBM, dequantizes
block-by-block in VMEM, and applies the per-output-channel scale once on
the accumulated tile — the role the reference's hand-written
``decompress_kernels.cu`` plays for its cuBLAS GEMMs.

Layout contract (matches flexflow_tpu.quantization int8):
    x [B, K] bf16/f32, q int8 [K, N], scale f32 [N] -> out [B, N] (x.dtype)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_BK = 1024   # K-block (reduction) — swept on v5e: 1024x512 best
_BN = 512    # N-block (output channels)


def _kernel(x_ref, q_ref, scale_ref, out_ref, acc_ref):
    from jax.experimental import pallas as pl

    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # dequantize the weight block in VMEM (int8 -> bf16) and hit the MXU;
    # the per-channel scale is applied once at the end, not per block
    w = q_ref[:].astype(jnp.bfloat16)
    acc_ref[:] += jnp.dot(x_ref[:].astype(jnp.bfloat16), w,
                          preferred_element_type=jnp.float32)

    @pl.when(k == pl.num_programs(1) - 1)
    def _finish():
        out_ref[:] = (acc_ref[:] * scale_ref[:]).astype(out_ref.dtype)


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    rem = (-size) % mult
    if rem == 0:
        return x, size
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad), size


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_matmul(x, q, scale, interpret: bool = False):
    """x [B, K] @ dequant(q [K, N] int8, scale [N]) -> [B, N] in x.dtype.

    Pads B to the sublane tile and K/N to the block sizes; the padded
    K rows of q are zero so they contribute nothing.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    out_dtype = x.dtype
    B, K = x.shape
    N = q.shape[1]
    x, _ = _pad_to(x, 0, 16)        # bf16 sublane tile
    x, _ = _pad_to(x, 1, _BK)
    q, _ = _pad_to(q, 0, _BK)
    q, _ = _pad_to(q, 1, _BN)
    # 2-D scale: 1-D f32 operands hit an XLA/Mosaic tiling mismatch
    scale, _ = _pad_to(scale.reshape(1, -1), 1, _BN)
    Bp, Kp = x.shape
    Np = q.shape[1]

    grid = (Np // _BN, Kp // _BK)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((Bp, _BK), lambda n, k: (0, k)),
            pl.BlockSpec((_BK, _BN), lambda n, k: (k, n)),
            pl.BlockSpec((1, _BN), lambda n, k: (0, n)),
        ],
        out_specs=pl.BlockSpec((Bp, _BN), lambda n, k: (0, n)),
        out_shape=jax.ShapeDtypeStruct((Bp, Np), out_dtype),
        scratch_shapes=[pltpu.VMEM((Bp, _BN), jnp.float32)],
        interpret=interpret,
    )(x, q, scale.astype(jnp.float32))
    return out[:B, :N]


def _fast_bn(n: int, k: int = 0):
    """Largest output-block width that divides n AND keeps the int8
    weight block (k x bn bytes) inside the VMEM budget — a greedy pick
    ignoring k rejected 7B's down_proj (k=11008: 512-wide blocks are
    5.6M > 4M, but 256-wide fit)."""
    for bn in (512, 256, 128):
        if n % bn == 0 and (not k or k * bn <= 4 * 1024 * 1024):
            return bn
    return None


def fast_path_ok(rows: int, k: int, n: int) -> bool:
    """Shape gate for :func:`int8_matmul_fast`: whole-K blocks need
    tile-aligned dims and must fit VMEM."""
    return (_fast_bn(n, k) is not None and k % 128 == 0 and rows <= 64
            and k <= 16384)


def _fast_kernel(x_ref, q_ref, scale_ref, out_ref):
    from jax.experimental import pallas as pl  # noqa: F401

    w = q_ref[:].astype(jnp.bfloat16)
    acc = jnp.dot(x_ref[:].astype(jnp.bfloat16), w,
                  preferred_element_type=jnp.float32)
    out_ref[:] = (acc * scale_ref[:]).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_matmul_fast(x, q, scale, interpret: bool = False):
    """Whole-K fused dequant matmul for decode-sized batches.

    Unlike :func:`int8_matmul` it never reshapes or pads the WEIGHT at call
    time — inside a lax.scan body (the decode block) any pad/reshape of q
    copies the whole matrix every iteration, which is how the first
    in-model attempt ran 100x slower than XLA.  Only the tiny activation
    pads.  Requires :func:`fast_path_ok` shapes.
    """
    from jax.experimental import pallas as pl

    B, K = x.shape
    N = q.shape[1]
    bn = _fast_bn(N, K)
    assert bn is not None and K % 128 == 0, (K, N)
    Bp = -(-max(B, 16) // 16) * 16
    if B < Bp:
        x = jnp.pad(x, ((0, Bp - B), (0, 0)))
    out = pl.pallas_call(
        _fast_kernel,
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((Bp, K), lambda n: (0, 0)),
            pl.BlockSpec((K, bn), lambda n: (0, n)),
            pl.BlockSpec((1, bn), lambda n: (0, n)),
        ],
        out_specs=pl.BlockSpec((Bp, bn), lambda n: (0, n)),
        out_shape=jax.ShapeDtypeStruct((Bp, N), x.dtype),
        interpret=interpret,
    )(x, q, scale.reshape(1, N).astype(jnp.float32))
    return out[:B]


def int8_matmul_reference(x, q, scale):
    """jnp reference (the XLA-dequant path) for parity tests/fallback."""
    w = q.astype(jnp.float32) * scale[None, :]
    return jnp.dot(x.astype(jnp.float32), w).astype(x.dtype)


def pallas_tpu_available() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False
