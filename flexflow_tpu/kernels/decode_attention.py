"""Pallas TPU kernel: fused single-token decode attention.

Decode at small batch is per-kernel floor-bound (PARITY.md known-gaps):
the unfused path spends ~5 XLA kernels per layer on cache scatter +
attention einsums + masking.  This kernel fuses, per request row,

    scatter k/v at the row's depth into the KV cache (in place, aliased)
    -> causal-masked q@K^T over the cache -> softmax -> @V

into ONE program — the TPU analogue of the reference's hand-written
generation kernel (inc_multihead_self_attention.cu:46
compute_attention_kernel_generation_kernel + :603 update_kv_cache_kernel).

Layout contract (matches ops/serving_attention.py):
    q      [R, H, D]    post-RoPE queries, one token per row
    k_new  [R, KV, D]   post-RoPE key for the new token
    v_new  [R, KV, D]
    ck/cv  [R, S, KV, D] caches; S % 16 == 0 (VMEM block tiling)
    depth  [R] int32    the new token's cache slot (= tokens cached)
    active [R] int32    0 rows skip the scatter (slot S-1 slack) and
                        output zeros
Returns (out [R, H, D], ck', cv') — caches aliased in place.
GQA folds as H = KV * G.  ALiBi is NOT handled (the jnp path covers
MPT); tp/sp-sharded meshes use the jnp path too.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _masked_attention(q_all, k_all, v_all, depth, active, S,
                      kv_heads, groups, scale):
    """Shared masked-softmax attention body for both kernel variants:
    per-kv-head qK^T -> causal mask -> stable softmax -> probs@V (probs
    cast to the cache dtype, bit-exact with _attend), inactive rows 0."""
    span = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
    mask = (span <= depth) & (active > 0)
    outs = []
    for kv in range(kv_heads):
        qg = q_all[kv * groups:(kv + 1) * groups, :]
        k = k_all[:, kv, :]
        logits = jax.lax.dot_general(
            qg.astype(jnp.float32), k.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        logits = jnp.where(mask, logits, NEG_INF)
        m = jnp.max(logits, axis=-1, keepdims=True)
        p = jnp.exp(logits - m)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        v = v_all[:, kv, :]
        o = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        outs.append(o)
    o = jnp.concatenate(outs, axis=0)
    return jnp.where(active > 0, o, 0.0)


def _kernel(depth_sref, active_sref, q_ref, kn_ref, vn_ref, ck_ref,
            cv_ref, out_ref, cko_ref, cvo_ref, *, kv_heads: int,
            groups: int, scale: float):
    from jax.experimental import pallas as pl

    r = pl.program_id(0)
    depth = depth_sref[r]
    active = active_sref[r]
    S = cko_ref.shape[0]
    # output blocks are NOT initialized from the aliased input — each
    # program writes its whole block back, so copy-in first, then scatter
    # the new token's k/v at the row's depth (inactive rows write into
    # the never-attended slack tail, like the jnp _scatter_chunk)
    cko_ref[:] = ck_ref[:]
    cvo_ref[:] = cv_ref[:]
    slot = jnp.where(active > 0, depth, S - 1)
    cko_ref[pl.dslice(slot, 1)] = kn_ref[:].reshape(1, kv_heads, -1)
    cvo_ref[pl.dslice(slot, 1)] = vn_ref[:].reshape(1, kv_heads, -1)

    # read whole blocks as values: strided middle-dim REF reads
    # (cko_ref[:, kv, :]) mis-lower on Mosaic, value slicing is safe
    o = _masked_attention(q_ref[:], cko_ref[:], cvo_ref[:], depth, active,
                          S, kv_heads, groups, scale)
    out_ref[:] = o.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def fused_decode_attention(q, k_new, v_new, ck, cv, depth, active,
                           scale: float, interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H, D = q.shape
    S, KV = ck.shape[1], ck.shape[2]
    assert S % 16 == 0, f"cache length {S} must be a multiple of 16"
    G = H // KV
    kern = functools.partial(_kernel, kv_heads=KV, groups=G, scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(R,),
        in_specs=[
            pl.BlockSpec((None, H, D), lambda r, d, a: (r, 0, 0)),
            pl.BlockSpec((None, KV, D), lambda r, d, a: (r, 0, 0)),
            pl.BlockSpec((None, KV, D), lambda r, d, a: (r, 0, 0)),
            pl.BlockSpec((None, S, KV, D), lambda r, d, a: (r, 0, 0, 0)),
            pl.BlockSpec((None, S, KV, D), lambda r, d, a: (r, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, H, D), lambda r, d, a: (r, 0, 0)),
            pl.BlockSpec((None, S, KV, D), lambda r, d, a: (r, 0, 0, 0)),
            pl.BlockSpec((None, S, KV, D), lambda r, d, a: (r, 0, 0, 0)),
        ],
    )
    out, cko, cvo = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((R, H, D), q.dtype),
            jax.ShapeDtypeStruct(ck.shape, ck.dtype),
            jax.ShapeDtypeStruct(cv.shape, cv.dtype),
        ],
        input_output_aliases={5: 1, 6: 2},    # caches update in place
        interpret=interpret,
    )(depth.astype(jnp.int32), active.astype(jnp.int32), q,
      k_new.astype(ck.dtype), v_new.astype(cv.dtype), ck, cv)
    return out, cko, cvo


def _dma_kernel(depth_sref, active_sref, q_ref, kn_ref, vn_ref, ck_hbm,
                cv_hbm, out_ref, cko_hbm, cvo_hbm, ks, vs, sem_k, sem_v,
                sem_wk, sem_wv, *, kv_heads: int, groups: int,
                scale: float):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r = pl.program_id(0)
    depth = depth_sref[r]
    active = active_sref[r]
    S = ks.shape[0]
    slot = jnp.where(active > 0, depth, S - 1)
    # fetch row r HBM -> VMEM (needed for attention regardless)
    fk = pltpu.make_async_copy(ck_hbm.at[r], ks, sem_k)
    fv = pltpu.make_async_copy(cv_hbm.at[r], vs, sem_v)
    fk.start()
    fv.start()
    # write ONLY the new slot back to the (aliased) HBM cache — no
    # whole-row write-back, the win over the blocked variant
    wk = pltpu.make_async_copy(kn_ref, cko_hbm.at[r, pl.ds(slot, 1)],
                               sem_wk)
    wv = pltpu.make_async_copy(vn_ref, cvo_hbm.at[r, pl.ds(slot, 1)],
                               sem_wv)
    wk.start()
    wv.start()
    fk.wait()
    fv.wait()
    # the VMEM copy may predate the slot write: patch it locally
    ks[pl.dslice(slot, 1)] = kn_ref[:]
    vs[pl.dslice(slot, 1)] = vn_ref[:]

    o = _masked_attention(q_ref[:], ks[:], vs[:], depth, active, S,
                          kv_heads, groups, scale)
    out_ref[:] = o.astype(out_ref.dtype)
    wk.wait()
    wv.wait()


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def fused_decode_attention_dma(q, k_new, v_new, ck, cv, depth, active,
                               scale: float, interpret: bool = False):
    """Manual-DMA variant: caches stay in HBM; only the new token's slot
    is written back (the blocked variant pays a whole-row write-back per
    step).  Same contract as :func:`fused_decode_attention`."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H, D = q.shape
    S, KV = ck.shape[1], ck.shape[2]
    assert S % 16 == 0, f"cache length {S} must be a multiple of 16"
    G = H // KV
    kern = functools.partial(_dma_kernel, kv_heads=KV, groups=G,
                             scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(R,),
        in_specs=[
            pl.BlockSpec((None, H, D), lambda r, d, a: (r, 0, 0)),
            pl.BlockSpec((1, KV, D), lambda r, d, a: (r, 0, 0)),
            pl.BlockSpec((1, KV, D), lambda r, d, a: (r, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((None, H, D), lambda r, d, a: (r, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((S, KV, D), ck.dtype),
            pltpu.VMEM((S, KV, D), cv.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    out, cko, cvo = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((R, H, D), q.dtype),
            jax.ShapeDtypeStruct(ck.shape, ck.dtype),
            jax.ShapeDtypeStruct(cv.shape, cv.dtype),
        ],
        input_output_aliases={5: 1, 6: 2},
        interpret=interpret,
    )(depth.astype(jnp.int32), active.astype(jnp.int32), q,
      k_new.astype(ck.dtype), v_new.astype(cv.dtype), ck, cv)
    return out, cko, cvo


def decode_attention_reference(q, k_new, v_new, ck, cv, depth, active,
                               scale: float):
    """jnp reference mirroring ops/serving_attention.py's C=1 path."""
    S = ck.shape[1]
    safe = jnp.where(active > 0, depth, S - 1)

    def upd(cache_row, new_row, s):
        return jax.lax.dynamic_update_slice(
            cache_row, new_row[None].astype(cache_row.dtype), (s, 0, 0))

    ck = jax.vmap(upd)(ck, k_new, safe)
    cv = jax.vmap(upd)(cv, v_new, safe)
    R, H, D = q.shape
    KV = ck.shape[2]
    G = H // KV
    qg = q.reshape(R, KV, G, D)
    logits = jnp.einsum("rkgd,rskd->rkgs", qg, ck,
                        preferred_element_type=jnp.float32) * scale
    span = jnp.arange(S)[None, None, None, :]
    mask = (span <= depth[:, None, None, None]) & (
        active[:, None, None, None] > 0)
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("rkgs,rskd->rkgd", probs.astype(cv.dtype), cv,
                     preferred_element_type=jnp.float32)
    out = jnp.where(active[:, None, None] > 0,
                    out.reshape(R, H, D), 0.0)
    return out.astype(q.dtype), ck, cv
