"""Length-tiled flash-prefill attention (Pallas TPU).

Chunked-prefill attention whose VMEM footprint is independent of the
cache length: the grid walks (row, C-tile, S-tile) with a running-
softmax accumulator carried across a (row, C-tile)'s S-tiles — the
flash_decode kernel (kernels/flash_decode.py) extended from one query
per row to a tile of TC queries, covering the reference's prompt-phase
attention (/root/reference/src/ops/inc_multihead_self_attention.cu:902
compute_attention_kernel_prompt, a batched GEMM over the prompt whose
scores materialize per request) without materializing [C, S] logits in
HBM.

Why this exists (r4, chip-measured): at 1.4B/8k the XLA prefill attend
costs ~3.6 ms per 1024 positions of attend bucket per 512-token chunk —
the f32 [C, H, S] logits round-trip through HBM twice (write + softmax
read).  The flash kernel keeps logits in VMEM, reading only the K/V
tiles (~2 KB/position), which turns the whole 8k prompt's attention
from ~400 ms into ~10 ms and roughly halves long-prompt TTFT.

Layouts (no in-kernel relayout — the r3 lesson):
- cache stays the serving-native ``[R, KV, S, D]``: K/V tiles arrive
  ``[1, KV, TS, D]`` with kv leading both dot operands.
- q is pre-transposed ONCE on the XLA side to ``[R, KV, G, C, D]`` so a
  q block reshapes to ``[KV, G*TC, D]`` contiguously (transposing the
  small q tensor in XLA is ~free; transposing per-tile in VMEM is not).

Per-(row, C-tile) tile pruning: queries in C-tile c attend positions
<= depth_r + c_end, so a scalar-prefetch clamped index map re-requests
the same K/V block for every S-tile past the tile's last needed one;
Mosaic skips the duplicate DMA and @pl.when skips the compute.  Rows
whose prompt span ends before the C-tile prune to a single tile.

r5 additions (mirroring kernels/flash_decode.py):
- ALiBi slopes (MPT position bias) as a fused add on the logits tile.
- Sharded meshes: ``flash_prefill_attention_sharded`` shard_maps over
  tp (kv heads — independent) and sp (cache length — partial online
  softmax per shard + the standard flash merge over 'sp'); the chunk
  append handles chunks STRADDLING sp shard boundaries (each shard
  overlays its intersection of [depth, depth+ntok)).

Hybrid steps (stall-free mixed batches): the fused step's RIDER
sub-pass (inference_manager.hybrid_step) is an ordinary prefill batch
through these kernels — rider rows active at their budgeted chunk, the
decode rows inactive.  The inactive-row pruning above is what makes
that composition cheap: bystander rows clamp to a single K/V tile
(``has_q & active`` in the ``last`` map), so a mostly-decode batch's
rider dispatch streams only the riders' caches.  The 16-aligned
chunk-start and 32-wide int8 RMW-window invariants bound the
scheduler's rider chunks exactly as they bound separate prefill
chunks (batch_config.budgeted_chunk keeps budgeted chunks on the same
pow2 ladder).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _kernel(last_ref, depth_ref, ntok_ref, act_ref,   # scalar prefetch
            q_ref, k_ref, v_ref,                      # blocks
            *rest,                          # [ks, vs], [slopes], outs, scr
            ts: int, tc: int, kv: int, g: int, d: int,
            s_total: int, scale: float,
            alibi: bool, partial: bool, quant: bool = False,
            pack: int = 1):
    from jax.experimental import pallas as pl

    ks_ref = vs_ref = None
    if quant:
        ks_ref, vs_ref, *rest = rest
    slopes_ref = None
    if alibi:
        slopes_ref, *rest = rest
    if partial:
        o_ref, m_ref, l_ref, m_sc, l_sc, acc_sc = rest
    else:
        (o_ref, m_sc, l_sc, acc_sc), m_ref, l_ref = rest, None, None

    r = pl.program_id(0)
    c = pl.program_id(1)
    t = pl.program_id(2)
    nt = pl.num_programs(2)
    rows = kv * g * tc

    @pl.when(t == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, -1e30)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    @pl.when(t <= last_ref[r, c])
    def _step():
        qv = q_ref[:].reshape(kv, g * tc, d)
        kt = k_ref[:].reshape(kv, ts // pack, d)
        vt = v_ref[:].reshape(kv, ts // pack, d)
        if pack == 2:
            # int4 carrier tile: in-register nibble unpack to ``ts``
            # logical positions (2 codes/byte along the sequence axis)
            # BEFORE the dequant cast — the HBM->VMEM stream stays at
            # quarter the bf16 bandwidth (flash_decode._unpack_int4_tile)
            from .flash_decode import _unpack_int4_tile

            kt = _unpack_int4_tile(kt, kv, ts, d)
            vt = _unpack_int4_tile(vt, kv, ts, d)
        if ks_ref is not None:
            # int8 cache: the HBM->VMEM K/V stream is int8; dequant is
            # in-register — K's per-position scale folds into the logits
            # AFTER the dot (exact: constant along the contracted d)
            kt = kt.astype(qv.dtype)
        # logits[kv, g*tc, ts] = qv . kt (batch kv; contract d)
        logits = jax.lax.dot_general(
            qv, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        if ks_ref is not None:
            logits = logits * ks_ref[:].reshape(kv, 1, ts)
        # causal + query-validity mask.  Query at lane (g_, ci) sits at
        # absolute position depth + c*tc + ci and is real iff
        # c*tc + ci < ntok; key j sits at absolute position t*ts + j.
        ci = jax.lax.broadcasted_iota(
            jnp.int32, (g, tc, ts), 1).reshape(g * tc, ts)
        sj = t * ts + jax.lax.broadcasted_iota(
            jnp.int32, (g, tc, ts), 2).reshape(g * tc, ts)
        qpos = depth_ref[r] + c * tc + ci
        if slopes_ref is not None:
            # ALiBi: slope_h * (k_pos - q_pos); under sp sharding both
            # positions are shard-local so the difference stays global
            rel = (sj - qpos).astype(jnp.float32)     # [G*TC, TS]
            # slopes arrive pre-expanded [KV, G*TC] (lane order (g, ci))
            bias = slopes_ref[:][:, :, None] * rel[None, :, :]
            logits = logits + bias
        # sj < s_total guards the padded tail of a partial final tile
        # (sharded callers pass local depths that may exceed the local
        # extent, so sj <= qpos does not exclude the pad by itself)
        ok = ((sj <= qpos) & (sj < s_total)
              & (c * tc + ci < ntok_ref[r]) & (act_ref[r] > 0))
        logits = jnp.where(ok[None], logits, -1e30)
        l2 = logits.reshape(rows, ts)
        tile_max = jnp.max(l2, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_sc[:], tile_max)
        alpha = jnp.exp(m_sc[:] - m_new)
        # fully-masked lanes keep m_new at the -1e30 fill; force p to 0
        # so l stays 0 and the finish-guard zeros the output
        p = jnp.where(m_new > -1e29, jnp.exp(l2 - m_new), 0.0)
        l_sc[:] = l_sc[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_sc[:] = m_new
        # vt's out-of-range pad columns (partial final S tile) may hold
        # NaN; p is 0 there but 0*NaN = NaN, so zero them explicitly
        col_ok = (t * ts + jax.lax.broadcasted_iota(
            jnp.int32, (1, ts, 1), 1)) < s_total
        p_kv = p.reshape(kv, g * tc, ts)
        if vs_ref is not None:
            # V dequant: fold the per-position scale into p (f32).  The
            # scale tile's out-of-range pad columns may hold NaN like
            # vt's — p is 0 there but 0*NaN = NaN, so zero the scales
            # on the same col_ok guard vt gets below
            vst = jnp.where(col_ok.reshape(1, 1, ts),
                            vs_ref[:].reshape(kv, 1, ts), 0.0)
            p_kv = p_kv * vst
            vt = vt.astype(qv.dtype)
        vt = jnp.where(col_ok, vt, 0)
        pv = jax.lax.dot_general(
            p_kv.astype(vt.dtype), vt,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha + pv.reshape(rows, d)

    @pl.when(t == nt - 1)
    def _finish():
        if partial:
            o_ref[:] = acc_sc[:].reshape(1, kv, g, tc, d)
            m_ref[:] = m_sc[:].reshape(1, 1, rows)
            l_ref[:] = l_sc[:].reshape(1, 1, rows)
        else:
            l = l_sc[:]
            l = jnp.where(l == 0, 1.0, l)      # invalid queries: zeros
            o_ref[:] = (acc_sc[:] / l).reshape(1, kv, g, tc, d).astype(
                o_ref.dtype)


def _pick_tiles(C: int, S: int, KV: int, G: int, D: int):
    """Joint (TC, TS) choice minimizing K/V re-reads under the VMEM
    logits budget.

    Every C-tile re-reads the row's whole attended K/V prefix, so the
    cache traffic is proportional to NC = C/TC — r5 XProf on a 1.4B/8k
    prefill chunk showed the attend at 42% of the step with the old
    ts=1024/tc=32 choice (16 re-reads of the prefix per chunk per
    layer).  Shrinking TS buys a larger TC inside the same
    KVG*TC*TS f32 logits budget and cuts NC ~4x; TS stays >= 256 so
    the K/V tile DMAs keep their efficiency and the grid stays coarse.
    Tie-break prefers the larger TS (fewer grid steps)."""
    import os

    if os.environ.get("FF_PF_TS") and os.environ.get("FF_PF_TC"):
        return (int(os.environ["FF_PF_TC"]),
                int(os.environ["FF_PF_TS"]))   # calibration override
    budget = 6 * 1024 * 1024                   # logits + p f32 temps
    best = None
    for ts in (1024, 512, 256):
        if ts > max(S, 256):
            continue
        cap = budget // (KV * G * ts * 2 * 4)
        tc = C
        while tc > 16 and tc > cap:
            tc //= 2
        nc = -(-C // tc)
        # chip-calibrated cost (r5, 1.4B/8k in-model sweep): each C-tile
        # re-reads the attended prefix (~nc * S/ts tile reads), and each
        # grid step pays a fixed pipeline/rescale cost worth ~6 tile
        # reads — shrinking ts below 512 multiplied the grid and LOST
        # in-model despite fewer prefix re-reads
        steps = nc * (S // ts)
        cost = steps * (1 + 6 * 1024 // ts)
        if best is None or cost < best[0]:
            best = (cost, tc, ts)
    return best[1], best[2]


def _prefill_call(q, ck, cv, depth, ntok, active, scale, interpret,
                  tc, ts, s_bound, slopes, partial: bool,
                  k_scale=None, v_scale=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, C, H, D = q.shape
    KV = ck.shape[1]
    G = H // KV
    quant = k_scale is not None
    assert quant == (v_scale is not None)
    # int4 carriers pack 2 codes/byte along S: the carrier is half the
    # LOGICAL length and the f32 scale frames (always logical-length)
    # reveal the ratio — pack derives from static shapes, no new
    # static_argnames (flash_decode._attend_call's convention)
    pack = (k_scale.shape[2] // ck.shape[2]) if quant else 1
    assert pack in (1, 2), (k_scale.shape, ck.shape)
    S = ck.shape[2] * pack                       # logical positions
    assert H == KV * G and ck.shape == cv.shape == (R, KV, S // pack, D)
    if quant:
        assert k_scale.shape == v_scale.shape == (R, KV, S), (
            k_scale.shape, (R, KV, S))
    if tc is None or ts is None:
        tc0, ts0 = _pick_tiles(C, S, KV, G, D)
        tc, ts = tc or tc0, ts or ts0
    assert C % tc == 0, (C, tc)
    assert ts % pack == 0, (ts, pack)
    nc = C // tc
    nt = pl.cdiv(min(s_bound, S) if s_bound else S, ts)
    depth = depth.astype(jnp.int32)
    ntok = ntok.astype(jnp.int32)
    active = active.astype(jnp.int32)
    # last S-tile each (row, C-tile) needs: its highest real query sits
    # at depth + min((c+1)*tc, ntok) - 1.  C-tiles past the row's span
    # (or inactive rows) clamp to tile 0 — one DMA, compute skipped.
    # Clamp below at 0: sharded callers pass signed local depths.
    qmax = jnp.minimum((jnp.arange(nc, dtype=jnp.int32) + 1) * tc,
                       ntok[:, None])                      # [R, NC]
    has_q = (jnp.arange(nc, dtype=jnp.int32) * tc < ntok[:, None])
    last = jnp.where(has_q & (active[:, None] > 0),
                     jnp.clip((depth[:, None] + qmax - 1) // ts,
                              0, nt - 1), 0).astype(jnp.int32)

    # pre-transpose q once in XLA: [R,C,H,D] -> [R,KV,G,C,D]
    qt = q.reshape(R, C, KV, G, D).transpose(0, 2, 3, 1, 4)

    alibi = slopes is not None
    kernel = functools.partial(_kernel, ts=ts, tc=tc, kv=KV, g=G, d=D,
                               s_total=S, scale=float(scale),
                               alibi=alibi, partial=partial, quant=quant,
                               pack=pack)
    # carrier K/V blocks are ts//pack wide on the SAME clamped index
    # maps (block-index space is unchanged — block t holds logical
    # positions [t*ts, (t+1)*ts) at half width when packed)
    in_specs = [
        pl.BlockSpec((1, KV, G, tc, D),
                     lambda r, c, t, *_: (r, 0, 0, c, 0)),
        pl.BlockSpec((1, KV, ts // pack, D),
                     lambda r, c, t, last, *_: (
                         r, 0, jnp.minimum(t, last[r, c]), 0)),
        pl.BlockSpec((1, KV, ts // pack, D),
                     lambda r, c, t, last, *_: (
                         r, 0, jnp.minimum(t, last[r, c]), 0)),
    ]
    inputs = [qt, ck, cv]
    if quant:
        # f32 scale tiles ride the K/V tiles' clamped index map
        for sc in (k_scale, v_scale):
            in_specs.append(pl.BlockSpec(
                (1, KV, ts),
                lambda r, c, t, last, *_: (
                    r, 0, jnp.minimum(t, last[r, c]))))
            inputs.append(sc)
    if alibi:
        # per-KV-head slopes: within a kv group the G query heads have
        # distinct slopes, so ship the full [H] table reshaped [KV, G]
        # and index it [kv, g*tc] in-kernel — but g*tc interleaves g and
        # ci, so expand to [KV, G*TC] host-side instead (tiny)
        sl = jnp.broadcast_to(
            jnp.asarray(slopes, jnp.float32).reshape(KV, G, 1),
            (KV, G, tc)).reshape(KV, G * tc)
        in_specs.append(
            pl.BlockSpec((KV, G * tc), lambda r, c, t, *_: (0, 0)))
        inputs.append(sl)
    out_spec = pl.BlockSpec((1, KV, G, tc, D),
                            lambda r, c, t, *_: (r, 0, 0, c, 0))
    if partial:
        out_specs = (out_spec,
                     pl.BlockSpec((1, 1, KV * G * tc),
                                  lambda r, c, t, *_: (r, c, 0)),
                     pl.BlockSpec((1, 1, KV * G * tc),
                                  lambda r, c, t, *_: (r, c, 0)))
        out_shape = (
            jax.ShapeDtypeStruct((R, KV, G, C, D), jnp.float32),
            jax.ShapeDtypeStruct((R, nc, KV * G * tc), jnp.float32),
            jax.ShapeDtypeStruct((R, nc, KV * G * tc), jnp.float32))
    else:
        out_specs = out_spec
        out_shape = jax.ShapeDtypeStruct((R, KV, G, C, D), q.dtype)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(R, nc, nt),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((KV * G * tc, 1), jnp.float32),   # running max
            pltpu.VMEM((KV * G * tc, 1), jnp.float32),   # running sum
            pltpu.VMEM((KV * G * tc, D), jnp.float32),   # accumulator
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec, out_shape=out_shape,
        interpret=interpret,
    )(last, depth, ntok, active, *inputs)


def _ml_to_heads(ml, R, nc, tc, KV, G):
    """[R, NC, KV*G*TC] kernel layout -> [R, KV, G, NC*TC] (= C)."""
    return (ml.reshape(R, nc, KV, G, tc)
              .transpose(0, 2, 3, 1, 4).reshape(R, KV, G, nc * tc))


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret", "tc", "ts",
                                    "s_bound"))
def flash_prefill_attend(q, ck, cv, depth, ntok, active, scale: float,
                         interpret: bool = False, tc=None, ts=None,
                         s_bound=None, slopes=None, k_scale=None,
                         v_scale=None):
    """q [R,C,H,D] against cache [R,KV,S,D], causal at per-row offset
    ``depth`` (query c attends cache positions <= depth[r]+c, queries
    c >= ntok[r] and inactive rows produce zeros) -> [R,C,H,D].
    ``slopes``: optional [H] ALiBi per-head slopes.

    ``s_bound``: static upper bound on attended positions (the host's
    attend bucket, >= every depth+ntok).  It bounds the GRID, not just
    the mask: without it a shallow chunk still cycles cdiv(S, ts) grid
    steps per (row, C-tile) whose pruned programs cost ~1-2 us each —
    at 24 layers x 8 C-tiles that fixed overhead erased the kernel's
    win on the early chunks of a long prompt.

    The caller scatters the chunk's K/V into the cache FIRST
    (positions [depth, depth+ntok)), mirroring the jnp path
    (ops/serving_attention.py _scatter_chunk then _attend).
    """
    R, C, H, D = q.shape
    out = _prefill_call(q, ck, cv, depth, ntok, active, scale,
                        interpret, tc, ts, s_bound, slopes,
                        partial=False, k_scale=k_scale, v_scale=v_scale)
    # [R,KV,G,C,D] -> [R,C,H,D]
    return out.transpose(0, 3, 1, 2, 4).reshape(R, C, H, D)


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret", "tc", "ts",
                                    "s_bound"))
def flash_prefill_attend_partial(q, ck, cv, depth, ntok, active,
                                 scale: float, interpret: bool = False,
                                 tc=None, ts=None, s_bound=None,
                                 slopes=None, k_scale=None,
                                 v_scale=None):
    """Partial (unnormalized) flash prefill for cross-shard combines:
    returns (acc [R,KV,G,C,D] f32, m [R,KV,G,C] f32, l [R,KV,G,C] f32)
    where out = acc / l after the standard flash merge across shards."""
    from jax.experimental import pallas as pl

    R, C, H, D = q.shape
    KV = ck.shape[1]
    G = H // KV
    # scale frames are always logical-length: int4 carriers are half
    # the logical extent, so size the tiles off the scales when present
    s_log = k_scale.shape[2] if k_scale is not None else ck.shape[2]
    tc0, ts0 = _pick_tiles(C, s_log, KV, G, D)
    tc, ts = tc or tc0, ts or ts0
    acc, m, l = _prefill_call(q, ck, cv, depth, ntok, active, scale,
                              interpret, tc, ts, s_bound, slopes,
                              partial=True, k_scale=k_scale,
                              v_scale=v_scale)
    nc = C // tc
    return (acc, _ml_to_heads(m, R, nc, tc, KV, G),
            _ml_to_heads(l, R, nc, tc, KV, G))


def _append_kernel(base_ref, roll_ref, lo_ref, hi_ref, act_ref,  # prefetch
                   kal_ref, val_ref,     # VMEM [1, KV, W, D] row blocks
                   ck_hbm, cv_hbm,               # ANY (aliased inputs)
                   ck_out, cv_out,               # aliased outputs
                   win_k, win_v, sem_k, sem_v, *, align: int = 16,
                   pack: int = 1):
    """Per-row in-place chunk append: overlay the row's ``align``-ed
    window [base, base+W) with the pre-aligned new K/V on the window-
    relative span [lo, hi) (chunk entry jj - shift lands at window
    position jj; the rotate amount arrives pre-reduced mod W in
    ``roll``).  ``align`` is the CARRIER-row multiplier for the
    prefetched base: 16 for bf16/f32 caches, 32 for int8 AND for int4
    carriers (64 logical positions = 32 carrier sublanes — the int8
    sublane tiling at half width).  Same rationale as
    flash_decode._append_kernel: with both the append and the attend as
    Pallas calls the cache never crosses an XLA layout boundary (XLA
    prefers S-major for its own scatter and inserts whole-cache
    relayout copies at custom-call boundaries — measured ~9 ms/step at
    1.4B/8k).  Quantized chunks arrive as EXACT integer codes staged
    f32 AT LOGICAL LENGTH (the rotate needs 32-bit data); the overlay's
    astype to the int8 window truncates losslessly, and for ``pack`` ==
    2 the kernel packs pairs of rotated logical codes into carrier
    bytes in-register, masking each nibble by its own logical-position
    bound (a chunk may start/end mid-byte)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r = pl.program_id(0)
    W = win_k.shape[1]                 # carrier rows (= logical / pack)

    @pl.when(act_ref[r] > 0)
    def _():
        # base*align keeps the S-offset PROVABLY divisible by the
        # sublane tiling (a raw scalar-prefetch offset fails Mosaic's
        # divisibility check on the memref slice)
        b = base_ref[r] * align
        ink = pltpu.make_async_copy(
            ck_out.at[r, :, pl.ds(b, W), :], win_k, sem_k)
        inv = pltpu.make_async_copy(
            cv_out.at[r, :, pl.ds(b, W), :], win_v, sem_v)
        ink.start()
        inv.start()
        ink.wait()
        inv.wait()
        # align the zero-padded chunk to the window offset with a
        # dynamic sublane rotate (entry jj of the rolled chunk is
        # chunk[jj - shift]; wrapped entries land outside sel's range) —
        # doing this shift in XLA was a take_along_axis gather measured
        # at ~1.5 ms/layer, ~60% of a whole flash prefill step.  The
        # rotate is per-kv-head 2D (tpu.dynamic_rotate rejects 3D
        # vectors; kv is statically small) on f32 staging (it also
        # rejects 16-bit data — the chunk is shipped f32 and cast on
        # the overlay, exact for bf16-derived values).
        kv = win_k.shape[0]
        if pack == 1:
            jj = jax.lax.broadcasted_iota(jnp.int32, (1, W, 1), 1)
            sel = (jj >= lo_ref[r]) & (jj < hi_ref[r])
            for i in range(kv):
                win_k[i] = jnp.where(
                    sel[0],
                    pltpu.roll(kal_ref[0, i], roll_ref[r], 0).astype(
                        win_k.dtype),
                    win_k[i])
                win_v[i] = jnp.where(
                    sel[0],
                    pltpu.roll(val_ref[0, i], roll_ref[r], 0).astype(
                        win_v.dtype),
                    win_v[i])
        else:
            # int4 pack: carrier byte at window row jc covers LOGICAL
            # window positions 2*jc (low nibble) and 2*jc+1 (high) —
            # each nibble overlays independently so lo/hi (logical)
            # may land mid-byte and the neighbour nibble survives
            d = win_k.shape[2]
            jc = jax.lax.broadcasted_iota(jnp.int32, (W, 1), 0)
            in_lo = (2 * jc >= lo_ref[r]) & (2 * jc < hi_ref[r])
            in_hi = (2 * jc + 1 >= lo_ref[r]) & (2 * jc + 1 < hi_ref[r])
            for i in range(kv):
                rk = pltpu.roll(kal_ref[0, i], roll_ref[r], 0)
                rv = pltpu.roll(val_ref[0, i], roll_ref[r], 0)
                # [2W logical, D] -> even/odd logical rows per byte
                rk = rk[:2 * W].astype(jnp.int32).reshape(W, 2, d)
                rv = rv[:2 * W].astype(jnp.int32).reshape(W, 2, d)
                ok32 = win_k[i].astype(jnp.int32)
                ov32 = win_v[i].astype(jnp.int32)
                k_lo = jnp.where(in_lo, rk[:, 0] & 0x0F, ok32 & 0x0F)
                k_hi = jnp.where(in_hi, rk[:, 1] & 0x0F,
                                 (ok32 >> 4) & 0x0F)
                v_lo = jnp.where(in_lo, rv[:, 0] & 0x0F, ov32 & 0x0F)
                v_hi = jnp.where(in_hi, rv[:, 1] & 0x0F,
                                 (ov32 >> 4) & 0x0F)
                win_k[i] = (k_lo | (k_hi << 4)).astype(win_k.dtype)
                win_v[i] = (v_lo | (v_hi << 4)).astype(win_v.dtype)
        outk = pltpu.make_async_copy(
            win_k, ck_out.at[r, :, pl.ds(b, W), :], sem_k)
        outv = pltpu.make_async_copy(
            win_v, cv_out.at[r, :, pl.ds(b, W), :], sem_v)
        outk.start()
        outv.start()
        outk.wait()
        outv.wait()


def chunk_append(ck, cv, k_new, v_new, depth, ntok, active,
                 interpret: bool = False, s_offset=None,
                 pack: int = 1):
    """In-place (aliased) chunk KV append on [R,KV,S,D] caches via async
    DMA — the Pallas twin of _scatter_chunk for the flash-prefill path.

    k_new/v_new arrive [R, C, KV, D] (projection layout); XLA only
    transposes and zero-pads them to the window extent (cheap, fused),
    while the per-row shift to the 16-aligned window offset happens
    inside the kernel as a dynamic sublane rotate; the kernel does a
    masked overlay read-modify-write of the [base, base+C+32) window.

    ``s_offset``: global position of this cache's first slot (sharded
    callers).  The row's local span [depth-s_offset, +ntok) may partly
    or wholly miss [0, S) — the overlay writes just the intersection,
    so a chunk straddling sp shard boundaries appends correctly with
    each shard taking its piece.

    int8 caches: pass the chunk PRE-QUANTIZED (int8 codes from
    quantization.quantize_kv) — the f32 staging carries the exact
    integer codes and the overlay's cast back to int8 is lossless; the
    [R, KV, S] scale tensors are the caller's to update
    (flash_prefill_attention scatters them XLA-side).

    ``pack`` == 2 (int4 carriers): ``ck``/``cv`` are int8 carriers at
    HALF the logical extent; the chunk arrives as int4 codes in [-7, 7]
    (quantization.quantize_kv_int4) staged f32 at LOGICAL length, and
    the kernel packs them into carrier nibbles in-register.  All window
    arithmetic here stays in LOGICAL positions — the alignment widens
    to 64 (= 32 carrier sublanes, the PR-2 invariant doubled)."""
    import functools as _ft

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, KV, S_c, D = ck.shape
    S = S_c * pack                    # logical positions
    C = k_new.shape[1]
    assert pack in (1, 2) and (pack == 1 or ck.dtype.itemsize == 1)
    align = (32 * pack) if ck.dtype.itemsize == 1 else 16
    W = C + max(align, 32)            # logical window extent
    assert S % align == 0 and W <= S, (S, W, align)
    assert W % align == 0, (C, align)   # gate: int8 C%32, int4 C%64
    depth = depth.astype(jnp.int32)
    ntok = jnp.minimum(ntok.astype(jnp.int32), C)
    active = active.astype(jnp.int32)
    loc = depth - s_offset if s_offset is not None else depth  # signed
    active = active * ((loc < S) & (loc + ntok > 0))
    base = jnp.clip((jnp.maximum(loc, 0) // align) * align, 0, S - W)
    shift = loc - base                 # window pos of chunk entry 0
    roll = shift % W                   # nonneg rotate amount
    pad = [(0, 0), (0, 0), (0, W - C), (0, 0)]
    # f32 staging: the in-kernel dynamic rotate needs 32-bit data
    k_al = jnp.pad(k_new.transpose(0, 2, 1, 3),          # [R, KV, W, D]
                   pad).astype(jnp.float32)
    v_al = jnp.pad(v_new.transpose(0, 2, 1, 3),
                   pad).astype(jnp.float32)
    Wc = W // pack                     # carrier window rows

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(R,),
        in_specs=[
            # per-row blocks: whole-array VMEM staging would put
            # R x KV x W x D f32 on chip at once (~18 MB at batch 8,
            # C=512 — over the VMEM budget); one row at a time is ~1 MB
            pl.BlockSpec((1, KV, W, D), lambda r, *_: (r, 0, 0, 0)),
            pl.BlockSpec((1, KV, W, D), lambda r, *_: (r, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),           # ck
            pl.BlockSpec(memory_space=pl.ANY),           # cv
        ],
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)),
        scratch_shapes=[pltpu.VMEM((KV, Wc, D), ck.dtype),
                        pltpu.VMEM((KV, Wc, D), cv.dtype),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(())],
    )
    return pl.pallas_call(
        _ft.partial(_append_kernel, align=align // pack, pack=pack),
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(ck.shape, ck.dtype),
                   jax.ShapeDtypeStruct(cv.shape, cv.dtype)),
        input_output_aliases={7: 0, 8: 1},   # +5 scalar-prefetch args
        interpret=interpret,
    )(base // align, roll, shift, shift + ntok, active, k_al, v_al,
      ck, cv)


def flash_prefill_attention(q, k_new, v_new, ck, cv, depth, ntok,
                            active, scale: float,
                            interpret: bool = False, s_bound=None,
                            slopes=None, k_scale=None, v_scale=None):
    """Scatter-then-attend prefill step (drop-in for the op layer):
    writes the chunk's K/V at each active row's [depth, depth+ntok)
    (in place, Pallas DMA), then runs the length-tiled attention.
    q [R,C,H,D], k_new/v_new [R,C,KV,D], caches [R,KV,S,D];
    ``s_bound`` = the host's static attend bucket (grid bound).
    Returns (out [R,C,H,D], ck, cv) — int8 caches (``k_scale``/
    ``v_scale`` [R, KV, S] f32 passed) additionally return the updated
    scale tensors: (out, ck, cv, k_scale, v_scale)."""
    if k_scale is not None:
        from ..quantization import (quantize_kv, quantize_kv_int4,
                                    scatter_kv_scales)

        pack = k_scale.shape[2] // ck.shape[2]   # 2 = int4 carrier
        qfn = quantize_kv_int4 if pack == 2 else quantize_kv
        k_q, k_sc = qfn(k_new)               # [R,C,KV,D] -> q, [R,C,KV]
        v_q, v_sc = qfn(v_new)
        ck, cv = chunk_append(ck, cv, k_q, v_q, depth, ntok, active,
                              interpret=interpret, pack=pack)
        k_scale = scatter_kv_scales(k_scale, k_sc, depth, active)
        v_scale = scatter_kv_scales(v_scale, v_sc, depth, active)
        out = flash_prefill_attend(q, ck, cv, depth, ntok, active,
                                   scale, interpret=interpret,
                                   s_bound=s_bound, slopes=slopes,
                                   k_scale=k_scale, v_scale=v_scale)
        return out, ck, cv, k_scale, v_scale
    ck, cv = chunk_append(ck, cv, k_new, v_new, depth, ntok, active,
                          interpret=interpret)
    out = flash_prefill_attend(q, ck, cv, depth, ntok, active, scale,
                               interpret=interpret, s_bound=s_bound,
                               slopes=slopes)
    return out, ck, cv


def flash_prefill_attention_sharded(q, k_new, v_new, ck, cv, depth,
                                    ntok, active, scale: float, mesh,
                                    interpret: bool = False,
                                    slopes=None, s_bound=None,
                                    k_scale=None, v_scale=None):
    """shard_map'd scatter-then-attend prefill over the serving mesh —
    the chunked-prefill twin of
    flash_decode.flash_decode_attention_sharded.

    tp shards the kv-head axis (independent heads, no collective); sp
    shards the cache length: each shard appends its INTERSECTION of the
    chunk span [depth, depth+ntok) (chunk_append's s_offset handling),
    runs a partial online softmax over its local positions, and the
    outputs merge with the standard flash combine over 'sp'.  int8
    caches carry their [R, KV, S] scale tensors through the same
    sharding (each shard scatters its intersection of the chunk's
    scales at shard-local offsets).
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from .flash_decode import mesh_axes

    tp_ax, sp_ax, tp, sp = mesh_axes(mesh)
    q_spec = P(None, None, tp_ax, None)        # [R, C, H, D]
    cache_spec = P(None, tp_ax, sp_ax, None)
    sc_spec = P(None, tp_ax, sp_ax)
    slope_spec = P(tp_ax)
    has_alibi = slopes is not None
    quant = k_scale is not None
    # pack from GLOBAL shapes: sp shards carrier and scales in
    # lockstep, so the logical/carrier ratio is shard-invariant
    pack = (k_scale.shape[2] // ck.shape[2]) if quant else 1
    depth = depth.astype(jnp.int32)
    ntok = ntok.astype(jnp.int32)
    active = active.astype(jnp.int32)

    def body(q, kn, vn, ck, cv, depth, ntok, active, *rest):
        from .flash_decode import flash_merge

        rest = list(rest)
        ks, vs = (rest.pop(0), rest.pop(0)) if quant else (None, None)
        sl = rest.pop(0) if has_alibi else None
        S_l = ck.shape[2] * pack            # logical shard extent
        s0 = (jax.lax.axis_index(sp_ax) * S_l) if sp > 1 else 0
        loc = depth - s0
        # local grid bound: the host's GLOBAL attend bucket clipped to
        # the shard extent (short prompts on a long allocation must not
        # cycle the full pruned grid — flash_prefill_attend docstring)
        sb = min(s_bound, S_l) if s_bound else None
        if quant:
            from ..quantization import (quantize_kv, quantize_kv_int4,
                                        scatter_kv_scales)

            qfn = quantize_kv_int4 if pack == 2 else quantize_kv
            kn_q, k_sc = qfn(kn)
            vn_q, v_sc = qfn(vn)
            ck, cv = chunk_append(ck, cv, kn_q, vn_q, depth, ntok,
                                  active, interpret=interpret,
                                  s_offset=s0, pack=pack)
            ks = scatter_kv_scales(ks, k_sc, loc, active)
            vs = scatter_kv_scales(vs, v_sc, loc, active)
        else:
            ck, cv = chunk_append(ck, cv, kn, vn, depth, ntok, active,
                                  interpret=interpret, s_offset=s0)
        if sp <= 1:
            out = flash_prefill_attend(q, ck, cv, depth, ntok, active,
                                       scale, interpret=interpret,
                                       slopes=sl, s_bound=sb,
                                       k_scale=ks, v_scale=vs)
            return ((out, ck, cv, ks, vs) if quant else (out, ck, cv))
        # shards wholly above every query of the row (loc + ntok <= 0)
        # are fully masked; sj <= qpos handles partial overlap since
        # both are local
        att_act = active * (loc + ntok > 0)
        acc, m, l = flash_prefill_attend_partial(
            q, ck, cv, loc, ntok, att_act, scale, interpret=interpret,
            slopes=sl, s_bound=sb, k_scale=ks, v_scale=vs)
        out = flash_merge(acc, m, l, sp_ax)
        R, KV, G, C, D = out.shape
        out = out.transpose(0, 3, 1, 2, 4).reshape(R, C, KV * G, D)
        return ((out.astype(q.dtype), ck, cv, ks, vs) if quant
                else (out.astype(q.dtype), ck, cv))

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(q_spec, q_spec, q_spec, cache_spec, cache_spec,
                  P(), P(), P())
        + ((sc_spec, sc_spec) if quant else ())
        + ((slope_spec,) if has_alibi else ()),
        out_specs=(q_spec, cache_spec, cache_spec)
        + ((sc_spec, sc_spec) if quant else ()),
        check_rep=False)
    args = (q, k_new, v_new, ck, cv, depth, ntok, active)
    if quant:
        args += (k_scale, v_scale)
    if has_alibi:
        args += (jnp.asarray(slopes, jnp.float32),)
    return fn(*args)


# --------------------------------------------------------------- paged
# Physical paged KV (PR 10) — the chunked-prefill / tree-verify twin
# of flash_decode's paged kernels: the (row, C-tile, S-tile) grid's
# S axis walks LOGICAL PAGES and the K/V BlockSpec index maps resolve
# each page to its frame through the scalar-prefetched page table.
# The kernel body is the dense `_kernel` unchanged (grid index t is
# the logical page; all causal/ALiBi math stays in global positions).


def _paged_kernel(table_ref, *rest, **kw):
    """The dense prefill kernel behind a table indirection (the table
    ref feeds the BlockSpec index maps alone)."""
    return _kernel(*rest, **kw)


def _pick_tc_paged(C: int, L: int, KV: int, G: int) -> int:
    """Largest C-tile whose f32 logits+p temps ([KVG*TC, L] twice) fit
    the VMEM budget — the paged S-tile is pinned to the frame length,
    so only TC is free."""
    budget = 6 * 1024 * 1024
    cap = max(1, budget // (KV * G * L * 2 * 4))
    tc = C
    while tc > 16 and tc > cap:
        tc //= 2
    return tc


def _paged_prefill_call(q, pk, pv, table, depth, ntok, active, scale,
                        interpret, tc, s_bound, slopes,
                        k_scale=None, v_scale=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, C, H, D = q.shape
    F, KV = pk.shape[:2]
    G = H // KV
    P = table.shape[1]
    quant = k_scale is not None
    assert quant == (v_scale is not None)
    # int4 carrier frames are half the logical frame length; the f32
    # scale frames stay logical-length and reveal the pack ratio
    pack = (k_scale.shape[2] // pk.shape[2]) if quant else 1
    assert pack in (1, 2), (k_scale.shape, pk.shape)
    L = pk.shape[2] * pack                        # logical frame length
    assert H == KV * G and pk.shape == pv.shape == (F, KV, L // pack, D)
    if quant:
        assert k_scale.shape == v_scale.shape == (F, KV, L), (
            k_scale.shape, (F, KV, L))
    if tc is None:
        tc = _pick_tc_paged(C, L, KV, G)
    assert C % tc == 0, (C, tc)
    nc = C // tc
    nt = min(P, pl.cdiv(s_bound, L)) if s_bound else P
    depth = depth.astype(jnp.int32)
    ntok = ntok.astype(jnp.int32)
    active = active.astype(jnp.int32)
    table = jnp.clip(jnp.asarray(table, jnp.int32), 0, F - 1)
    # last logical page each (row, C-tile) needs (the dense kernel's
    # pruning clamp, with ts = the frame length)
    qmax = jnp.minimum((jnp.arange(nc, dtype=jnp.int32) + 1) * tc,
                       ntok[:, None])                      # [R, NC]
    has_q = (jnp.arange(nc, dtype=jnp.int32) * tc < ntok[:, None])
    last = jnp.where(has_q & (active[:, None] > 0),
                     jnp.clip((depth[:, None] + qmax - 1) // L,
                              0, nt - 1), 0).astype(jnp.int32)

    qt = q.reshape(R, C, KV, G, D).transpose(0, 2, 3, 1, 4)

    alibi = slopes is not None
    kernel = functools.partial(_paged_kernel, ts=L, tc=tc, kv=KV, g=G,
                               d=D, s_total=nt * L, scale=float(scale),
                               alibi=alibi, partial=False, quant=quant,
                               pack=pack)
    kv_map = lambda r, c, t, tab, last, *_: (  # noqa: E731
        tab[r, jnp.minimum(t, last[r, c])], 0, 0, 0)
    in_specs = [
        pl.BlockSpec((1, KV, G, tc, D),
                     lambda r, c, t, *_: (r, 0, 0, c, 0)),
        pl.BlockSpec((1, KV, L // pack, D), kv_map),
        pl.BlockSpec((1, KV, L // pack, D), kv_map),
    ]
    inputs = [qt, pk, pv]
    if quant:
        for sc in (k_scale, v_scale):
            in_specs.append(pl.BlockSpec(
                (1, KV, L),
                lambda r, c, t, tab, last, *_: (
                    tab[r, jnp.minimum(t, last[r, c])], 0, 0)))
            inputs.append(sc)
    if alibi:
        sl = jnp.broadcast_to(
            jnp.asarray(slopes, jnp.float32).reshape(KV, G, 1),
            (KV, G, tc)).reshape(KV, G * tc)
        in_specs.append(
            pl.BlockSpec((KV, G * tc), lambda r, c, t, *_: (0, 0)))
        inputs.append(sl)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(R, nc, nt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, KV, G, tc, D),
                               lambda r, c, t, *_: (r, 0, 0, c, 0)),
        scratch_shapes=[
            pltpu.VMEM((KV * G * tc, 1), jnp.float32),   # running max
            pltpu.VMEM((KV * G * tc, 1), jnp.float32),   # running sum
            pltpu.VMEM((KV * G * tc, D), jnp.float32),   # accumulator
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, KV, G, C, D), q.dtype),
        interpret=interpret,
    )(table, last, depth, ntok, active, *inputs)


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret", "tc",
                                    "s_bound"))
def paged_prefill_attend(q, pk, pv, table, depth, ntok, active,
                         scale: float, interpret: bool = False,
                         tc=None, s_bound=None, slopes=None,
                         k_scale=None, v_scale=None):
    """q [R,C,H,D] against the paged pool through ``table``, causal at
    per-row offset ``depth`` — the page-table twin of
    :func:`flash_prefill_attend` (chunked prefill AND the spec
    drivers' tree-verify prompt phase ride this shape).  ``s_bound``
    bounds the walked pages like the dense kernel bounds its grid."""
    R, C, H, D = q.shape
    out = _paged_prefill_call(q, pk, pv, table, depth, ntok, active,
                              scale, interpret, tc, s_bound, slopes,
                              k_scale=k_scale, v_scale=v_scale)
    return out.transpose(0, 3, 1, 2, 4).reshape(R, C, H, D)


def _paged_chunk_kernel(frame_ref, roll_ref, lo_ref, hi_ref, act_ref,
                        kal_ref, val_ref,     # VMEM [1, KV, Wc, D]
                        pk_hbm, pv_hbm,       # ANY (aliased inputs)
                        pk_out, pv_out,       # aliased outputs
                        win_k, win_v, sem_k, sem_v, *, L: int,
                        pack: int = 1):
    """Per-(row, straddled-frame) chunk overlay: frame p of the chunk's
    span RMWs as a WHOLE frame window [0, L) — frames are page_len
    wide, page_len % 32 == 0, so every window is sublane-legal for
    every cache dtype.  The chunk arrives zero-padded f32 and rotates
    to the window offset in-kernel (the dense chunk_append's dynamic
    sublane rotate, with per-(r, p) rotate amounts).  ``L`` and the
    lo/hi bounds are LOGICAL positions; ``pack`` == 2 packs the rotated
    int4 codes into the frame's L/2 carrier bytes with per-nibble
    overlay masks (the dense _append_kernel's int4 path)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(act_ref[r, p] > 0)
    def _():
        f = frame_ref[r, p]
        ink = pltpu.make_async_copy(pk_out.at[f], win_k, sem_k)
        inv = pltpu.make_async_copy(pv_out.at[f], win_v, sem_v)
        ink.start()
        inv.start()
        ink.wait()
        inv.wait()
        kv = win_k.shape[0]
        if pack == 1:
            jj = jax.lax.broadcasted_iota(jnp.int32, (1, L, 1), 1)
            sel = (jj >= lo_ref[r, p]) & (jj < hi_ref[r, p])
            for i in range(kv):
                rk = pltpu.roll(kal_ref[0, i], roll_ref[r, p], 0)
                rv = pltpu.roll(val_ref[0, i], roll_ref[r, p], 0)
                win_k[i] = jnp.where(sel[0], rk[:L].astype(win_k.dtype),
                                     win_k[i])
                win_v[i] = jnp.where(sel[0], rv[:L].astype(win_v.dtype),
                                     win_v[i])
        else:
            Lc = L // 2
            d = win_k.shape[2]
            jc = jax.lax.broadcasted_iota(jnp.int32, (Lc, 1), 0)
            in_lo = ((2 * jc >= lo_ref[r, p])
                     & (2 * jc < hi_ref[r, p]))
            in_hi = ((2 * jc + 1 >= lo_ref[r, p])
                     & (2 * jc + 1 < hi_ref[r, p]))
            for i in range(kv):
                rk = pltpu.roll(kal_ref[0, i], roll_ref[r, p], 0)
                rv = pltpu.roll(val_ref[0, i], roll_ref[r, p], 0)
                rk = rk[:L].astype(jnp.int32).reshape(Lc, 2, d)
                rv = rv[:L].astype(jnp.int32).reshape(Lc, 2, d)
                ok32 = win_k[i].astype(jnp.int32)
                ov32 = win_v[i].astype(jnp.int32)
                k_lo = jnp.where(in_lo, rk[:, 0] & 0x0F, ok32 & 0x0F)
                k_hi = jnp.where(in_hi, rk[:, 1] & 0x0F,
                                 (ok32 >> 4) & 0x0F)
                v_lo = jnp.where(in_lo, rv[:, 0] & 0x0F, ov32 & 0x0F)
                v_hi = jnp.where(in_hi, rv[:, 1] & 0x0F,
                                 (ov32 >> 4) & 0x0F)
                win_k[i] = (k_lo | (k_hi << 4)).astype(win_k.dtype)
                win_v[i] = (v_lo | (v_hi << 4)).astype(win_v.dtype)
        outk = pltpu.make_async_copy(win_k, pk_out.at[f], sem_k)
        outv = pltpu.make_async_copy(win_v, pv_out.at[f], sem_v)
        outk.start()
        outv.start()
        outk.wait()
        outv.wait()


def paged_chunk_append(pk, pv, k_new, v_new, table, depth, ntok,
                       active, interpret: bool = False,
                       pack: int = 1):
    """In-place (aliased) chunk KV append on paged pools: the chunk
    [depth, depth+ntok) straddles up to cdiv(C, page_len)+1 frames and
    each (row, frame) program overlays its intersection — the same
    piecewise-overlay contract as the dense kernel's sp straddle
    handling, with the pieces resolved through the page table.  int8
    pools take the chunk PRE-QUANTIZED (exact codes staged f32, cast
    lossless); scale frames are the caller's (scatter_kv_scales_paged).
    ``pack`` == 2: int4 carrier frames at half the logical page_len —
    all span math here stays LOGICAL, the kernel packs nibbles."""
    import functools as _ft

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, KV, L_c, D = pk.shape
    L = L_c * pack                    # logical page length
    R, C = k_new.shape[:2]
    P = table.shape[1]
    assert pack in (1, 2) and (pack == 1 or pk.dtype.itemsize == 1)
    align = (32 * pack) if pk.dtype.itemsize == 1 else 16
    assert L % align == 0, (L, align)
    assert C % 16 == 0, C   # host chunk gate (pick_chunk pow2 >= 16)
    npc = -(-C // L) + 1    # frames a chunk can straddle
    depth = jnp.clip(depth.astype(jnp.int32), 0, P * L - 1)
    ntok = jnp.minimum(ntok.astype(jnp.int32), C)
    active = active.astype(jnp.int32)
    pidx = (depth // L)[:, None] + jnp.arange(npc,
                                              dtype=jnp.int32)  # [R,NPC]
    shift = depth[:, None] - pidx * L     # window pos of chunk entry 0
    lo = jnp.clip(shift, 0, L)
    hi = jnp.clip(shift + ntok[:, None], 0, L)
    frame = jnp.take_along_axis(jnp.asarray(table, jnp.int32),
                                jnp.clip(pidx, 0, P - 1), axis=1)
    # unleased pages carry the out-of-range sentinel: mask the overlay
    # instead of clipping onto somebody else's frame
    act = (active[:, None] * (hi > lo) * (pidx < P)
           * (frame >= 0) * (frame < F))
    frame = jnp.clip(frame, 0, F - 1)
    wc = max(C, L)          # rolled width must cover the window
    roll = shift % wc
    pad = [(0, 0), (0, 0), (0, wc - C), (0, 0)]
    k_al = jnp.pad(k_new.transpose(0, 2, 1, 3),          # [R, KV, Wc, D]
                   pad).astype(jnp.float32)
    v_al = jnp.pad(v_new.transpose(0, 2, 1, 3),
                   pad).astype(jnp.float32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(R, npc),
        in_specs=[
            pl.BlockSpec((1, KV, wc, D), lambda r, p, *_: (r, 0, 0, 0)),
            pl.BlockSpec((1, KV, wc, D), lambda r, p, *_: (r, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),           # pk
            pl.BlockSpec(memory_space=pl.ANY),           # pv
        ],
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)),
        scratch_shapes=[pltpu.VMEM((KV, L_c, D), pk.dtype),
                        pltpu.VMEM((KV, L_c, D), pv.dtype),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(())],
    )
    return pl.pallas_call(
        _ft.partial(_paged_chunk_kernel, L=L, pack=pack),
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(pk.shape, pk.dtype),
                   jax.ShapeDtypeStruct(pv.shape, pv.dtype)),
        input_output_aliases={7: 0, 8: 1},   # +5 scalar-prefetch args
        interpret=interpret,
    )(frame, roll, lo, hi, act, k_al, v_al, pk, pv)


def paged_prefill_attention(q, k_new, v_new, pk, pv, table, depth,
                            ntok, active, scale: float,
                            interpret: bool = False, s_bound=None,
                            slopes=None, k_scale=None, v_scale=None):
    """Scatter-then-attend prefill step on a paged pool (drop-in for
    the op layer): overlay the chunk across its straddled frames, then
    run the page-table attend.  Returns (out, pk, pv[, k_scale,
    v_scale]) like the dense twin."""
    if k_scale is not None:
        from ..quantization import (quantize_kv, quantize_kv_int4,
                                    scatter_kv_scales_paged)

        pack = k_scale.shape[2] // pk.shape[2]   # 2 = int4 carrier
        qfn = quantize_kv_int4 if pack == 2 else quantize_kv
        k_q, k_sc = qfn(k_new)               # [R,C,KV] scales
        v_q, v_sc = qfn(v_new)
        pk, pv = paged_chunk_append(pk, pv, k_q, v_q, table, depth,
                                    ntok, active, interpret=interpret,
                                    pack=pack)
        k_scale = scatter_kv_scales_paged(k_scale, k_sc, depth, active,
                                          table)
        v_scale = scatter_kv_scales_paged(v_scale, v_sc, depth, active,
                                          table)
        out = paged_prefill_attend(q, pk, pv, table, depth, ntok,
                                   active, scale, interpret=interpret,
                                   s_bound=s_bound, slopes=slopes,
                                   k_scale=k_scale, v_scale=v_scale)
        return out, pk, pv, k_scale, v_scale
    pk, pv = paged_chunk_append(pk, pv, k_new, v_new, table, depth,
                                ntok, active, interpret=interpret)
    out = paged_prefill_attend(q, pk, pv, table, depth, ntok, active,
                               scale, interpret=interpret,
                               s_bound=s_bound, slopes=slopes)
    return out, pk, pv


def paged_prefill_attention_sharded(q, k_new, v_new, pk, pv, table,
                                    depth, ntok, active, scale: float,
                                    mesh, interpret: bool = False,
                                    slopes=None, s_bound=None,
                                    k_scale=None, v_scale=None):
    """shard_map'd paged prefill: frames shard on the KV-head axis
    over the merged tp/sp group (see
    flash_decode.paged_decode_attention_sharded), tables replicate,
    each shard appends and attends its local heads — no collective."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from .flash_decode import paged_head_axes

    axes, size = paged_head_axes(mesh)
    head = axes[0] if len(axes) == 1 else (axes or None)
    q_spec = P(None, None, head, None)         # [R, C, H, D]
    pool_spec = P(None, head, None, None)
    sc_spec = P(None, head, None)
    slope_spec = P(head)
    has_alibi = slopes is not None
    quant = k_scale is not None
    depth = depth.astype(jnp.int32)
    ntok = ntok.astype(jnp.int32)
    active = active.astype(jnp.int32)
    table = jnp.asarray(table, jnp.int32)

    def body(q, kn, vn, pk, pv, table, depth, ntok, active, *rest):
        rest = list(rest)
        ks, vs = (rest.pop(0), rest.pop(0)) if quant else (None, None)
        sl = rest.pop(0) if has_alibi else None
        return paged_prefill_attention(
            q, kn, vn, pk, pv, table, depth, ntok, active, scale,
            interpret=interpret, s_bound=s_bound, slopes=sl,
            k_scale=ks, v_scale=vs)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(q_spec, q_spec, q_spec, pool_spec, pool_spec,
                  P(), P(), P(), P())
        + ((sc_spec, sc_spec) if quant else ())
        + ((slope_spec,) if has_alibi else ()),
        out_specs=(q_spec, pool_spec, pool_spec)
        + ((sc_spec, sc_spec) if quant else ()),
        check_rep=False)
    args = (q, k_new, v_new, pk, pv, table, depth, ntok, active)
    if quant:
        args += (k_scale, v_scale)
    if has_alibi:
        args += (jnp.asarray(slopes, jnp.float32),)
    return fn(*args)


def paged_prefill_path_ok(C: int, pk, mesh, pack: int = 1) -> bool:
    """Shape gate for the paged prefill kernels: an align-divisible
    multi-token chunk (16 bf16 / 32 int8 / 64 int4 — the overlay's
    cast and the window RMW; packed carriers double the logical
    alignment to keep 32 carrier sublanes), lane-aligned head dim, a
    per-program VMEM footprint (f32-staged LOGICAL chunk + carrier
    whole-frame windows) inside the budget, and an unsharded pool OR
    KV heads divisible by the merged tp/sp group.  ``L``/``C`` math is
    in LOGICAL positions (``pk`` is the carrier — half-width for
    int4)."""
    F, KV, L_c, D = pk.shape
    L = L_c * pack
    align = (32 * pack) if pk.dtype.itemsize == 1 else 16
    size = 1
    if mesh is not None:
        from .flash_decode import paged_head_axes

        axes, size = paged_head_axes(mesh)
        other = [a for a, s in mesh.shape.items()
                 if s > 1 and a not in axes]
        if other or KV % size:
            return False
    kv_l = KV // max(1, size)
    wc = max(C, L)
    append_vmem = kv_l * D * (wc * 8 + 2 * L_c * pk.dtype.itemsize)
    return (C >= align and C % align == 0 and D % 128 == 0
            and L % align == 0
            and append_vmem <= 11 * 1024 * 1024)


def prefill_path_ok(C: int, ck, mesh, pack: int = 1) -> bool:
    """Shape gate for the production op: multi-token chunk with
    lane-aligned head dim and a 16-divisible chunk (the append window
    arithmetic), an append window that FITS VMEM — the per-row window
    carries 8 bytes/position/KV-head/lane for the f32-staged chunk
    (k_al + v_al) plus 2 x cache-dtype for the win scratch, so wide-KV
    models (7B-class MHA, KV=32) cap at small chunks and a bf16
    KV=4/D=128 cache caps at ~C<=1750 (the C=2048 case, ~12.8 MB,
    failed Mosaic compilation on chip; the 11 MB budget keeps a margin
    below that single calibration point) — and an unsharded cache OR
    one sharded over tp/sp with shard-aligned extents (the per-SHARD
    window/VMEM limits are what count).  WHETHER flash beats the XLA
    attend is the host's cost decision
    (inference_manager.flash_prefill_wins) — this only says the kernel
    can run.  int8 caches additionally need 32-divisible chunks and
    per-shard extents (the int8 sublane tiling widens the append
    window's alignment to 32); int4 carriers (``pack`` == 2) double
    that to 64 LOGICAL positions — still 32 carrier sublanes — and
    the S math below is in logical positions (``ck`` is the
    half-width carrier)."""
    R, KV, S_c, D = ck.shape
    S = S_c * pack
    align = (32 * pack) if ck.dtype.itemsize == 1 else 16
    W = C + max(align, 32)            # logical append window
    tp = sp = 1
    if mesh is not None:
        from .flash_decode import mesh_axes

        tp_ax, sp_ax, tp, sp = mesh_axes(mesh)
        other = [a for a, s in mesh.shape.items()
                 if s > 1 and a not in (tp_ax, sp_ax)]
        if other or KV % tp or S % sp or (S // sp) % align:
            return False
    kv_l, s_l = KV // tp, S // sp
    # f32 LOGICAL staging (8 bytes/pos for k_al+v_al) + two carrier
    # windows at itemsize/pack bytes per logical position
    append_vmem = W * kv_l * D * (8 + 2 * ck.dtype.itemsize // pack)
    return (C >= align and C % align == 0
            and D % 128 == 0 and s_l % align == 0 and W <= s_l
            and append_vmem <= 11 * 1024 * 1024)
