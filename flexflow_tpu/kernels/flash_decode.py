"""Length-tiled flash-decode attention (Pallas TPU).

Single-token decode attention whose VMEM footprint is independent of the
cache length: the grid walks (row-block, S-tile) with a running-softmax
accumulator carried in scratch across a row-block's tiles — the
structure of the reference's hand-written generation kernel
(/root/reference/src/ops/inc_multihead_self_attention.cu:46-430, a
threadblock-per-head loop over cache pages with online softmax), built
the Pallas way.

Why this kernel exists (round-2 verdict, missing #1): the earlier
whole-row decode kernels held a row's entire K/V in VMEM and OOM'd past
S~512-1500, which made long context structurally impossible on one chip.
Here each grid step stages only an [RB, TS, KV, D] tile; S=8k/32k/128k
all run in the same few MB.

Per-row-block tile pruning — the capability the XLA einsum path cannot
express: rows attend only [0, depth_r], so a scalar-prefetch clamped
index map re-requests the SAME block for every tile past the row-block's
max needed tile; Mosaic's pipeline skips the duplicate DMA and @pl.when
skips the compute.  In a ragged continuous batch (one row at 8k context,
the rest at a few hundred tokens) the XLA path must read every row's
full bucketed allocation, while this kernel reads ~sum(depth_r) — the
host-side attend_len bucket only bounds the BATCH maximum.

GQA layout: H = KV * G query heads share KV cache heads; both dots batch
over (row, KV) — no KV duplication in memory or traffic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _kernel(last_ref, depth_ref, act_ref,      # scalar prefetch
            q_ref, k_ref, v_ref,               # blocks
            o_ref,                             # out
            m_sc, l_sc, acc_sc,                # scratch
            *, ts: int, rb: int, kv: int, g: int, d: int,
            s_total: int, scale: float):
    from jax.experimental import pallas as pl

    r = pl.program_id(0)
    t = pl.program_id(1)
    nt = pl.num_programs(1)
    kvg = kv * g

    @pl.when(t == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, -1e30)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    @pl.when(t <= last_ref[r])
    def _step():
        qv = q_ref[:]                          # [RB, H, D] model dtype
        # fold (rb, kv) into ONE batch dim — Mosaic's matmul supports a
        # single batch dimension; the kt/vt transpose is VMEM-local
        kt = k_ref[:].swapaxes(1, 2).reshape(rb * kv, ts, d)
        vt = v_ref[:].swapaxes(1, 2).reshape(rb * kv, ts, d)
        q3 = qv.reshape(rb * kv, g, d)
        # logits[rb*kv, g, ts] = q3 . kt  (batch rb*kv; contract d)
        logits = jax.lax.dot_general(
            q3, kt,
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        span = (t * ts
                + jax.lax.broadcasted_iota(jnp.int32, (rb, ts), 1))
        # per-row scalars read individually (rb is small and static;
        # fancy 2-D gathers from SMEM refs are not supported)
        depth_col = jnp.stack(
            [depth_ref[r * rb + i] for i in range(rb)]).reshape(rb, 1)
        act_col = jnp.stack(
            [act_ref[r * rb + i] for i in range(rb)]).reshape(rb, 1)
        ok = (span <= depth_col) & (act_col > 0)   # [RB, TS]
        logits = logits.reshape(rb, kv, g, ts)
        logits = jnp.where(ok[:, None, None, :], logits, -1e30)
        l2 = logits.reshape(rb * kvg, ts)
        tile_max = jnp.max(l2, axis=-1, keepdims=True)    # [RB*KVG, 1]
        m_new = jnp.maximum(m_sc[:], tile_max)
        alpha = jnp.exp(m_sc[:] - m_new)
        # fully-masked lanes (inactive rows / no valid position yet) keep
        # m_new at the -1e30 fill; exp(l2 - m_new) would be exp(0)=1
        # there, silently averaging V — force p to 0 so l stays 0 and the
        # finish-guard zeros the output
        p = jnp.where(m_new > -1e29, jnp.exp(l2 - m_new), 0.0)
        l_sc[:] = l_sc[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_sc[:] = m_new
        # pv[rb*kv, g, d] = p . vt (batch rb*kv; contract ts).  vt's
        # out-of-range pad columns (partial final S tile) may hold NaN;
        # p is 0 there but 0*NaN = NaN, so zero them explicitly
        col_ok = (t * ts + jax.lax.broadcasted_iota(
            jnp.int32, (1, ts, 1), 1)) < s_total
        vt = jnp.where(col_ok, vt, 0)
        pv = jax.lax.dot_general(
            p.reshape(rb * kv, g, ts).astype(vt.dtype), vt,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha + pv.reshape(rb * kvg, d)

    @pl.when(t == nt - 1)
    def _finish():
        l = l_sc[:]
        l = jnp.where(l == 0, 1.0, l)          # inactive rows: zeros out
        o_ref[:] = (acc_sc[:] / l).reshape(rb, kv * g, d).astype(
            o_ref.dtype)


def _pick_rb_ts(R: int, S: int, KV: int, D: int,
                budget_bytes: int = 5 * 1024 * 1024):
    """One row per program (finest pruning granularity — measured best on
    chip) with the largest S tile the VMEM budget allows.  The budget
    covers the double-buffered K+V tiles; the in-kernel transposed copies
    and f32 logits temps take roughly another budget's worth, which
    together must stay under the ~16 MB scoped-VMEM limit."""
    per_pos = KV * D * 2 * 2 * 2       # k+v, bf16, double buffer
    for ts in (1024, 512, 256, 128):
        if ts * per_pos <= budget_bytes and ts <= max(S, 128):
            return 1, ts
    return 1, 128


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret", "rb", "ts"))
def flash_decode_attend(q, ck, cv, depth, active, scale: float,
                        interpret: bool = False, rb=None, ts=None):
    """q [R,H,D] against cache [R,S,KV,D] masked to span<=depth[r]
    -> [R,H,D].  VMEM = O(RB*TS*KV*D), any S.  Inactive rows -> zeros.

    The caller scatters the current token's K/V into the cache FIRST
    (position depth[r]) — mirroring the production jnp path
    (ops/serving_attention.py _scatter_chunk then _attend).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H, D = q.shape
    S, KV = ck.shape[1], ck.shape[2]
    G = H // KV
    assert H == KV * G and ck.shape == cv.shape == (R, S, KV, D)
    if rb is None or ts is None:
        rb, ts = _pick_rb_ts(R, S, KV, D)
    nt = pl.cdiv(S, ts)
    depth = depth.astype(jnp.int32)
    active = active.astype(jnp.int32)
    # last tile any row of each row-block needs; pruned tiles re-request
    # that block index and Mosaic skips the duplicate DMA
    blk_depth = jnp.max(depth.reshape(R // rb, rb), axis=1)
    last = jnp.minimum(blk_depth // ts, nt - 1)

    kernel = functools.partial(_kernel, ts=ts, rb=rb, kv=KV, g=G, d=D,
                               s_total=S, scale=float(scale))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(R // rb, nt),
        in_specs=[
            pl.BlockSpec((rb, H, D), lambda r, t, *_: (r, 0, 0)),
            pl.BlockSpec((rb, ts, KV, D),
                         lambda r, t, last, *_: (r, jnp.minimum(t, last[r]),
                                                 0, 0)),
            pl.BlockSpec((rb, ts, KV, D),
                         lambda r, t, last, *_: (r, jnp.minimum(t, last[r]),
                                                 0, 0)),
        ],
        out_specs=pl.BlockSpec((rb, H, D), lambda r, t, *_: (r, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rb * KV * G, 1), jnp.float32),   # running max
            pltpu.VMEM((rb * KV * G, 1), jnp.float32),   # running sum
            pltpu.VMEM((rb * KV * G, D), jnp.float32),   # out accumulator
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, H, D), q.dtype),
        interpret=interpret,
    )(last, depth, active, q, ck, cv)


def _kernel_t(last_ref, depth_ref, act_ref,    # scalar prefetch
              q_ref, k_ref, v_ref,             # blocks ([1,KV,TS,D])
              o_ref,                           # out
              m_sc, l_sc, acc_sc,              # scratch
              *, ts: int, kv: int, g: int, d: int,
              s_total: int, scale: float):
    """Transposed-layout kernel body: cache [R, KV, S, D] so K/V tiles
    arrive [1, KV, TS, D] — the kv batch dim leads BOTH dot operands and
    the in-VMEM swapaxes relayout of the [R, S, KV, D] kernel (the
    measured 4.4x uniform-case loss, r3 PARITY §3) disappears.  One row
    per program (rb = 1)."""
    from jax.experimental import pallas as pl

    r = pl.program_id(0)
    t = pl.program_id(1)
    nt = pl.num_programs(1)
    kvg = kv * g

    @pl.when(t == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, -1e30)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    @pl.when(t <= last_ref[r])
    def _step():
        qv = q_ref[:].reshape(kv, g, d)
        kt = k_ref[:].reshape(kv, ts, d)       # native layout: no swap
        vt = v_ref[:].reshape(kv, ts, d)
        # logits[kv, g, ts] = qv . kt (batch kv; contract d)
        logits = jax.lax.dot_general(
            qv, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        span = (t * ts
                + jax.lax.broadcasted_iota(jnp.int32, (1, ts), 1))
        ok = (span <= depth_ref[r]) & (act_ref[r] > 0)     # [1, TS]
        logits = jnp.where(ok[None, :, :] > 0, logits, -1e30)
        l2 = logits.reshape(kvg, ts)
        tile_max = jnp.max(l2, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_sc[:], tile_max)
        alpha = jnp.exp(m_sc[:] - m_new)
        p = jnp.where(m_new > -1e29, jnp.exp(l2 - m_new), 0.0)
        l_sc[:] = l_sc[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_sc[:] = m_new
        col_ok = (t * ts + jax.lax.broadcasted_iota(
            jnp.int32, (1, ts, 1), 1)) < s_total
        vt = jnp.where(col_ok, vt, 0)
        # pv[kv, g, d] = p . vt (batch kv; contract ts)
        pv = jax.lax.dot_general(
            p.reshape(kv, g, ts).astype(vt.dtype), vt,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha + pv.reshape(kvg, d)

    @pl.when(t == nt - 1)
    def _finish():
        l = l_sc[:]
        l = jnp.where(l == 0, 1.0, l)
        o_ref[:] = (acc_sc[:] / l).reshape(1, kv * g, d).astype(
            o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret", "ts"))
def flash_decode_attend_t(q, ck, cv, depth, active, scale: float,
                          interpret: bool = False, ts=None):
    """Transposed-cache flash decode: q [R,H,D] against cache
    [R,KV,S,D] masked to span<=depth[r] -> [R,H,D].  The tile arrives
    pre-transposed so both dots run with a leading kv batch dim — no
    in-kernel relayout (the r3 uniform-case fix, PARITY §3)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H, D = q.shape
    KV, S = ck.shape[1], ck.shape[2]
    G = H // KV
    assert H == KV * G and ck.shape == cv.shape == (R, KV, S, D)
    if ts is None:
        ts = _pick_rb_ts(R, S, KV, D)[1]
    nt = pl.cdiv(S, ts)
    depth = depth.astype(jnp.int32)
    active = active.astype(jnp.int32)
    last = jnp.minimum(depth // ts, nt - 1)

    kernel = functools.partial(_kernel_t, ts=ts, kv=KV, g=G, d=D,
                               s_total=S, scale=float(scale))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(R, nt),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda r, t, *_: (r, 0, 0)),
            pl.BlockSpec((1, KV, ts, D),
                         lambda r, t, last, *_: (r, 0,
                                                 jnp.minimum(t, last[r]),
                                                 0)),
            pl.BlockSpec((1, KV, ts, D),
                         lambda r, t, last, *_: (r, 0,
                                                 jnp.minimum(t, last[r]),
                                                 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda r, t, *_: (r, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((KV * G, 1), jnp.float32),
            pltpu.VMEM((KV * G, 1), jnp.float32),
            pltpu.VMEM((KV * G, D), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, H, D), q.dtype),
        interpret=interpret,
    )(last, depth, active, q, ck, cv)


def flash_decode_attention(q, k_new, v_new, ck, cv, depth, active,
                           scale: float, interpret: bool = False):
    """Scatter-then-attend decode step (drop-in for the op layer): writes
    the new token's K/V at each active row's depth, then runs the
    length-tiled attention.  Returns (out [R,H,D], ck, cv)."""
    from ..ops.serving_attention import _scatter_chunk

    ck = _scatter_chunk(ck, k_new[:, None], depth, active)
    cv = _scatter_chunk(cv, v_new[:, None], depth, active)
    out = flash_decode_attend(q, ck, cv, depth, active, scale,
                              interpret=interpret)
    return out, ck, cv


def flash_path_ok(C: int, ck, mesh) -> bool:
    """Shape gate for the production op (consumed by
    serving_attention._flash_decode_ok): single-token decode, unsharded
    cache, lane-aligned head dim.  WHETHER flash beats the XLA attend is
    the host's cost decision (inference_manager.flash_wins) — this only
    says the kernel can run."""
    R, S, KV, D = ck.shape
    return C == 1 and mesh is None and D % 128 == 0
