"""Length-tiled flash-decode attention (Pallas TPU).

Single-token decode attention whose VMEM footprint is independent of the
cache length: the grid walks (row, S-tile) with a running-softmax
accumulator carried in scratch across a row's tiles — the structure of
the reference's hand-written generation kernel
(/root/reference/src/ops/inc_multihead_self_attention.cu:46-430, a
threadblock-per-head loop over cache pages with online softmax), built
the Pallas way.

r4 layout: the serving KV cache is stored ``[R, KV, S, D]`` so K/V
tiles arrive ``[1, KV, TS, D]`` — the kv batch dim leads BOTH dot
operands and no in-kernel relayout is needed.  The r1-r3 kernel held
the cache ``[R, S, KV, D]`` and paid a VMEM swapaxes per tile, which
made the uniform full-length case 4.4x SLOWER than the XLA attend
(r3 PARITY §3); with the native layout the kernel beats the XLA attend
even there (measured S=8192 uniform: 357 vs 414 us; ragged
one-8k-row-in-16: 50 vs 368 us), so the r1-r3 kernel was deleted (the
round-3 precedent: losing kernels do not stay in the tree).

Per-row tile pruning — the capability the XLA einsum path cannot
express: rows attend only [0, depth_r], so a scalar-prefetch clamped
index map re-requests the SAME block for every tile past the row's max
needed tile; Mosaic's pipeline skips the duplicate DMA and @pl.when
skips the compute.  In a ragged continuous batch (one row at 8k
context, the rest at a few hundred tokens) the XLA path must read every
row's full bucketed allocation, while this kernel reads ~sum(depth_r) —
the host-side attend_len bucket only bounds the BATCH maximum.

GQA layout: H = KV * G query heads share KV cache heads; both dots
batch over kv — no KV duplication in memory or traffic.

r5 additions:
- ALiBi (``slopes``): the MPT position bias slope_h * (k_pos - q_pos)
  is one fused add on the logits tile (reference
  apply_position_bias_qkprd, inc_multihead_self_attention.cu:304-325),
  so position-bias models decode on the fast path too.
- Sharded meshes: ``flash_decode_attention_sharded`` shard_maps the
  scatter+attend over the serving mesh — tp shards the kv-head axis
  (heads are independent, no collective; the reference TP-shards its
  generation kernel by heads the same way,
  inc_multihead_self_attention.cc:694-697), sp shards the cache length
  (each shard runs a PARTIAL online softmax via the same kernel and the
  combine is the standard flash merge: pmax of maxima, psum of
  rescaled sums/accumulators — the decode twin of
  ops/ring_attention.py's combine).

PR 10: the PAGED twins (``paged_decode_attention`` + friends, bottom
of this file) run the SAME kernel bodies against a global
``[num_frames, KV, page_len, D]`` frame pool indexed through
scalar-prefetched per-row page tables — the vLLM PagedAttention block
table, built the Pallas way (docs/INTERNALS.md "Paged KV cache").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _init_scratch(m_sc, l_sc, acc_sc):
    m_sc[:] = jnp.full_like(m_sc, -1e30)
    l_sc[:] = jnp.zeros_like(l_sc)
    acc_sc[:] = jnp.zeros_like(acc_sc)


def _unpack_int4_tile(t, kv, ts, d):
    """In-register unpack of a packed-int4 carrier tile ``[kv, ts//2,
    d]`` int8 -> sign-extended codes ``[kv, ts, d]`` int32 (low nibble
    = even logical position).  int32 arithmetic: Mosaic's shift/mask
    support is widest there, and the codes feed a convert-to-float
    next anyway.  The interleave is a minor-dim stack + sublane-merge
    reshape — the lane dim (d) is untouched."""
    t32 = t.astype(jnp.int32)
    lo = (t32 << 28) >> 28                     # sign-extend low nibble
    hi = t32 >> 4                              # arithmetic: high nibble
    return jnp.stack([lo, hi], axis=2).reshape(kv, ts, d)


def _online_softmax_step(r, t, depth_ref, act_ref, q_ref, k_ref, v_ref,
                         slopes_ref, m_sc, l_sc, acc_sc,
                         *, ts, kv, g, d, s_total, scale,
                         ks_ref=None, vs_ref=None, pack: int = 1):
    """One S-tile of the running softmax (shared by the full and partial
    kernels).

    ``ks_ref``/``vs_ref``: f32 per-position-per-head scale tiles
    ``[1, KV, TS]`` for int8 caches.  The HBM->VMEM K/V stream stays
    int8 (half the bf16 bytes); dequantization happens in-register —
    K's scale folds into the logits AFTER the dot (exact: the scale is
    constant along the contracted head_dim), V's scale folds into the
    probabilities before the PV dot.

    ``pack`` = 2 (int4 carriers): the K/V tiles arrive PACKED at half
    the logical tile width ``[1, KV, TS//2, D]`` — a quarter of bf16's
    HBM bytes — and unpack in-register before the dots; the scale
    tiles and every mask stay at the logical width."""
    kvg = kv * g
    qv = q_ref[:].reshape(kv, g, d)
    kt = k_ref[:].reshape(kv, ts // pack, d)   # native layout: no swap
    vt = v_ref[:].reshape(kv, ts // pack, d)
    if pack == 2:
        kt = _unpack_int4_tile(kt, kv, ts, d)
        vt = _unpack_int4_tile(vt, kv, ts, d)
    if ks_ref is not None:
        # int8 values are exact in bf16/f32; the dot runs on the raw
        # codes and the per-position scale multiplies the logits tile
        kt = kt.astype(qv.dtype)
    # logits[kv, g, ts] = qv . kt (batch kv; contract d)
    logits = jax.lax.dot_general(
        qv, kt, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale
    if ks_ref is not None:
        logits = logits * ks_ref[:].reshape(kv, 1, ts)
    span = (t * ts
            + jax.lax.broadcasted_iota(jnp.int32, (1, ts), 1))
    if slopes_ref is not None:
        # ALiBi: bias = slope_h * (k_pos - q_pos); q sits at depth_r.
        rel = (span - depth_ref[r]).astype(jnp.float32)      # [1, TS]
        logits = logits + (slopes_ref[:].reshape(kv, g, 1)
                           * rel[None, :, :])
    # span < s_total guards the padded tail of a partial final tile: a
    # sharded caller passes local depths that may EXCEED the local
    # extent (shard wholly below the row's span), so span <= depth no
    # longer excludes the pad columns by itself
    ok = ((span <= depth_ref[r]) & (span < s_total)
          & (act_ref[r] > 0))                                # [1, TS]
    logits = jnp.where(ok[None, :, :] > 0, logits, -1e30)
    l2 = logits.reshape(kvg, ts)
    tile_max = jnp.max(l2, axis=-1, keepdims=True)           # [KVG, 1]
    m_new = jnp.maximum(m_sc[:], tile_max)
    alpha = jnp.exp(m_sc[:] - m_new)
    # fully-masked lanes (inactive rows / no valid position yet) keep
    # m_new at the -1e30 fill; exp(l2 - m_new) would be exp(0)=1
    # there, silently averaging V — force p to 0 so l stays 0 and the
    # finish-guard zeros the output
    p = jnp.where(m_new > -1e29, jnp.exp(l2 - m_new), 0.0)
    l_sc[:] = l_sc[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    m_sc[:] = m_new
    # pv[kv, g, d] = p . vt (batch kv; contract ts).  vt's
    # out-of-range pad columns (partial final S tile) may hold NaN;
    # p is 0 there but 0*NaN = NaN, so zero them explicitly
    col_ok = (t * ts + jax.lax.broadcasted_iota(
        jnp.int32, (1, ts, 1), 1)) < s_total
    p_kv = p.reshape(kv, g, ts)
    if vs_ref is not None:
        # V dequant: fold the per-position scale into p (f32) so the
        # int8 codes go to the dot after one cast.  The scale tile's
        # out-of-range pad columns (partial final S tile) may hold NaN
        # like vt's — p is 0 there but 0*NaN = NaN, so zero the scales
        # on the same col_ok guard vt gets below
        vst = jnp.where(col_ok.reshape(1, 1, ts),
                        vs_ref[:].reshape(kv, 1, ts), 0.0)
        p_kv = p_kv * vst
        vt = vt.astype(qv.dtype)
    vt = jnp.where(col_ok, vt, 0)
    pv = jax.lax.dot_general(
        p_kv.astype(vt.dtype), vt,
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    acc_sc[:] = acc_sc[:] * alpha + pv.reshape(kvg, d)


def _kernel(last_ref, depth_ref, act_ref,      # scalar prefetch
            q_ref, k_ref, v_ref,               # blocks ([1,KV,TS,D])
            *rest,                             # [ks, vs], [slopes], outs,
            ts: int, kv: int, g: int, d: int,  # scratch
            s_total: int, scale: float,
            alibi: bool, partial: bool, quant: bool = False,
            pack: int = 1):
    from jax.experimental import pallas as pl

    ks_ref = vs_ref = None
    if quant:
        ks_ref, vs_ref, *rest = rest
    slopes_ref = None
    if alibi:
        slopes_ref, *rest = rest
    if partial:
        o_ref, m_ref, l_ref, m_sc, l_sc, acc_sc = rest
    else:
        (o_ref, m_sc, l_sc, acc_sc), m_ref, l_ref = rest, None, None

    r = pl.program_id(0)
    t = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(t == 0)
    def _init():
        _init_scratch(m_sc, l_sc, acc_sc)

    @pl.when(t <= last_ref[r])
    def _step():
        _online_softmax_step(r, t, depth_ref, act_ref, q_ref, k_ref,
                             v_ref, slopes_ref, m_sc, l_sc, acc_sc,
                             ts=ts, kv=kv, g=g, d=d, s_total=s_total,
                             scale=scale, ks_ref=ks_ref, vs_ref=vs_ref,
                             pack=pack)

    @pl.when(t == nt - 1)
    def _finish():
        if partial:
            # raw accumulators for the cross-shard flash merge: the sp
            # combine rescales by exp(m - pmax(m)) and psums
            o_ref[:] = acc_sc[:].reshape(1, kv * g, d)
            m_ref[:] = m_sc[:].reshape(1, kv * g)
            l_ref[:] = l_sc[:].reshape(1, kv * g)
        else:
            l = l_sc[:]
            l = jnp.where(l == 0, 1.0, l)      # inactive rows: zeros out
            o_ref[:] = (acc_sc[:] / l).reshape(1, kv * g, d).astype(
                o_ref.dtype)


def _pick_ts(S: int, KV: int, D: int,
             budget_bytes: int = 5 * 1024 * 1024, itemsize: int = 2,
             pack: int = 1):
    """One row per program (finest pruning granularity — measured best
    on chip) with the largest S tile the VMEM budget allows.  The budget
    covers the double-buffered K+V tiles (``itemsize`` bytes each — 1
    for int8 caches, whose f32 scale tiles add 8 more bytes/position;
    int4 carriers pack ``pack`` positions per byte so the code bytes
    halve again); f32 logits temps take roughly another budget's worth,
    which together must stay under the ~16 MB scoped-VMEM limit."""
    per_pos = KV * D * 2 * itemsize * 2 // pack   # k+v codes, dbl buffer
    if itemsize == 1:
        per_pos += KV * 4 * 2 * 2          # k+v f32 scale tiles
    for ts in (1024, 512, 256, 128):
        if ts * per_pos <= budget_bytes and ts <= max(S, 128):
            return ts
    return 128


def _attend_call(q, ck, cv, depth, active, scale, interpret, ts,
                 slopes, partial: bool, k_scale=None, v_scale=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H, D = q.shape
    KV = ck.shape[1]
    G = H // KV
    quant = k_scale is not None
    assert quant == (v_scale is not None)
    # pack factor from static shapes: int4 carriers hold 2 codes/byte
    # along axis 2 while the scale frames keep the LOGICAL length
    pack = (k_scale.shape[2] // ck.shape[2]) if quant else 1
    S = ck.shape[2] * pack
    assert H == KV * G and ck.shape == cv.shape == (R, KV, S // pack, D)
    if quant:
        assert k_scale.shape == v_scale.shape == (R, KV, S), (
            k_scale.shape, (R, KV, S))
    if ts is None:
        ts = _pick_ts(S, KV, D, itemsize=ck.dtype.itemsize, pack=pack)
    nt = pl.cdiv(S, ts)
    depth = depth.astype(jnp.int32)
    active = active.astype(jnp.int32)
    # last tile each row needs; pruned tiles re-request that block index
    # and Mosaic skips the duplicate DMA.  Clamp below at 0: a sharded
    # caller may pass negative local depths (shard above the query row's
    # span — fully masked, gated by `active`), and a negative block
    # index would walk off the cache.  INACTIVE rows prune to tile 0
    # outright: the hybrid step's decode sub-pass carries the rider
    # rows inactive at their (deep, mid-prefill) depths, and without
    # the clamp their whole cache would stream for fully-masked compute
    last = jnp.where(active > 0, jnp.clip(depth // ts, 0, nt - 1), 0)

    alibi = slopes is not None
    kernel = functools.partial(_kernel, ts=ts, kv=KV, g=G, d=D,
                               s_total=S, scale=float(scale),
                               alibi=alibi, partial=partial, quant=quant,
                               pack=pack)
    # packed carriers tile at ts//pack bytes per logical ts-tile; the
    # block-INDEX space is unchanged (carrier block t covers logical
    # positions [t*ts, (t+1)*ts)), so the clamped pruning maps are
    # shared verbatim with the full-width layouts
    in_specs = [
        pl.BlockSpec((1, H, D), lambda r, t, *_: (r, 0, 0)),
        pl.BlockSpec((1, KV, ts // pack, D),
                     lambda r, t, last, *_: (r, 0,
                                             jnp.minimum(t, last[r]),
                                             0)),
        pl.BlockSpec((1, KV, ts // pack, D),
                     lambda r, t, last, *_: (r, 0,
                                             jnp.minimum(t, last[r]),
                                             0)),
    ]
    inputs = [q, ck, cv]
    if quant:
        # f32 scale tiles ride the same clamped index map as their K/V
        # tiles, so pruned tiles skip their DMAs too
        for sc in (k_scale, v_scale):
            in_specs.append(pl.BlockSpec(
                (1, KV, ts),
                lambda r, t, last, *_: (r, 0, jnp.minimum(t, last[r]))))
            inputs.append(sc)
    if alibi:
        in_specs.append(pl.BlockSpec((H, 1), lambda r, t, *_: (0, 0)))
        inputs.append(jnp.asarray(slopes, jnp.float32).reshape(H, 1))
    out_spec = pl.BlockSpec((1, H, D), lambda r, t, *_: (r, 0, 0))
    if partial:
        out_specs = (out_spec,
                     pl.BlockSpec((1, H), lambda r, t, *_: (r, 0)),
                     pl.BlockSpec((1, H), lambda r, t, *_: (r, 0)))
        out_shape = (jax.ShapeDtypeStruct((R, H, D), jnp.float32),
                     jax.ShapeDtypeStruct((R, H), jnp.float32),
                     jax.ShapeDtypeStruct((R, H), jnp.float32))
    else:
        out_specs = out_spec
        out_shape = jax.ShapeDtypeStruct((R, H, D), q.dtype)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(R, nt),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((KV * G, 1), jnp.float32),   # running max
            pltpu.VMEM((KV * G, 1), jnp.float32),   # running sum
            pltpu.VMEM((KV * G, D), jnp.float32),   # out accumulator
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec, out_shape=out_shape,
        interpret=interpret,
    )(last, depth, active, *inputs)


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret", "ts"))
def flash_decode_attend(q, ck, cv, depth, active, scale: float,
                        interpret: bool = False, ts=None, slopes=None,
                        k_scale=None, v_scale=None):
    """q [R,H,D] against cache [R,KV,S,D] masked to span<=depth[r]
    -> [R,H,D].  VMEM = O(TS*KV*D), any S.  Inactive rows -> zeros.
    ``slopes``: optional [H] ALiBi per-head slopes (adds
    slope_h * (k_pos - depth_r) to the logits).
    ``k_scale``/``v_scale``: f32 [R, KV, S] per-position scales for an
    int8 cache — the HBM stream stays int8, dequant happens in-register.

    The caller scatters the current token's K/V into the cache FIRST
    (position depth[r]) — mirroring the production jnp path
    (ops/serving_attention.py _scatter_chunk then _attend).
    """
    return _attend_call(q, ck, cv, depth, active, scale, interpret, ts,
                        slopes, partial=False, k_scale=k_scale,
                        v_scale=v_scale)


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret", "ts"))
def flash_decode_attend_partial(q, ck, cv, depth, active, scale: float,
                                interpret: bool = False, ts=None,
                                slopes=None, k_scale=None, v_scale=None):
    """Partial (unnormalized) flash attend for cross-shard combines:
    returns (acc [R,H,D] f32, m [R,H] f32, l [R,H] f32) where
    out = acc / l after the standard flash merge across shards.  Rows or
    shards with no valid position report m=-1e30, l=0, acc=0."""
    return _attend_call(q, ck, cv, depth, active, scale, interpret, ts,
                        slopes, partial=True, k_scale=k_scale,
                        v_scale=v_scale)


def _nibble_merge(win, new, sel, nib):
    """Merge int4 ``new`` codes ``[KV, 1, D]`` into the carrier bytes
    of an RMW window ``[KV, w, D]`` at the ``sel``-marked row: ``nib``
    (the logical depth's parity) picks the low or high nibble; the
    neighbouring nibble keeps its old value.  int32 arithmetic, then a
    wrap-around cast back to the int8 carrier."""
    old = win.astype(jnp.int32)
    c4 = new.astype(jnp.int32) & 0x0F
    merged = jnp.where(nib > 0,
                       (old & 0x0F) | (c4 << 4),
                       (old & ~0x0F) | c4)
    return jnp.where(sel, merged, old).astype(win.dtype)


def _append_kernel(depth_ref, act_ref,           # scalar prefetch
                   *refs,                        # see below
                   w: int, quant: bool, pack: int = 1):
    """Per-row in-place cache append: ck[r, :, depth[r], :] = k_new[r].

    ``refs``: knew, vnew (VMEM [R, KV, 1, D] float), then for quantized
    caches ksc, vsc (VMEM [R, KV, 1, 1] f32 per-head scales), then the
    aliased ck/cv in/out pairs and the window/semaphore scratch.

    Exists so a flash-dispatched decode step contains NO XLA cache op:
    XLA's layout assignment physically prefers S-major ({3,1,2,0}) for
    its scatter and would insert a WHOLE-CACHE relayout copy per layer
    per step at the Pallas boundary (custom calls require the default
    descending layout) — measured 9.3 ms/step of copies at 1.4B/8k
    before this kernel; with both the append and the attend as Pallas
    calls the cache stays in the default layout end to end.

    Mosaic requires S-slices aligned to the sublane tiling, so the
    write is a read-modify-write of the ``w``-aligned window around
    depth (w = 16 for bf16/f32 caches, 32 for int8 — the int8 sublane
    tiling is (32, 128); one extra window read per row — bytes are
    negligible vs the attend; cache allocations are w-aligned by the
    InferenceManager).  For quantized caches the NEW TOKEN IS QUANTIZED
    IN-KERNEL inside the window overlay (rint(x / scale) on the float
    payload; the scale itself is a tiny XLA-side reduction scattered
    into the [R, KV, S] scale tensor by the wrapper).

    ``pack`` = 2 (int4 carriers): ``depth`` stays LOGICAL; the target
    byte is carrier row depth//2 and depth's parity picks the nibble,
    merged against the byte's other nibble (_nibble_merge).  The w=32
    carrier-row window then spans 64 LOGICAL positions — the PR-2
    32-alignment invariant widens to 64, enforced by the wrapper's
    carrier-extent assert and the path gates."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if quant:
        (knew_ref, vnew_ref, ksc_ref, vsc_ref, ck_hbm, cv_hbm,
         ck_out, cv_out, win_k, win_v, sem_k, sem_v) = refs
    else:
        (knew_ref, vnew_ref, ck_hbm, cv_hbm,
         ck_out, cv_out, win_k, win_v, sem_k, sem_v) = refs
        ksc_ref = vsc_ref = None

    r = pl.program_id(0)
    qmax = 7 if pack == 2 else 127

    @pl.when(act_ref[r] > 0)
    def _():
        d = depth_ref[r]
        row = d // pack                        # carrier row of depth
        base = (row // w) * w
        ink = pltpu.make_async_copy(
            ck_out.at[r, :, pl.ds(base, w), :], win_k, sem_k)
        inv = pltpu.make_async_copy(
            cv_out.at[r, :, pl.ds(base, w), :], win_v, sem_v)
        ink.start()
        inv.start()
        ink.wait()
        inv.wait()
        sel = jax.lax.broadcasted_iota(jnp.int32, (1, w, 1), 1) \
            == (row - base)
        kn, vn = knew_ref[r], vnew_ref[r]
        if quant:
            kn = jnp.clip(jnp.rint(kn.astype(jnp.float32) / ksc_ref[r]),
                          -qmax, qmax)
            vn = jnp.clip(jnp.rint(vn.astype(jnp.float32) / vsc_ref[r]),
                          -qmax, qmax)
        if pack == 2:
            nib = d - row * 2                  # logical parity
            win_k[:] = _nibble_merge(win_k[:], kn, sel, nib)
            win_v[:] = _nibble_merge(win_v[:], vn, sel, nib)
        else:
            win_k[:] = jnp.where(sel, kn.astype(win_k.dtype), win_k[:])
            win_v[:] = jnp.where(sel, vn.astype(win_v.dtype), win_v[:])
        outk = pltpu.make_async_copy(
            win_k, ck_out.at[r, :, pl.ds(base, w), :], sem_k)
        outv = pltpu.make_async_copy(
            win_v, cv_out.at[r, :, pl.ds(base, w), :], sem_v)
        outk.start()
        outv.start()
        outk.wait()
        outv.wait()


def cache_append(ck, cv, k_new, v_new, depth, active,
                 interpret: bool = False, k_scale_new=None,
                 v_scale_new=None, pack: int = 1):
    """In-place (donated/aliased) single-token KV append on [R,KV,S,D]
    caches via async DMA — the Pallas twin of _scatter_chunk for the
    flash path.  Inactive rows write nothing.

    int8 caches: pass ``k_scale_new``/``v_scale_new`` ([R, KV] f32,
    the per-head scales of the NEW token — quantization.quantize_kv's
    scale half); the kernel quantizes the float payload in-kernel.  The
    caller owns scattering the scales into the [R, KV, S] scale tensor
    (flash_decode_attention does both).

    ``pack`` = 2 (int4 carriers, ck axis 2 at HALF the logical length):
    ``depth`` stays logical and the kernel merges the +-7 code into the
    target byte's nibble; the scales come from quantize_kv_int4."""
    import functools as _ft

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, KV, S_c, D = ck.shape
    S = S_c * pack                 # logical positions
    quant = ck.dtype.itemsize == 1
    w = 32 if quant else 16        # CARRIER-row window (64 logical int4)
    assert S_c % w == 0, (S_c, w)  # aligned windows must stay in bounds
    assert quant == (k_scale_new is not None) == (v_scale_new is not None)
    assert pack == 1 or quant, pack
    depth = jnp.clip(depth.astype(jnp.int32), 0, S - 1)
    active = active.astype(jnp.int32)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.VMEM),   # k_new
        pl.BlockSpec(memory_space=pltpu.VMEM),   # v_new
    ]
    inputs = [k_new[:, :, None] if quant
              else k_new[:, :, None].astype(ck.dtype),
              v_new[:, :, None] if quant
              else v_new[:, :, None].astype(cv.dtype)]
    if quant:
        in_specs += [pl.BlockSpec(memory_space=pltpu.VMEM)] * 2
        inputs += [k_scale_new.astype(jnp.float32)[:, :, None, None],
                   v_scale_new.astype(jnp.float32)[:, :, None, None]]
    in_specs += [pl.BlockSpec(memory_space=pl.ANY),    # ck
                 pl.BlockSpec(memory_space=pl.ANY)]    # cv
    n_in = 2 + len(inputs)         # + scalar-prefetch args
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(R,),
        in_specs=in_specs,
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)),
        scratch_shapes=[pltpu.VMEM((KV, w, D), ck.dtype),
                        pltpu.VMEM((KV, w, D), cv.dtype),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(())],
    )
    return pl.pallas_call(
        _ft.partial(_append_kernel, w=w, quant=quant, pack=pack),
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(ck.shape, ck.dtype),
                   jax.ShapeDtypeStruct(cv.shape, cv.dtype)),
        input_output_aliases={n_in: 0, n_in + 1: 1},
        interpret=interpret,
    )(depth, active, *inputs, ck, cv)


def flash_decode_attention(q, k_new, v_new, ck, cv, depth, active,
                           scale: float, interpret: bool = False,
                           slopes=None, k_scale=None, v_scale=None):
    """Scatter-then-attend decode step (drop-in for the op layer): writes
    the new token's K/V at each active row's depth (in place, Pallas
    DMA), then runs the length-tiled attention.  Caches are
    [R, KV, S, D].  Returns (out [R,H,D], ck, cv) — quantized caches
    (when ``k_scale``/``v_scale`` [R, KV, S] f32 are passed; int4
    carriers are detected from the carrier/scale length ratio)
    additionally return the updated scale tensors:
    (out, ck, cv, k_scale, v_scale)."""
    if k_scale is not None:
        from ..quantization import (quantize_kv, quantize_kv_int4,
                                    scatter_kv_scales)

        pack = k_scale.shape[2] // ck.shape[2]
        # clamp ONCE, shared by the code write and the scale write:
        # cache_append clamps internally but scatter_kv_scales drops
        # out-of-range positions, and a clamped code paired with a
        # dropped (stale) scale would dequantize garbage at S-1
        depth = jnp.clip(depth.astype(jnp.int32), 0,
                         k_scale.shape[2] - 1)
        # the q half is dead code XLA drops — only the scale is needed
        # here, the kernel quantizes the payload in-window itself
        qfn = quantize_kv_int4 if pack == 2 else quantize_kv
        _, k_sc = qfn(k_new)                            # [R, KV]
        _, v_sc = qfn(v_new)
        ck, cv = cache_append(ck, cv, k_new, v_new, depth, active,
                              interpret=interpret, k_scale_new=k_sc,
                              v_scale_new=v_sc, pack=pack)
        k_scale = scatter_kv_scales(k_scale, k_sc[:, None], depth, active)
        v_scale = scatter_kv_scales(v_scale, v_sc[:, None], depth, active)
        out = flash_decode_attend(q, ck, cv, depth, active, scale,
                                  interpret=interpret, slopes=slopes,
                                  k_scale=k_scale, v_scale=v_scale)
        return out, ck, cv, k_scale, v_scale
    ck, cv = cache_append(ck, cv, k_new, v_new, depth, active,
                          interpret=interpret)
    out = flash_decode_attend(q, ck, cv, depth, active, scale,
                              interpret=interpret, slopes=slopes)
    return out, ck, cv


def flash_merge(acc, m, l, axis):
    """The standard cross-shard flash-softmax merge: rescale partial
    accumulators by exp(m - pmax(m)) and psum over ``axis``; rows with
    no valid position anywhere (l == 0 after the merge) yield zeros.
    Shared by the sharded decode and prefill wrappers — numerically
    delicate code lives once.  acc [..., D] f32, m/l [...] f32."""
    import jax

    m_g = jax.lax.pmax(m, axis)
    coef = jnp.exp(m - m_g)                    # fully-masked shard -> 0
    l_g = jax.lax.psum(l * coef, axis)
    acc_g = jax.lax.psum(acc * coef[..., None], axis)
    return acc_g / jnp.where(l_g == 0, 1.0, l_g)[..., None]


def mesh_axes(mesh):
    """(tp_axis_or_None, sp_axis_or_None, tp_size, sp_size) of a serving
    mesh; axes the mesh lacks report size 1."""
    from ..config import AXIS_MODEL, AXIS_SEQ

    shape = dict(mesh.shape)
    tp_ax = AXIS_MODEL if AXIS_MODEL in shape else None
    sp_ax = AXIS_SEQ if AXIS_SEQ in shape else None
    return (tp_ax, sp_ax,
            shape.get(AXIS_MODEL, 1), shape.get(AXIS_SEQ, 1))


def flash_decode_attention_sharded(q, k_new, v_new, ck, cv, depth,
                                   active, scale: float, mesh,
                                   interpret: bool = False, slopes=None,
                                   k_scale=None, v_scale=None):
    """shard_map'd scatter-then-attend decode step over the serving mesh.

    tp shards the kv-head axis — heads are independent, so each shard
    runs the plain kernel on its local heads (the reference TP-shards
    its generation kernel by heads the same way,
    inc_multihead_self_attention.cc:694-697).  sp shards the cache
    length: only the shard owning position depth[r] appends the new
    token; every shard computes a PARTIAL online softmax over its local
    positions and the combine is the standard flash merge (pmax of
    maxima, psum of rescaled l/acc) over 'sp'.

    Global layouts (= serving cache_pspec): q/k_new/v_new
    [R, heads over tp, D]; caches [R, KV over tp, S over sp, D];
    scales (int8 caches) [R, KV over tp, S over sp]; depth/active
    replicated.  Returns (out [R,H,D], ck, cv[, k_scale, v_scale]) with
    out sharded over tp like q.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    tp_ax, sp_ax, tp, sp = mesh_axes(mesh)
    head_spec = P(None, tp_ax, None)
    cache_spec = P(None, tp_ax, sp_ax, None)
    sc_spec = P(None, tp_ax, sp_ax)
    slope_spec = P(tp_ax)
    has_alibi = slopes is not None
    quant = k_scale is not None
    # int4 pack factor from the GLOBAL shapes (sp shards carrier and
    # scale lengths in lockstep, so the per-shard ratio matches)
    pack = (k_scale.shape[2] // ck.shape[2]) if quant else 1
    depth = depth.astype(jnp.int32)
    active = active.astype(jnp.int32)

    def body(q, kn, vn, ck, cv, depth, active, *rest):
        rest = list(rest)
        ks, vs = (rest.pop(0), rest.pop(0)) if quant else (None, None)
        sl = rest.pop(0) if has_alibi else None
        S_l = ck.shape[2] * pack               # LOGICAL shard extent
        s0 = (jax.lax.axis_index(sp_ax) * S_l) if sp > 1 else 0
        loc = depth - s0                       # signed local depth
        app_act = active * ((loc >= 0) & (loc < S_l))
        if quant:
            from ..quantization import (quantize_kv, quantize_kv_int4,
                                        scatter_kv_scales)

            qfn = quantize_kv_int4 if pack == 2 else quantize_kv
            _, k_sc = qfn(kn)
            _, v_sc = qfn(vn)
            ck, cv = cache_append(ck, cv, kn, vn, loc, app_act,
                                  interpret=interpret, k_scale_new=k_sc,
                                  v_scale_new=v_sc, pack=pack)
            ks = scatter_kv_scales(ks, k_sc[:, None], loc, app_act)
            vs = scatter_kv_scales(vs, v_sc[:, None], loc, app_act)
        else:
            ck, cv = cache_append(ck, cv, kn, vn, loc, app_act,
                                  interpret=interpret)
        if sp <= 1:
            out = flash_decode_attend(q, ck, cv, depth, active, scale,
                                      interpret=interpret, slopes=sl,
                                      k_scale=ks, v_scale=vs)
            return ((out, ck, cv, ks, vs) if quant
                    else (out, ck, cv))
        # shards wholly below the row's span (loc >= S_l) attend ALL
        # their positions (span <= loc holds everywhere); shards above
        # it (loc < 0) are fully masked via `active`
        att_act = active * (loc >= 0)
        acc, m, l = flash_decode_attend_partial(
            q, ck, cv, loc, att_act, scale, interpret=interpret,
            slopes=sl, k_scale=ks, v_scale=vs)
        out = flash_merge(acc, m, l, sp_ax)
        return ((out.astype(q.dtype), ck, cv, ks, vs) if quant
                else (out.astype(q.dtype), ck, cv))

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(head_spec, head_spec, head_spec, cache_spec,
                  cache_spec, P(), P())
        + ((sc_spec, sc_spec) if quant else ())
        + ((slope_spec,) if has_alibi else ()),
        out_specs=(head_spec, cache_spec, cache_spec)
        + ((sc_spec, sc_spec) if quant else ()),
        check_rep=False)
    args = (q, k_new, v_new, ck, cv, depth, active)
    if quant:
        args += (k_scale, v_scale)
    if has_alibi:
        args += (jnp.asarray(slopes, jnp.float32),)
    return fn(*args)


# --------------------------------------------------------------- paged
# Physical paged KV (PR 10): K/V live in a GLOBAL frame pool
# [num_frames, KV, page_len, D] and each row's logical pages map to
# frames through an int32 [R, max_pages] page table (the vLLM
# PagedAttention block-table idiom, built the Pallas way).  The grid
# walks (row, logical page) and the K/V BlockSpec index maps read the
# scalar-prefetched table — so the DMA stream touches exactly the
# row's LEASED frames, in whatever fragmented order the allocator
# handed them out, and HBM residency equals leased frames instead of
# rows x max_seq.  The kernel BODY is the dense `_kernel` unchanged:
# grid index t IS the logical page, so every span/depth/ALiBi
# computation stays in global position space; only the address of the
# tile moved.  Tables are DATA (fixed [R, max_pages] shape) — contents
# change per step with zero retracing.


def _paged_kernel(table_ref, *rest, **kw):
    """The dense kernel behind a table indirection: the table ref is
    consumed by the BlockSpec index maps alone."""
    return _kernel(*rest, **kw)


def paged_head_axes(mesh):
    """(merged head-shard axes tuple, group size) of a serving mesh for
    paged pools: frames have no global length axis, so BOTH tp and sp
    shard the KV-head axis (heads are independent — no collective, no
    flash merge)."""
    from ..config import AXIS_MODEL, AXIS_SEQ

    shape = dict(mesh.shape)
    axes = tuple(a for a in (AXIS_MODEL, AXIS_SEQ)
                 if shape.get(a, 1) > 1)
    size = 1
    for a in axes:
        size *= shape[a]
    return axes, size


def _paged_attend_call(q, pk, pv, table, depth, active, scale,
                       interpret, slopes, s_bound,
                       k_scale=None, v_scale=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H, D = q.shape
    F, KV = pk.shape[:2]
    G = H // KV
    P = table.shape[1]
    quant = k_scale is not None
    assert quant == (v_scale is not None)
    # int4 pack factor from the carrier/scale-frame length ratio
    pack = (k_scale.shape[2] // pk.shape[2]) if quant else 1
    L = pk.shape[2] * pack         # LOGICAL page length
    assert H == KV * G and pk.shape == pv.shape == (F, KV, L // pack, D)
    assert table.shape == (R, P), (table.shape, (R, P))
    if quant:
        assert k_scale.shape == v_scale.shape == (F, KV, L), (
            k_scale.shape, (F, KV, L))
    nt = min(P, pl.cdiv(s_bound, L)) if s_bound else P
    depth = depth.astype(jnp.int32)
    active = active.astype(jnp.int32)
    # table entries of unleased pages may be stale — clip so the
    # clamped re-request of a pruned tile never walks off the pool
    # (reads there are fully masked by span <= depth)
    table = jnp.clip(table.astype(jnp.int32), 0, F - 1)
    # inactive rows prune to page 0 like the dense kernel's tile 0 (the
    # hybrid decode sub-pass carries rider rows inactive at deep depths)
    last = jnp.where(active > 0, jnp.clip(depth // L, 0, nt - 1), 0)

    alibi = slopes is not None
    kernel = functools.partial(_paged_kernel, ts=L, kv=KV, g=G, d=D,
                               s_total=nt * L, scale=float(scale),
                               alibi=alibi, partial=False, quant=quant,
                               pack=pack)
    kv_map = lambda r, t, tab, last, *_: (  # noqa: E731 — shared by K/V
        tab[r, jnp.minimum(t, last[r])], 0, 0, 0)
    in_specs = [
        pl.BlockSpec((1, H, D), lambda r, t, *_: (r, 0, 0)),
        pl.BlockSpec((1, KV, L // pack, D), kv_map),
        pl.BlockSpec((1, KV, L // pack, D), kv_map),
    ]
    inputs = [q, pk, pv]
    if quant:
        # f32 scale frames ride the same table indirection
        for sc in (k_scale, v_scale):
            in_specs.append(pl.BlockSpec(
                (1, KV, L),
                lambda r, t, tab, last, *_: (
                    tab[r, jnp.minimum(t, last[r])], 0, 0)))
            inputs.append(sc)
    if alibi:
        in_specs.append(pl.BlockSpec((H, 1), lambda r, t, *_: (0, 0)))
        inputs.append(jnp.asarray(slopes, jnp.float32).reshape(H, 1))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(R, nt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, D), lambda r, t, *_: (r, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((KV * G, 1), jnp.float32),   # running max
            pltpu.VMEM((KV * G, 1), jnp.float32),   # running sum
            pltpu.VMEM((KV * G, D), jnp.float32),   # out accumulator
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, H, D), q.dtype),
        interpret=interpret,
    )(table, last, depth, active, q, *inputs[1:])


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret", "s_bound"))
def paged_decode_attend(q, pk, pv, table, depth, active, scale: float,
                        interpret: bool = False, slopes=None,
                        s_bound=None, k_scale=None, v_scale=None):
    """q [R,H,D] against the paged pool pk/pv [F,KV,page_len,D] read
    through ``table`` int32 [R,max_pages], masked to span<=depth[r]
    -> [R,H,D].  Grid walks the row's LEASED frames (pruned past
    depth//page_len like the dense kernel's S tiles); ``s_bound``
    statically bounds the walked pages (the host's attend bucket)."""
    return _paged_attend_call(q, pk, pv, table, depth, active, scale,
                              interpret, slopes, s_bound,
                              k_scale=k_scale, v_scale=v_scale)


def _paged_append_kernel(frame_ref, off_ref, act_ref,   # scalar prefetch
                         *refs, w: int, quant: bool, pack: int = 1):
    """Per-row in-place single-token append into the FRAME holding the
    row's current depth: pk[frame[r], :, off[r], :] = k_new[r].  The
    same ``w``-aligned RMW window as the dense kernel (16 bf16 / 32
    int8 — page_len % 32 == 0 keeps every window inside one frame),
    with the window base computed inside the frame instead of the
    row slab.  ``pack`` = 2: ``off`` is the LOGICAL in-frame offset;
    the code nibble-merges into carrier row off//2 like the dense
    twin (page_len % 64 == 0 keeps the 32-carrier-row window inside
    one frame)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if quant:
        (knew_ref, vnew_ref, ksc_ref, vsc_ref, ck_hbm, cv_hbm,
         ck_out, cv_out, win_k, win_v, sem_k, sem_v) = refs
    else:
        (knew_ref, vnew_ref, ck_hbm, cv_hbm,
         ck_out, cv_out, win_k, win_v, sem_k, sem_v) = refs
        ksc_ref = vsc_ref = None

    r = pl.program_id(0)
    qmax = 7 if pack == 2 else 127

    @pl.when(act_ref[r] > 0)
    def _():
        f = frame_ref[r]
        off = off_ref[r]
        row = off // pack                      # carrier row in frame
        base = (row // w) * w
        ink = pltpu.make_async_copy(
            ck_out.at[f, :, pl.ds(base, w), :], win_k, sem_k)
        inv = pltpu.make_async_copy(
            cv_out.at[f, :, pl.ds(base, w), :], win_v, sem_v)
        ink.start()
        inv.start()
        ink.wait()
        inv.wait()
        sel = jax.lax.broadcasted_iota(jnp.int32, (1, w, 1), 1) \
            == (row - base)
        kn, vn = knew_ref[r], vnew_ref[r]
        if quant:
            kn = jnp.clip(jnp.rint(kn.astype(jnp.float32) / ksc_ref[r]),
                          -qmax, qmax)
            vn = jnp.clip(jnp.rint(vn.astype(jnp.float32) / vsc_ref[r]),
                          -qmax, qmax)
        if pack == 2:
            nib = off - row * 2
            win_k[:] = _nibble_merge(win_k[:], kn, sel, nib)
            win_v[:] = _nibble_merge(win_v[:], vn, sel, nib)
        else:
            win_k[:] = jnp.where(sel, kn.astype(win_k.dtype), win_k[:])
            win_v[:] = jnp.where(sel, vn.astype(win_v.dtype), win_v[:])
        outk = pltpu.make_async_copy(
            win_k, ck_out.at[f, :, pl.ds(base, w), :], sem_k)
        outv = pltpu.make_async_copy(
            win_v, cv_out.at[f, :, pl.ds(base, w), :], sem_v)
        outk.start()
        outv.start()
        outk.wait()
        outv.wait()


def paged_cache_append(pk, pv, k_new, v_new, table, depth, active,
                       interpret: bool = False, k_scale_new=None,
                       v_scale_new=None, pack: int = 1):
    """In-place (aliased) single-token KV append on paged
    [F,KV,page_len,D] pools — the table-indirected twin of
    :func:`cache_append`.  The host side resolves depth to (frame,
    in-frame offset) through the table; the kernel's RMW window never
    crosses a frame boundary (page_len % 32 == 0; int4 carriers at
    ``pack`` = 2 need logical page_len % 64 == 0)."""
    import functools as _ft

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, KV, L_c, D = pk.shape
    L = L_c * pack                 # logical page length
    R = k_new.shape[0]
    P = table.shape[1]
    quant = pk.dtype.itemsize == 1
    w = 32 if quant else 16        # carrier-row window
    assert L_c % w == 0, (L_c, w)
    assert quant == (k_scale_new is not None) == (v_scale_new is not None)
    assert pack == 1 or quant, pack
    depth = jnp.clip(depth.astype(jnp.int32), 0, P * L - 1)
    frame = jnp.take_along_axis(jnp.asarray(table, jnp.int32),
                                (depth // L)[:, None], axis=1)[:, 0]
    # unleased pages carry the out-of-range sentinel: mask the write
    # instead of clipping onto somebody else's frame
    active = active.astype(jnp.int32) * (frame >= 0) * (frame < F)
    frame = jnp.clip(frame, 0, F - 1)
    off = depth % L
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.VMEM),   # k_new
        pl.BlockSpec(memory_space=pltpu.VMEM),   # v_new
    ]
    inputs = [k_new[:, :, None] if quant
              else k_new[:, :, None].astype(pk.dtype),
              v_new[:, :, None] if quant
              else v_new[:, :, None].astype(pv.dtype)]
    if quant:
        in_specs += [pl.BlockSpec(memory_space=pltpu.VMEM)] * 2
        inputs += [k_scale_new.astype(jnp.float32)[:, :, None, None],
                   v_scale_new.astype(jnp.float32)[:, :, None, None]]
    in_specs += [pl.BlockSpec(memory_space=pl.ANY),    # pk
                 pl.BlockSpec(memory_space=pl.ANY)]    # pv
    n_in = 3 + len(inputs)         # + scalar-prefetch args
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(R,),
        in_specs=in_specs,
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)),
        scratch_shapes=[pltpu.VMEM((KV, w, D), pk.dtype),
                        pltpu.VMEM((KV, w, D), pv.dtype),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(())],
    )
    return pl.pallas_call(
        _ft.partial(_paged_append_kernel, w=w, quant=quant, pack=pack),
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(pk.shape, pk.dtype),
                   jax.ShapeDtypeStruct(pv.shape, pv.dtype)),
        input_output_aliases={n_in: 0, n_in + 1: 1},
        interpret=interpret,
    )(frame, off, active, *inputs, pk, pv)


def paged_decode_attention(q, k_new, v_new, pk, pv, table, depth,
                           active, scale: float,
                           interpret: bool = False, slopes=None,
                           s_bound=None, k_scale=None, v_scale=None):
    """Scatter-then-attend decode step on a paged pool (drop-in for
    the op layer): append the new token into the frame holding each
    active row's depth, then run the page-table attend.  Returns
    (out, pk, pv[, k_scale, v_scale]) like the dense twin."""
    if k_scale is not None:
        from ..quantization import (quantize_kv, quantize_kv_int4,
                                    scatter_kv_scales_paged)

        pack = k_scale.shape[2] // pk.shape[2]
        depth = jnp.clip(depth.astype(jnp.int32), 0,
                         table.shape[1] * k_scale.shape[2] - 1)
        qfn = quantize_kv_int4 if pack == 2 else quantize_kv
        _, k_sc = qfn(k_new)                            # [R, KV]
        _, v_sc = qfn(v_new)
        pk, pv = paged_cache_append(pk, pv, k_new, v_new, table, depth,
                                    active, interpret=interpret,
                                    k_scale_new=k_sc, v_scale_new=v_sc,
                                    pack=pack)
        k_scale = scatter_kv_scales_paged(k_scale, k_sc[:, None], depth,
                                          active, table)
        v_scale = scatter_kv_scales_paged(v_scale, v_sc[:, None], depth,
                                          active, table)
        out = paged_decode_attend(q, pk, pv, table, depth, active,
                                  scale, interpret=interpret,
                                  slopes=slopes, s_bound=s_bound,
                                  k_scale=k_scale, v_scale=v_scale)
        return out, pk, pv, k_scale, v_scale
    pk, pv = paged_cache_append(pk, pv, k_new, v_new, table, depth,
                                active, interpret=interpret)
    out = paged_decode_attend(q, pk, pv, table, depth, active, scale,
                              interpret=interpret, slopes=slopes,
                              s_bound=s_bound)
    return out, pk, pv


def paged_decode_attention_sharded(q, k_new, v_new, pk, pv, table,
                                   depth, active, scale: float, mesh,
                                   interpret: bool = False, slopes=None,
                                   s_bound=None, k_scale=None,
                                   v_scale=None):
    """shard_map'd paged decode step: frames shard on the KV-HEAD axis
    over the merged tp/sp group (paged pools have no length axis for
    sp — heads are the only independent dimension), tables/depths
    replicate, and each shard runs the plain paged kernels on its
    local heads.  No collective, no flash merge."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    axes, size = paged_head_axes(mesh)
    head = axes[0] if len(axes) == 1 else (axes or None)
    head_spec = P(None, head, None)
    pool_spec = P(None, head, None, None)
    sc_spec = P(None, head, None)
    slope_spec = P(head)
    has_alibi = slopes is not None
    quant = k_scale is not None
    depth = depth.astype(jnp.int32)
    active = active.astype(jnp.int32)
    table = jnp.asarray(table, jnp.int32)

    def body(q, kn, vn, pk, pv, table, depth, active, *rest):
        rest = list(rest)
        ks, vs = (rest.pop(0), rest.pop(0)) if quant else (None, None)
        sl = rest.pop(0) if has_alibi else None
        res = paged_decode_attention(q, kn, vn, pk, pv, table, depth,
                                     active, scale, interpret=interpret,
                                     slopes=sl, s_bound=s_bound,
                                     k_scale=ks, v_scale=vs)
        return res

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(head_spec, head_spec, head_spec, pool_spec, pool_spec,
                  P(), P(), P())
        + ((sc_spec, sc_spec) if quant else ())
        + ((slope_spec,) if has_alibi else ()),
        out_specs=(head_spec, pool_spec, pool_spec)
        + ((sc_spec, sc_spec) if quant else ()),
        check_rep=False)
    args = (q, k_new, v_new, pk, pv, table, depth, active)
    if quant:
        args += (k_scale, v_scale)
    if has_alibi:
        args += (jnp.asarray(slopes, jnp.float32),)
    return fn(*args)


def paged_path_ok(C: int, pk, mesh, pack: int = 1) -> bool:
    """Shape gate for the paged decode kernels: single-token decode,
    lane-aligned head dim, frame length a legal RMW window multiple
    (32 for int8 pools, 16 otherwise — page_len % 32 == 0 satisfies
    both by construction; int4 carriers at ``pack`` = 2 widen the
    requirement to LOGICAL page_len % 64 == 0, i.e. 32 carrier
    sublanes), and an unsharded pool OR one whose KV-head axis divides
    the merged tp/sp head group.  Misaligned int4 shapes fall back to
    the jnp path (serving_attention) rather than fail to tile."""
    F, KV, L_c, D = pk.shape
    L = L_c * pack                 # logical page length
    align = 32 * pack if pk.dtype.itemsize == 1 else 16
    if C != 1 or D % 128 != 0 or L % align != 0:
        return False
    if mesh is None:
        return True
    axes, size = paged_head_axes(mesh)
    other = [a for a, s in mesh.shape.items()
             if s > 1 and a not in axes]
    return not other and KV % size == 0


def flash_path_ok(C: int, ck, mesh, pack: int = 1) -> bool:
    """Shape gate for the production op (consumed by
    serving_attention._flash_decode_ok): single-token decode with a
    lane-aligned head dim, on an unsharded cache OR one sharded over
    the tp (kv heads) / sp (length) serving axes with shard-aligned
    extents.  int8 caches need 32-aligned per-shard extents (the int8
    sublane tiling widens the append's RMW window to 32); int4
    carriers (``pack`` = 2) widen it again to 64 LOGICAL positions —
    32 carrier sublanes — with the jnp path as the fallback where the
    alignment fails.  WHETHER flash beats the XLA attend is the host's
    cost decision (inference_manager.flash_wins) — this only says the
    kernel can run."""
    R, KV, S_c, D = ck.shape
    S = S_c * pack                 # logical length
    align = 32 * pack if ck.dtype.itemsize == 1 else 16
    if C != 1 or D % 128 != 0 or S % align != 0:
        return False
    if mesh is None:
        return True
    tp_ax, sp_ax, tp, sp = mesh_axes(mesh)
    other = [a for a, s in mesh.shape.items()
             if s > 1 and a not in (tp_ax, sp_ax)]
    return (not other and KV % tp == 0 and S % sp == 0
            and (S // sp) % align == 0)
