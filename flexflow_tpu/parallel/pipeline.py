"""Pipeline parallelism over the `pp` mesh axis.

TPU-native equivalent of the reference's pipeline parallelism.  The
reference expresses pipelining through per-stage MachineViews (stage =
transformer_layer_id / layers_per_stage, src/runtime/graph.cc:2016,
src/runtime/inference_manager.cc:131) and gets stage overlap for free from
Legion's future-driven task scheduling across ≤4 in-flight batches
(src/runtime/request_manager.cc:1947).  In a single-controller JAX program
there is no task runtime to overlap stages, so pipelining is expressed the
TPU way: a GPipe-style fill/drain schedule written as a `lax.scan` of
microbatch ticks inside `jax.shard_map`, with `lax.ppermute` rotating
activations stage→stage over ICI.

Composition with the other parallel dims: `shard_map(axis_names={"pp"})`
makes only the pipeline axis manual — dp/tp/sp stay in GSPMD "auto" mode,
so tensor-parallel shardings inside the stage body and data/sequence
sharding of the microbatched inputs keep working unchanged inside the
pipeline (this replaces the reference's composition of pipeline
MachineViews with NCCL TP comms).

Reverse-mode AD through the scan+ppermute reverses the schedule
automatically (the transpose of ppermute is ppermute with inverted pairs),
yielding the backward pipeline without extra code — the role Legion's
dependence analysis plays for the reference's backward pass.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from ..config import AXIS_PIPE

P = PartitionSpec


def microbatch(x: jax.Array, num_microbatches: int) -> jax.Array:
    """[B, ...] -> [M, B // M, ...]."""
    b = x.shape[0]
    assert b % num_microbatches == 0, (b, num_microbatches)
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    """[M, mb, ...] -> [M * mb, ...]."""
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])


def stage_fn_from_blocks(block_fn: Callable[[Any, Any], Any]):
    """Lift a single-block fn into a stage fn that scans the blocks assigned
    to this stage.

    ``block_fn(block_params, h) -> h`` is applied over the leading
    (layers-per-stage) dim of ``stage_params``.  This is the analogue of the
    reference grouping `layers_per_stage` transformer layers into one
    pipeline stage (inference_manager.cc:131).
    """

    def stage_fn(stage_params, h):
        def body(carry, block_params):
            return block_fn(block_params, carry), None

        out, _ = jax.lax.scan(body, h, stage_params)
        return out

    return stage_fn


def spmd_pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    num_stages: int,
    num_microbatches: int,
    axis: str = AXIS_PIPE,
    mesh: Optional[Mesh] = None,
    extra_manual_axes: Sequence[str] = (),
    xs_spec: Optional[PartitionSpec] = None,
) -> Callable[[Any, jax.Array], jax.Array]:
    """Build ``run(stacked_params, xs) -> ys``: a GPipe fill/drain pipeline.

    - ``stacked_params``: pytree whose leaves have a leading dim of size
      ``num_stages``, sharded ``PartitionSpec(axis, ...)`` — each device on
      the `axis` ring holds exactly its stage's slice (the TPU form of the
      reference's per-stage weight placement via MachineView
      start_device_id, graph.cc:2016-2024).
    - ``xs``: microbatched inputs ``[M, mb, ...]`` (replicated over `axis`;
      may be sharded over auto axes like dp/sp).
    - returns ``ys``: ``[M, mb, ...]``, the last stage's outputs, replicated
      over `axis`.

    stage_fn must preserve the activation shape (stage outputs feed the next
    stage's inputs over the ppermute ring).

    ``extra_manual_axes`` binds additional mesh axes as manual inside the
    pipeline body (shardy forbids a nested shard_map from re-binding a
    parent's axis, so collectives the stage body issues — e.g. the sp ring
    of ring_attention — must be bound HERE).  ``xs_spec`` then describes how
    xs/ys are sharded over those axes (e.g. P(None, None, "sp", None) for
    sequence-sharded [M, mb, T, E] activations).
    """
    S, M = num_stages, num_microbatches
    fwd_ring = [(i, i + 1) for i in range(S - 1)]

    def run_sharded(stacked_params, xs):
        # each pp rank sees leading stage dim of 1 -> squeeze to this
        # stage's params
        params = jax.tree.map(lambda p: jax.lax.squeeze(p, (0,)),
                              stacked_params)
        stage = jax.lax.axis_index(axis)
        mb_aval = jax.eval_shape(lambda a: a[0], xs)
        state = jnp.zeros(mb_aval.shape, mb_aval.dtype)
        # output dtype/shape must match input (ring constraint) — probe it
        out_aval = jax.eval_shape(stage_fn, params, state)
        assert out_aval.shape == mb_aval.shape, (
            f"pipeline stage must preserve activation shape: "
            f"{mb_aval.shape} -> {out_aval.shape}")
        outs = jnp.zeros((M,) + out_aval.shape, out_aval.dtype)

        def tick(carry, t):
            state, outs = carry
            # stage 0 injects microbatch t during the fill phase
            x_t = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, x_t.astype(state.dtype), state)
            out = stage_fn(params, inp)
            # last stage banks microbatch t-(S-1) during the drain phase
            oi = t - (S - 1)
            oi_c = jnp.clip(oi, 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, oi_c, 0, keepdims=False)
            sel = jnp.where((stage == S - 1) & (oi >= 0), out, cur)
            outs = jax.lax.dynamic_update_index_in_dim(outs, sel, oi_c, 0)
            # rotate activations one stage forward over ICI
            nxt = jax.lax.ppermute(out, axis, fwd_ring)
            return (nxt, outs), None

        (_, outs), _ = jax.lax.scan(tick, (state, outs), jnp.arange(M + S - 1))
        # broadcast banked outputs from the last stage to the whole pp ring
        # (masked psum = one-to-all); its transpose routes cotangents only
        # to the last stage, which is exactly the backward schedule's entry.
        outs = jax.lax.psum(
            jnp.where(stage == S - 1, outs, jnp.zeros_like(outs)), axis)
        return outs

    xs_spec = xs_spec if xs_spec is not None else P()

    if S == 1:
        # degenerate pipeline: plain scan over microbatches, no pp
        # collectives (stage-body collectives like the sp ring open their
        # own shard_map from auto mode)
        def run_single(stacked_params, xs):
            params = jax.tree.map(lambda p: jax.lax.squeeze(p, (0,)),
                                  stacked_params)
            def body(_, x):
                return None, stage_fn(params, x)
            _, ys = jax.lax.scan(body, None, xs)
            return ys
        return run_single

    def run(stacked_params, xs):
        in_specs = (jax.tree.map(lambda _: P(axis), stacked_params), xs_spec)
        fn = jax.shard_map(
            run_sharded, mesh=mesh, in_specs=in_specs, out_specs=xs_spec,
            axis_names=frozenset({axis, *extra_manual_axes}), check_vma=False)
        return fn(stacked_params, xs)

    return run


def stack_stage_params(layer_params: Sequence[Any], num_stages: int) -> Any:
    """Stack per-layer param pytrees [L x tree] -> tree with leading
    [S, L // S] dims (stage-major), ready for `spmd_pipeline` +
    `stage_fn_from_blocks`."""
    L = len(layer_params)
    assert L % num_stages == 0, (L, num_stages)
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layer_params)
    return jax.tree.map(
        lambda x: x.reshape((num_stages, L // num_stages) + x.shape[1:]),
        stacked)
