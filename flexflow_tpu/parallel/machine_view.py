"""Machine views: device-assignment records.

TPU-native equivalent of the reference's ``MachineView``
(include/flexflow/machine_view.h:18-39: {device_type, ndims,
start_device_id, dim[], stride[]} mapping a task index-space point to a
device id) and its legacy twin ``ParallelConfig`` (machine_view.h:66-100).

On TPU the executable form of a MachineView is a `jax.sharding.Mesh` slice +
axis naming: ``to_mesh`` realises the view over concrete devices.  The view
remains a first-class value (hashable, comparable) because the
auto-parallelization search manipulates views symbolically before any device
is touched — same role as in the reference, where views key NCCL comms and
simulator cache entries.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Sequence, Tuple

import numpy as np


class DeviceType(enum.Enum):
    TPU = "tpu"     # reference: DeviceType::GPU
    CPU = "cpu"


@dataclasses.dataclass(frozen=True)
class MachineView:
    """N-dimensional strided view over a linear device space
    (reference: machine_view.h:18-39)."""

    device_type: DeviceType = DeviceType.TPU
    start_device_id: int = 0
    dims: Tuple[int, ...] = (1,)
    strides: Tuple[int, ...] = (1,)

    def __post_init__(self):
        assert len(self.dims) == len(self.strides)

    @property
    def ndims(self) -> int:
        return len(self.dims)

    def num_parts(self) -> int:
        return int(np.prod(self.dims))

    def get_device_id(self, point: Sequence[int]) -> int:
        """reference: MachineView::get_device_id — linearise an index-space
        point through the strides."""
        assert len(point) == self.ndims
        dev = self.start_device_id
        for p, d, s in zip(point, self.dims, self.strides):
            assert 0 <= p < d
            dev += p * s
        return dev

    def device_ids(self) -> Tuple[int, ...]:
        """All device ids covered, in row-major point order."""
        out = []
        for flat in range(self.num_parts()):
            point = []
            rem = flat
            for d in reversed(self.dims):
                point.append(rem % d)
                rem //= d
            out.append(self.get_device_id(tuple(reversed(point))))
        return tuple(out)

    def to_mesh(self, devices: Sequence, axis_names: Sequence[str]):
        """Realise as a Mesh over concrete jax devices (the executable form;
        replaces FFMapper's slice_task placement, mapper.cc:376)."""
        import jax

        ids = self.device_ids()
        devs = np.array([devices[i] for i in ids]).reshape(self.dims)
        assert len(axis_names) == self.ndims
        return jax.sharding.Mesh(devs, tuple(axis_names))

    def hash(self) -> int:
        return hash(self)


def make_1d_view(num_devices: int, start: int = 0, stride: int = 1) -> MachineView:
    """The common data-parallel view (reference: graph.cc:1969-1992 builds
    exactly this for only_data_parallel training)."""
    return MachineView(DeviceType.TPU, start, (num_devices,), (stride,))
