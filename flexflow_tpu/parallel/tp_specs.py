"""Shared tensor-parallel PartitionSpec tables.

Single source for the per-parameter TP layouts used by BOTH the serving
pspec builder (serving/inference_manager._param_pspecs) and the training
strategy application (core/model._train_pspec) — the sharding knowledge
the reference hard-codes in its insertion rules (model.cc:3243-3296) and
weight loader (file_loader.cc:209-330).
"""

from jax.sharding import PartitionSpec

from ..config import AXIS_MODEL

# serving attention params: wq/wk/wv [E, H, D], wo [H, D, E] — heads shard
ATTN_WEIGHT_SPECS = {
    "wq": PartitionSpec(None, AXIS_MODEL, None),
    "wk": PartitionSpec(None, AXIS_MODEL, None),
    "wv": PartitionSpec(None, AXIS_MODEL, None),
    "wo": PartitionSpec(AXIS_MODEL, None, None),
}
ATTN_BIAS_SPECS = {
    "bq": PartitionSpec(AXIS_MODEL, None),
    "bk": PartitionSpec(AXIS_MODEL, None),
    "bv": PartitionSpec(AXIS_MODEL, None),
    "bo": PartitionSpec(None),
}

# linear [in, out] kernels
LINEAR_COL = {"kernel": PartitionSpec(None, AXIS_MODEL),
              "bias": PartitionSpec(AXIS_MODEL)}
LINEAR_ROW = {"kernel": PartitionSpec(AXIS_MODEL, None),
              "bias": PartitionSpec(None)}
LINEAR_REPLICATED = {"kernel": PartitionSpec(None, None),
                     "bias": PartitionSpec(None)}

# conv OIHW: shard out-channels
CONV_SPECS = {"kernel": PartitionSpec(AXIS_MODEL, None, None, None),
              "bias": PartitionSpec(AXIS_MODEL)}

# embedding [vocab, features]: shard features
EMBEDDING_SPECS = {"embedding": PartitionSpec(None, AXIS_MODEL)}
