"""First-class parallelism IR ops.

TPU-native equivalents of the reference's parallel operators
(src/parallel_ops/: Repartition, Combine, Replicate, Reduction, AllReduce,
FusedParallelOp — §2.3 of SURVEY.md).  In the reference these are explicit
data-movement tasks with their own CUDA kernels; on TPU they are *sharding
annotations*: inside jit each lowers to `jax.lax.with_sharding_constraint`
and the GSPMD partitioner inserts the matching ICI collective
(all-gather/all-reduce/reduce-scatter/all-to-all), replacing the NCCL calls
in allreduce_kernels.cu:27-76 etc.

They stay first-class graph ops (not just annotations scattered in model
code) so the auto-parallelization search can insert/remove/rewrite them —
the same reason the reference keeps them in the PCG.

Semantics table (reference file -> TPU lowering):
- Repartition (partition.cc):  shard dim d over axis a      -> wsc(P(..., a, ...))
- Combine     (combine.cc):    unshard dim d (gather)       -> wsc(P(..., None, ...))
- Replicate   (replicate.cc):  broadcast to a replica axis  -> wsc replicated; grad = psum (automatic via transpose of broadcast)
- Reduction   (reduction.cc):  sum partials over axis, then scatter -> psum/reduce-scatter inside shard_map paths
- AllReduce   (allreduce.cc):  sum partials, result replicated -> psum
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import TensorSpec
from ..fftype import OpType
from ..ops.registry import OpContext, OpDef, register


def _wsc(x, mesh, spec: PartitionSpec):
    """with_sharding_constraint when a mesh is present; identity otherwise
    (single-device eager paths and tests)."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _spec_for_dim(ndim: int, dim: int, axis: Optional[str]) -> PartitionSpec:
    entries = [None] * ndim
    if axis is not None:
        entries[dim] = axis
    return PartitionSpec(*entries)


def _check_degree(mesh, axis: str, degree: int, what: str):
    """The IR's declared degree must match the mesh axis it lowers onto
    (keeps graph metadata truthful for the search/cost model)."""
    if mesh is not None and axis in mesh.axis_names:
        actual = mesh.shape[axis]
        if degree != actual:
            raise ValueError(
                f"{what}: declared degree {degree} != mesh axis "
                f"'{axis}' size {actual}")


@register
class Repartition(OpDef):
    """Split tensor dim across devices (reference: src/parallel_ops/
    partition.cc; kernel = identity copy per shard,
    partition_kernels.cu:27-47)."""

    type = OpType.REPARTITION

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def forward(self, params, inputs, attrs, ctx: OpContext):
        (x,) = inputs
        _check_degree(ctx.mesh, attrs["axis"], attrs["degree"], "Repartition")
        return [_wsc(x, ctx.mesh, _spec_for_dim(x.ndim, attrs["dim"],
                                                attrs["axis"]))]


@register
class Combine(OpDef):
    """Gather shards of a dim (reference: src/parallel_ops/combine.cc;
    inverse of Repartition)."""

    type = OpType.COMBINE

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def forward(self, params, inputs, attrs, ctx: OpContext):
        (x,) = inputs
        mesh = ctx.mesh
        return [_wsc(x, mesh, _spec_for_dim(x.ndim, attrs["dim"], None))]


@register
class Replicate(OpDef):
    """Broadcast to a replica dim; backward sums replica gradients
    (reference: src/parallel_ops/replicate.cc,
    replicate_backward_kernel replicate_kernels.cu:39).  Under GSPMD the
    backward psum comes from the transpose of the broadcast automatically."""

    type = OpType.REPLICATE

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def forward(self, params, inputs, attrs, ctx: OpContext):
        (x,) = inputs
        mesh = ctx.mesh
        return [_wsc(x, mesh, PartitionSpec(*([None] * x.ndim)))]


@register
class AllReduce(OpDef):
    """Sum partial results; output replicated (reference:
    src/parallel_ops/allreduce.cc — ncclAllReduce on fwd and inference
    paths; the TP-sum after a row-parallel matmul).

    Inside jit/GSPMD the partial-sum state is expressed by the producer
    having contracted over a sharded dim; XLA inserts the all-reduce on its
    own.  When called under shard_map (explicit-collective paths) we issue a
    real psum over the named axis."""

    type = OpType.ALLREDUCE

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def forward(self, params, inputs, attrs, ctx: OpContext):
        (x,) = inputs
        axis = attrs["axis"]
        if _inside_shard_map(axis):
            return [jax.lax.psum(x, axis)]
        mesh = ctx.mesh
        return [_wsc(x, mesh, PartitionSpec(*([None] * x.ndim)))]


@register
class Reduction(OpDef):
    """Reduce-scatter: sum ``degree`` stacked partial copies along ``dim``,
    shrinking that dim by ``degree`` (reference: src/parallel_ops/
    reduction.cc — reduction_kernels.cu:28-54 sums num_replicas strided
    chunks, output size = input/num_replicas).

    Both lowerings agree on the logical output shape dims[dim]//degree:
    - under shard_map: psum_scatter(tiled) over the named axis;
    - under jit/GSPMD (or no mesh): strided chunk-sum via reshape, with the
      result sharded over the axis."""

    type = OpType.REDUCTION

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        dim, degree = attrs["dim"], attrs["degree"]
        assert x.shape[dim] % degree == 0, (x.shape, dim, degree)
        shape = list(x.shape)
        shape[dim] //= degree
        return [TensorSpec(tuple(shape), x.dtype)]

    def forward(self, params, inputs, attrs, ctx: OpContext):
        (x,) = inputs
        axis, dim, degree = attrs["axis"], attrs["dim"], attrs["degree"]
        if _inside_shard_map(axis):
            return [jax.lax.psum_scatter(x, axis, scatter_dimension=dim,
                                         tiled=True)]
        _check_degree(ctx.mesh, axis, degree, "Reduction")
        # strided chunk sum: reshape dim -> (degree, dim//degree), sum copies
        shape = x.shape
        split = shape[:dim] + (degree, shape[dim] // degree) + shape[dim + 1:]
        y = jnp.sum(jnp.reshape(x, split), axis=dim)
        return [_wsc(y, ctx.mesh, _spec_for_dim(y.ndim, dim, axis))]


@register
class FusedParallelOp(OpDef):
    """Chain of parallel-op transitions applied as one step (reference:
    src/parallel_ops/fused_parallel_op.cc).  Under GSPMD only the final
    sharding matters, so this is a single constraint with the last spec."""

    type = OpType.FUSED_PARALLEL

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def forward(self, params, inputs, attrs, ctx: OpContext):
        (x,) = inputs
        mesh = ctx.mesh
        return [_wsc(x, mesh, attrs["spec"])]


def _inside_shard_map(axis_name: str) -> bool:
    """True when `axis_name` is a bound collective axis (i.e. we're tracing
    inside shard_map/pmap), so explicit psum is legal."""
    try:
        jax.lax.axis_index(axis_name)
        return True
    except NameError:
        return False
    except Exception:
        return False
