"""Multi-host initialization.

TPU-native replacement for the reference's multi-node launch stack
(GASNet/UCX Legion networks + mpirun wrappers, MULTI-NODE.md,
tests/multinode_helpers/mpi_wrapper*.sh): one `jax.distributed.initialize`
call per process, after which device meshes span every host — ICI
collectives within a slice, DCN across slices, no MPI anywhere.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host runtime (reference: mpirun + GASNet bootstrap).

    On Cloud TPU the arguments auto-detect from the metadata server; pass
    them explicitly elsewhere (coordinator "host:port", world size, rank).
    Environment fallbacks: FF_COORDINATOR, FF_NUM_PROCESSES, FF_PROCESS_ID
    (mirroring the reference's env-driven config/config.linux scheme).
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = (coordinator_address
                           or os.environ.get("FF_COORDINATOR"))
    if num_processes is None and "FF_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["FF_NUM_PROCESSES"])
    if process_id is None and "FF_PROCESS_ID" in os.environ:
        process_id = int(os.environ["FF_PROCESS_ID"])
    if num_processes == 1:
        # single-process "cluster": nothing to coordinate (the reference's
        # launcher also skips MPI when -np 1)
        process_id = process_id or 0
        if coordinator_address is None:
            # ephemeral loopback port so concurrent jobs don't collide
            import socket

            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                coordinator_address = f"127.0.0.1:{s.getsockname()[1]}"
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True


def is_multi_host() -> bool:
    return jax.process_count() > 1


def local_devices():
    return jax.local_devices()


def global_device_count() -> int:
    return jax.device_count()
