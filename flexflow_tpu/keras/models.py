"""Keras-style Sequential and functional Model.

TPU-native re-design of the reference's drop-in Keras frontend
(python/flexflow/keras/models/sequential.py + model.py): same user surface
(``compile(optimizer=..., loss=..., metrics=[...])``, ``fit``, ``evaluate``,
``predict``, ``summary``), lowered onto the core
:class:`flexflow_tpu.Model` instead of the cffi FFModel.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..config import FFConfig
from ..core.model import Model as CoreModel
from ..fftype import DataType, LossType, MetricsType
from ..training.optimizer import AdamOptimizer, Optimizer, SGDOptimizer
from .layers import Input, KerasLayer, KTensor

_LOSSES = {
    "categorical_crossentropy": LossType.CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy":
        LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
    "mse": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
}
_METRICS = {
    "accuracy": MetricsType.ACCURACY,
    "categorical_crossentropy": MetricsType.CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy":
        MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": MetricsType.MEAN_SQUARED_ERROR,
    "mean_absolute_error": MetricsType.MEAN_ABSOLUTE_ERROR,
}


def _to_optimizer(opt) -> Optimizer:
    if isinstance(opt, Optimizer):
        return opt
    if isinstance(opt, str):
        return {"sgd": SGDOptimizer(), "adam": AdamOptimizer()}[opt.lower()]
    raise TypeError(f"unsupported optimizer {opt!r}")


class Model:
    """Functional-API model (reference keras/models/model.py)."""

    def __init__(self, inputs: Union[KTensor, Sequence[KTensor]] = None,
                 outputs: Union[KTensor, Sequence[KTensor]] = None,
                 name: str = "keras_model", batch_size: int = 32,
                 config: Optional[FFConfig] = None):
        self.inputs = ([inputs] if isinstance(inputs, KTensor)
                       else list(inputs or []))
        self.outputs = ([outputs] if isinstance(outputs, KTensor)
                        else list(outputs or []))
        self.name = name
        self.batch_size = batch_size
        self.config = config
        self.core: Optional[CoreModel] = None
        self._layer_order: Optional[List[KerasLayer]] = None

    # ------------------------------------------------------------ topology
    def _toposort(self) -> List[KerasLayer]:
        order: List[KerasLayer] = []
        seen = set()

        def visit(t: KTensor):
            l = t.layer
            if l is None or id(l) in seen:
                return
            seen.add(id(l))
            for src in l.inbound:
                visit(src)
            order.append(l)

        for out in self.outputs:
            visit(out)
        return order

    # ------------------------------------------------------------- compile
    def compile(self, optimizer="sgd", loss="sparse_categorical_crossentropy",
                metrics: Sequence[str] = ("accuracy",),
                batch_size: Optional[int] = None, seed: int = 0):
        batch_size = batch_size or self.batch_size
        cfg = self.config or FFConfig(batch_size=batch_size)
        cfg.batch_size = batch_size
        core = CoreModel(cfg, name=self.name)
        sym_to_core: Dict[int, Any] = {}
        for i, t in enumerate(self.inputs):
            shape = (batch_size,) + tuple(t.shape[1:])
            sym_to_core[id(t)] = core.create_tensor(shape, t.dtype,
                                                    name=t.name)
        self._layer_order = self._toposort()
        for layer in self._layer_order:
            ins = [sym_to_core[id(t)] for t in layer.inbound]
            out = layer.build_on(core, ins)
            sym_to_core[id(layer.output)] = out
        loss_t = (_LOSSES[loss] if isinstance(loss, str)
                  else getattr(loss, "type", None) or loss)
        metric_ts = [(_METRICS[m] if isinstance(m, str)
                      else getattr(m, "type", None) or m)
                     for m in metrics]
        opt = _to_optimizer(optimizer)
        # keras kernel_regularizer=L2(...) lowers to the optimizer's
        # decoupled weight decay (reference regularizers.py scope; applied
        # globally — the strongest layer's coefficient wins).  The
        # user-supplied optimizer instance is COPIED before the override:
        # mutating it would leak regularization into other models
        # compiled with the same object
        from .regularizers import L2 as _L2

        l2s = [l.kernel_regularizer.l2 for l in self._layer_order
               if isinstance(getattr(l, "kernel_regularizer", None), _L2)]
        if l2s and getattr(opt, "weight_decay", 0.0) == 0.0:
            import copy

            opt = copy.copy(opt)
            opt.weight_decay = max(l2s)
        core.compile(opt, loss_type=loss_t, metrics=metric_ts, seed=seed)
        self.core = core
        return self

    # ----------------------------------------------------------- training
    def fit(self, x, y, epochs: int = 1, batch_size: Optional[int] = None,
            callbacks: Sequence[Any] = (), verbose: bool = True):
        assert self.core is not None, "call compile() first"
        if not isinstance(x, (list, tuple)):
            x = [x]
        for cb in callbacks:
            cb.set_model(self)
            cb.on_train_begin()
        perf = None
        for epoch in range(epochs):
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            perf = self.core.fit(x, y, epochs=1, batch_size=batch_size,
                                 verbose=verbose)
            logs = {"accuracy": perf.accuracy, "loss": perf.last_loss}
            for cb in callbacks:
                cb.on_epoch_end(epoch, logs)
            if any(getattr(cb, "stop_training", False) for cb in callbacks):
                break
        for cb in callbacks:
            cb.on_train_end()
        return perf

    def evaluate(self, x, y, batch_size: Optional[int] = None):
        assert self.core is not None, "call compile() first"
        if not isinstance(x, (list, tuple)):
            x = [x]
        return self.core.eval(x, y, batch_size=batch_size)

    def predict(self, x, batch_size: Optional[int] = None) -> np.ndarray:
        assert self.core is not None, "call compile() first"
        if not isinstance(x, (list, tuple)):
            x = [x]
        bs = batch_size or self.core.config.batch_size
        outs = []
        n = x[0].shape[0]
        for i in range(0, n, bs):
            batch = [np.asarray(xi[i:i + bs]) for xi in x]
            tail = batch[0].shape[0]
            if tail < bs:   # pad the last partial batch, slice after
                batch = [np.concatenate(
                    [b, np.repeat(b[-1:], bs - tail, axis=0)]) for b in batch]
            out = np.asarray(self.core.apply(self.core.params, *batch))
            outs.append(out[:tail])
        return np.concatenate(outs, axis=0)

    def summary(self) -> str:
        lines = [f'Model: "{self.name}"']
        for t in self.inputs:
            lines.append(f"  Input {t.name}: {t.shape}")
        for l in (self._layer_order or self._toposort()):
            lines.append(f"  {type(l).__name__} {l.name}: "
                         f"{l.output.shape if l.output else '?'}")
        s = "\n".join(lines)
        print(s)
        return s


class Sequential(Model):
    """reference: keras/models/sequential.py."""

    def __init__(self, layers: Sequence[KerasLayer] = (),
                 name: str = "sequential", batch_size: int = 32,
                 config: Optional[FFConfig] = None):
        super().__init__(name=name, batch_size=batch_size, config=config)
        self._pending: List[KerasLayer] = list(layers)
        self.input_shape: Optional[tuple] = None

    def add(self, layer: KerasLayer):
        self._pending.append(layer)

    def compile(self, optimizer="sgd",
                loss="sparse_categorical_crossentropy",
                metrics: Sequence[str] = ("accuracy",),
                input_shape: Optional[Sequence[int]] = None,
                input_dtype: DataType = DataType.FLOAT,
                batch_size: Optional[int] = None, seed: int = 0):
        shape = input_shape or self.input_shape
        if shape is None:
            first = self._pending[0]
            shape = getattr(first, "input_shape", None)
        assert shape is not None, \
            "Sequential needs input_shape (pass to compile())"
        t = Input(tuple(shape), dtype=input_dtype)
        self.inputs = [t]
        for layer in self._pending:
            t = layer(t)
        self.outputs = [t]
        return super().compile(optimizer, loss, metrics,
                               batch_size=batch_size, seed=seed)
