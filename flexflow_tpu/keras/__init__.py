"""Keras frontend (reference: python/flexflow/keras/ — a drop-in
``tensorflow.keras`` replacement, ~4,400 LoC: models, layers, optimizers,
losses, metrics, callbacks)."""

from . import (callbacks, datasets, initializers, layers, losses, metrics,
               regularizers)
from .layers import (Activation, Add, AveragePooling2D, BatchNormalization,
                     Concatenate, Conv2D, Dense, Dropout, Embedding, Flatten,
                     Input, KerasLayer, KTensor, LayerNormalization,
                     Maximum, MaxPooling2D, Minimum, Multiply, Permute,
                     Reshape, Subtract)
from .models import Model, Sequential
from ..training.optimizer import AdamOptimizer as Adam
from ..training.optimizer import SGDOptimizer as SGD

__all__ = [
    "Model", "Sequential", "Input", "KerasLayer", "KTensor", "Dense",
    "Activation", "Flatten", "Dropout", "Embedding", "Conv2D",
    "MaxPooling2D", "AveragePooling2D", "BatchNormalization",
    "LayerNormalization", "Add", "Subtract", "Multiply", "Maximum",
    "Minimum", "Concatenate", "Reshape", "Permute",
    "SGD", "Adam", "callbacks", "datasets", "initializers", "layers",
    "losses", "metrics", "regularizers",
]
