"""Keras loss objects (reference: python/flexflow/keras/losses.py —
class wrappers resolving to LossType enums; ``Model.compile`` accepts
either these objects or the equivalent strings)."""

from __future__ import annotations

from ..fftype import LossType


class Loss:
    type: LossType = None

    def __init__(self, name: str = "loss"):
        self.name = name


class CategoricalCrossentropy(Loss):
    type = LossType.CATEGORICAL_CROSSENTROPY

    def __init__(self, name: str = "categorical_crossentropy"):
        super().__init__(name)


class SparseCategoricalCrossentropy(Loss):
    type = LossType.SPARSE_CATEGORICAL_CROSSENTROPY

    def __init__(self, name: str = "sparse_categorical_crossentropy"):
        super().__init__(name)


class MeanSquaredError(Loss):
    type = LossType.MEAN_SQUARED_ERROR_AVG_REDUCE

    def __init__(self, name: str = "mean_squared_error"):
        super().__init__(name)


class Identity(Loss):
    type = LossType.IDENTITY

    def __init__(self, name: str = "identity"):
        super().__init__(name)
