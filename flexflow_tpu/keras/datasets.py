"""Keras-style dataset loaders.

API parity with the reference's keras frontend datasets
(python/flexflow/keras/datasets/{mnist,cifar10,reuters}.py — each exposes
``load_data() -> (x_train, y_train), (x_test, y_test)``).  The reference
downloads from public URLs via ``get_file``; here datasets load from a
local cache (``FF_DATASET_DIR`` or ``~/.keras/datasets``, the reference's
cache location) and, when the file is absent (e.g. an air-gapped TPU pod),
fall back to a DETERMINISTIC synthetic stand-in of the right shapes/dtypes
so examples and CI always run — the fallback is seeded and labeled
linearly-separable, so convergence thresholds remain meaningful.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np

Arrays = Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]


def _cache_path(name: str) -> str:
    root = os.environ.get(
        "FF_DATASET_DIR", os.path.expanduser("~/.keras/datasets"))
    return os.path.join(root, name)


def _synthetic_images(shape, classes: int, n_train: int, n_test: int,
                      seed: int) -> Arrays:
    """Class-conditional Gaussian blobs rendered into image tensors —
    linearly separable, so accuracy gates still measure learning."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes,) + shape).astype(np.float32) * 64
    def make(n, seed2):
        r = np.random.default_rng(seed2)
        y = r.integers(0, classes, n)
        x = centers[y] + r.normal(size=(n,) + shape).astype(np.float32) * 32
        return np.clip(x + 128, 0, 255).astype(np.uint8), y.astype(np.int64)
    xtr, ytr = make(n_train, seed + 1)
    xte, yte = make(n_test, seed + 2)
    return (xtr, ytr), (xte, yte)


class mnist:
    """reference: keras/datasets/mnist.py load_data."""

    @staticmethod
    def load_data(path: str = "mnist.npz") -> Arrays:
        p = _cache_path(path)
        if os.path.exists(p):
            with np.load(p, allow_pickle=True) as f:
                return ((f["x_train"], f["y_train"]),
                        (f["x_test"], f["y_test"]))
        return _synthetic_images((28, 28), 10, 6000, 1000, seed=0)


class cifar10:
    """reference: keras/datasets/cifar10.py load_data (NCHW like the
    reference's conv layout)."""

    @staticmethod
    def load_data(path: str = "cifar10.npz") -> Arrays:
        p = _cache_path(path)
        if os.path.exists(p):
            with np.load(p, allow_pickle=True) as f:
                return ((f["x_train"], f["y_train"]),
                        (f["x_test"], f["y_test"]))
        return _synthetic_images((3, 32, 32), 10, 5000, 1000, seed=1)


class reuters:
    """reference: keras/datasets/reuters.py load_data (token-id
    sequences + topic labels)."""

    @staticmethod
    def load_data(path: str = "reuters.npz", num_words: int = 10000,
                  maxlen: int = 80, test_split: float = 0.2) -> Arrays:
        p = _cache_path(path)
        if os.path.exists(p):
            with np.load(p, allow_pickle=True) as f:
                xs, ys = f["x"], f["y"]
            # honor the caller's bounds like the synthetic path does
            # (behavior must not flip on cache presence)
            xs = np.minimum(xs[:, :maxlen], num_words - 1)
            n_train = len(xs) - int(len(xs) * test_split)
            return ((xs[:n_train], ys[:n_train]),
                    (xs[n_train:], ys[n_train:]))
        # synthetic: class-dependent token distributions, fixed length
        rng = np.random.default_rng(2)
        classes = 46
        base = rng.integers(4, num_words, size=(classes, maxlen))
        def make(n, seed2):
            r = np.random.default_rng(seed2)
            y = r.integers(0, classes, n)
            noise = r.integers(4, num_words, size=(n, maxlen))
            keep = r.random((n, maxlen)) < 0.7
            x = np.where(keep, base[y], noise)
            return x.astype(np.int64), y.astype(np.int64)
        xtr, ytr = make(2000, 3)
        xte, yte = make(400, 4)
        return (xtr, ytr), (xte, yte)
