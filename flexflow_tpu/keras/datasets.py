"""Keras-style dataset loaders.

API parity with the reference's keras frontend datasets
(python/flexflow/keras/datasets/{mnist,cifar10,reuters}.py — each exposes
``load_data() -> (x_train, y_train), (x_test, y_test)``).  The reference
downloads from public URLs via ``get_file``; here datasets load from a
local cache (``FF_DATASET_DIR`` or ``~/.keras/datasets``, the reference's
cache location) in the reference's own artifact formats (mnist.npz,
cifar-10-python.tar.gz pickled batches, ragged reuters.npz), and, when the
artifact is absent (e.g. an air-gapped TPU pod), fall back to a
DETERMINISTIC synthetic stand-in of the right shapes/dtypes so examples
and CI always run — the fallback is seeded and labeled
linearly-separable, so convergence thresholds remain meaningful.

One deliberate deviation: reuters returns a rectangular int array (padded
with 0) instead of the reference's ragged lists — the layer API consumes
arrays.  Over-``maxlen`` sequences are DROPPED, matching the reference's
_remove_long_seq (reuters.py:70-71), never truncated.
"""

from __future__ import annotations

import os
import pickle
import tarfile
from typing import Optional, Tuple

import numpy as np

Arrays = Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]


def _cache_path(name: str) -> str:
    root = os.environ.get(
        "FF_DATASET_DIR", os.path.expanduser("~/.keras/datasets"))
    return os.path.join(root, name)


def _load_npz(path: str, keys):
    p = _cache_path(path)
    if not os.path.exists(p):
        return None
    with np.load(p, allow_pickle=True) as f:
        return tuple(f[k] for k in keys)


def _synthetic_images(shape, classes: int, n_train: int, n_test: int,
                      seed: int) -> Arrays:
    """Class-conditional Gaussian blobs rendered into image tensors —
    linearly separable, so accuracy gates still measure learning."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes,) + shape).astype(np.float32) * 64

    def make(n, seed2):
        r = np.random.default_rng(seed2)
        y = r.integers(0, classes, n)
        x = centers[y] + r.normal(size=(n,) + shape).astype(np.float32) * 32
        return np.clip(x + 128, 0, 255).astype(np.uint8), y.astype(np.int64)

    xtr, ytr = make(n_train, seed + 1)
    xte, yte = make(n_test, seed + 2)
    return (xtr, ytr), (xte, yte)


class mnist:
    """reference: keras/datasets/mnist.py load_data (mnist.npz cache)."""

    @staticmethod
    def load_data(path: str = "mnist.npz") -> Arrays:
        got = _load_npz(path, ("x_train", "y_train", "x_test", "y_test"))
        if got is not None:
            xtr, ytr, xte, yte = got
            return (xtr, ytr), (xte, yte)
        return _synthetic_images((28, 28), 10, 6000, 1000, seed=0)


class cifar10:
    """reference: keras/datasets/cifar10.py load_data — reads the
    reference's cached ``cifar-10-python.tar.gz`` (five pickled
    data_batch_N + test_batch, NCHW uint8), cifar10.npz, or synthetic."""

    @staticmethod
    def _load_tarball(p: str) -> Arrays:
        def batch(tf_, name):
            with tf_.extractfile(f"cifar-10-batches-py/{name}") as f:
                d = pickle.load(f, encoding="bytes")
            x = d[b"data"].reshape(-1, 3, 32, 32)
            y = np.asarray(d[b"labels"], np.int64)
            return x, y

        with tarfile.open(p) as tf_:
            parts = [batch(tf_, f"data_batch_{i}") for i in range(1, 6)]
            xtr = np.concatenate([x for x, _ in parts])
            ytr = np.concatenate([y for _, y in parts])
            xte, yte = batch(tf_, "test_batch")
        return (xtr, ytr), (xte, yte)

    @staticmethod
    def load_data(path: str = "cifar-10-python.tar.gz") -> Arrays:
        p = _cache_path(path)
        if os.path.exists(p) and not path.endswith(".npz"):
            return cifar10._load_tarball(p)
        npz = path if path.endswith(".npz") else "cifar10.npz"
        got = _load_npz(npz, ("x_train", "y_train", "x_test", "y_test"))
        if got is not None:
            xtr, ytr, xte, yte = got
            return (xtr, ytr), (xte, yte)
        return _synthetic_images((3, 32, 32), 10, 5000, 1000, seed=1)


class reuters:
    """reference: keras/datasets/reuters.py load_data (ragged token-id
    sequences + topic labels; reference signature honored — skip_top,
    start_char, oov_char, index_from included)."""

    @staticmethod
    def load_data(path: str = "reuters.npz",
                  num_words: Optional[int] = None, skip_top: int = 0,
                  maxlen: Optional[int] = None, test_split: float = 0.2,
                  seed: int = 113, start_char: int = 1, oov_char: int = 2,
                  index_from: int = 3) -> Arrays:
        got = _load_npz(path, ("x", "y"))
        if got is not None:
            xs_raw, ys = got
            # the reference's artifact is a 1-D object array of ragged
            # lists; rectangularize (drop over-maxlen rows, pad with 0)
            # per the reference's preprocessing semantics
            seqs = [list(s) for s in xs_raw]
            ys = list(np.asarray(ys))
            if maxlen is None:
                # +1: every sequence gains a start_char slot
                maxlen_eff = max((len(s) for s in seqs), default=0) + 1
            else:
                # the reference DROPS over-long sequences rather than
                # truncating (_remove_long_seq keeps len < maxlen,
                # reuters.py:70-71) — sample counts and label mix match
                maxlen_eff = maxlen
                kept = [(s, y) for s, y in zip(seqs, ys)
                        if len(s) + 1 < maxlen]  # +1: start_char slot
                seqs = [s for s, _ in kept]
                ys = [y for _, y in kept]
            out = np.zeros((len(seqs), maxlen_eff), np.int64)
            for i, s in enumerate(seqs):
                s = [start_char] + [w + index_from for w in s]
                if num_words is not None or skip_top:
                    top = num_words if num_words is not None else max(
                        max(s, default=0) + 1, skip_top + 1)
                    s = [w if skip_top <= w < top else oov_char
                         for w in s]
                out[i, :len(s)] = s
            rng = np.random.default_rng(seed)
            order = rng.permutation(len(out))
            out, ys = out[order], np.asarray(ys, np.int64)[order]
            n_train = len(out) - int(len(out) * test_split)
            return ((out[:n_train], ys[:n_train]),
                    (out[n_train:], ys[n_train:]))
        # synthetic: class-dependent token distributions, fixed length
        vocab = num_words or 10000
        length = maxlen or 80
        rng = np.random.default_rng(2)
        classes = 46
        base = rng.integers(max(4, skip_top), vocab,
                            size=(classes, length))

        def make(n, seed2):
            r = np.random.default_rng(seed2)
            y = r.integers(0, classes, n)
            noise = r.integers(max(4, skip_top), vocab, size=(n, length))
            keep = r.random((n, length)) < 0.7
            x = np.where(keep, base[y], noise)
            return x.astype(np.int64), y.astype(np.int64)

        xtr, ytr = make(2000, 3)
        xte, yte = make(400, 4)
        return (xtr, ytr), (xte, yte)
