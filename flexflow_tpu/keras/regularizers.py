"""Keras regularizer objects (reference:
python/flexflow/keras/regularizers.py).  The reference lowers L1/L2 to
its weight-decay hook; here L2 maps onto the optimizers' decoupled
``weight_decay`` (the TPU-idiomatic equivalent) and Model.compile reads
a Dense/Conv2D layer's ``kernel_regularizer`` to set it.  L1 has no
optimizer-side analogue and raises, like the reference's unsupported
paths do."""

from __future__ import annotations


class Regularizer:
    pass


class L2(Regularizer):
    def __init__(self, l2: float = 0.01):
        self.l2 = float(l2)


class L1(Regularizer):
    def __init__(self, l1: float = 0.01):
        raise NotImplementedError(
            "L1 regularization has no decoupled-weight-decay equivalent; "
            "use L2 (lowered to the optimizer's weight_decay)")
