"""Keras initializer objects (reference:
python/flexflow/keras/initializers.py — thin wrappers over the core
initializers so keras layer kwargs accept the keras vocabulary)."""

from __future__ import annotations

from ..core.initializers import (ConstantInitializer, GlorotUniform,
                                 NormInitializer, UniformInitializer,
                                 ZeroInitializer)

DefaultInitializer = GlorotUniform
Zeros = ZeroInitializer
Constant = ConstantInitializer


def RandomUniform(minval: float = -0.05, maxval: float = 0.05,
                  seed: int = 0):
    return UniformInitializer(seed, minval, maxval)


def RandomNormal(mean: float = 0.0, stddev: float = 0.05, seed: int = 0):
    return NormInitializer(seed, mean, stddev)
