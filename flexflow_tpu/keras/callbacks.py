"""Keras-style callbacks (reference python/flexflow/keras/callbacks.py:
Callback base, LearningRateScheduler, VerifyMetrics, EpochVerifyMetrics
— plus ModelCheckpoint/EarlyStopping which the reference delegates to
user code via get/set_tensor)."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional


class Callback:
    def set_model(self, model):
        self.model = model

    def on_train_begin(self):
        pass

    def on_train_end(self):
        pass

    def on_epoch_begin(self, epoch: int):
        pass

    def on_epoch_end(self, epoch: int, logs: Dict[str, Any]):
        pass


class LearningRateScheduler(Callback):
    """reference: keras/callbacks.py LearningRateScheduler."""

    def __init__(self, schedule: Callable[[int, float], float]):
        self.schedule = schedule

    def on_epoch_begin(self, epoch: int):
        opt = self.model.core.optimizer
        attr = "lr" if hasattr(opt, "lr") else "alpha"
        setattr(opt, attr, self.schedule(epoch, getattr(opt, attr)))


class VerifyMetrics(Callback):
    """Assert final accuracy meets a threshold (reference keras/callbacks.py
    VerifyMetrics, used by the training integration tests to gate CI,
    tests/training_tests.sh semantics)."""

    def __init__(self, accuracy: float):
        self.accuracy = accuracy
        self.last: Optional[float] = None

    def on_epoch_end(self, epoch: int, logs):
        self.last = logs.get("accuracy")

    def on_train_end(self):
        assert self.last is not None and self.last >= self.accuracy, (
            f"accuracy {self.last} below threshold {self.accuracy}")


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "loss", patience: int = 3,
                 min_delta: float = 0.0):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.best: Optional[float] = None
        self.wait = 0
        self.stop_training = False

    def on_epoch_end(self, epoch: int, logs):
        cur = logs.get(self.monitor)
        if cur is None:
            return
        better = (self.best is None
                  or cur < self.best - self.min_delta)
        if self.monitor == "accuracy":
            better = self.best is None or cur > self.best + self.min_delta
        if better:
            self.best, self.wait = cur, 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True


class ModelCheckpoint(Callback):
    """Saves full training state per epoch via the checkpoint subsystem."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        from ..training.checkpoint import CheckpointManager

        self.mgr = CheckpointManager(directory, max_to_keep=max_to_keep)

    def on_epoch_end(self, epoch: int, logs):
        self.mgr.save(epoch, self.model.core)
