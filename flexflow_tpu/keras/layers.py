"""Keras-style layer classes.

TPU-native re-design of the reference's Keras frontend layer set
(python/flexflow/keras/layers/: core.py Dense/Flatten/Dropout/Activation/
Embedding, convolutional.py Conv2D, pool.py MaxPooling2D/AveragePooling2D,
merge.py Add/Subtract/Multiply/Concatenate, normalization.py
BatchNormalization).  Layers are symbolic: calling one on a KTensor records
a node; ``build_on`` replays it onto the core :class:`~flexflow_tpu.Model`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..fftype import ActiMode, DataType, PoolType

_ACTIVATIONS = {
    None: ActiMode.NONE, "linear": ActiMode.NONE, "relu": ActiMode.RELU,
    "sigmoid": ActiMode.SIGMOID, "tanh": ActiMode.TANH, "gelu": ActiMode.GELU,
    "softmax": "softmax",
}


@dataclasses.dataclass
class KTensor:
    """Symbolic tensor in the Keras graph (reference keras/models/tensor.py)."""

    layer: Optional["KerasLayer"]
    idx: int
    shape: Tuple[Optional[int], ...]   # batch dim is None
    dtype: DataType = DataType.FLOAT
    name: str = ""


class KerasLayer:
    _count = 0

    def __init__(self, name: Optional[str] = None, **kw):
        KerasLayer._count += 1
        self.name = name or f"{type(self).__name__.lower()}_{KerasLayer._count}"
        # keras-style Dense(..., input_shape=(16,)) on the first layer
        self.input_shape = kw.get("input_shape")
        self.inbound: List[KTensor] = []
        self.output: Optional[KTensor] = None

    def __call__(self, inputs):
        if isinstance(inputs, KTensor):
            inputs = [inputs]
        self.inbound = list(inputs)
        self.output = KTensor(self, 0, self.compute_output_shape(
            [t.shape for t in inputs]), inputs[0].dtype, name=self.name)
        return self.output

    # subclass API ----------------------------------------------------------
    def compute_output_shape(self, in_shapes):
        return in_shapes[0]

    def build_on(self, model, inputs):
        raise NotImplementedError


def Input(shape: Sequence[int], dtype: DataType = DataType.FLOAT,
          name: Optional[str] = None) -> KTensor:
    """Functional-API input (reference keras/models/input_layer.py)."""
    KerasLayer._count += 1
    return KTensor(None, 0, (None,) + tuple(shape), dtype,
                   name=name or f"input_{KerasLayer._count}")


def _maybe_activation(model, t, activation):
    if not isinstance(activation, ActiMode):
        if activation not in _ACTIVATIONS:
            raise KeyError(
                f"unknown activation {activation!r}; supported: "
                f"{sorted(k for k in _ACTIVATIONS if isinstance(k, str))}")
        act = _ACTIVATIONS[activation]
    else:
        act = activation
    if act == "softmax":
        return model.softmax(t)
    return t if act in (ActiMode.NONE,) else {
        ActiMode.RELU: model.relu, ActiMode.SIGMOID: model.sigmoid,
        ActiMode.TANH: model.tanh, ActiMode.GELU: model.gelu}[act](t)


class Dense(KerasLayer):
    def __init__(self, units: int, activation=None, use_bias: bool = True,
                 kernel_regularizer=None, name: Optional[str] = None, **_):
        super().__init__(name, **_)
        self.units, self.activation, self.use_bias = units, activation, use_bias
        self.kernel_regularizer = kernel_regularizer

    def compute_output_shape(self, in_shapes):
        return in_shapes[0][:-1] + (self.units,)

    def build_on(self, model, inputs):
        t = model.dense(inputs[0], self.units, use_bias=self.use_bias,
                        name=model._unique_name("linear", None))
        return _maybe_activation(model, t, self.activation)


class Activation(KerasLayer):
    def __init__(self, activation, name: Optional[str] = None):
        super().__init__(name)
        self.activation = activation

    def build_on(self, model, inputs):
        return _maybe_activation(model, inputs[0], self.activation)


class Flatten(KerasLayer):
    def compute_output_shape(self, in_shapes):
        n = 1
        for s in in_shapes[0][1:]:
            n *= s
        return (in_shapes[0][0], n)

    def build_on(self, model, inputs):
        return model.flat(inputs[0])


class Dropout(KerasLayer):
    def __init__(self, rate: float, name: Optional[str] = None, **_):
        super().__init__(name, **_)
        self.rate = rate

    def build_on(self, model, inputs):
        return model.dropout(inputs[0], rate=self.rate)


class Embedding(KerasLayer):
    def __init__(self, input_dim: int, output_dim: int,
                 name: Optional[str] = None, **_):
        super().__init__(name, **_)
        self.input_dim, self.output_dim = input_dim, output_dim

    def compute_output_shape(self, in_shapes):
        return in_shapes[0] + (self.output_dim,)

    def build_on(self, model, inputs):
        return model.embedding(inputs[0], self.input_dim, self.output_dim)


class Conv2D(KerasLayer):
    """NCHW like the reference's keras frontend (channels_first)."""

    def __init__(self, filters: int, kernel_size, strides=(1, 1),
                 padding="valid", activation=None, use_bias: bool = True,
                 groups: int = 1, kernel_regularizer=None,
                 name: Optional[str] = None, **_):
        super().__init__(name, **_)
        self.kernel_regularizer = kernel_regularizer
        self.filters = filters
        self.kernel = (kernel_size, kernel_size) if isinstance(
            kernel_size, int) else tuple(kernel_size)
        self.strides = (strides, strides) if isinstance(strides, int) \
            else tuple(strides)
        self.padding = padding
        self.activation = activation
        self.use_bias = use_bias
        self.groups = groups

    def _pads(self):
        if self.padding == "same":
            return self.kernel[0] // 2, self.kernel[1] // 2
        return 0, 0

    def compute_output_shape(self, in_shapes):
        b, c, h, w = in_shapes[0]
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.kernel[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.kernel[1]) // self.strides[1] + 1
        return (b, self.filters, oh, ow)

    def build_on(self, model, inputs):
        ph, pw = self._pads()
        t = model.conv2d(inputs[0], self.filters, *self.kernel,
                         *self.strides, ph, pw, groups=self.groups,
                         use_bias=self.use_bias)
        return _maybe_activation(model, t, self.activation)


class _Pool2D(KerasLayer):
    pool_type = PoolType.MAX

    def __init__(self, pool_size=(2, 2), strides=None, padding="valid",
                 name: Optional[str] = None, **_):
        super().__init__(name, **_)
        self.pool = (pool_size, pool_size) if isinstance(pool_size, int) \
            else tuple(pool_size)
        strides = strides or self.pool
        self.strides = (strides, strides) if isinstance(strides, int) \
            else tuple(strides)
        self.padding = padding

    def _pads(self):
        if self.padding == "same":
            return self.pool[0] // 2, self.pool[1] // 2
        return 0, 0

    def compute_output_shape(self, in_shapes):
        b, c, h, w = in_shapes[0]
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.pool[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.pool[1]) // self.strides[1] + 1
        return (b, c, oh, ow)

    def build_on(self, model, inputs):
        ph, pw = self._pads()
        return model.pool2d(inputs[0], *self.pool, *self.strides, ph, pw,
                            pool_type=self.pool_type)


class MaxPooling2D(_Pool2D):
    pool_type = PoolType.MAX


class AveragePooling2D(_Pool2D):
    pool_type = PoolType.AVG


class BatchNormalization(KerasLayer):
    def __init__(self, name: Optional[str] = None, **_):
        super().__init__(name, **_)

    def build_on(self, model, inputs):
        return model.batch_norm(inputs[0], relu=False)


class LayerNormalization(KerasLayer):
    def __init__(self, epsilon: float = 1e-3, name: Optional[str] = None,
                 **_):
        super().__init__(name, **_)
        self.epsilon = epsilon

    def build_on(self, model, inputs):
        return model.layer_norm(inputs[0], eps=self.epsilon)


class _Merge(KerasLayer):
    def compute_output_shape(self, in_shapes):
        return in_shapes[0]


class Add(_Merge):
    def build_on(self, model, inputs):
        return model.add(inputs[0], inputs[1])


class Subtract(_Merge):
    def build_on(self, model, inputs):
        return model.subtract(inputs[0], inputs[1])


class Multiply(_Merge):
    def build_on(self, model, inputs):
        return model.multiply(inputs[0], inputs[1])


class Maximum(_Merge):
    def build_on(self, model, inputs):
        return model.max(inputs[0], inputs[1])


class Minimum(_Merge):
    def build_on(self, model, inputs):
        return model.min(inputs[0], inputs[1])


class Reshape(KerasLayer):
    """reference: keras/layers/core.py Reshape — target_shape excludes the
    batch dim."""

    def __init__(self, target_shape, name: Optional[str] = None):
        super().__init__(name)
        self.target_shape = tuple(int(d) for d in target_shape)
        if sum(1 for d in self.target_shape if d == -1) > 1:
            raise ValueError(
                f"Reshape target_shape {self.target_shape} has more than "
                "one -1")

    def _resolve(self, in_shape):
        shape = self.target_shape
        if -1 not in shape:
            return shape
        total = int(np.prod(in_shape[1:]))
        known = int(np.prod([d for d in shape if d != -1]))
        if known == 0 or total % known:
            raise ValueError(
                f"Reshape target_shape {shape} incompatible with input "
                f"shape {tuple(in_shape)}")
        return tuple(total // known if d == -1 else d for d in shape)

    def compute_output_shape(self, in_shapes):
        # batch may be symbolic here, so -1 must resolve against the
        # non-batch dims locally; the core RESHAPE op re-resolves (and
        # re-validates) at build time
        return (in_shapes[0][0],) + self._resolve(in_shapes[0])

    def build_on(self, model, inputs):
        batch = inputs[0].spec.shape[0]
        return model.reshape(inputs[0], (batch,) + self.target_shape)


class Permute(KerasLayer):
    """reference: keras/layers/core.py Permute — dims are 1-indexed over
    the non-batch axes (the keras convention)."""

    def __init__(self, dims, name: Optional[str] = None):
        super().__init__(name)
        self.dims = tuple(int(d) for d in dims)

    def compute_output_shape(self, in_shapes):
        s = in_shapes[0]
        return (s[0],) + tuple(s[d] for d in self.dims)

    def build_on(self, model, inputs):
        return model.transpose(inputs[0], (0,) + self.dims)


class Concatenate(KerasLayer):
    def __init__(self, axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.axis = axis

    def compute_output_shape(self, in_shapes):
        ax = self.axis if self.axis >= 0 else len(in_shapes[0]) + self.axis
        out = list(in_shapes[0])
        out[ax] = sum(s[ax] for s in in_shapes)
        return tuple(out)

    def build_on(self, model, inputs):
        return model.concat(inputs, axis=self.axis)
