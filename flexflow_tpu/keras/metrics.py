"""Keras metric objects (reference: python/flexflow/keras/metrics.py —
class wrappers resolving to MetricsType enums)."""

from __future__ import annotations

from ..fftype import MetricsType


class Metric:
    type: MetricsType = None

    def __init__(self, name: str = "metric"):
        self.name = name


class Accuracy(Metric):
    type = MetricsType.ACCURACY

    def __init__(self, name: str = "accuracy"):
        super().__init__(name)


class CategoricalCrossentropy(Metric):
    type = MetricsType.CATEGORICAL_CROSSENTROPY

    def __init__(self, name: str = "categorical_crossentropy"):
        super().__init__(name)


class SparseCategoricalCrossentropy(Metric):
    type = MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY

    def __init__(self, name: str = "sparse_categorical_crossentropy"):
        super().__init__(name)


class MeanSquaredError(Metric):
    type = MetricsType.MEAN_SQUARED_ERROR

    def __init__(self, name: str = "mean_squared_error"):
        super().__init__(name)


class RootMeanSquaredError(Metric):
    type = MetricsType.ROOT_MEAN_SQUARED_ERROR

    def __init__(self, name: str = "root_mean_squared_error"):
        super().__init__(name)


class MeanAbsoluteError(Metric):
    type = MetricsType.MEAN_ABSOLUTE_ERROR

    def __init__(self, name: str = "mean_absolute_error"):
        super().__init__(name)
