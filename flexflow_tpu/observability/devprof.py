"""Device profiling plane: compiled-record cost reports, sampled
per-dispatch device timing, and cost-model drift/calibration.

Everything the serving stack measured before this module was HOST time
(step latencies, TTFT/TPOT, queue waits).  Every pricing decision the
stack makes — paged restore-vs-recompute, disaggregated
migrate-vs-recompute, the hybrid rider budget, the Unity-style search —
trusts ``SimpleMachineModel``'s hand-set ``hbm_bandwidth`` /
``peak_flops`` / link constants unvalidated.  The reference closes the
same loop with ``Simulator::measure_operator_cost`` (measured per-op
costs feed the search); this module is the serving-side equivalent:

- :class:`CompileReport` — at every step-compile site in
  ``inference_manager.py`` the jitted program is built ahead-of-time
  (``jit(...).lower(args).compile()`` — the SAME single XLA compile the
  lazy jit path would pay on first call) and the executable's
  ``cost_analysis()`` + ``memory_analysis()`` are harvested: XLA's own
  FLOP count, HBM bytes accessed and argument/output/temp footprints
  per compiled record, registered beside the record and exposed as
  ``serving_compiled_*`` gauges.
- :class:`DispatchProfiler` — sampled per-dispatch DEVICE timing:
  every ``FF_DEVPROF_SAMPLE``-th dispatch per (phase, path) does a
  timed ``jax.block_until_ready`` on the dispatch result (ticked
  through the existing ``note_host_sync`` discipline at sites where
  the block adds a sync the driver would not otherwise pay).  Off by
  default (``FF_DEVPROF_SAMPLE=0``): the hot path costs two attribute
  reads; a no-op under ``FF_TELEMETRY=0`` either way.
- **Drift + calibration** — each sample lands a
  ``serving_costmodel_drift_ratio{phase,path}`` gauge
  (cost-model-predicted / measured, from the record's CompileReport
  roofline under the active machine model) plus per-bound roofline
  attainment, and :func:`calibrate_machine_profile` fits ``hbm_bw``,
  flop rate, host-link and device-link bandwidths from the sample ring
  into a machine-profile JSON (``tools/ffprof.py --calibrate``) that
  ``MachineModel.from_json`` / ``search.cost_model.default_machine``
  (env ``FF_MACHINE_PROFILE``) feed back into ``RecoveryPolicy`` and
  the search cost model.

See docs/OBSERVABILITY.md "Device profiling & cost-model calibration".
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: bounded sample ring (FF_DEVPROF_RING overrides)
DEFAULT_RING = 512

#: phase vocabulary the dispatch sites emit — used by the calibration
#: fit to decide which roofline bound a phase's samples pin down.
BANDWIDTH_PHASES = ("decode", "hybrid")          # weight-stream bound
FLOP_PHASES = ("prefill", "spec_verify", "spec_draft")
HOST_LINK_PHASES = ("spill", "restore")          # host<->device payloads
DEVICE_LINK_PHASES = ("migrate",)                # slice-to-slice payloads


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class CompileReport:
    """XLA's own cost/memory analysis of ONE compiled serving step
    (``jax.stages.Compiled.cost_analysis()`` / ``memory_analysis()``):
    FLOPs, HBM bytes accessed, and the argument/output/temp byte
    footprints.  The roofline these numbers induce under a
    :class:`~flexflow_tpu.search.cost_model.MachineModel` is what the
    drift gauges compare measured device time against."""

    __slots__ = ("key", "model", "flops", "bytes_accessed",
                 "argument_bytes", "output_bytes", "temp_bytes",
                 "generated_code_bytes")

    def __init__(self, key: str, model: Any = None, flops: float = 0.0,
                 bytes_accessed: float = 0.0, argument_bytes: int = 0,
                 output_bytes: int = 0, temp_bytes: int = 0,
                 generated_code_bytes: int = 0):
        self.key = str(key)
        self.model = model
        self.flops = float(flops)
        self.bytes_accessed = float(bytes_accessed)
        self.argument_bytes = int(argument_bytes)
        self.output_bytes = int(output_bytes)
        self.temp_bytes = int(temp_bytes)
        self.generated_code_bytes = int(generated_code_bytes)

    @property
    def peak_bytes(self) -> int:
        """Peak HBM the executable needs live at once (arguments +
        outputs + XLA temp allocations; donated caches alias, so this
        over-counts by the aliased bytes — a conservative bound)."""
        return self.argument_bytes + self.output_bytes + self.temp_bytes

    # ------------------------------------------------------------ roofline
    def t_flops(self, machine) -> float:
        """Compute-bound floor under ``machine`` (seconds)."""
        return self.flops / machine.peak_flops if self.flops > 0 else 0.0

    def t_mem(self, machine) -> float:
        """Bandwidth-bound floor under ``machine`` (seconds)."""
        return (self.bytes_accessed / machine.hbm_bandwidth
                if self.bytes_accessed > 0 else 0.0)

    def predicted_s(self, machine) -> float:
        """The cost model's step-time prediction: the roofline max of
        the two bounds (the same shape as
        ``search.cost_model.estimate_op_cost``)."""
        return max(self.t_flops(machine), self.t_mem(machine))

    # --------------------------------------------------------- serialization
    def as_dict(self) -> Dict[str, Any]:
        return {"key": self.key, "model": self.model,
                "flops": self.flops,
                "bytes_accessed": self.bytes_accessed,
                "argument_bytes": self.argument_bytes,
                "output_bytes": self.output_bytes,
                "temp_bytes": self.temp_bytes,
                "peak_bytes": self.peak_bytes,
                "generated_code_bytes": self.generated_code_bytes}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CompileReport":
        return cls(key=d.get("key", "?"), model=d.get("model"),
                   flops=d.get("flops", 0.0),
                   bytes_accessed=d.get("bytes_accessed", 0.0),
                   argument_bytes=d.get("argument_bytes", 0),
                   output_bytes=d.get("output_bytes", 0),
                   temp_bytes=d.get("temp_bytes", 0),
                   generated_code_bytes=d.get("generated_code_bytes", 0))


def step_key_str(key) -> str:
    """Canonical compact spelling of a record's step-cache key tuple
    (the ``step`` label of the ``serving_compiled_*`` gauges)."""
    if isinstance(key, (tuple, list)):
        return ":".join("_" if k is None else str(k) for k in key)
    return str(key)


def harvest_compile_report(compiled, key, model: Any = None
                           ) -> Optional[CompileReport]:
    """Extract a :class:`CompileReport` from a ``jax.stages.Compiled``.
    Best-effort and backend-tolerant: ``cost_analysis`` returns a list
    of per-computation dicts on some backends and a dict on others, and
    either analysis may be unimplemented — returns None rather than
    raising (the compile site falls back to report-less serving)."""
    flops = bytes_accessed = 0.0
    have = False
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            flops = float(ca.get("flops", 0.0) or 0.0)
            bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)
            have = True
    except Exception:
        pass
    arg = out = temp = code = 0
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            arg = int(getattr(ma, "argument_size_in_bytes", 0) or 0)
            out = int(getattr(ma, "output_size_in_bytes", 0) or 0)
            temp = int(getattr(ma, "temp_size_in_bytes", 0) or 0)
            code = int(getattr(ma, "generated_code_size_in_bytes", 0)
                       or 0)
            have = True
    except Exception:
        pass
    if not have:
        return None
    return CompileReport(step_key_str(key), model=model, flops=flops,
                         bytes_accessed=bytes_accessed,
                         argument_bytes=arg, output_bytes=out,
                         temp_bytes=temp, generated_code_bytes=code)


class _Sample:
    """An in-flight sampled dispatch (begin() token)."""

    __slots__ = ("phase", "path", "t0")

    def __init__(self, phase: str, path: str, t0: float):
        self.phase = phase
        self.path = path
        self.t0 = t0


class DispatchProfiler:
    """Sampled per-dispatch device timing + compile-report registry.

    ``begin(phase, path)`` returns None on unsampled dispatches (the
    hot-path cost: two attribute reads when sampling is off, one lock'd
    counter bump when on); every ``sample_every``-th dispatch per
    (phase, path) returns a token whose ``end()`` does the timed
    ``jax.block_until_ready`` and lands the histogram/drift gauges.
    Thread-safe (RLock — snapshots ride watchdog signal-path bundles).
    """

    def __init__(self, registry=None, sample_every: Optional[int] = None,
                 ring: Optional[int] = None, machine=None):
        if registry is None:
            from . import get_registry

            registry = get_registry()
        self._registry = registry
        if sample_every is None:
            sample_every = (0 if os.environ.get("FF_DEVPROF", "1") == "0"
                            else _env_int("FF_DEVPROF_SAMPLE", 0))
        # plain (unlocked) attribute: read on EVERY dispatch — keeping
        # it out of the guarded set means the hot path never takes the
        # lock while sampling is off (writes are single attr stores)
        self._sample_every = max(0, int(sample_every))
        self._machine = machine
        self._lock = threading.RLock()
        self._counts: Dict[tuple, int] = {}
        self._samples: deque = deque(
            maxlen=max(16, ring or _env_int("FF_DEVPROF_RING",
                                            DEFAULT_RING)))
        self._reports: Dict[str, CompileReport] = {}
        m = registry
        self._h_seconds = m.histogram("serving_devprof_device_seconds")
        self._c_samples = m.counter("serving_devprof_samples_total")
        self._g_attain = m.gauge("serving_devprof_roofline_attainment")
        self._g_drift = m.gauge("serving_costmodel_drift_ratio")
        self._g_flops = m.gauge("serving_compiled_flops")
        self._g_bytes = m.gauge("serving_compiled_bytes_accessed")
        self._g_peak = m.gauge("serving_compiled_peak_bytes")

    # -------------------------------------------------------------- control
    @property
    def sample_every(self) -> int:
        return self._sample_every

    def set_sample_every(self, n: int) -> None:
        """Runtime sampling-cadence override (0 disables; benches and
        tests use this instead of re-importing with the env set)."""
        self._sample_every = max(0, int(n))

    def set_machine(self, machine) -> None:
        """Pin the machine model drift compares against (tests; the
        default is ``search.cost_model.default_machine()``, which honors
        a calibrated FF_MACHINE_PROFILE)."""
        self._machine = machine

    def machine(self):
        if self._machine is None:
            from ..search.cost_model import default_machine

            self._machine = default_machine()
        return self._machine

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._samples.clear()
            self._reports.clear()

    # ------------------------------------------------------ compile reports
    def register_report(self, report: CompileReport) -> None:
        """Register one record's CompileReport (the compile sites in
        inference_manager call this once per step variant) and expose
        the ``serving_compiled_*`` gauges."""
        rkey = f"{report.model}/{report.key}"
        with self._lock:
            self._reports[rkey] = report
        labels = {"model": report.model, "step": report.key}
        self._g_flops.set(report.flops, **labels)
        self._g_bytes.set(report.bytes_accessed, **labels)
        self._g_peak.set(report.peak_bytes, **labels)
        if self._registry.enabled:
            from .flight_recorder import get_flight_recorder

            get_flight_recorder().record_event(
                "compile-report", model=report.model, key=report.key,
                flops=report.flops, bytes=report.bytes_accessed)

    def reports(self) -> Dict[str, CompileReport]:
        with self._lock:
            return dict(self._reports)

    # ------------------------------------------------------------- sampling
    def begin(self, phase: str, path: str = "dense"
              ) -> Optional[_Sample]:
        """Nth-dispatch sampling gate.  None (the overwhelmingly common
        case) means: dispatch normally, no timing."""
        if self._sample_every <= 0 or not self._registry.enabled:
            return None
        with self._lock:
            n = self._counts.get((phase, path), 0) + 1
            self._counts[(phase, path)] = n
        if n % self._sample_every:
            return None
        return _Sample(phase, path, time.perf_counter())

    def end(self, sample: _Sample, result=None, im=None, report=None,
            payload_bytes: int = 0, tokens: int = 0,
            machine=None) -> float:
        """Finish a sampled dispatch: block until ``result`` is ready
        on device, stamp the elapsed device-inclusive wall time, and
        land the histogram + drift gauges.  The block is one genuine
        extra synchronization point per sample; sites that block pass
        ``im`` (an InferenceManager) so it ticks ``note_host_sync`` —
        uniformly, since a caller's subsequent materialization (where
        one follows) is a *second* real round trip with its own tick.
        Transfer sites whose payload already materialized (spill
        fetches) pass neither ``result`` nor ``im``."""
        if result is not None:
            import jax

            jax.block_until_ready(result)
        dt = time.perf_counter() - sample.t0
        if im is not None:
            im.note_host_sync()
        self.observe(sample.phase, sample.path, dt, report=report,
                     payload_bytes=payload_bytes, tokens=tokens,
                     machine=machine)
        return dt

    def observe(self, phase: str, path: str, seconds: float,
                report: Optional[CompileReport] = None,
                payload_bytes: int = 0, tokens: int = 0,
                machine=None) -> None:
        """Land one device-time observation (the ``end()`` tail; the
        disaggregated migrator feeds its already-timed transfers here
        directly).  Gated on the sampling knob like ``begin()`` —
        ``FF_DEVPROF_SAMPLE=0`` means the whole plane is off, external
        feeds included."""
        if self._sample_every <= 0 or not self._registry.enabled:
            return
        seconds = float(seconds)
        self._h_seconds.observe(seconds, phase=phase, path=path)
        self._c_samples.inc(phase=phase, path=path)
        entry: Dict[str, Any] = {"phase": phase, "path": path,
                                 "seconds": round(seconds, 9)}
        if payload_bytes:
            entry["payload_bytes"] = int(payload_bytes)
        if tokens:
            entry["tokens"] = int(tokens)
        if report is not None and seconds > 0:
            m = machine or self.machine()
            t_mem, t_fl = report.t_mem(m), report.t_flops(m)
            entry.update(key=report.key, model=report.model,
                         flops=report.flops,
                         bytes_accessed=report.bytes_accessed,
                         predicted_s=round(max(t_mem, t_fl), 9))
            self._g_attain.set(t_mem / seconds, phase=phase, path=path,
                               bound="mem")
            self._g_attain.set(t_fl / seconds, phase=phase, path=path,
                               bound="flops")
            drift = max(t_mem, t_fl) / seconds
            entry["drift"] = round(drift, 6)
            self._g_drift.set(drift, phase=phase, path=path)
        from .flight_recorder import get_flight_recorder

        get_flight_recorder().record_event(
            "devprof-sample", phase=phase, path=path,
            seconds=round(seconds, 9))
        with self._lock:
            self._samples.append(entry)

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable state: the sample ring, the compile-report
        registry and the per-(phase, path) dispatch counts — embedded
        in watchdog bundles and bench round records, rendered by
        tools/ffprof.py."""
        with self._lock:
            return {
                "sample_every": self._sample_every,
                "counts": {f"{p}/{pa}": n
                           for (p, pa), n in sorted(self._counts.items())},
                "samples": list(self._samples),
                "reports": {k: r.as_dict()
                            for k, r in sorted(self._reports.items())},
            }


# ------------------------------------------------------------ drift table
def drift_table(snapshot: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Per-(phase, path) measured-vs-predicted summary from a devprof
    snapshot's sample ring: sample count, median measured seconds,
    median predicted seconds (when the samples carried a CompileReport
    roofline) and the drift ratio predicted/measured.  The table bench
    rounds stamp beside their metrics and ``ffprof`` renders."""
    groups: Dict[tuple, List[Dict[str, Any]]] = {}
    for s in snapshot.get("samples") or []:
        groups.setdefault((s.get("phase", "?"), s.get("path", "?")),
                          []).append(s)
    rows = []
    for (phase, path), ss in sorted(groups.items()):
        meas = sorted(s["seconds"] for s in ss)
        row: Dict[str, Any] = {"phase": phase, "path": path,
                               "samples": len(ss),
                               "measured_s_p50": _median(meas)}
        preds = sorted(s["predicted_s"] for s in ss
                       if s.get("predicted_s"))
        if preds and row["measured_s_p50"] > 0:
            row["predicted_s_p50"] = _median(preds)
            row["drift_ratio"] = round(
                row["predicted_s_p50"] / row["measured_s_p50"], 6)
        rows.append(row)
    return rows


def _median(xs: List[float]) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    n = len(xs)
    mid = n // 2
    return round(xs[mid] if n % 2 else (xs[mid - 1] + xs[mid]) / 2.0, 9)


# -------------------------------------------------------------- calibration
def calibrate_machine_profile(snapshot: Dict[str, Any],
                              num_devices: int = 1) -> Dict[str, Any]:
    """Fit a machine-profile dict from a devprof snapshot's sample ring.

    Each phase class pins the bound its dispatches are limited by:

    - BANDWIDTH_PHASES (decode, hybrid): the step streams the weights
      (+ attended KV) from HBM — implied ``hbm_bw = bytes_accessed /
      seconds`` per sample (XLA's own byte count over measured time).
    - FLOP_PHASES (prefill, spec verify/draft): chunk-wide passes are
      compute-bound — implied ``flop rate = flops / seconds``.
    - HOST_LINK_PHASES (spill, restore): ``payload_bytes / seconds``
      prices the host link (the RecoveryPolicy restore arm).
    - DEVICE_LINK_PHASES (migrate): ``payload_bytes / seconds`` prices
      the slice-to-slice device link (the disagg migrate arm).

    Medians, not means — a cold first sample (compile, page fault) must
    not drag the fit.  Keys follow EnhancedMachineModel's config
    vocabulary so :meth:`MachineModel.from_json` loads the result
    directly; phases with no samples leave their key absent (the loader
    keeps its defaults).  The fit is an *effective* rate — it folds
    dispatch overhead into the bandwidth term, which is exactly what a
    pricing model for THIS serving stack should use."""
    samples = snapshot.get("samples") or []

    def rates(phases: tuple, num: str, den_floor: float = 0.0):
        out = []
        for s in samples:
            if s.get("phase") not in phases:
                continue
            n, d = float(s.get(num, 0) or 0), float(s.get("seconds", 0))
            if n > den_floor and d > 0:
                out.append(n / d)
        return out

    prof: Dict[str, Any] = {"profile_version": 1,
                            "source": "devprof-calibrate",
                            "num_devices": int(num_devices)}
    counts: Dict[str, int] = {}
    hbm = rates(BANDWIDTH_PHASES, "bytes_accessed")
    if hbm:
        prof["hbm_gbps"] = round(_median(hbm) / 1e9, 6)
        counts["hbm"] = len(hbm)
    flop = rates(FLOP_PHASES, "flops")
    if flop:
        prof["peak_tflops"] = round(_median(flop) / 1e12, 9)
        counts["flops"] = len(flop)
    host = rates(HOST_LINK_PHASES, "payload_bytes")
    if host:
        prof["dcn_gbps"] = round(_median(host) / 1e9, 6)
        counts["host_link"] = len(host)
    link = rates(DEVICE_LINK_PHASES, "payload_bytes")
    if link:
        prof["device_link_gbps"] = round(_median(link) / 1e9, 6)
        counts["device_link"] = len(link)
    prof["sample_counts"] = counts
    return prof


# ---------------------------------------------------------------- singleton
_DEVPROF: Optional[DispatchProfiler] = None
_DEVPROF_LOCK = threading.Lock()


def get_devprof() -> DispatchProfiler:
    """The process-wide dispatch profiler (built lazily so the package
    registry exists first; env knobs FF_DEVPROF / FF_DEVPROF_SAMPLE /
    FF_DEVPROF_RING are read at first use)."""
    global _DEVPROF
    if _DEVPROF is None:
        with _DEVPROF_LOCK:
            if _DEVPROF is None:
                _DEVPROF = DispatchProfiler()
    return _DEVPROF
