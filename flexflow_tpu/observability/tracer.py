"""StepTracer: host-side structured step events as Chrome trace JSON.

The reference gets its serving timeline from NVTX ranges + Legion
``-lg:prof`` (SURVEY.md §5); the rebuild's equivalent is this host-side
event recorder.  Events use the Chrome Trace Event format (the JSON
Perfetto / chrome://tracing load natively): ``B``/``E`` begin-end pairs
for phases (prefill-chunk, decode-step, spec-draft, spec-verify) and
``i`` instants for points (admit, prefix-match, commit, donate, evict).

Host/XLA alignment: every span additionally enters a
``jax.profiler.TraceAnnotation`` so when a device trace is being
captured (``utils/profiling.trace`` / ``jax.profiler.trace``) the same
phase names appear on the XLA timeline — the host JSON and the XProf
capture line up by name.

Cost model: when no trace is active, ``span()`` returns a shared
null context manager and ``instant()`` returns immediately — one
attribute read per call site, nothing allocated (the telemetry-disabled
bench gate).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .schema import EVENT_SCHEMA

# The serving event taxonomy — one vocabulary with the FlightRecorder
# (schema.EVENT_SCHEMA holds the help text); the tracer's span/instant
# subset excludes the recorder-only events (host-sync, compile), which
# would flood an interactive trace.  Emitters stick to these names so
# tools/trace_summary.py's per-phase breakdown stays stable; args carry
# the variable detail (guid, row, chunk, tokens, ...).
EVENT_NAMES = tuple(n for n in EVENT_SCHEMA
                    if n not in ("host-sync", "compile"))

_NULL_CM = contextlib.nullcontext()


class _Span:
    """One B/E pair plus a jax.profiler.TraceAnnotation (host and XLA
    timelines share the phase name)."""

    __slots__ = ("_tr", "_name", "_args", "_ann")

    def __init__(self, tracer: "StepTracer", name: str, args: Dict):
        self._tr = tracer
        self._name = name
        self._args = args
        self._ann = None

    def __enter__(self):
        self._tr._emit("B", self._name, self._args)
        try:
            import jax

            self._ann = jax.profiler.TraceAnnotation(self._name)
            self._ann.__enter__()
        except Exception:   # jax absent / backend without annotations
            self._ann = None
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self._tr._emit("E", self._name, None)
        return False


class StepTracer:
    """Collects Chrome-trace events while active; inert otherwise."""

    def __init__(self):
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.active = False

    # -------------------------------------------------------------- control
    def start(self):
        with self._lock:
            self._events = []
            self._t0 = time.monotonic()
        self.active = True

    def stop(self):
        self.active = False

    @contextlib.contextmanager
    def trace(self, path: Optional[str] = None):
        """Collect events for the duration of the block; write the trace
        file on exit when ``path`` is given."""
        self.start()
        try:
            yield self
        finally:
            self.stop()
            if path:
                self.save(path)

    # -------------------------------------------------------------- events
    def _emit(self, ph: str, name: str, args: Optional[Dict]):
        ev = {"ph": ph, "name": name, "cat": "serving",
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        if ph == "i":
            ev["s"] = "t"   # thread-scoped instant
        with self._lock:
            # _t0 is rewritten by start(): BOTH the clock read and the
            # subtraction happen inside the lock so an event raced with
            # a restart lands wholly on one epoch — capturing the clock
            # before acquiring would pair an old-epoch reading with the
            # new _t0 (a negative ts in the fresh trace)
            ev["ts"] = round((time.monotonic() - self._t0) * 1e6, 1)
            self._events.append(ev)

    def span(self, name: str, **args):
        """Context manager for a phase; no-op (shared null CM, nothing
        allocated) when no trace is active."""
        if not self.active:
            return _NULL_CM
        return _Span(self, name, args)

    def instant(self, name: str, **args):
        if not self.active:
            return
        self._emit("i", name, args or None)

    def begin(self, name: str, **args):
        """Explicit B event — for phases spanning loop bodies where a
        ``with`` block would force re-indentation; pair with :meth:`end`
        (same thread, LIFO) or the trace will not nest."""
        if not self.active:
            return
        self._emit("B", name, args or None)

    def end(self, name: str):
        if not self.active:
            return
        self._emit("E", name, None)

    # ------------------------------------------------------------- output
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def to_chrome_trace(self) -> Dict[str, Any]:
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def save(self, path: str):
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
            f.write("\n")
