"""Fleet trace plane: wire-propagated trace context, cross-process
timeline assembly and metrics time-series history.

Every observability surface below this module stops at the process
boundary: the ledger's timelines, the flight-recorder ring and the
registry's gauges all describe ONE process.  The serving stack is now N
replica processes behind a router (serve/net/), so three cross-process
primitives live here:

- :class:`TraceContext` — the Dapper-style propagation unit.  A
  ``trace_id`` (random 128-bit hex, unique across processes by
  construction) plus a ``hop`` index (0 = the process that minted it;
  each forwarding hop sends ``child()`` downstream).  On the wire it is
  the ``X-FFServe-Trace: <trace_id>/<hop>`` header
  (serve/net/protocol.py); in-process it is stamped onto the request's
  ledger timeline (``trace_id``/``hop`` fields), so a request that
  crossed the router and failed over across two replicas leaves
  timelines in three processes sharing one join key.

- :class:`TraceAssembler` — merges ledger timelines from any number of
  sources (a router's own ledger, per-replica ``/v1/timelines``
  payloads, watchdog bundles, bench records) into ONE Chrome-trace /
  Perfetto file per trace_id.  Cross-process clock alignment uses each
  timeline's ``enqueue_wall``/``enqueue_mono`` anchor pair (the same
  trick the flight recorder uses for log correlation): every monotonic
  stamp converts to wall time through its own timeline's anchors, so
  sources never need synchronized monotonic clocks — just sane wall
  clocks, which same-fleet hosts have.  Span/instant names reuse the
  ledger/StepTracer event vocabulary (schema.EVENT_SCHEMA).

- :class:`MetricsHistory` — a bounded ring of registry snapshots
  sampled on an interval, answering "goodput over the last minute"
  instead of only "goodput now".  Near-zero cost when telemetry is
  disabled (one enabled check, nothing sampled), bounded memory always
  (deque ring + compact scalar samples), thread-safe behind an RLock
  (``snapshot()`` runs inside watchdog signal handlers — the bundle's
  ``metrics_history`` section).  The router keeps one per replica, fed
  from its /metrics scrapes, so load-score decisions are explainable
  from the retained series, not just the instantaneous scrape.

See docs/OBSERVABILITY.md "Distributed tracing & metrics history".
"""

from __future__ import annotations

import collections
import dataclasses
import os
import re
import threading
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = ["TraceContext", "MetricsHistory", "TraceAssembler",
           "scalar_values", "get_metrics_history"]


# -------------------------------------------------------- trace context
#: wire shape of one context: <trace_id>/<hop> (lowercase hex / int)
_TRACE_RE = re.compile(r"^([0-9a-f]{8,32})/(\d{1,4})$")


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One hop's view of a distributed trace.

    ``trace_id`` is shared by every hop of one request's journey;
    ``hop`` is this process's position in the forwarding chain (0 = the
    minter).  Immutable — forwarding downstream creates :meth:`child`.
    """

    trace_id: str
    hop: int = 0

    @classmethod
    def mint(cls) -> "TraceContext":
        """A fresh hop-0 context.  uuid4 (os.urandom) — unique across
        processes without coordination, which is the whole point: two
        replicas minting concurrently must never collide (pinned by
        tests/test_traceplane.py across real processes)."""
        return cls(trace_id=uuid.uuid4().hex, hop=0)

    @classmethod
    def parse(cls, value: str) -> "TraceContext":
        """Decode a wire header value; raises ``ValueError`` on
        anything but ``<hex>/<int>``."""
        m = _TRACE_RE.match(value.strip().lower())
        if not m:
            raise ValueError(
                f"bad trace context {value!r} (expected <hex-id>/<hop>)")
        return cls(trace_id=m.group(1), hop=int(m.group(2)))

    def child(self) -> "TraceContext":
        """The context to forward DOWNSTREAM: same trace, next hop."""
        return TraceContext(trace_id=self.trace_id, hop=self.hop + 1)

    def header_value(self) -> str:
        return f"{self.trace_id}/{self.hop}"


# ------------------------------------------------------ metrics history
def scalar_values(snapshot: Dict[str, Any]) -> Dict[str, float]:
    """Flatten a ``MetricsRegistry.snapshot()`` dict to one compact
    ``{name: float}`` sample: counters/gauges collapse label splits by
    summation (the same stance as the router's Prometheus scrape
    decoder), histograms contribute ``_count``/``_sum`` series.  This
    is the per-sample payload the history ring stores — a few hundred
    floats, not the full nested snapshot."""
    out: Dict[str, float] = {}
    for name, snap in (snapshot.get("counters") or {}).items():
        if isinstance(snap, dict):
            out[name] = float(snap.get("total", 0.0))
        else:
            out[name] = float(snap)
    for name, snap in (snapshot.get("gauges") or {}).items():
        if isinstance(snap, dict):
            out[name] = float(sum(snap.values()))
        else:
            out[name] = float(snap)
    for name, snap in (snapshot.get("histograms") or {}).items():
        if isinstance(snap, dict):
            out[name + "_count"] = float(snap.get("count", 0))
            out[name + "_sum"] = float(snap.get("sum", 0.0))
    return out


class MetricsHistory:
    """Bounded time-series ring of metric samples.

    Two feed paths share the ring:

    - :meth:`sample` — pull one sample from a live registry (the
      process-local sampler thread started by :meth:`start`);
    - :meth:`append` — push an externally-obtained value map (the
      router's per-replica retention, fed from /metrics scrapes).

    Each sample is ``{"wall": time.time(), "mono": time.monotonic(),
    "values": {name: float}}``.  Memory is bounded by the ring capacity
    no matter how long the process serves; ``dropped`` counts what fell
    off.  Disabled telemetry (``registry.enabled`` False) makes
    :meth:`sample` a no-op, so the sampler thread costs one attribute
    read per interval under ``FF_TELEMETRY=0``.
    """

    def __init__(self, capacity: int = 512,
                 interval_s: float = 1.0):
        self.capacity = max(2, int(capacity))
        self.interval_s = max(0.01, float(interval_s))
        # RLock, not Lock: snapshot() runs inside watchdog signal
        # handlers (the bundle's metrics_history tail) which can
        # interrupt a mid-append main thread — a plain Lock would
        # self-deadlock the dump (fflint lock-discipline)
        self._lock = threading.RLock()
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._seq = 0
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    # ---------------------------------------------------------------- feed
    def append(self, values: Dict[str, float],
               wall: Optional[float] = None) -> None:
        """Push one externally-sampled value map (already scalar)."""
        sample = {"wall": float(wall if wall is not None
                                else time.time()),
                  "mono": time.monotonic(),
                  "values": dict(values)}
        with self._lock:
            self._ring.append(sample)
            self._seq += 1

    def sample(self, registry=None) -> bool:
        """Pull one sample from ``registry`` (default: the process-wide
        one).  Returns False without touching the ring when telemetry
        is disabled — the near-zero-cost gate."""
        if registry is None:
            from . import get_registry

            registry = get_registry()
        if not registry.enabled:
            return False
        self.append(scalar_values(registry.snapshot()))
        return True

    # ------------------------------------------------------------- sampler
    def start(self, interval_s: Optional[float] = None) -> "MetricsHistory":
        """Start (idempotently) the background sampler thread against
        the process-wide registry."""
        if interval_s is not None:
            self.interval_s = max(0.01, float(interval_s))
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="ff-metrics-history",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.sample()
            except Exception:       # one bad sample must not kill the ring
                pass

    # ---------------------------------------------------------------- read
    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._seq - len(self._ring))

    def series(self, name: str) -> List[Tuple[float, float]]:
        """``[(wall, value), ...]`` for one metric across the ring —
        the plot-ready view ('goodput over the last minute')."""
        with self._lock:
            samples = list(self._ring)
        return [(s["wall"], s["values"][name]) for s in samples
                if name in s["values"]]

    def snapshot(self, tail: Optional[int] = None) -> Dict[str, Any]:
        """JSON-serializable dump (the ``/v1/metrics/history`` payload
        and the watchdog bundle's ``metrics_history`` section).
        ``tail`` keeps only the most recent N samples."""
        with self._lock:
            samples = list(self._ring)
            seq = self._seq
        # dropped = what the RING evicted, not what `tail` trimmed —
        # a tail-truncated dump of a never-full ring lost nothing
        dropped = max(0, seq - len(samples))
        if tail is not None:
            samples = samples[-max(0, int(tail)):]
        return {
            "capacity": self.capacity,
            "interval_s": self.interval_s,
            "recorded": seq,
            "dropped": dropped,
            "samples": samples,
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0


_HISTORY = MetricsHistory(
    capacity=int(os.environ.get("FF_HISTORY_SAMPLES", "512") or 512),
    interval_s=float(os.environ.get("FF_HISTORY_INTERVAL_S", "1.0")
                     or 1.0))


def get_metrics_history() -> MetricsHistory:
    """The process-wide metrics history ring (allocated always; the
    sampler thread only runs once something calls ``start()`` — the
    wire server and bench.py do)."""
    return _HISTORY


# ----------------------------------------------------- timeline assembly
def _wall_of(t: Dict[str, Any], mono: Optional[float]) -> Optional[float]:
    """Convert one monotonic stamp to wall time through the timeline's
    own ``enqueue_wall``/``enqueue_mono`` anchor pair; None when the
    stamp or the anchors are missing (hand-built timelines)."""
    if mono is None:
        return None
    w0, m0 = t.get("enqueue_wall"), t.get("enqueue_mono")
    if w0 is None or m0 is None:
        return None
    return float(w0) + (float(mono) - float(m0))


class TraceAssembler:
    """Merge ledger timelines from N sources into one Chrome trace.

    Each source is a labeled list of timeline dicts (the shape
    ``RequestLedger.snapshot()['live'|'retired']`` / ``/v1/timelines``
    carry).  ``build(trace_id)`` selects every timeline stamped with
    that trace_id, converts each to wall-clock-anchored Chrome-trace
    events (one ``pid`` per source, ``tid`` = the timeline's guid) and
    returns the Perfetto-loadable dict: lifecycle phases as ``X``
    complete spans (queue, ttft, stream), every ledger event as a
    thread-scoped instant under its schema name.
    """

    def __init__(self) -> None:
        self._sources: List[Tuple[str, List[Dict[str, Any]]]] = []

    def add_source(self, label: str,
                   timelines: Iterable[Dict[str, Any]]) -> int:
        """Register one source; returns how many of its timelines carry
        a trace_id (the mergeable subset)."""
        tls = [t for t in timelines if isinstance(t, dict)]
        self._sources.append((str(label), tls))
        return sum(1 for t in tls if t.get("trace_id"))

    def trace_ids(self) -> Dict[str, int]:
        """``{trace_id: timeline count}`` across every source — the
        menu ``fftrace`` prints when no --trace is given."""
        out: Dict[str, int] = {}
        for _, tls in self._sources:
            for t in tls:
                tid = t.get("trace_id")
                if tid:
                    out[tid] = out.get(tid, 0) + 1
        return out

    # ------------------------------------------------------------- build
    def build(self, trace_id: str) -> Dict[str, Any]:
        """One Chrome trace for ``trace_id``.  Raises ``ValueError``
        when no source holds a timeline with it."""
        picked: List[Tuple[int, str, Dict[str, Any]]] = []
        for pid, (label, tls) in enumerate(self._sources):
            for t in tls:
                if t.get("trace_id") == trace_id:
                    picked.append((pid, label, t))
        if not picked:
            raise ValueError(
                f"trace {trace_id!r} not found in any source "
                f"({[s[0] for s in self._sources]})")
        # global wall origin: earliest stamp across every picked
        # timeline, so ts is a small positive µs offset
        origins = [w for _, _, t in picked
                   for w in (_wall_of(t, t.get("enqueue_mono")),)
                   if w is not None]
        t0 = min(origins) if origins else 0.0
        events: List[Dict[str, Any]] = []
        seen_pids: Dict[int, str] = {}
        for pid, label, t in picked:
            hop = t.get("hop")
            if pid not in seen_pids:
                name = (f"{label} (hop {hop})" if hop is not None
                        else label)
                seen_pids[pid] = name
                events.append({"ph": "M", "name": "process_name",
                               "pid": pid, "tid": 0,
                               "args": {"name": name}})
            events.extend(self._timeline_events(pid, t, t0))
        events.sort(key=lambda e: e.get("ts", 0))
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "trace_id": trace_id,
                "sources": [seen_pids[p] for p in sorted(seen_pids)],
                "timelines": len(picked),
            },
        }

    @staticmethod
    def _timeline_events(pid: int, t: Dict[str, Any],
                         t0: float) -> List[Dict[str, Any]]:
        tid = int(t.get("guid") or 0)
        base = {"pid": pid, "tid": tid, "cat": "serving"}

        def ts_us(mono: Optional[float]) -> Optional[float]:
            w = _wall_of(t, mono)
            return None if w is None else round((w - t0) * 1e6, 1)

        out: List[Dict[str, Any]] = []
        # lifecycle phases as complete spans, from the timeline's
        # scalar stamps (never subject to per-request event-ring
        # eviction — same stance as ffreq.phases_of)
        enq, adm = t.get("enqueue_mono"), t.get("admit_mono")
        first, last = t.get("first_commit_mono"), t.get("last_commit_mono")
        spans = []
        if enq is not None and adm is not None:
            spans.append(("queue", enq, adm))
        if adm is not None and t.get("ttft_s") is not None:
            spans.append(("ttft", adm, adm + t["ttft_s"]))
        elif adm is not None and first is not None:
            spans.append(("ttft", adm, first))
        if first is not None and last is not None and last > first:
            spans.append(("stream", first, last))
        for name, lo, hi in spans:
            ts = ts_us(lo)
            if ts is None:
                continue
            out.append({**base, "ph": "X", "name": name, "ts": ts,
                        "dur": max(0.0, round((hi - lo) * 1e6, 1)),
                        "args": {"guid": t.get("guid"),
                                 "hop": t.get("hop")}})
        # every ledger event as a thread-scoped instant under its
        # schema name (the StepTracer vocabulary)
        for ev in t.get("events") or []:
            ts = ts_us(ev.get("t"))
            if ts is None:
                continue
            args = {k: v for k, v in ev.items() if k not in ("name", "t")}
            out.append({**base, "ph": "i", "s": "t",
                        "name": str(ev.get("name", "?")), "ts": ts,
                        "args": args})
        return out
