"""Central metric + event schema: everything the serving stack emits.

The registry validates metric names against ``METRICS_SCHEMA`` at
creation time and the fflint ``metric-schema`` rule validates the *call
sites* statically — a metric incremented anywhere in the serving stack
but missing here fails CI before it ships an undocumented name.  The
reference ships its observability vocabulary the same way: a fixed
``ProfileInfo`` struct (request_manager.h:244-250) and fixed
``--profiling`` timer names, not free-form strings.

``EVENT_SCHEMA`` plays the same role for the step-event vocabulary
shared by the StepTracer (Chrome-trace spans/instants) and the
FlightRecorder (always-on post-mortem ring): the recorder refuses
undeclared names at runtime and the fflint rule checks
``record_event(...)`` call sites.

Schema entry: name -> {"type": counter|gauge|histogram, "agg":
sum|max|last|histogram, "help": str, optional "buckets": tuple} —
histograms default to the registry's fixed exponential ladder when
"buckets" is absent.  "agg" declares how the fleet aggregator
(observability/fleet.py) merges the metric across replicas: counters
sum, histograms bucket-merge, and each gauge declares sum (additive
level — queue depths, free frames, goodput), max (identical-per-replica
value where max dedups — compiled-step cost reports) or last
(a ratio/level where neither sum nor max means anything fleet-wide —
attainment, drift; the fleet series keeps the cross-replica mean and
the per-replica values feed the outlier score instead).  The fflint
metric-schema rule errors on a registered metric whose declaration
lacks a valid "agg", so a new metric cannot ship unmergeable.
"""

from __future__ import annotations

# 0-1 ratio buckets (acceptance rates, occupancy): the exponential
# latency ladder would put every observation in two buckets.
RATIO_BUCKETS = tuple(i / 10 for i in range(1, 11))

# token-count buckets: pow2, matching the serving chunk ladder
TOKEN_BUCKETS = tuple(float(1 << i) for i in range(11))

METRICS_SCHEMA = {
    # ---------------------------------------------------- host round trips
    "serving_host_syncs_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Host<->device round trips (step results materialized to "
                "numpy).  The serving path's key overhead metric on a "
                "network-attached chip; mirrors the per-InferenceManager "
                "host_syncs odometer.",
    },
    # ------------------------------------------------------- kernel paths
    "serving_kernel_path_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Attention-kernel dispatch decisions, labeled "
                "phase=decode|prefill, path=flash|xla, "
                "reason=forced|path_gate|cost_model and cache=int4|int8|fp "
                "(the record's KV storage dtype, so multi-record "
                "processes — e.g. the bench kvdtype A/B — attribute "
                "fallbacks to an arm).  path=xla with reason=path_gate "
                "is the silent-fallback class the int8 16-chunk bug hid "
                "in (ROADMAP open item).",
    },
    # --------------------------------------------------- request lifecycle
    "serving_requests_admitted_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Requests admitted from the pending queue into batch rows.",
    },
    "serving_requests_retired_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Requests retired (EOS or length budget).",
    },
    "serving_tokens_generated_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Generated (non-prompt) tokens committed across requests.",
    },
    "serving_queue_depth": {
        "type": "gauge",
        "agg": "sum",
        "help": "Pending (not yet admitted) requests after the latest "
                "admission pass.",
    },
    "serving_active_requests": {
        "type": "gauge",
        "agg": "sum",
        "help": "Requests currently occupying batch rows.",
    },
    "serving_batch_occupancy": {
        "type": "gauge",
        "agg": "last",
        "help": "Active rows / max_requests_per_batch at the latest "
                "scheduled step (the continuous-batching fill factor).",
    },
    # ----------------------------------------------------------- latencies
    "serving_ttft_seconds": {
        "type": "histogram",
        "agg": "histogram",
        "help": "Host-observed time to first generated token per request "
                "(monotonic-clock deltas; observed at retirement).",
    },
    "serving_tpot_seconds": {
        "type": "histogram",
        "agg": "histogram",
        "help": "Time per output token after the first (decode-phase "
                "inter-token latency), per retired request.",
    },
    "serving_step_latency_seconds": {
        "type": "histogram",
        "agg": "histogram",
        "help": "Wall time of one driver-loop step (dispatch + any host "
                "sync).  A decode block counts as one step committing K "
                "tokens; see serving_step_tokens for the per-step yield.",
    },
    "serving_step_tokens": {
        "type": "histogram",
        "agg": "histogram",
        "help": "Tokens committed per driver-loop step, summed across "
                "batch rows (rows completing a prompt for single-step "
                "syncs, the folded block yield for fused decode blocks, "
                "all rows' accepted+bonus tokens per spec sync).",
        "buckets": TOKEN_BUCKETS,
    },
    "serving_prefill_chunk_tokens": {
        "type": "histogram",
        "agg": "histogram",
        "help": "Chunk sizes (tokens per row) of scheduled prefill steps.",
        "buckets": TOKEN_BUCKETS,
    },
    # ------------------------------------------------------- hybrid steps
    # (stall-free mixed batches: chunked prefill fused into decode
    # dispatches — request_manager._hybrid_batch / _dispatch_hybrid)
    "serving_hybrid_steps_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Mixed-batch (decode rows + prefilling rows) steps by "
                "dispatch mode: mode=hybrid (ONE fused dispatch — the "
                "full decode batch at the 1-token path plus a roofline-"
                "budgeted rider chunk of the prefilling rows) | "
                "separate (the legacy chunk-wide dispatch every row "
                "pays for — the BENCH_r03 TPOT-spike class).  An A/B's "
                "two arms are attributable from one snapshot.",
    },
    "serving_hybrid_rider_tokens": {
        "type": "histogram",
        "agg": "histogram",
        "help": "Prefill tokens riding each hybrid step (summed across "
                "rider rows; the roofline budget caps them so the "
                "decode rows' TPOT holds — "
                "search/cost_model.hybrid_rider_budget).",
        "buckets": TOKEN_BUCKETS,
    },
    # -------------------------------------------------------- speculation
    "serving_spec_draft_tokens_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Speculative tokens proposed by SSM drafts (profile "
                "speculated_tokens, summed at retirement).",
    },
    "serving_spec_accepted_tokens_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Speculated tokens accepted by tree verification "
                "(profile accepted_tokens, summed at retirement).",
    },
    "serving_spec_acceptance_rate": {
        "type": "histogram",
        "agg": "histogram",
        "help": "Per-request accepted/speculated ratio, observed at "
                "retirement (matches distill.measured_acceptance over "
                "the same requests).",
        "buckets": RATIO_BUCKETS,
    },
    "serving_spec_verify_tokens": {
        "type": "histogram",
        "agg": "histogram",
        "help": "Verify-batch tree sizes (tokens per row fed to the "
                "tree-verify step).",
        "buckets": TOKEN_BUCKETS,
    },
    # ------------------------------------------------------- prefix cache
    "serving_prefix_lookups_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Prefix-pool lookups at admission (PrefixCacheStats "
                "re-emission).",
    },
    "serving_prefix_hits_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Prefix-pool lookups that matched a usable pooled prefix.",
    },
    "serving_prefix_tokens_matched_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Prompt tokens served from the prefix pool (prefill "
                "skipped).",
    },
    "serving_prefix_tokens_prompt_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Total prompt tokens admitted while the prefix pool was "
                "on (denominator of tokens-saved).",
    },
    "serving_prefix_donations_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Retired rows donated to the prefix pool.",
    },
    "serving_prefix_donations_rejected_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Donations rejected (redundant prefix / pool full of "
                "referenced entries).",
    },
    "serving_prefix_evictions_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Pool entries evicted (LRU reclaim or supersede).",
    },
    # -------------------------------------------------------- KV cache
    "serving_kv_cache_bytes_resident": {
        "type": "gauge",
        "agg": "sum",
        "help": "HBM pinned by a compiled record's KV caches (K + V + "
                "scales at the padded allocation), labeled model=<id>.",
    },
    # ----------------------------------------------------- paged KV
    # (serving/kv_pager.py: block-granular page accounting + host-RAM
    # spill + preemptive scheduling over the dense cache rows)
    "serving_kv_pages_total": {
        "type": "gauge",
        "agg": "sum",
        "help": "Page budget of the KV pager (pages of page_len "
                "committed-KV positions the scheduler may lease "
                "across rows + resident prefix-pool entries).",
    },
    "serving_kv_pages_free": {
        "type": "gauge",
        "agg": "sum",
        "help": "Unleased pages in the KV pager's budget (clamped at "
                "0 while forced decode-block growth overcommits; the "
                "overage is trued up by preemption at the next fold "
                "boundary and visible in the pager snapshot).",
    },
    "serving_kv_spill_bytes_total": {
        "type": "counter",
        "agg": "sum",
        "help": "KV bytes fetched device->host by preemption spills "
                "and prefix-pool page spills (bucketed transfers "
                "outside the jitted steps; int8 caches spill at ~half "
                "the bf16 byte cost).",
    },
    "serving_kv_restore_bytes_total": {
        "type": "counter",
        "agg": "sum",
        "help": "KV bytes restored host->device at re-admission "
                "(device_put + the jitted donated row write, "
                "InferenceManager.restore_row).",
    },
    "serving_kv_frames_total": {
        "type": "gauge",
        "agg": "sum",
        "help": "Physical frames in a paged record's global KV frame "
                "pool ([num_frames, KV, page_len, D] per layer; the "
                "page tables index this axis).  Set by a KVPager "
                "constructed with num_frames — HBM residency is "
                "leased frames x frame bytes, not rows x max_seq.",
    },
    "serving_kv_frames_free": {
        "type": "gauge",
        "agg": "sum",
        "help": "Frames on the physical pager's free list (distinct "
                "from serving_kv_pages_free: the page BUDGET may sit "
                "below the physical pool — the surplus is the forced-"
                "overcommit headroom that replaces dense-slab slack).",
    },
    "serving_prefix_frames_shared_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Whole KV frames leased by refcount from a prefix-pool "
                "donor at admission instead of device-copied (paged "
                "records; saved bytes = count x frame bytes of the "
                "served record).",
    },
    # ------------------------------------------- disaggregated serving
    "serving_migrations_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Prefill->decode slice handoffs under disaggregated "
                "serving (serving/disagg.py), labeled decision=migrate "
                "(whole-frame KV transfer over the device link) | "
                "recompute (the decode slice re-prefills — chosen when "
                "RecoveryPolicy.choose_migrate prices the transfer "
                "above the re-prefill, or when the destination cannot "
                "lease frames).",
    },
    "serving_migration_bytes_total": {
        "type": "counter",
        "agg": "sum",
        "help": "KV cache bytes moved between mesh slices by frame "
                "migration (decision=migrate handoffs; int8 payloads "
                "include their f32 scale frames).",
    },
    "serving_migration_seconds": {
        "type": "histogram",
        "agg": "histogram",
        "help": "Wall time of one whole-request KV migration (source "
                "fetch + destination lease/table push + restore) — the "
                "victim-TTFT component disaggregation adds, and what "
                "the device-link bandwidth term in SimpleMachineModel "
                "prices.",
    },
    "serving_preemptions_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Requests preempted by the KV pager, labeled "
                "reason=pages (lease growth exhausted the budget) | "
                "admission (pressure-aware scheduler freed a row/pages "
                "for a TTFT-threatened queue head) | pool (a pooled "
                "prefix's pages were reclaimed).  The preempted "
                "request re-enters the pending queue with resume "
                "priority and restores or recomputes at re-admission.",
    },
    "serving_admission_blocked_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Admission passes that left the queue head waiting, "
                "labeled reason=no_rows|no_pages — counted once per "
                "(request, reason) transition, not per retry, so the "
                "total reads as 'requests that experienced this "
                "block', and queue_wait_s spikes in tools/ffreq.py "
                "are attributable (each transition also lands a "
                "ledger note on the request's timeline).",
    },
    # ------------------------------------------- async front-end
    # (serve/frontend.py: continuous-admission asyncio front-end with
    # per-token streaming, deadlines, backpressure and load shedding
    # over the blocking driver loops — docs/SERVING.md)
    "serving_cancellations_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Requests cancelled before natural retirement "
                "(RequestManager.cancel_request), labeled reason="
                "deadline (SLO-derived per-request deadline expired "
                "mid-stream) | disconnect (client stream closed) | "
                "slow_client (bounded stream queue overflowed) | "
                "client (explicit API cancel) | shed:* (load-shed "
                "victims — the shed reason rides the label) | stall/"
                "closed/driver_failed (server-side teardown of work "
                "whose streams were failed — never misread as client "
                "disconnects).  A "
                "cancelled request's pager pages, pool donations and "
                "ledger timeline are released exactly like a "
                "retirement; its committed tokens stay counted in "
                "serving_tokens_generated_total (reconciliation).",
    },
    "serving_shed_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Requests dropped by the front-end's load-shed policy "
                "under overload, labeled reason=hopeless (remaining "
                "deadline budget < estimated remaining service time — "
                "the request cannot attain its SLO, so shedding it "
                "costs nothing) | overload (pending queue over the "
                "shed watermark; newest arrivals first) | "
                "pager_pressure (KV page budget exhausted with a deep "
                "queue).  Every shed also ticks "
                "serving_cancellations_total{reason=shed:<reason>}.",
    },
    "serving_rejected_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Intake submissions rejected before enqueue, labeled "
                "reason=backpressure (pending deque at the intake "
                "watermark — the client got Overloaded with a "
                "retry_after_s hint instead of unbounded queue "
                "growth) | closed (front-end shut down or failed).",
    },
    # ------------------------------------------------- SLO / goodput
    # (per-request ledger, observability/ledger.py: evaluated per
    # retired request against the installed SLOPolicy; all four refresh
    # together at each retirement over the retired-request window)
    "serving_slo_attainment": {
        "type": "gauge",
        "agg": "last",
        "help": "Fraction of retired requests meeting EVERY configured "
                "SLO component (TTFT and TPOT targets), over the "
                "ledger's retired window.",
    },
    "serving_slo_ttft_attainment": {
        "type": "gauge",
        "agg": "last",
        "help": "Fraction of retired requests whose admit->first-token "
                "latency met the SLOPolicy ttft_s target.",
    },
    "serving_slo_tpot_attainment": {
        "type": "gauge",
        "agg": "last",
        "help": "Fraction of retired requests whose mean inter-token "
                "gap met the SLOPolicy tpot_s target.",
    },
    "serving_goodput_tokens_per_s": {
        "type": "gauge",
        "agg": "sum",
        "help": "Tokens from SLO-attaining retired requests per second "
                "of the retired window (first admit -> last retire) — "
                "the ROADMAP async-serving headline: throughput that "
                "actually met latency targets, not just throughput.",
    },
    # ------------------------------------------------ network serving
    # (serve/net/: the HTTP/1.1 + SSE wire surface over the async
    # front-end — docs/SERVING.md "Wire protocol & router")
    "serving_net_requests_total": {
        "type": "counter",
        "agg": "sum",
        "help": "HTTP requests served by the wire front-end, labeled "
                "endpoint=generate|cancel|health|stats|timelines|"
                "history|metrics|kv_export|kv_import|debug_bundle|"
                "fleet_health|other "
                "and code=<http status>.  endpoint=generate with "
                "code=429 is the Overloaded/backpressure class (the "
                "body carries retry_after_s and the response a "
                "Retry-After header); code=503 is draining/closed.",
    },
    "serving_net_active_streams": {
        "type": "gauge",
        "agg": "sum",
        "help": "SSE token streams currently open on the wire server "
                "(connected generate clients mid-stream).",
    },
    "serving_net_stream_tokens_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Tokens framed as SSE `token` events onto client "
                "sockets (after any skip_tokens router-resume "
                "suppression; compare serving_tokens_generated_total "
                "for what the engine produced).",
    },
    "serving_net_disconnects_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Client sockets that closed mid-stream (read-EOF or "
                "write failure while tokens were flowing).  Each one "
                "also ticks serving_cancellations_total{reason="
                "disconnect} when the engine-side cancel lands — the "
                "wire twin of the front-end's disconnect path.",
    },
    "serving_net_request_seconds": {
        "type": "histogram",
        "agg": "histogram",
        "help": "Wall time of one wire request from head-parse to "
                "response flush (generate requests span the whole SSE "
                "stream — the wire-side latency envelope the bench "
                "`net` mode A/Bs against in-process streaming).",
    },
    # ------------------------------------------------ fleet trace plane
    # (observability/traceplane.py + serve/net/: wire-propagated trace
    # context — X-FFServe-Trace — and cross-replica timeline assembly)
    "serving_trace_hops_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Trace contexts adopted by this process, labeled "
                "source=wire (an X-FFServe-Trace header arrived with "
                "the submit — this hop joins an existing distributed "
                "trace) | minted (no header: this hop minted a fresh "
                "trace_id — it is hop 0 of the chain).  One tick per "
                "request, so wire/minted splits say how much traffic "
                "arrives already-traced vs starts here.",
    },
    # ------------------------------------------------ replica router
    # (serve/net/router.py: multi-replica prefix-affinity router over
    # N wire servers, scored from scraped /metrics)
    "router_route_seconds": {
        "type": "histogram",
        "agg": "histogram",
        "help": "Wall time of one routing decision: submit arrival at "
                "the router to a replica ACCEPTING the upstream "
                "submit, including the candidate retry walk past "
                "rejecting/dead replicas (a failover's re-route "
                "observes here too).  The router-side latency the "
                "assembled trace's router-route span renders.",
    },
    "router_requests_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Requests the router accepted for routing, labeled "
                "outcome=completed (done event relayed) | failed "
                "(retries exhausted or non-retriable transport error) "
                "| rejected (every candidate replica circuit-open or "
                "upstream 429/503 passed through).",
    },
    "router_failovers_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Mid-request replica failovers: the upstream socket "
                "died before a `done` event, and the router resubmitted "
                "to another replica with skip_tokens set to the count "
                "already relayed (greedy decode is deterministic, so "
                "the client stream stays byte-identical).",
    },
    "router_affinity_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Prefix-affinity routing decisions, labeled outcome="
                "hit (request followed its prefix-hash map entry to "
                "the replica already holding the tenant's frames) | "
                "spill (mapped replica over the pressure threshold — "
                "routed to the best-scored replica and remapped) | "
                "new (first sighting of the prefix key).",
    },
    "router_replica_score": {
        "type": "gauge",
        "agg": "last",
        "help": "Latest load-balance score per replica (labeled "
                "replica=<url>): normalized serving_goodput_tokens_"
                "per_s + frames-free headroom - queue depth, from the "
                "most recent /metrics scrape.  Higher = preferred.",
    },
    "router_circuit_open_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Circuit-breaker trips, labeled replica=<url>: a "
                "transport failure marked the replica dead and "
                "routing excludes it until the cooldown expires.",
    },
    # ---------------------------------------------- device profiling
    # (observability/devprof.py: compiled-record cost reports + sampled
    # per-dispatch device timing + cost-model drift — the measurement
    # substrate for BENCH chip rounds and cost-model calibration)
    "serving_compiled_flops": {
        "type": "gauge",
        "agg": "max",
        "help": "XLA cost_analysis FLOPs of one compiled serving step "
                "(labeled model=<id>, step=<step-cache key>) — "
                "harvested at the AOT compile site in "
                "inference_manager, the numerator of the compute-bound "
                "roofline term the drift gauge compares against.",
    },
    "serving_compiled_bytes_accessed": {
        "type": "gauge",
        "agg": "max",
        "help": "XLA cost_analysis HBM bytes accessed per invocation "
                "of one compiled serving step (labeled model=<id>, "
                "step=<key>) — the bandwidth-bound roofline numerator; "
                "decode steps are expected to sit near weight bytes + "
                "attended KV.",
    },
    "serving_compiled_peak_bytes": {
        "type": "gauge",
        "agg": "max",
        "help": "memory_analysis argument+output+temp bytes of one "
                "compiled serving step (labeled model=<id>, "
                "step=<key>): the executable's live-HBM bound "
                "(donated caches alias, so this over-counts by the "
                "aliased bytes — a conservative ceiling).",
    },
    "serving_devprof_device_seconds": {
        "type": "histogram",
        "agg": "histogram",
        "help": "Sampled per-dispatch device time (a timed "
                "block_until_ready on the dispatch result), labeled "
                "phase=decode|prefill|hybrid|spec_draft|spec_verify|"
                "spill|restore|migrate and path=dense|paged|pp (the "
                "record's cache layout).  Only "
                "every FF_DEVPROF_SAMPLE-th dispatch per (phase, path) "
                "observes here — the histogram is a sample, not a "
                "census (serving_devprof_samples_total counts them).",
    },
    "serving_devprof_samples_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Sampled dispatch timings taken per (phase, path) — "
                "the denominator discipline for the device-seconds "
                "histogram and the drift gauges (each sample costs one "
                "block_until_ready; FF_DEVPROF_SAMPLE sets the "
                "cadence, 0 = off).",
    },
    "serving_devprof_roofline_attainment": {
        "type": "gauge",
        "agg": "last",
        "help": "Per-bound roofline attainment of the latest sampled "
                "dispatch: labeled phase, path and bound=mem|flops — "
                "t_bound / measured, where t_mem = compiled bytes "
                "accessed / machine hbm_bw and t_flops = compiled "
                "FLOPs / machine peak.  ~1.0 means the dispatch runs "
                "at that bound; <<1 on both bounds means overhead-"
                "dominated (or a mis-set machine model — see the drift "
                "gauge).",
    },
    "serving_costmodel_drift_ratio": {
        "type": "gauge",
        "agg": "last",
        "help": "Cost-model drift per (phase, path): predicted / "
                "measured for the latest sampled dispatch, where "
                "predicted = max(t_mem, t_flops) from the record's "
                "CompileReport under the active machine model "
                "(default_machine — honors FF_MACHINE_PROFILE).  1.0 "
                "= the model prices this hardware correctly; the "
                "ffprof --calibrate workflow exists to drive this "
                "toward 1.",
    },
    # --------------------------------------------------- pipeline serving
    "serving_pp_stage_dispatches_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Per-stage step dispatches of the pipeline-parallel "
                "decode block (labeled stage=<s>); re-emits the record's "
                "pp_dispatches odometer so scheduling regressions are "
                "visible in the snapshot.",
    },
    # ---------------------------------------------------- fleet KV economy
    "serving_kv_wire_export_bytes_total": {
        "type": "counter",
        "agg": "sum",
        "help": "KV bundle bytes serialized out of this replica's "
                "prefix pool through /v1/kv/export (magic + header + "
                "frames + scale frames) — the donor half of the "
                "router-directed cross-replica prefix migration.",
    },
    "serving_kv_wire_import_bytes_total": {
        "type": "counter",
        "agg": "sum",
        "help": "KV bundle bytes accepted into this replica's prefix "
                "pool through /v1/kv/import (counted only when the "
                "adoption commits — a rejected or failed import counts "
                "zero, matching the lease-release double-spend "
                "contract).",
    },
    "router_prefix_migrations_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Router-directed cross-replica prefix migrations, "
                "labeled decision=migrate|recompute|failed: migrate = "
                "the bundle was priced cheaper than re-prefill "
                "(RecoveryPolicy.choose_wire over the calibrated wire "
                "bandwidth) and the export->import relay committed; "
                "recompute = pricing chose local re-prefill; failed = "
                "the relay died mid-transfer and routing fell back to "
                "recompute.",
    },
    # ---------------------------------------------------- fleet health plane
    # (observability/fleet.py: cross-replica metrics federation + SLO
    # burn-rate alerting over the router's retained per-replica history
    # rings — docs/OBSERVABILITY.md "Fleet health & alerting")
    "router_fleet_alerts_total": {
        "type": "counter",
        "agg": "sum",
        "help": "Fleet alert state transitions at the router, labeled "
                "rule=<alert rule name> and state=firing (both burn-"
                "rate windows crossed the threshold — the alert "
                "opened and, when the rule is replica-scoped, the "
                "replica's diagnostic bundle was auto-captured) | "
                "resolved (the fast window recovered past the re-arm "
                "margin and the alert closed).  One tick per "
                "transition, never per evaluation, so the total reads "
                "as 'times this rule opened/closed'.",
    },
}

# The step-event vocabulary: every name the StepTracer (spans/instants)
# and the FlightRecorder (post-mortem ring) may emit.  One table so the
# host trace, the XLA TraceAnnotation names, the flight record and
# tools/{trace_summary,ffstat}.py all agree; the recorder validates at
# record time and fflint's metric-schema rule validates the
# record_event(...) call sites statically.
EVENT_SCHEMA = {
    "enqueue": {
        "help": "Request registered into the pending queue (guid, "
                "prompt_len) — the ledger's timeline birth; enqueue->"
                "admit is the queue-wait component of latency.",
    },
    "admit": {
        "help": "Request admitted into a batch row (guid, row, "
                "prompt_len).  The TTFT clock starts HERE (not at "
                "enqueue) — see docs/OBSERVABILITY.md.",
    },
    "prefix-match": {
        "help": "Pooled prefix matched at admission (guid, matched, "
                "prompt_len).",
    },
    "prefill-chunk": {
        "help": "One chunked-prefill step scheduled (chunk, rows).",
    },
    "decode-step": {
        "help": "One decode step or fused K-step decode block dispatched "
                "(block, rows).",
    },
    "hybrid-step": {
        "help": "One stall-free mixed dispatch: the decode batch plus a "
                "budgeted rider slice of prefilling rows in ONE device "
                "program (chunk, rows, decode_rows, rider_rows, "
                "rider_tokens).  Rider rows additionally land "
                "guid-scoped prefill-chunk notes with rider=True on "
                "their ledger timelines (tools/ffreq.py renders the "
                "spans).",
    },
    "spec-draft": {
        "help": "SSM drafting phase started (ssms, rows).",
    },
    "spec-verify": {
        "help": "LLM tree-verify phase (host loop) or one dispatch+sync "
                "round of the fused spec block (device loop).",
    },
    "commit": {
        "help": "Tokens committed to a request (guid, tokens, accepted).",
    },
    "retire": {
        "help": "Request retired — EOS or length budget (guid, tokens; "
                "the ledger feed additionally carries the authoritative "
                "ProfileInfo latencies: ttft_s, tpot_s, latency_s, "
                "queue_s, accepted, speculated, prefix_matched).",
    },
    "donate": {
        "help": "Retired row donated to the prefix pool (guid, slot, "
                "length).",
    },
    "cancel": {
        "help": "Request cancelled before natural retirement (guid, "
                "reason=deadline|disconnect|slow_client|client|shed:*, "
                "tokens committed so far; the ledger feed additionally "
                "carries ttft_s/latency_s/queue_s).  Finalizes the "
                "request's timeline with cancelled=True — the cancel "
                "twin of `retire`.",
    },
    "shed": {
        "help": "The front-end's load-shed policy dropped a request "
                "(guid, reason=hopeless|overload|pager_pressure), "
                "recorded when the enacting cancel lands (beside its "
                "cancel event, whose reason is shed:<reason>) — "
                "selection alone is never counted, so shed totals "
                "can't outnumber actual cancellations.",
    },
    "disconnect": {
        "help": "A streaming client went away mid-request (guid, "
                "streamed = tokens delivered before the disconnect); "
                "the front-end cancels the request so its row, pages "
                "and pool refs free immediately instead of decoding "
                "for a dead socket.",
    },
    "preempt": {
        "help": "Running request preempted by the KV pager (guid, row, "
                "reason=pages|admission, mode=spill|recompute, tokens "
                "= committed KV positions released).  The request "
                "re-enters the pending queue with resume priority; "
                "look for the following restore/admit pair — the "
                "preempt->restore/recompute span — in its ffreq "
                "timeline.",
    },
    "spill": {
        "help": "Committed KV fetched device->host (guid for request "
                "spills, slot for prefix-pool page spills; tokens, "
                "bytes).  A bucketed transfer outside the jitted "
                "steps — never inside the decode loop.",
    },
    "restore": {
        "help": "Spilled KV restored host->device at re-admission "
                "(guid, row, tokens, bytes) — the device_put + jitted "
                "donated row write; the alternative outcome is plain "
                "re-prefill (recompute), visible as the request's "
                "prefill-chunk events instead.",
    },
    "admission-blocked": {
        "help": "The queue head could not be admitted (guid, "
                "reason=no_rows|no_pages); noted once per (request, "
                "reason) transition so a timeline shows WHY its "
                "queue_wait_s grew.",
    },
    "migrate": {
        "help": "Disaggregated prefill->decode handoff at a fold "
                "boundary (guid, src_row, dst_row, tokens, bytes, "
                "seconds, decision=migrate|recompute): the request's "
                "prefilled KV left the prefill slice — as a whole-"
                "frame device-to-device transfer (migrate) or by "
                "re-prefilling on the decode slice (recompute).  "
                "tools/ffreq.py renders the prefill-slice -> transfer "
                "-> decode-slice span from it.",
    },
    "evict": {
        "help": "Prefix-pool entry evicted (slot, reason=lru|superseded"
                "|host-lru; slot=None for spilled entries dropped from "
                "the host-RAM ring).",
    },
    "host-sync": {
        "help": "Device->host materialization of step results (n); the "
                "flight-record twin of serving_host_syncs_total.",
    },
    "net-request": {
        "help": "One wire request accepted by the HTTP/SSE server "
                "(endpoint, guid for generate submissions, peer) — the "
                "network-side birth of a request the frontend's "
                "enqueue event then tracks.",
    },
    "net-disconnect": {
        "help": "A client socket closed mid-SSE-stream (guid, streamed "
                "= tokens framed before the close).  The server "
                "cancels the engine-side request (reason=disconnect) "
                "so rows/frames free instead of decoding for a dead "
                "socket — the wire twin of the `disconnect` event.",
    },
    "net-drain": {
        "help": "The wire server began graceful drain (SIGTERM or "
                "programmatic close): intake answers 503, in-flight "
                "SSE streams flush, then the front-end closes behind "
                "a drain barrier (live = streams open at drain start).",
    },
    "router-route": {
        "help": "The router bound a request to a replica (replica, "
                "affinity=hit|spill|new, key) — the prefix-affinity "
                "decision trail for one routed submission.",
    },
    "router-failover": {
        "help": "Mid-request failover: the upstream replica died "
                "before `done` (replica, relayed = tokens already "
                "forwarded); the router resubmits elsewhere with "
                "skip_tokens=relayed so the client stream stays "
                "byte-identical.",
    },
    "router-circuit-open": {
        "help": "Circuit breaker opened on a replica after a "
                "transport failure (replica, cooldown_s); routing "
                "excludes it until the cooldown expires.",
    },
    "router-migrate": {
        "help": "The router priced and (maybe) relayed a cross-replica "
                "prefix migration before routing (guid, donor, target, "
                "digest, decision=migrate|recompute|failed, bytes, "
                "seconds): the fleet-KV-economy decision trail — "
                "export from the donor, wire relay, import into the "
                "target, then the normal route.  tools/ffreq.py "
                "renders the export -> wire -> import -> admit span "
                "from it.",
    },
    "kv-export": {
        "help": "This replica serialized a pooled prefix into a wire "
                "bundle for a peer (tokens = exported span, bytes, "
                "seconds, digest).  Donor-side, read-only: nothing is "
                "released; lands on a synthetic donor timeline stamped "
                "with the migration's trace_id so fftrace grafts the "
                "donor hop into the traced request.",
    },
    "kv-import": {
        "help": "This replica adopted a peer's exported prefix bundle "
                "(tokens = imported span, bytes, seconds, digest, "
                "resident = landed in a leased batch slot vs a "
                "slot-less host entry).  The import either fully "
                "commits (lease + restore + pool insert) or fully "
                "releases — frame counts return to baseline on any "
                "failure.",
    },
    "trace-adopt": {
        "help": "A request adopted a distributed trace context (guid, "
                "trace_id, hop, source=wire|minted): the X-FFServe-"
                "Trace header's id/hop when one arrived with the "
                "submit, else a freshly-minted hop-0 context.  The "
                "ledger stamps trace_id/hop onto the request's "
                "timeline here — the join key tools/fftrace.py merges "
                "cross-process timelines on.",
    },
    "trace-assemble": {
        "help": "A TraceAssembler merged one trace_id's timelines "
                "across sources into a single Chrome trace (trace_id, "
                "sources, timelines, events) — the router's "
                "assemble_trace and tools/fftrace.py both record it.",
    },
    "fleet-alert": {
        "help": "A fleet alert rule changed state at the router (rule, "
                "scope=fleet|<replica url>, state=firing|resolved, "
                "fast, slow, threshold: the two window burn values "
                "that crossed — or the fast value that recovered).  "
                "The declared input contract for the fleet placement "
                "policy / autoscaler: act on transitions, not on raw "
                "series.",
    },
    "fleet-capture": {
        "help": "The router auto-captured a replica's diagnostic "
                "bundle because a replica-scoped alert fired (rule, "
                "replica, path = the ffbundle_*.json written to disk, "
                "ok; on a failed pull, ok=False and path=None).  The "
                "bundle is the watchdog shape — tools/ffstat.py reads "
                "it and names the replica's in-flight GUIDs.",
    },
    "compile": {
        "help": "A serving record compiled + caches allocated (model, "
                "mode, rows, alloc_len) — a burst of these mid-serve is "
                "the recompile-loop stall signature.",
    },
    "compile-report": {
        "help": "One compiled step's XLA cost/memory analysis was "
                "harvested into a CompileReport (model, key, flops, "
                "bytes) — the devprof twin of `compile`; rendered by "
                "tools/ffprof.py and stamped into bench rounds.",
    },
    "devprof-sample": {
        "help": "One sampled dispatch timing landed (phase, path, "
                "seconds) — the flight-record twin of the device-"
                "seconds histogram.  In a stall bundle the per-phase "
                "devprof tail splits two bug classes: healthy recent "
                "device seconds point at a hung NEXT dispatch, while "
                "zero sampled device time in the window points "
                "host-side (scheduler/queue) — tools/ffstat.py prints "
                "the split.",
    },
}
