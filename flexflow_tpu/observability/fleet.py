"""Fleet health plane: cross-replica metrics federation + SLO
burn-rate alerting over the router's retained per-replica history
rings.

Every observability surface below this one is per-replica (registry,
ledger, trace plane, devprof).  The router already scrapes ``/metrics``
and retains a :class:`~..observability.traceplane.MetricsHistory` ring
per replica; this module is the read-and-alarm half of the
self-driving loop built on top of that retention:

- :class:`FleetAggregator` merges the per-replica rings into fleet
  time-series using the aggregation kind every metric declares in
  ``schema.py`` (``"agg"``: counters sum, histograms bucket-merge —
  their flattened series are all per-replica cumulative counts, so the
  merge is a sum over equal keys — and each gauge declares
  sum/max/last), derives the fleet headline series (goodput, SLO
  attainment, KV frame headroom, cost-model drift) and scores every
  replica's deviation from the fleet median (the outlier table a
  placement policy or autoscaler reads before it acts).  Replicas whose
  latest scrape is older than ``stale_after_s`` are EXCLUDED from the
  merge and flagged ``stale`` instead of silently dragging sums down.

- :class:`AlertEngine` evaluates declarative, schema-validated rules
  with SRE-style multi-window burn-rate semantics: the FAST window
  (~1m) and the SLOW window (~10m) must BOTH breach before a rule
  fires — a fast-only breach is a blip, a slow-only breach is an old
  incident already recovering — and a fired rule re-arms only after
  the fast window recovers past the threshold by the rule's hysteresis
  margin.  Transitions (never evaluations) tick
  ``router_fleet_alerts_total{rule,state}`` and land ``fleet-alert``
  recorder/ledger events; an ``on_fire`` hook lets the router pull the
  offending replica's ``/v1/debug/bundle`` the moment a replica-scoped
  rule opens.

Both classes are near-zero-cost under ``FF_TELEMETRY=0``: every entry
point starts with one ``registry.enabled`` attribute read and returns.
State is guarded by an RLock (health snapshots ride watchdog bundles,
which dump from signal handlers — fflint lock-discipline).

Consumed by ``serve/net/router.py`` (scrape-loop evaluation +
``/v1/fleet/health``), ``tools/ffdash.py`` (terminal rendering) and
``bench.py`` (fleet-health stamps in ``live``/``fleetkv`` records).
Documented in docs/OBSERVABILITY.md "Fleet health & alerting".
"""

from __future__ import annotations

import statistics
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .schema import METRICS_SCHEMA
from .traceplane import MetricsHistory

#: fleet aggregation vocabulary (must match the fflint metric-schema
#: rule's AGG_KINDS — a metric cannot register without one of these)
AGG_KINDS = ("sum", "max", "last", "histogram")

#: outlier indicator metrics and their GOOD direction: +1 = higher is
#: better (a replica BELOW the fleet median accrues deviation), -1 =
#: lower is better.  Only bad-direction deviation scores — with two
#: replicas both sit equally far from the median, and the healthy one
#: must not be penalized for being better.
OUTLIER_DIRECTIONS: Dict[str, int] = {
    "serving_goodput_tokens_per_s": +1,
    "serving_slo_attainment": +1,
    "serving_slo_ttft_attainment": +1,
    "serving_kv_frames_free": +1,
    "serving_queue_depth": -1,
}

#: per-metric deviation scale floor (deviation = bad-direction delta /
#: max(|median|, floor)): ratios deviate meaningfully at small absolute
#: deltas, so their floor sits below the default.
_OUTLIER_FLOOR: Dict[str, float] = {
    "serving_slo_attainment": 0.25,
    "serving_slo_ttft_attainment": 0.25,
}

#: headline series the /v1/fleet/health payload tails (beside every
#: derived fleet_* series) — the full flattened key set (label splits,
#: histogram buckets) stays queryable from the aggregator's ring but
#: would bloat a health poll.
HEALTH_SERIES = (
    "serving_goodput_tokens_per_s",
    "serving_slo_attainment",
    "serving_queue_depth",
    "serving_active_requests",
    "serving_kv_frames_free",
    "serving_net_active_streams",
)


def _registry_enabled() -> bool:
    from . import get_registry

    return get_registry().enabled


def base_metric(series_key: str,
                schema: Dict[str, Dict] = METRICS_SCHEMA) -> str:
    """Flattened-series key -> owning schema metric: strip the
    ``{labels}`` tag, then a histogram's ``_bucket/_sum/_count``
    suffix when the stem is a declared histogram."""
    name = series_key.split("{", 1)[0]
    for suf in ("_bucket", "_sum", "_count"):
        if name.endswith(suf):
            stem = name[:-len(suf)]
            if (schema.get(stem) or {}).get("type") == "histogram":
                return stem
    return name


def agg_kind(series_key: str,
             schema: Dict[str, Dict] = METRICS_SCHEMA) -> Optional[str]:
    """The cross-replica merge rule for one flattened series key, or
    None for keys outside the schema (derived/foreign series are never
    merged blind).  Histogram series flatten to cumulative counts and
    sums, so the declared ``histogram`` kind resolves to ``sum``."""
    decl = schema.get(base_metric(series_key, schema))
    if decl is None:
        return None
    kind = decl.get("agg")
    return "sum" if kind == "histogram" else kind


class FleetAggregator:
    """Merges per-replica :class:`MetricsHistory` rings into fleet
    time-series + a per-replica outlier table (see module docstring).

    ``merge()`` is driven from the router's scrape loop; readers
    (``/v1/fleet/health``, ffdash, bench stamps) call
    :meth:`health_snapshot` / :meth:`series_tail`.
    """

    def __init__(self, schema: Optional[Dict[str, Dict]] = None,
                 capacity: int = 512,
                 stale_after_s: float = 10.0,
                 outlier_threshold: float = 1.0):
        self.schema = METRICS_SCHEMA if schema is None else schema
        self.stale_after_s = max(0.1, float(stale_after_s))
        self.outlier_threshold = float(outlier_threshold)
        #: the fleet time-series ring (fed by merge(), never sampled)
        self.history = MetricsHistory(capacity=capacity)
        # RLock: health snapshots can ride watchdog bundles (signal
        # handlers) while the scrape loop is mid-merge
        self._lock = threading.RLock()
        self._replicas: Dict[str, Dict[str, Any]] = {}
        self._merges = 0

    # ------------------------------------------------------------ merging
    def merge(self, rings: Dict[str, MetricsHistory],
              now: Optional[float] = None) -> Optional[Dict[str, float]]:
        """Fold every replica's LATEST sample into one fleet sample,
        append it to the fleet ring and refresh the outlier table.
        Returns the merged value map (None when telemetry is disabled
        — the near-zero-cost gate — or when no replica is fresh)."""
        if not _registry_enabled():
            return None
        now = time.time() if now is None else float(now)
        latest: Dict[str, Dict[str, float]] = {}
        meta: Dict[str, Dict[str, Any]] = {}
        for url, ring in rings.items():
            snap = ring.snapshot(tail=1)
            samples = snap.get("samples") or []
            if not samples:
                meta[url] = {"stale": True, "age_s": None,
                             "last_scrape_wall": None}
                continue
            wall = float(samples[-1].get("wall", 0.0))
            age = now - wall
            stale = age > self.stale_after_s
            meta[url] = {"stale": stale, "age_s": round(age, 3),
                         "last_scrape_wall": wall}
            if not stale:
                latest[url] = samples[-1].get("values") or {}
        merged = self._merge_values(latest)
        if latest:
            merged.update(self._derived(latest))
        merged["fleet_replicas"] = float(len(latest))
        merged["fleet_replicas_stale"] = float(
            sum(1 for m in meta.values() if m["stale"]))
        scores = self._outlier_scores(latest)
        for url, m in meta.items():
            sc = scores.get(url, {"score": 0.0, "deviations": {}})
            m["outlier_score"] = round(sc["score"], 4)
            m["outlier"] = sc["score"] >= self.outlier_threshold
            m["deviations"] = sc["deviations"]
        if latest:
            self.history.append(merged, wall=now)
        with self._lock:
            self._replicas = meta
            self._merges += 1
        return merged if latest else None

    def _merge_values(self, latest: Dict[str, Dict[str, float]]
                      ) -> Dict[str, float]:
        out: Dict[str, float] = {}
        kinds: Dict[str, Optional[str]] = {}
        counts: Dict[str, int] = {}
        for values in latest.values():
            for key, v in values.items():
                kind = kinds.get(key)
                if kind is None and key not in kinds:
                    kind = kinds[key] = agg_kind(key, self.schema)
                if kind is None:
                    continue
                if key not in out:
                    out[key] = float(v)
                    counts[key] = 1
                elif kind == "max":
                    out[key] = max(out[key], float(v))
                else:           # sum now; "last" divides by count below
                    out[key] += float(v)
                    counts[key] += 1
        for key, kind in kinds.items():
            # "last" gauges (ratios/levels where neither sum nor max
            # means anything fleet-wide) keep the cross-replica mean
            if kind == "last" and key in out and counts[key] > 1:
                out[key] /= counts[key]
        return out

    def _derived(self, latest: Dict[str, Dict[str, float]]
                 ) -> Dict[str, float]:
        def col(name: str) -> List[float]:
            return [v[name] for v in latest.values() if name in v]

        out: Dict[str, float] = {}
        goodput = col("serving_goodput_tokens_per_s")
        if goodput:
            out["fleet_goodput_tokens_per_s"] = sum(goodput)
        att = col("serving_slo_attainment")
        if att:
            out["fleet_slo_attainment"] = sum(att) / len(att)
        free, total = (col("serving_kv_frames_free"),
                       col("serving_kv_frames_total"))
        if total and sum(total) > 0:
            out["fleet_kv_frame_headroom"] = sum(free) / sum(total)
        drift = col("serving_costmodel_drift_ratio")
        if drift:
            out["fleet_costmodel_drift"] = sum(drift) / len(drift)
        return out

    def _outlier_scores(self, latest: Dict[str, Dict[str, float]]
                        ) -> Dict[str, Dict[str, Any]]:
        scores = {url: {"score": 0.0, "deviations": {}}
                  for url in latest}
        if len(latest) < 2:
            return scores
        for metric, direction in OUTLIER_DIRECTIONS.items():
            vals = {url: values[metric]
                    for url, values in latest.items() if metric in values}
            if len(vals) < 2:
                continue
            med = statistics.median(vals.values())
            scale = max(abs(med), _OUTLIER_FLOOR.get(metric, 1.0))
            for url, v in vals.items():
                dev = (med - v) if direction > 0 else (v - med)
                if dev > 0:
                    d = dev / scale
                    scores[url]["deviations"][metric] = round(d, 4)
                    scores[url]["score"] += d
        return scores

    # ------------------------------------------------------------- reading
    def replica_table(self) -> Dict[str, Dict[str, Any]]:
        """The latest per-replica staleness + outlier table."""
        with self._lock:
            return {url: dict(m) for url, m in self._replicas.items()}

    def series_tail(self, names: Optional[List[str]] = None,
                    tail: int = 120) -> Dict[str, List[List[float]]]:
        """``{name: [[wall, value], ...]}`` tails of the fleet ring —
        default: every derived ``fleet_*`` series plus the
        ``HEALTH_SERIES`` headliners that have samples."""
        snap = self.history.snapshot(tail=tail)
        samples = snap.get("samples") or []
        if names is None:
            seen: Dict[str, None] = {}
            for s in samples:
                for k in s.get("values", {}):
                    if k.startswith("fleet_") or k in HEALTH_SERIES:
                        seen[k] = None
            names = list(seen)
        out: Dict[str, List[List[float]]] = {}
        for name in names:
            pts = [[s["wall"], s["values"][name]] for s in samples
                   if name in s.get("values", {})]
            if pts:
                out[name] = pts
        return out

    def health_snapshot(self, alerts: Optional["AlertEngine"] = None,
                        tail: int = 120) -> Dict[str, Any]:
        """The ``/v1/fleet/health`` payload (also stamped into bench
        records and rendered by tools/ffdash.py): fleet series tails,
        the per-replica outlier/staleness table and — when an engine
        is attached — active alerts + recent transitions."""
        with self._lock:
            merges = self._merges
        payload: Dict[str, Any] = {
            "time_unix": time.time(),
            "stale_after_s": self.stale_after_s,
            "merges": merges,
            "replicas": self.replica_table(),
            "fleet": {"series": self.series_tail(tail=tail)},
        }
        if alerts is not None:
            payload["alerts"] = {"active": alerts.active(),
                                 "recent": alerts.recent()}
        return payload


# ---------------------------------------------------------------- alerting
#: the declarative rule schema: field -> (required, validator).  A rule
#: is a plain dict; validate_rule() normalizes it (defaults applied)
#: or raises ValueError — the engine refuses un-validatable rules at
#: construction, never at evaluation time.
ALERT_RULE_SCHEMA: Dict[str, Tuple[bool, Callable[[Any], bool]]] = {
    "name": (True, lambda v: isinstance(v, str) and v != ""),
    "metric": (True, lambda v: isinstance(v, str) and v != ""),
    "scope": (True, lambda v: v in ("fleet", "replica")),
    "kind": (True, lambda v: v in ("below", "above")),
    "threshold": (True, lambda v: isinstance(v, (int, float))),
    "fast_window_s": (True, lambda v: isinstance(v, (int, float))
                      and v > 0),
    "slow_window_s": (True, lambda v: isinstance(v, (int, float))
                      and v > 0),
    "rearm_margin": (False, lambda v: isinstance(v, (int, float))
                     and v >= 0),
    "capture": (False, lambda v: isinstance(v, bool)),
    "help": (False, lambda v: isinstance(v, str)),
}


def validate_rule(rule: Dict[str, Any]) -> Dict[str, Any]:
    """Validate one alert rule against :data:`ALERT_RULE_SCHEMA` and
    return the normalized copy (defaults filled).  Raises ValueError
    naming the offending field — a mistyped rule fails loudly at
    engine construction, not silently at 3am."""
    if not isinstance(rule, dict):
        raise ValueError(f"alert rule must be a dict, got {type(rule)}")
    unknown = set(rule) - set(ALERT_RULE_SCHEMA)
    if unknown:
        raise ValueError(f"alert rule {rule.get('name')!r}: unknown "
                         f"fields {sorted(unknown)}")
    out = dict(rule)
    for field, (required, ok) in ALERT_RULE_SCHEMA.items():
        if field not in out:
            if required:
                raise ValueError(f"alert rule {rule.get('name')!r}: "
                                 f"missing required field {field!r}")
            continue
        if not ok(out[field]):
            raise ValueError(f"alert rule {rule.get('name')!r}: "
                             f"invalid {field!r}: {out[field]!r}")
    if out["slow_window_s"] < out["fast_window_s"]:
        raise ValueError(f"alert rule {out['name']!r}: slow window "
                         f"shorter than fast window")
    out.setdefault("rearm_margin", 0.0)
    # replica-scoped rules default to capturing the offender's bundle
    out.setdefault("capture", out["scope"] == "replica")
    return out


#: the stock rule set: SLO burn at replica and fleet scope, plus fleet
#: frame-headroom exhaustion.  Thresholds are workload-independent
#: ratios; absolute-valued rules (goodput floors, queue ceilings) are
#: deployment-specific and belong to the caller.
DEFAULT_ALERT_RULES: Tuple[Dict[str, Any], ...] = (
    {"name": "replica-slo-burn", "metric": "serving_slo_attainment",
     "scope": "replica", "kind": "below", "threshold": 0.9,
     "fast_window_s": 60.0, "slow_window_s": 600.0,
     "rearm_margin": 0.02,
     "help": "one replica is burning its SLO error budget in both "
             "windows — capture its bundle and look for the stall"},
    {"name": "fleet-slo-burn", "metric": "fleet_slo_attainment",
     "scope": "fleet", "kind": "below", "threshold": 0.9,
     "fast_window_s": 60.0, "slow_window_s": 600.0,
     "rearm_margin": 0.02,
     "help": "the FLEET is missing SLO — capacity, not one replica"},
    {"name": "fleet-frame-headroom",
     "metric": "fleet_kv_frame_headroom",
     "scope": "fleet", "kind": "below", "threshold": 0.05,
     "fast_window_s": 60.0, "slow_window_s": 600.0,
     "rearm_margin": 0.02,
     "help": "fleet-wide KV frame pool nearly exhausted — admission "
             "is about to block everywhere at once"},
)


class AlertEngine:
    """Multi-window burn-rate alerting over fleet + per-replica series
    (see module docstring for the fire/re-arm semantics).

    ``on_fire(rule, scope_key, info)`` runs after a firing transition
    commits, outside the engine lock — the router's bundle-capture
    hook.  Exceptions in the hook are swallowed: a broken capture path
    must not wedge alert evaluation.
    """

    def __init__(self, rules: Optional[List[Dict[str, Any]]] = None,
                 on_fire: Optional[Callable[
                     [Dict[str, Any], str, Dict[str, Any]], None]] = None,
                 recent_capacity: int = 64):
        source = DEFAULT_ALERT_RULES if rules is None else rules
        self.rules = [validate_rule(r) for r in source]
        names = [r["name"] for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alert rule names: {names}")
        self.on_fire = on_fire
        self._lock = threading.RLock()
        self._states: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._recent: List[Dict[str, Any]] = []
        self._recent_cap = max(1, int(recent_capacity))

    # ---------------------------------------------------------- evaluation
    @staticmethod
    def _window_mean(ring: MetricsHistory, metric: str,
                     window_s: float, now: float) -> Optional[float]:
        pts = [v for wall, v in ring.series(metric)
               if wall >= now - window_s]
        if not pts:
            return None
        return sum(pts) / len(pts)

    def evaluate(self, fleet_history: MetricsHistory,
                 replica_histories: Dict[str, MetricsHistory],
                 now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation pass over every rule x scope.  Returns the
        transitions that happened (also retained in :meth:`recent`).
        No-op under disabled telemetry."""
        if not _registry_enabled():
            return []
        now = time.time() if now is None else float(now)
        transitions: List[Dict[str, Any]] = []
        fired: List[Tuple[Dict[str, Any], str, Dict[str, Any]]] = []
        for rule in self.rules:
            if rule["scope"] == "fleet":
                scopes: List[Tuple[str, MetricsHistory]] = [
                    ("fleet", fleet_history)]
            else:
                scopes = sorted(replica_histories.items())
            for scope_key, ring in scopes:
                t = self._evaluate_one(rule, scope_key, ring, now)
                if t is not None:
                    transitions.append(t)
                    if t["state"] == "firing":
                        fired.append((rule, scope_key, t))
        for rule, scope_key, info in fired:
            self._emit(rule, scope_key, info)
            if self.on_fire is not None and rule.get("capture"):
                try:
                    self.on_fire(rule, scope_key, info)
                except Exception:
                    pass
        for t in transitions:
            if t["state"] == "resolved":
                self._emit_resolved(t)
        return transitions

    def _evaluate_one(self, rule: Dict[str, Any], scope_key: str,
                      ring: MetricsHistory,
                      now: float) -> Optional[Dict[str, Any]]:
        fast = self._window_mean(ring, rule["metric"],
                                 rule["fast_window_s"], now)
        slow = self._window_mean(ring, rule["metric"],
                                 rule["slow_window_s"], now)
        below = rule["kind"] == "below"
        thr = float(rule["threshold"])

        def breach(v: Optional[float]) -> bool:
            return v is not None and (v < thr if below else v > thr)

        key = (rule["name"], scope_key)
        with self._lock:
            st = self._states.setdefault(
                key, {"state": "ok", "since": None,
                      "fast": None, "slow": None})
            st["fast"], st["slow"] = fast, slow
            transition: Optional[str] = None
            if st["state"] == "ok":
                # BOTH windows must burn before the rule opens
                if breach(fast) and breach(slow):
                    st["state"], st["since"] = "firing", now
                    transition = "firing"
            else:
                # hysteresis: only a fast-window recovery past the
                # re-arm margin closes the alert (the slow window keeps
                # burning long after the incident ends by construction)
                margin = float(rule["rearm_margin"])
                recovered = (fast is not None
                             and (fast >= thr + margin if below
                                  else fast <= thr - margin))
                if recovered:
                    st["state"], st["since"] = "ok", None
                    transition = "resolved"
            if transition is None:
                return None
            info = {"rule": rule["name"], "scope": scope_key,
                    "metric": rule["metric"], "state": transition,
                    "kind": rule["kind"], "threshold": thr,
                    "fast": fast, "slow": slow, "wall": now,
                    "capture": bool(rule.get("capture"))}
            self._recent.append(info)
            del self._recent[:-self._recent_cap]
        return info

    def _emit(self, rule: Dict[str, Any], scope_key: str,
              info: Dict[str, Any]) -> None:
        from . import get_registry
        from .flight_recorder import get_flight_recorder
        from .ledger import get_ledger

        get_registry().counter("router_fleet_alerts_total").inc(
            rule=rule["name"], state="firing")
        get_flight_recorder().record_event(
            "fleet-alert", rule=rule["name"], scope=scope_key,
            state="firing", fast=info["fast"], slow=info["slow"],
            threshold=info["threshold"])
        get_ledger().note_event(
            "fleet-alert", rule=rule["name"], scope=scope_key,
            state="firing", threshold=info["threshold"])

    def _emit_resolved(self, info: Dict[str, Any]) -> None:
        from . import get_registry
        from .flight_recorder import get_flight_recorder

        get_registry().counter("router_fleet_alerts_total").inc(
            rule=info["rule"], state="resolved")
        get_flight_recorder().record_event(
            "fleet-alert", rule=info["rule"], scope=info["scope"],
            state="resolved", fast=info["fast"], slow=info["slow"],
            threshold=info["threshold"])

    # ------------------------------------------------------------- reading
    def active(self) -> List[Dict[str, Any]]:
        """Currently-firing alerts (rule, scope, since, latest window
        values)."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for (name, scope), st in sorted(self._states.items()):
                if st["state"] != "firing":
                    continue
                rule = next(r for r in self.rules if r["name"] == name)
                out.append({"rule": name, "scope": scope,
                            "metric": rule["metric"],
                            "kind": rule["kind"],
                            "threshold": rule["threshold"],
                            "since": st["since"],
                            "fast": st["fast"], "slow": st["slow"]})
        return out

    def recent(self) -> List[Dict[str, Any]]:
        """Recent transitions, oldest first (bounded ring)."""
        with self._lock:
            return [dict(t) for t in self._recent]
