"""Serving telemetry: metrics registry + step tracing + flight recorder.

One emission surface for the serving stack (request_manager,
inference_manager, spec_infer, spec_block, prefix_cache,
pipeline_serving) replacing three generations of ad-hoc counters
(``host_syncs``, ``PrefixCacheStats``, ``KVCacheStats`` — the legacy
structs stay as views; their values now also flow through here).

- :class:`MetricsRegistry` (registry.py): counters / gauges /
  histograms with fixed exponential buckets; thread-safe; near-zero
  cost when disabled.  The process-wide default registry validates
  names against :data:`schema.METRICS_SCHEMA`.
  :meth:`MetricsRegistry.expose_text` renders Prometheus text
  exposition for off-box scraping.
- :class:`StepTracer` (tracer.py): host-side structured step events
  (admit, prefix-match, prefill-chunk, decode-step, spec-draft,
  spec-verify, commit, donate, evict) as Chrome-trace JSON, with
  ``jax.profiler.TraceAnnotation`` spans so host and XLA timelines
  align.  ``tools/trace_summary.py`` prints a per-phase breakdown.
- :class:`FlightRecorder` (flight_recorder.py): ALWAYS-ON bounded ring
  of the same events plus host-sync/compile, the post-mortem black box.
- :class:`Watchdog` (watchdog.py): stall detection off the driver
  :class:`Heartbeat` + SIGTERM/SIGUSR1 handlers, dumping bundles
  (flight record + metrics + request ledger + all-thread stacks + jax
  memory stats) pretty-printed by ``tools/ffstat.py``.
- :class:`RequestLedger` (ledger.py): per-request lifecycle timelines
  (enqueue/admit/prefill/commit/retire with per-request TTFT/TPOT) plus
  :class:`SLOPolicy` attainment and goodput accounting, inspected by
  ``tools/ffreq.py`` and surfaced via ``serve.LLM.request_timelines()``
  / ``slo_report()``.
- :class:`FleetAggregator` / :class:`AlertEngine` (fleet.py): the
  fleet health plane — cross-replica federation of the router's
  per-replica history rings per the schema's ``"agg"`` kinds, derived
  fleet series + per-replica outlier scores, and declarative
  multi-window SLO burn-rate alerting with alert-triggered diagnostic
  bundle capture.  Served as ``/v1/fleet/health`` by the router and
  rendered by ``tools/ffdash.py``.

``FF_TELEMETRY=0`` disables the default registry AND the flight
recorder at import (both become no-ops; tracing stays explicit-opt-in
either way).  See docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import os

from .devprof import (CompileReport, DispatchProfiler,
                      calibrate_machine_profile, drift_table, get_devprof,
                      harvest_compile_report)
from .fleet import (ALERT_RULE_SCHEMA, DEFAULT_ALERT_RULES, AlertEngine,
                    FleetAggregator, validate_rule)
from .flight_recorder import FlightRecorder, get_flight_recorder
from .ledger import (RequestLedger, SLOPolicy, get_ledger,
                     slo_report_from, validate_slo_block)
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       exp_buckets, prometheus_text)
from .schema import EVENT_SCHEMA, METRICS_SCHEMA
from .traceplane import (MetricsHistory, TraceAssembler, TraceContext,
                         get_metrics_history, scalar_values)
from .tracer import EVENT_NAMES, StepTracer
from .watchdog import (Heartbeat, Watchdog, collect_bundle, dump_bundle,
                       get_heartbeat)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "StepTracer",
    "FlightRecorder", "Watchdog", "Heartbeat",
    "RequestLedger", "SLOPolicy",
    "CompileReport", "DispatchProfiler", "get_devprof",
    "harvest_compile_report", "drift_table", "calibrate_machine_profile",
    "TraceContext", "TraceAssembler", "MetricsHistory",
    "get_metrics_history", "scalar_values",
    "FleetAggregator", "AlertEngine", "validate_rule",
    "DEFAULT_ALERT_RULES", "ALERT_RULE_SCHEMA",
    "METRICS_SCHEMA", "EVENT_SCHEMA", "EVENT_NAMES", "exp_buckets",
    "get_registry", "get_tracer", "get_flight_recorder", "get_heartbeat",
    "get_ledger", "slo_report_from", "validate_slo_block",
    "collect_bundle", "dump_bundle", "metrics_snapshot",
    "prometheus_text", "set_telemetry_enabled",
]

_REGISTRY = MetricsRegistry(
    schema=METRICS_SCHEMA,
    enabled=os.environ.get("FF_TELEMETRY", "1") != "0")
_TRACER = StepTracer()


def get_registry() -> MetricsRegistry:
    """The process-wide serving metrics registry."""
    return _REGISTRY


def get_tracer() -> StepTracer:
    """The process-wide serving step tracer (inert until started)."""
    return _TRACER


def metrics_snapshot():
    """Snapshot of the default registry (the ``serve.LLM
    .metrics_snapshot()`` payload)."""
    return _REGISTRY.snapshot()


def set_telemetry_enabled(enabled: bool):
    """Runtime switch for the default registry, the flight recorder AND
    the request ledger (the FF_TELEMETRY env var decides the
    import-time default)."""
    _REGISTRY.enabled = bool(enabled)
    get_flight_recorder().enabled = bool(enabled)
    get_ledger().enabled = bool(enabled)
