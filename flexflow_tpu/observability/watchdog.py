"""Stall watchdog: post-mortem bundles when serving stops making progress.

A daemon thread watches the process-wide :class:`Heartbeat` (beaten by
every driver loop's ``_note_step`` — "last committed step").  When a
driver is inside a generate loop (``Heartbeat.driving`` scope) and no
step commits for ``stall_timeout`` seconds, the watchdog dumps a
**bundle**: the flight-recorder ring, a metrics snapshot, all-thread
stacks (``faulthandler`` into the text twin + ``sys._current_frames``
into the JSON), and jax device-memory / live-array stats.  It also
installs ``SIGTERM`` / ``SIGUSR1`` handlers so an external ``timeout``
kill (the BENCH_r05 rc=124 path) or an operator poke produces the same
bundle — a readable black box instead of a two-line stderr tail.

Limitations (inherent to CPython): the *signal* handlers run at the next
bytecode boundary of the main thread, so a main thread blocked inside
one native call (a dead-tunnel device fetch) cannot dump on SIGTERM —
but the watchdog THREAD still can (its stall timer keeps running and
``faulthandler`` dumps native-blocked threads fine), which is why both
mechanisms exist.
"""

from __future__ import annotations

import contextlib
import faulthandler
import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

from .flight_recorder import get_flight_recorder

#: default stall threshold (seconds without a committed step while a
#: driver loop is active)
DEFAULT_STALL_S = 120.0


# ------------------------------------------------------------- heartbeat
class Heartbeat:
    """Per-process driver progress stamp: last committed step, phase and
    monotonic beat time.  Drivers enter a :meth:`driving` scope for the
    duration of a generate loop (so idle processes never read as
    stalled) and :meth:`beat` once per committed driver-loop step."""

    def __init__(self):
        self._lock = threading.Lock()
        self.step = 0        # committed driver-loop steps, all drivers
        self.tokens = 0      # tokens committed across those steps
        self.phase = ""      # current/last driver label
        self.mono = 0.0      # monotonic stamp of the last beat
        self.active = 0      # drivers currently inside a generate loop

    def beat(self, tokens: int = 0, phase: Optional[str] = None) -> None:
        """One committed step (cost: a lock + a few attribute writes per
        driver-loop step — not per token, not per layer)."""
        with self._lock:
            self.step += 1
            self.tokens += int(tokens)
            self.mono = time.monotonic()
            if phase:
                self.phase = phase

    @contextlib.contextmanager
    def driving(self, phase: str):
        """Scope a generate loop: the watchdog only declares a stall
        while at least one driver is inside (idle processes never read
        as stalled)."""
        with self._lock:
            self.active += 1
            self.phase = phase
            self.mono = time.monotonic()
        try:
            yield self
        finally:
            with self._lock:
                self.active -= 1
                self.mono = time.monotonic()

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "step": self.step,
                "tokens": self.tokens,
                "phase": self.phase,
                "active": self.active,
                "age_s": (round(time.monotonic() - self.mono, 3)
                          if self.mono else None),
            }


_HEARTBEAT = Heartbeat()


def get_heartbeat() -> Heartbeat:
    """The process-wide driver heartbeat (beaten by every driver loop)."""
    return _HEARTBEAT


# ---------------------------------------------------------------- bundle
def _thread_stacks() -> Dict[str, Any]:
    """Python-level stacks for every thread (works from any thread, even
    while the main thread is blocked in native code — the frames just
    show the call into it)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        label = f"{names.get(tid, 'unknown')}-{tid}"
        out[label] = [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)]
    return out


def _jax_stats() -> Dict[str, Any]:
    """Device-memory / live-array stats, best-effort: never raises (the
    dump path must survive a wedged backend)."""
    out: Dict[str, Any] = {}
    try:
        import jax

        live = getattr(jax, "live_arrays", None)
        if callable(live):
            arrs = live()
            out["live_arrays"] = len(arrs)
            out["live_array_bytes"] = int(
                sum(getattr(a, "nbytes", 0) for a in arrs))
        dev = jax.devices()[0]
        out["platform"] = dev.platform
        ms = getattr(dev, "memory_stats", None)
        if callable(ms):
            out["device_memory_stats"] = ms() or {}
    except Exception as e:  # pragma: no cover - backend-dependent
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def collect_bundle(reason: str, heartbeat: Optional[Heartbeat] = None,
                   recorder=None, registry=None,
                   extra: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """Assemble the post-mortem dict (pure collection — no I/O), so
    tests and the serve API can inspect a bundle without touching disk."""
    hb = heartbeat if heartbeat is not None else get_heartbeat()
    rec = recorder if recorder is not None else get_flight_recorder()
    if registry is None:
        from . import get_registry

        registry = get_registry()
    from .ledger import get_ledger

    bundle = {
        "bundle_version": 1,
        "reason": reason,
        "pid": os.getpid(),
        "time_unix": round(time.time(), 3),
        "argv": list(sys.argv),
        "last_heartbeat": hb.state(),
        "flight_record": rec.snapshot(),
        "metrics": registry.snapshot(),
        # per-request lifecycle state: in-flight (non-retired) entries
        # are the stall suspects — ffstat names their GUIDs, ffreq
        # prints their full timelines
        "ledger": get_ledger().snapshot(),
        "threads": _thread_stacks(),
        "jax": _jax_stats(),
    }
    # metrics time-series leading into the dump: a stall bundle shows
    # the minutes BEFORE the stall (goodput/queue-depth/frames decay),
    # not just the terminal snapshot — ffstat prints the tail
    try:
        from .traceplane import get_metrics_history

        hist = get_metrics_history().snapshot(tail=240)
        if hist["samples"]:
            bundle["metrics_history"] = hist
    except Exception:  # pragma: no cover - partial install
        pass
    # device-profiling state: compile reports + the sampled per-phase
    # device-seconds tail — a stall whose window holds healthy recent
    # device time points at a hung NEXT dispatch; one with zero sampled
    # device time points host-side (ffstat prints the split)
    try:
        from .devprof import get_devprof

        dp = get_devprof().snapshot()
        if dp["samples"] or dp["reports"]:
            bundle["devprof"] = dp
    except Exception:  # pragma: no cover - partial install
        pass
    # paged-KV state: pages free/leased + spilled GUIDs per live pager
    # (lazy import — serving imports observability at module load, so
    # the reverse edge must only exist at bundle time; best-effort:
    # the dump path must survive a partial install)
    try:
        from ..serving.kv_pager import pager_snapshots

        pagers = pager_snapshots()
        if pagers:
            bundle["kv_pager"] = pagers
    except Exception:  # pragma: no cover - partial install
        pass
    if extra:
        bundle.update(extra)
    return bundle


def dump_bundle(bundle_dir: str, reason: str,
                heartbeat: Optional[Heartbeat] = None, recorder=None,
                registry=None, extra: Optional[Dict[str, Any]] = None
                ) -> str:
    """Write ``<dir>/ffbundle_<pid>_<n>.{json,txt}`` and return the JSON
    path.  The text twin leads with the stall diagnosis + faulthandler
    stacks (native-thread-safe) + the last ring events, so a human with
    only ``cat`` gets the story; ``tools/ffstat.py`` pretty-prints the
    JSON."""
    bundle = collect_bundle(reason, heartbeat=heartbeat, recorder=recorder,
                            registry=registry, extra=extra)
    os.makedirs(bundle_dir, exist_ok=True)
    # pid + time-based name: unique per dump, sortable, no collisions
    # across the SIGTERM-then-stall double-dump case
    stem = f"ffbundle_{os.getpid()}_{int(time.time() * 1000)}"
    json_path = os.path.join(bundle_dir, stem + ".json")
    txt_path = os.path.join(bundle_dir, stem + ".txt")
    with open(json_path, "w") as f:
        json.dump(bundle, f, indent=1, default=str)
        f.write("\n")
    try:
        with open(txt_path, "w") as f:
            hb = bundle["last_heartbeat"]
            f.write(f"== flight-recorder bundle: {reason}\n"
                    f"pid {bundle['pid']}  argv {' '.join(bundle['argv'])}\n"
                    f"last heartbeat: step {hb['step']} phase "
                    f"{hb['phase']!r} age {hb['age_s']}s "
                    f"active {hb['active']}\n\n-- all-thread stacks "
                    f"(faulthandler)\n")
            f.flush()
            faulthandler.dump_traceback(file=f, all_threads=True)
            f.write("\n-- last flight-record events\n")
            for ev in bundle["flight_record"]["events"][-64:]:
                payload = {k: v for k, v in ev.items()
                           if k not in ("name", "t", "seq")}
                f.write(f"  #{ev['seq']:>6} t={ev['t']:.3f} "
                        f"{ev['name']:<14} {payload}\n")
    except Exception:  # pragma: no cover - the JSON half already landed
        pass
    return json_path


# -------------------------------------------------------------- watchdog
_SIG_BY_NAME = {"SIGTERM": signal.SIGTERM, "SIGUSR1": signal.SIGUSR1,
                "SIGINT": signal.SIGINT}


class Watchdog:
    """Daemon thread + signal handlers dumping post-mortem bundles.

    - **Stall**: while a driver loop is active (``Heartbeat.driving``)
      and no step commits for ``stall_timeout`` seconds, dump once per
      stall (re-arms when progress resumes).
    - **SIGTERM**: dump, then restore the previous handler and re-raise
      so the external killer's exit semantics (rc 143 under ``timeout``)
      are preserved.
    - **SIGUSR1**: dump and continue — the live-poke path.

    ``on_bundle(path, reason)`` runs after every dump (bench stamps the
    round record with it).  Use as a context manager or start()/stop().
    """

    def __init__(self, stall_timeout: float = DEFAULT_STALL_S,
                 bundle_dir: Optional[str] = None,
                 heartbeat: Optional[Heartbeat] = None,
                 recorder=None, registry=None,
                 poll_interval: Optional[float] = None,
                 signals: tuple = ("SIGTERM", "SIGUSR1"),
                 on_bundle: Optional[Callable[[str, str], None]] = None):
        self.stall_timeout = float(stall_timeout)
        self.bundle_dir = bundle_dir or os.path.join(
            os.getcwd(), "ffbundles")
        self.heartbeat = (heartbeat if heartbeat is not None
                          else get_heartbeat())
        self.recorder = recorder
        self.registry = registry
        self.poll_interval = poll_interval or max(
            0.05, min(5.0, self.stall_timeout / 4))
        self.signals = tuple(signals or ())
        self.on_bundle = on_bundle
        self.last_bundle: Optional[str] = None
        self.stall_count = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev_handlers: Dict[int, Any] = {}
        # serialize concurrent dumps.  RLock, not Lock: dump() is
        # reachable from the SIGTERM/SIGUSR1 handlers, which run at an
        # arbitrary bytecode boundary of the main thread — if that
        # thread is already inside dump() (serve-API poke) when the
        # signal lands, a plain Lock deadlocks the process right as it
        # is trying to explain why it is stuck
        self._lock = threading.RLock()

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self._install_signal_handlers()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="ff-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None
        self._restore_signal_handlers()

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -------------------------------------------------------------- dumps
    def dump(self, reason: str) -> str:
        """Dump a bundle now (thread-safe; also the signal/stall path)."""
        with self._lock:
            path = dump_bundle(self.bundle_dir, reason,
                               heartbeat=self.heartbeat,
                               recorder=self.recorder,
                               registry=self.registry)
            self.last_bundle = path
        if self.on_bundle is not None:
            try:
                self.on_bundle(path, reason)
            except Exception:  # pragma: no cover - hook must not kill dump
                traceback.print_exc()
        return path

    # ------------------------------------------------------------ signals
    def _install_signal_handlers(self) -> None:
        for name in self.signals:
            sig = _SIG_BY_NAME.get(name)
            if sig is None:
                continue
            try:
                self._prev_handlers[sig] = signal.signal(
                    sig, self._on_signal)
            except ValueError:
                # not the main thread: the stall timer still works;
                # signal dumps just aren't available from here
                break

    def _restore_signal_handlers(self) -> None:
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # pragma: no cover
                pass
        self._prev_handlers.clear()

    def _on_signal(self, signum, frame) -> None:
        name = signal.Signals(signum).name
        self.dump(f"signal:{name}")
        if signum == signal.SIGTERM:
            # preserve the killer's semantics: restore whatever handler
            # was there and re-deliver, so `timeout` still reports 124
            # and the process still dies 143
            prev = self._prev_handlers.pop(signum, signal.SIG_DFL)
            try:
                signal.signal(signum, prev)
            except (ValueError, TypeError):  # pragma: no cover
                pass
            os.kill(os.getpid(), signum)

    # --------------------------------------------------------------- loop
    def _run(self) -> None:
        # re-arm on any BEAT (age drops below the threshold), not on the
        # step count: a stall before the first committed step leaves the
        # step unchanged, and keying on it would eat every later dump —
        # driving() stamps the clock on entry, so each new generate loop
        # re-arms even if the previous one died step-less
        fired = False
        while not self._stop.wait(self.poll_interval):
            st = self.heartbeat.state()
            if (st["active"] <= 0 or st["age_s"] is None
                    or st["age_s"] <= self.stall_timeout):
                fired = False
                continue
            if not fired:
                fired = True                 # once per stall
                self.stall_count += 1
                self.dump(f"stall>{self.stall_timeout:g}s")
