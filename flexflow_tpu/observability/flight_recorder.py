"""FlightRecorder: a bounded in-memory black box for the serving stack.

The metrics registry and step tracer (PR 3) only help when a run
*finishes* — a hung collective, a recompile loop, or a dead tunnel
leaves nothing but whatever stderr survived the kill (the BENCH_r05
``rc: 124, parsed: null`` failure mode).  The idiom proven by
distributed-runtime flight recorders (the NCCL / PyTorch-distributed
flight recorder) is a fixed-size ring of structured events that is
ALWAYS on and dumped on stall, signal or crash, so the last thing the
process did is readable post mortem.

Design constraints:

- **Bounded memory always**: a ``collections.deque(maxlen=capacity)``
  of small dicts; old events fall off the far end (``dropped`` counts
  them) no matter how long the process serves.
- **Near-zero cost when disabled** (``FF_TELEMETRY=0``): every
  ``record_event`` starts with one attribute read and returns.
  Enabled, the cost is one monotonic() read + one lock + one deque
  append per event — events are per driver-loop *phase*, not per token.
- **Schema-validated names**: undeclared event names raise — the
  vocabulary in ``schema.EVENT_SCHEMA`` is shared with the StepTracer
  and the fflint ``metric-schema`` rule checks call sites statically.
- **Thread-safe**: drivers, the watchdog thread and signal handlers all
  read/write concurrently; every ring touch takes the lock.

Events carry ``seq`` (monotonically increasing, so drops are visible),
``t`` (``time.monotonic()``), ``name``, and whatever payload the site
passes (``guid``, ``step``, ``chunk``, ...).  ``snapshot()`` anchors the
monotonic clock to wall time so dumps correlate with logs.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .schema import EVENT_SCHEMA

#: ring capacity default (events, not bytes); override per-recorder or
#: via FF_FLIGHT_EVENTS for the process-wide one.
DEFAULT_CAPACITY = 2048


class FlightRecorder:
    """Fixed-size, thread-safe ring buffer of structured serving events."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool = True,
                 schema: Optional[Dict[str, Dict]] = EVENT_SCHEMA):
        self.capacity = max(1, int(capacity))
        self.enabled = enabled
        self._names = frozenset(schema) if schema is not None else None
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        # RLock, not Lock: snapshot() runs inside watchdog SIGNAL
        # handlers, which execute at an arbitrary bytecode boundary of
        # the main thread — if that thread is mid-record_event, a plain
        # Lock would self-deadlock the dump (the class fflint's
        # lock-discipline rule guards against)
        self._lock = threading.RLock()
        self._seq = 0
        # wall/monotonic anchor pair: event["t"] - t0_mono + t0_wall
        # reconstructs a wall-clock stamp for log correlation
        self._t0_wall = time.time()
        self._t0_mono = time.monotonic()

    # --------------------------------------------------------------- emit
    def record_event(self, name: str, **payload: Any) -> None:
        """Append one event; no-op when disabled (one attribute read).
        Unknown names raise ``ValueError`` — declare new events in
        ``observability/schema.py::EVENT_SCHEMA`` first."""
        if not self.enabled:
            return
        if self._names is not None and name not in self._names:
            raise ValueError(
                f"flight-recorder event {name!r} is not declared in "
                f"observability/schema.py EVENT_SCHEMA — declare it "
                f"(with help text) before emitting it")
        ev: Dict[str, Any] = dict(payload)
        ev["name"] = name
        with self._lock:
            # timestamp under the lock: ring order (seq) must agree
            # with t — ffstat/trace_summary derive per-phase wall time
            # from consecutive-event deltas in ring order
            ev["t"] = time.monotonic()
            ev["seq"] = self._seq
            self._seq += 1
            self._ring.append(ev)

    # --------------------------------------------------------------- read
    @property
    def recorded(self) -> int:
        """Total events ever recorded (ring holds the last ``capacity``)."""
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._seq - len(self._ring))

    def events(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        """Copy of the ring (oldest first); ``last`` keeps only the tail."""
        with self._lock:
            evs = list(self._ring)
        return evs[-last:] if last else evs

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._t0_wall = time.time()
            self._t0_mono = time.monotonic()

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable dump: the full ring plus clock anchors and
        drop accounting (the ``flight_record`` section of a watchdog
        bundle)."""
        with self._lock:
            # the anchors are rewritten by clear(): reading them in the
            # same critical section as the ring keeps a concurrent
            # clear() from pairing old events with new anchors
            evs = list(self._ring)
            seq = self._seq
            t0_wall, t0_mono = self._t0_wall, self._t0_mono
        return {
            "capacity": self.capacity,
            "recorded": seq,
            "dropped": max(0, seq - len(evs)),
            "t0_wall": t0_wall,
            "t0_mono": t0_mono,
            "events": evs,
        }


_RECORDER = FlightRecorder(
    capacity=int(os.environ.get("FF_FLIGHT_EVENTS", str(DEFAULT_CAPACITY))
                 or DEFAULT_CAPACITY),
    enabled=os.environ.get("FF_TELEMETRY", "1") != "0")


def get_flight_recorder() -> FlightRecorder:
    """The process-wide flight recorder (always allocated; inert when
    FF_TELEMETRY=0)."""
    return _RECORDER
