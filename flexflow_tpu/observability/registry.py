"""MetricsRegistry: counters, gauges and histograms for the serving stack.

The reference scatters its serving observability across per-kernel
``--profiling`` timers and the request manager's ``ProfileInfo`` dump
(request_manager.cc:404-441); this registry is the rebuild's single
emission surface.  Design constraints:

- **Near-zero cost when disabled**: every mutation starts with one
  attribute read (``registry.enabled``) and returns — no lock, no dict
  touch, no allocation.  The serving drivers keep their metric handles
  as attributes, so the enabled check is the only per-step cost.
- **Thread-safe**: mutations take the registry lock (serving is mostly
  single-threaded host-side, but bench harnesses and future async
  servers are not; the lock is uncontended in the common case).
- **Fixed exponential buckets**: histograms bucket into a fixed
  ladder (default 100 µs · 2^i) so snapshots are mergeable across
  processes and rounds; exact percentiles additionally come from the
  bucket counts by linear interpolation.
- **Schema-validated names**: the default registry refuses metric names
  not declared in ``schema.METRICS_SCHEMA`` — the runtime half of the
  ``tools/check_metrics_schema.py`` static gate.
"""

from __future__ import annotations

import bisect
import threading
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple


def exp_buckets(start: float = 1e-4, factor: float = 2.0,
                count: int = 22) -> Tuple[float, ...]:
    """The fixed exponential bucket ladder: ``start * factor**i``.
    Defaults span 100 µs .. ~210 s — TTFT, TPOT and step latencies all
    land mid-ladder."""
    return tuple(start * factor ** i for i in range(count))


DEFAULT_BUCKETS = exp_buckets()


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _fmt_labels(key: Tuple[Tuple[str, str], ...]) -> str:
    return ",".join(f"{k}={v}" for k, v in key)


class _Metric:
    kind = "metric"

    def __init__(self, registry: "MetricsRegistry", name: str,
                 help: str = ""):
        self._reg = registry
        self.name = name
        self.help = help


class Counter(_Metric):
    """Monotonically increasing count, optionally split by labels
    (e.g. ``inc(path="flash", reason="cost_model")``)."""

    kind = "counter"

    def __init__(self, registry, name, help=""):
        super().__init__(registry, name, help)
        self._values: Dict[Tuple, float] = {}

    def inc(self, n: float = 1, **labels):
        reg = self._reg
        if not reg.enabled:
            return
        key = _label_key(labels) if labels else ()
        with reg._lock:
            self._values[key] = self._values.get(key, 0) + n

    def value(self, **labels) -> float:
        if labels:
            return self._values.get(_label_key(labels), 0)
        return sum(self._values.values())

    def _reset(self):
        self._values.clear()

    def snapshot(self):
        if not self._values or set(self._values) == {()}:
            return self._values.get((), 0)
        return {"total": self.value(),
                "labels": {_fmt_labels(k): v
                           for k, v in sorted(self._values.items()) if k}}


class Gauge(_Metric):
    """Last-set value, optionally split by labels."""

    kind = "gauge"

    def __init__(self, registry, name, help=""):
        super().__init__(registry, name, help)
        self._values: Dict[Tuple, float] = {}

    def set(self, v: float, **labels):
        reg = self._reg
        if not reg.enabled:
            return
        key = _label_key(labels) if labels else ()
        with reg._lock:
            self._values[key] = v

    def value(self, **labels) -> float:
        return self._values.get(_label_key(labels) if labels else (), 0)

    def _reset(self):
        self._values.clear()

    def snapshot(self):
        if not self._values or set(self._values) == {()}:
            return self._values.get((), 0)
        return {_fmt_labels(k) or "_": v
                for k, v in sorted(self._values.items())}


class _HistState:
    """One histogram series' mutable state (the aggregate, plus one per
    label combination when a histogram observes with labels)."""

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +overflow
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def add(self, buckets: Tuple[float, ...], v: float) -> None:
        self.counts[bisect.bisect_left(buckets, v)] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v


class Histogram(_Metric):
    """Fixed-bucket histogram with count/sum/min/max and
    bucket-interpolated percentiles.  ``observe(v, **labels)`` with
    labels additionally tracks a per-label-combination series (the
    devprof per-(phase, path) device-seconds split); the top-level
    count/sum/percentiles stay the aggregate over every observation,
    so unlabeled callers and existing snapshot consumers see the exact
    pre-labels shape."""

    kind = "histogram"

    def __init__(self, registry, name, help="", buckets=None):
        super().__init__(registry, name, help)
        self.buckets: Tuple[float, ...] = tuple(buckets or DEFAULT_BUCKETS)
        assert list(self.buckets) == sorted(self.buckets), (
            f"{name}: bucket bounds must be sorted")
        self._agg = _HistState(len(self.buckets))
        self._series: Dict[Tuple, _HistState] = {}

    def observe(self, v: float, **labels):
        reg = self._reg
        if not reg.enabled:
            return
        v = float(v)
        with reg._lock:
            self._agg.add(self.buckets, v)
            if labels:
                key = _label_key(labels)
                st = self._series.get(key)
                if st is None:
                    st = self._series[key] = _HistState(len(self.buckets))
                st.add(self.buckets, v)

    @property
    def count(self) -> int:
        return self._agg.count

    @property
    def sum(self) -> float:
        return self._agg.sum

    def _percentile_of(self, st: _HistState, p: float) -> float:
        if st.count == 0:
            return 0.0
        target = (p / 100.0) * st.count
        cum = 0
        for i, c in enumerate(st.counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = self.buckets[i - 1] if i > 0 else min(
                    st.min, self.buckets[0])
                hi = (self.buckets[i] if i < len(self.buckets)
                      else st.max)
                frac = (target - cum) / c
                est = lo + (hi - lo) * max(0.0, min(1.0, frac))
                return max(st.min, min(st.max, est))
            cum += c
        return st.max

    def percentile(self, p: float) -> float:
        """Estimate the p-th percentile from the bucket counts by linear
        interpolation inside the target bucket (clamped to the observed
        min/max so the estimate never leaves the data's range)."""
        return self._percentile_of(self._agg, p)

    def _reset(self):
        self._agg = _HistState(len(self.buckets))
        self._series.clear()

    def _snap_state(self, st: _HistState):
        out = {"count": st.count, "sum": round(st.sum, 6)}
        if st.count:
            out.update(
                min=round(st.min, 6), max=round(st.max, 6),
                mean=round(st.sum / st.count, 6),
                p50=round(self._percentile_of(st, 50), 6),
                p90=round(self._percentile_of(st, 90), 6),
                p99=round(self._percentile_of(st, 99), 6),
                buckets={f"le_{b:g}": c
                         for b, c in zip(self.buckets, st.counts)
                         if c} | ({"overflow": st.counts[-1]}
                                  if st.counts[-1] else {}))
        return out

    def snapshot(self):
        out = self._snap_state(self._agg)
        if self._series:
            out["series"] = {_fmt_labels(k): self._snap_state(st)
                             for k, st in sorted(self._series.items())}
        return out


class MetricsRegistry:
    """Named metric store.  ``schema`` (name -> {type, help[, buckets]})
    makes creation strict: undeclared names raise, declared helps/buckets
    apply automatically.  ``schema=None`` is permissive (ad-hoc test
    registries)."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self, schema: Optional[Dict[str, Dict]] = None,
                 enabled: bool = True):
        self._metrics: Dict[str, _Metric] = {}
        # RLock: snapshot() runs inside watchdog signal handlers (the
        # bundle's "metrics" section) — a plain Lock self-deadlocks if
        # the signal lands while this thread is mid-inc/observe
        self._lock = threading.RLock()
        self._schema = schema
        self.enabled = enabled

    # ------------------------------------------------------------- control
    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    def reset(self):
        """Zero every metric IN PLACE — handles held by serving modules
        stay valid (drivers cache them as attributes)."""
        with self._lock:
            for m in self._metrics.values():
                m._reset()

    # ------------------------------------------------------------ creation
    def _get(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"requested {cls.kind}")
                return m
            if self._schema is not None:
                decl = self._schema.get(name)
                if decl is None:
                    raise ValueError(
                        f"metric {name!r} is not declared in the metrics "
                        f"schema (flexflow_tpu/observability/schema.py) — "
                        f"declare name, type and help there first")
                if decl["type"] != cls.kind:
                    raise TypeError(
                        f"metric {name!r} declared as {decl['type']}, "
                        f"requested {cls.kind}")
                help = help or decl.get("help", "")
                if cls is Histogram and decl.get("buckets") is not None:
                    kw.setdefault("buckets", decl["buckets"])
            m = cls(self, name, help, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        kw = {"buckets": buckets} if buckets is not None else {}
        return self._get(Histogram, name, help, **kw)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """One JSON-serializable dict of every metric's current state,
        grouped by kind."""
        with self._lock:
            out: Dict[str, Dict[str, Any]] = {
                "counters": {}, "gauges": {}, "histograms": {}}
            for name, m in sorted(self._metrics.items()):
                out[m.kind + "s"][name] = m.snapshot()
            return out

    def expose_text(self) -> str:
        """Prometheus text exposition (format 0.0.4) of the current
        state — write it behind any HTTP/file endpoint and snapshots are
        scrapeable off-box.  Rendered from :meth:`snapshot` so a dumped
        snapshot (stall bundle, bench record) produces the identical
        text via :func:`prometheus_text`."""
        return prometheus_text(self.snapshot(), schema=self._schema)


# -------------------------------------------------- prometheus rendering
def _prom_labels(label_str: str) -> str:
    """``"path=flash,reason=x"`` -> ``{path="flash",reason="x"}``."""
    if not label_str or label_str == "_":
        return ""
    pairs = []
    for part in label_str.split(","):
        k, _, v = part.partition("=")
        v = v.replace("\\", "\\\\").replace('"', '\\"')
        pairs.append(f'{k}="{v}"')
    return "{" + ",".join(pairs) + "}"


def prometheus_text(snapshot: Dict[str, Dict[str, Any]],
                    schema: Optional[Dict[str, Dict]] = None) -> str:
    """Render a :meth:`MetricsRegistry.snapshot` dict as Prometheus text
    exposition.  Pure function of the snapshot so off-process tooling
    (tools/ffstat.py ``--prom``) renders dumped bundles identically to a
    live registry.  Histograms emit cumulative ``_bucket{le=...}``
    series (+Inf included) plus ``_sum``/``_count``."""
    lines = []

    def _help(name: str) -> None:
        decl = (schema or {}).get(name) or {}
        h = " ".join(str(decl.get("help", "")).split())
        if h:
            lines.append(f"# HELP {name} {h}")

    for name, snap in (snapshot.get("counters") or {}).items():
        _help(name)
        lines.append(f"# TYPE {name} counter")
        if isinstance(snap, dict):
            for label_str, v in (snap.get("labels") or {}).items():
                lines.append(f"{name}{_prom_labels(label_str)} {v:g}")
            if not snap.get("labels"):
                lines.append(f"{name} {snap.get('total', 0):g}")
        else:
            lines.append(f"{name} {snap:g}")
    for name, snap in (snapshot.get("gauges") or {}).items():
        _help(name)
        lines.append(f"# TYPE {name} gauge")
        if isinstance(snap, dict):
            for label_str, v in snap.items():
                lines.append(f"{name}{_prom_labels(label_str)} {v:g}")
        else:
            lines.append(f"{name} {snap:g}")
    def _hist_series(name: str, snap: Dict[str, Any],
                     label_str: str = "") -> None:
        prefix = _prom_labels(label_str)
        # merge the series labels with le= (prometheus histogram form)
        pre = prefix[:-1] + "," if prefix else "{"
        count = int(snap.get("count", 0))
        cum = 0
        for le, c in (snap.get("buckets") or {}).items():
            if le == "overflow":
                continue
            cum += int(c)
            bound = le[len("le_"):]
            lines.append(f'{name}_bucket{pre}le="{bound}"}} {cum}')
        lines.append(f'{name}_bucket{pre}le="+Inf"}} {count}')
        lines.append(f"{name}_sum{prefix} {snap.get('sum', 0.0):g}")
        lines.append(f"{name}_count{prefix} {count}")

    for name, snap in (snapshot.get("histograms") or {}).items():
        _help(name)
        lines.append(f"# TYPE {name} histogram")
        series = snap.get("series")
        if series:
            # labeled histogram (per-series buckets): each label combo
            # is its own prometheus series — the aggregate would alias
            # the empty label set, so only the labeled series render
            for label_str, sub in series.items():
                _hist_series(name, sub, label_str)
        else:
            _hist_series(name, snap)
    return "\n".join(lines) + "\n"
