"""RequestLedger: per-request lifecycle timelines + SLO/goodput accounting.

Everything else in this package is aggregate: the registry's TTFT/TPOT
histograms mix every request together and the flight recorder's ring is
batch-scoped.  The ROADMAP's async-serving item needs *per-request*
SLO-goodput reporting (TTFT/TPOT attainment, not just throughput) — the
reference likewise tracks each BatchConfig slot's request individually
through admit/decode/commit (ProfileInfo, request_manager.h:244-250) so
latency is attributable to a request, not a batch.  The ledger is that
accounting layer: one timeline per request GUID, assembled from the
same driver sites that feed the recorder/tracer, with an SLO policy
evaluated per retired request and goodput (tokens from SLO-attaining
requests per second) derived from the retired window.

Design constraints (shared with the FlightRecorder):

- **Near-zero cost when disabled** (``FF_TELEMETRY=0``): every
  ``note_event`` starts with one attribute read and returns.
- **Bounded memory always**: live timelines are bounded by the serving
  queue itself plus a hard cap (oldest dropped, counted); retired
  timelines live in a fixed-capacity ring; each timeline's event list
  is a fixed-size ring of small dicts.
- **Schema-validated names**: ``note_event`` names must be declared in
  ``schema.EVENT_SCHEMA`` — the same vocabulary the recorder/tracer
  use, and the fflint ``metric-schema`` rule checks the call sites
  statically.
- **Thread-safe**: drivers feed while bench harnesses snapshot and the
  watchdog bundles from signal handlers; every touch takes the RLock
  (re-entrant: ``snapshot()`` runs inside signal handlers that can
  interrupt a mid-``note_event`` main thread).

Event routing: a ``guid=`` event lands on that request's timeline
(creating it lazily); a guid-less event (decode-step, prefill-chunk,
spec-draft/verify, host-sync, compile) broadcasts to every ADMITTED
in-flight timeline — a request's timeline contains the driver steps it
lived through.  Lifecycle names get extra bookkeeping:

- ``enqueue``   creates the timeline (queue entry stamp);
- ``admit``     stamps ``admit_mono`` — the TTFT clock start (see
  docs/OBSERVABILITY.md: TTFT measures admit -> first token, so a warm
  prefix hit is credited for the prefill it skipped, not for queue
  luck; enqueue -> admit is reported separately as ``queue_s``);
- ``prefix-match`` records the matched prefix length;
- ``commit``    accumulates committed tokens + stamps first/last
  commit (inter-token gaps -> per-request TPOT);
- ``retire``    finalizes: the driver passes the authoritative
  ProfileInfo latencies (``ttft_s``/``tpot_s``/...) so ledger numbers
  reconcile EXACTLY with the profile path (pinned by test), evaluates
  the SLO policy, moves the timeline to the retired ring and updates
  the ``serving_slo_*`` / ``serving_goodput_tokens_per_s`` gauges.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

from .schema import EVENT_SCHEMA

#: retired-timeline ring capacity (requests) / per-timeline event ring
#: capacity (events) / live-timeline hard cap.  Env-overridable for
#: the process-wide ledger via FF_LEDGER_RETIRED / FF_LEDGER_EVENTS /
#: FF_LEDGER_LIVE.
DEFAULT_RETIRED = 256
DEFAULT_EVENTS = 128
DEFAULT_LIVE = 4096


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """Per-request latency targets.  ``None`` disables that component.

    - ``ttft_s``: time-to-first-token budget (admit -> first committed
      token, host-observed monotonic).
    - ``tpot_s``: time-per-output-token budget (mean inter-token gap
      after the first token).

    A request ATTAINS the SLO when every configured component holds.
    A request that never produced a token fails a configured TTFT
    target; a single-token request has no inter-token gap and passes
    any TPOT target vacuously.
    """

    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None

    def evaluate(self, ttft_s: Optional[float],
                 tpot_s: Optional[float]) -> Dict[str, bool]:
        ttft_ok = (self.ttft_s is None
                   or (ttft_s is not None and ttft_s <= self.ttft_s))
        tpot_ok = (self.tpot_s is None
                   or tpot_s is None or tpot_s <= self.tpot_s)
        return {"ttft_ok": ttft_ok, "tpot_ok": tpot_ok,
                "attained": ttft_ok and tpot_ok}


def slo_report_from(timelines: Iterable[Dict[str, Any]],
                    policy: SLOPolicy) -> Dict[str, Any]:
    """Pure attainment + goodput report over RETIRED timeline dicts —
    shared by the live ledger, ``tools/ffreq.py`` (dumped snapshots)
    and the bench ``slo`` block, so all three agree by construction.

    Goodput = tokens from SLO-attaining requests / the retired window's
    wall span (first admit -> last retire, monotonic).  When the span
    is unavailable (timelines without admit/retire stamps) the summed
    latencies stand in, so the number stays finite and honest.
    """
    retired = [t for t in timelines if t.get("retired")]
    n = len(retired)
    out: Dict[str, Any] = {
        "policy": {"ttft_s": policy.ttft_s, "tpot_s": policy.tpot_s},
        "requests": n,
        # cancelled requests (deadline/shed/disconnect) stay in the
        # window: a shed request that produced nothing fails a TTFT
        # target and honestly drags attainment — goodput only ever
        # counts attaining requests' tokens
        "cancelled": sum(1 for t in retired if t.get("cancelled")),
    }
    if not n:
        out.update(attained=0, attainment=None, ttft_attainment=None,
                   tpot_attainment=None, total_tokens=0,
                   attained_tokens=0, window_s=0.0,
                   goodput_tokens_per_s=0.0, slowest=None)
        return out
    ttft_ok = tpot_ok = attained = 0
    tok_total = tok_attained = 0
    t_lo, t_hi, lat_sum = float("inf"), float("-inf"), 0.0
    slowest = None

    def _slow_key(t):
        # ttft_s=None means NO token was ever produced — the worst
        # case, not the fastest: rank it above any finite TTFT
        v = t.get("ttft_s")
        return float("inf") if v is None else float(v)

    for t in retired:
        v = policy.evaluate(t.get("ttft_s"), t.get("tpot_s"))
        ttft_ok += v["ttft_ok"]
        tpot_ok += v["tpot_ok"]
        attained += v["attained"]
        toks = int(t.get("tokens") or 0)
        tok_total += toks
        if v["attained"]:
            tok_attained += toks
        a = t.get("admit_mono")
        r = t.get("retire_mono")
        if a is not None:
            t_lo = min(t_lo, a)
        if r is not None:
            t_hi = max(t_hi, r)
        lat_sum += float(t.get("latency_s") or 0.0)
        if slowest is None or _slow_key(t) > _slow_key(slowest):
            slowest = t
    span = t_hi - t_lo if t_hi > t_lo else 0.0
    window = max(span if span > 0 else lat_sum, 1e-9)
    out.update(
        attained=attained,
        attainment=round(attained / n, 4),
        ttft_attainment=round(ttft_ok / n, 4),
        tpot_attainment=round(tpot_ok / n, 4),
        total_tokens=tok_total,
        attained_tokens=tok_attained,
        window_s=round(window, 6),
        goodput_tokens_per_s=round(tok_attained / window, 3),
        slowest=slowest,
    )
    return out


def validate_slo_block(block: Dict[str, Any]) -> List[str]:
    """Structural check of an ``slo`` report block (bench records, ffreq
    ``--slo``) — returns the list of violations (empty = valid).  The
    runtime twin of the metric schema: a round record claiming goodput
    must carry every field a trajectory reader parses."""
    errs: List[str] = []
    if not isinstance(block, dict):
        return [f"slo block is {type(block).__name__}, expected dict"]
    for key in ("policy", "requests", "attained", "attainment",
                "ttft_attainment", "tpot_attainment", "total_tokens",
                "attained_tokens", "window_s", "goodput_tokens_per_s",
                "slowest"):
        if key not in block:
            errs.append(f"missing key {key!r}")
    pol = block.get("policy")
    if not (isinstance(pol, dict) and {"ttft_s", "tpot_s"} <= set(pol)):
        errs.append("policy must carry ttft_s and tpot_s")
    n = block.get("requests")
    if not isinstance(n, int) or n < 0:
        errs.append("requests must be a non-negative int")
    if n:
        for key in ("attainment", "ttft_attainment", "tpot_attainment"):
            v = block.get(key)
            if not (isinstance(v, (int, float)) and 0.0 <= v <= 1.0):
                errs.append(f"{key} must be a 0..1 fraction, got {v!r}")
        g = block.get("goodput_tokens_per_s")
        if not (isinstance(g, (int, float)) and g >= 0):
            errs.append(f"goodput_tokens_per_s must be >= 0, got {g!r}")
        if not isinstance(block.get("slowest"), dict):
            errs.append("slowest must be the slowest request's timeline")
    return errs


class RequestLedger:
    """Thread-safe per-request lifecycle ledger (see module docstring)."""

    def __init__(self, retired_capacity: int = DEFAULT_RETIRED,
                 events_per_request: int = DEFAULT_EVENTS,
                 live_capacity: int = DEFAULT_LIVE,
                 enabled: bool = True,
                 schema: Optional[Dict[str, Dict]] = EVENT_SCHEMA):
        self.retired_capacity = max(1, int(retired_capacity))
        self.events_per_request = max(8, int(events_per_request))
        self.live_capacity = max(1, int(live_capacity))
        self.enabled = enabled
        self._names = frozenset(schema) if schema is not None else None
        # RLock, not Lock: snapshot() runs inside watchdog signal
        # handlers, which execute at an arbitrary bytecode boundary of
        # the main thread — if that thread is mid-note_event, a plain
        # Lock would self-deadlock the dump (fflint lock-discipline)
        self._lock = threading.RLock()
        self._live: "collections.OrderedDict[int, Dict]" = \
            collections.OrderedDict()
        # admitted-but-not-retired subset of _live: guid-less broadcast
        # events land on these, and they arrive once per driver-loop
        # phase — indexing the <= batch-size admitted set keeps the
        # broadcast O(batch) instead of O(pending queue depth)
        self._admitted: Dict[int, Dict] = {}
        self._retired: "collections.OrderedDict[int, Dict]" = \
            collections.OrderedDict()
        self._retired_dropped = 0
        self._live_dropped = 0
        self._policy: Optional[SLOPolicy] = None

    # ---------------------------------------------------------------- feed
    def note_event(self, name: str, guid: Optional[int] = None,
                   **payload: Any) -> None:
        """Feed one lifecycle event; no-op when disabled (one attribute
        read).  Unknown names raise ``ValueError`` — declare new events
        in ``observability/schema.py::EVENT_SCHEMA`` first (the fflint
        ``metric-schema`` rule checks these call sites statically, same
        as ``record_event``).  ``guid=None`` broadcasts to every
        admitted in-flight timeline."""
        if not self.enabled:
            return
        if self._names is not None and name not in self._names:
            raise ValueError(
                f"ledger event {name!r} is not declared in "
                f"observability/schema.py EVENT_SCHEMA — declare it "
                f"(with help text) before emitting it")
        with self._lock:
            now = time.monotonic()
            if guid is None:
                for t in self._admitted.values():
                    self._append(t, now, name, payload)
                return
            t = self._live.get(guid)
            if t is None:
                if name in ("retire", "cancel") or guid in self._retired:
                    return          # late event for an already-gone guid
                t = self._new_timeline(guid, now, payload)
                if name != "enqueue":
                    # a driver feeding a request the ledger never saw
                    # enqueued (enabled mid-run): lazily created above
                    t["enqueue_mono"] = None
            self._append(t, now, name, payload)
            if payload.get("trace_id") is not None:
                # distributed trace context (observability/traceplane):
                # any event may carry it (enqueue from a traced submit,
                # or a later trace-adopt), and the SCALARS are what the
                # TraceAssembler joins on — event rings can evict
                t["trace_id"] = str(payload["trace_id"])
                if payload.get("hop") is not None:
                    t["hop"] = int(payload["hop"])
            retired_with_policy = False
            if name == "admit":
                t["admit_mono"] = now
                t["row"] = payload.get("row")
                self._admitted[t["guid"]] = t
                if t["enqueue_mono"] is not None:
                    t["queue_s"] = now - t["enqueue_mono"]
            elif name == "prefix-match":
                t["prefix_matched"] = int(payload.get("matched", 0))
            elif name == "preempt":
                # un-admit: the request left its row for the pending
                # queue — broadcast driver events must stop landing on
                # it until the next admit (paged KV preemption)
                t["preempts"] += 1
                self._admitted.pop(t["guid"], None)
            elif name == "restore":
                t["restored_tokens"] += int(payload.get("tokens", 0))
            elif name == "commit":
                n = int(payload.get("tokens", 0))
                t["committed"] += n
                t["commit_events"] += 1
                t["accepted"] += int(payload.get("accepted", 0))
                if n > 0:
                    if t["first_commit_mono"] is None:
                        t["first_commit_mono"] = now
                        t["first_commit_tokens"] = n
                    t["last_commit_mono"] = now
            elif name == "retire":
                self._finalize(t, now, payload)
                retired_with_policy = self._policy is not None
            elif name == "cancel":
                # the cancel twin of retire: finalizes the timeline
                # into the retired ring with cancelled=True so the
                # committed-token reconciliation and the SLO window
                # keep covering it (a shed/deadline cancel IS an SLO
                # outcome, not a vanished request)
                t["cancelled"] = True
                t["cancel_reason"] = payload.get("reason")
                self._finalize(t, now, payload)
                retired_with_policy = self._policy is not None
        if retired_with_policy:
            # gauges refresh OUTSIDE the ledger lock (the report itself
            # briefly re-takes it): registry-lock acquisition must never
            # happen with the ledger lock held, or a future registry ->
            # ledger call path would deadlock
            self._update_slo_gauges()

    def _new_timeline(self, guid: int, now: float,
                      payload: Dict[str, Any]) -> Dict[str, Any]:
        # re-entrant re-acquire (already held by note_event): every
        # guarded-field touch sits lexically under the lock, which is
        # both what the fflint lock-discipline rule checks and what
        # keeps this helper safe if ever called from a new site
        with self._lock:
            while len(self._live) >= self.live_capacity:
                evicted_guid, _ = self._live.popitem(last=False)
                self._admitted.pop(evicted_guid, None)
                self._live_dropped += 1
            t = self._blank_timeline(guid, now, payload)
            self._live[guid] = t
            return t

    def _blank_timeline(self, guid: int, now: float,
                        payload: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "guid": guid,
            "trace_id": None, "hop": None,
            "prompt_len": payload.get("prompt_len"),
            "enqueue_wall": time.time(),
            "enqueue_mono": now,
            "admit_mono": None, "row": None, "queue_s": None,
            "prefix_matched": 0,
            "committed": 0, "commit_events": 0,
            "first_commit_mono": None, "first_commit_tokens": 0,
            "last_commit_mono": None,
            "accepted": 0, "speculated": 0,
            "preempts": 0, "restored_tokens": 0,
            "cancelled": False, "cancel_reason": None,
            "retired": False, "retire_mono": None,
            "tokens": None, "ttft_s": None, "tpot_s": None,
            "latency_s": None, "slo": None,
            "events": collections.deque(maxlen=self.events_per_request),
            "events_dropped": 0,
        }

    def _append(self, t: Dict, now: float, name: str,
                payload: Dict[str, Any]) -> None:
        ev = {k: v for k, v in payload.items() if k != "prompt_len"}
        ev["name"] = name
        ev["t"] = now
        if len(t["events"]) == t["events"].maxlen:
            t["events_dropped"] += 1
        t["events"].append(ev)

    def _finalize(self, t: Dict, now: float,
                  payload: Dict[str, Any]) -> None:
        # re-entrant re-acquire — see _new_timeline
        with self._lock:
            t["retired"] = True
            t["retire_mono"] = now
            t["tokens"] = int(payload.get("tokens", t["committed"]))
            t["accepted"] = int(payload.get("accepted", t["accepted"]))
            t["speculated"] = int(payload.get("speculated",
                                              t["speculated"]))
            if payload.get("prefix_matched") is not None:
                t["prefix_matched"] = int(payload["prefix_matched"])
            # the driver passes the authoritative ProfileInfo stamps so
            # the ledger and profile paths reconcile exactly; own stamps
            # are the fallback for feeds outside a RequestManager
            # (tests, ffreq)
            t["ttft_s"] = payload.get("ttft_s", self._own_ttft(t))
            t["tpot_s"] = payload.get("tpot_s", self._own_tpot(t))
            if payload.get("latency_s") is not None:
                t["latency_s"] = float(payload["latency_s"])
            elif t["admit_mono"] is not None:
                t["latency_s"] = now - t["admit_mono"]
            if payload.get("queue_s") is not None:
                t["queue_s"] = float(payload["queue_s"])
            if self._policy is not None:
                t["slo"] = self._policy.evaluate(t["ttft_s"], t["tpot_s"])
            self._live.pop(t["guid"], None)
            self._admitted.pop(t["guid"], None)
            self._retired[t["guid"]] = t
            while len(self._retired) > self.retired_capacity:
                self._retired.popitem(last=False)
                self._retired_dropped += 1

    @staticmethod
    def _own_ttft(t: Dict) -> Optional[float]:
        start = (t["admit_mono"] if t["admit_mono"] is not None
                 else t["enqueue_mono"])
        if t["first_commit_mono"] is None or start is None:
            return None
        return t["first_commit_mono"] - start

    @staticmethod
    def _own_tpot(t: Dict) -> Optional[float]:
        gap_tokens = t["committed"] - t["first_commit_tokens"]
        if (t["first_commit_mono"] is None or gap_tokens <= 0
                or t["last_commit_mono"] is None):
            return None
        return (t["last_commit_mono"] - t["first_commit_mono"]) / gap_tokens

    def _update_slo_gauges(self) -> None:
        """Refresh the serving_slo_* / goodput gauges from the retired
        window — called by note_event AFTER releasing the ledger lock
        (the report scan below takes it briefly; the registry-lock
        acquisitions in the gauge writes never overlap a ledger-lock
        hold).  Cost is one O(retired_capacity) scan per RETIREMENT —
        bounded at 256 small dicts by default and far rarer than
        per-step feeds; running O(1) aggregates would need
        eviction-time window adjustment for the admit/retire bounds —
        not worth it at this cap."""
        with self._lock:
            pol = self._policy
            if pol is None:
                return
            rep = slo_report_from(self._retired.values(), pol)
        if not rep["requests"]:
            return
        try:
            from . import get_registry
        except ImportError:         # pragma: no cover - partial install
            return
        m = get_registry()
        m.gauge("serving_slo_attainment").set(rep["attainment"])
        m.gauge("serving_slo_ttft_attainment").set(rep["ttft_attainment"])
        m.gauge("serving_slo_tpot_attainment").set(rep["tpot_attainment"])
        m.gauge("serving_goodput_tokens_per_s").set(
            rep["goodput_tokens_per_s"])

    # ---------------------------------------------------------------- read
    def set_slo_policy(self, policy: Optional[SLOPolicy]) -> None:
        with self._lock:
            self._policy = policy

    def slo_policy(self) -> Optional[SLOPolicy]:
        with self._lock:
            return self._policy

    def in_flight_guids(self) -> List[int]:
        """GUIDs admitted but not retired (stall suspects — ffstat
        names these in its bundle diagnosis)."""
        with self._lock:
            return list(self._admitted)

    def timeline(self, guid: int) -> Optional[Dict[str, Any]]:
        """JSON-serializable copy of one request's timeline (live or
        retired), or None."""
        with self._lock:
            t = self._live.get(guid) or self._retired.get(guid)
            return self._export(t) if t is not None else None

    def timelines(self, include_live: bool = True,
                  include_retired: bool = True) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            if include_retired:
                out.extend(self._export(t)
                           for t in self._retired.values())
            if include_live:
                out.extend(self._export(t) for t in self._live.values())
            return out

    def timelines_for_trace(self, trace_id: str) -> List[Dict[str, Any]]:
        """Every timeline (live + retired) stamped with ``trace_id`` —
        this process's contribution to one distributed trace (the
        ``/v1/timelines?trace=`` payload the TraceAssembler merges)."""
        with self._lock:
            return [self._export(t)
                    for store in (self._retired, self._live)
                    for t in store.values()
                    if t.get("trace_id") == trace_id]

    def ttft_of(self, guid: int) -> Optional[float]:
        with self._lock:
            t = self._retired.get(guid) or self._live.get(guid)
            if t is None:
                return None
            return t["ttft_s"] if t["retired"] else self._own_ttft(t)

    def committed_of(self, guid: int) -> Optional[int]:
        with self._lock:
            t = self._retired.get(guid) or self._live.get(guid)
            return None if t is None else t["committed"]

    def committed_total(self, retired_only: bool = False) -> int:
        """Sum of committed tokens across timelines — the reconciliation
        quantity: over retired requests it must equal the
        ``serving_tokens_generated_total`` counter (asserted per driver
        in tests/test_ledger.py)."""
        with self._lock:
            total = sum(t["committed"] for t in self._retired.values())
            if not retired_only:
                total += sum(t["committed"] for t in self._live.values())
            return total

    def slo_report(self, policy: Optional[SLOPolicy] = None
                   ) -> Optional[Dict[str, Any]]:
        """Attainment + goodput over the retired window; ``policy``
        overrides the installed one (ad-hoc what-if reports).  None
        when no policy is configured anywhere."""
        with self._lock:
            pol = policy or self._policy
            if pol is None:
                return None
            return slo_report_from(
                [self._export(t) for t in self._retired.values()], pol)

    @staticmethod
    def _export(t: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(t)
        out["events"] = list(t["events"])
        return out

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable dump (the ``ledger`` section of a watchdog
        bundle; the input ``tools/ffreq.py`` reads)."""
        with self._lock:
            return {
                "retired_capacity": self.retired_capacity,
                "events_per_request": self.events_per_request,
                "retired_dropped": self._retired_dropped,
                "live_dropped": self._live_dropped,
                "policy": (dataclasses.asdict(self._policy)
                           if self._policy is not None else None),
                "live": [self._export(t) for t in self._live.values()],
                "retired": [self._export(t)
                            for t in self._retired.values()],
            }

    def clear(self) -> None:
        with self._lock:
            self._live.clear()
            self._admitted.clear()
            self._retired.clear()
            self._retired_dropped = 0
            self._live_dropped = 0
            pol = self._policy
        if pol is None:
            return
        # the gauges describe the retired window just emptied (e.g. a
        # bench measurement-boundary clear dropping warmup requests):
        # zero them so metrics_snapshot()/expose_text() and slo_report()
        # cannot disagree about whether a window exists.  Outside the
        # ledger lock, like _update_slo_gauges.
        try:
            from . import get_registry
        except ImportError:         # pragma: no cover - partial install
            return
        m = get_registry()
        m.gauge("serving_slo_attainment").set(0.0)
        m.gauge("serving_slo_ttft_attainment").set(0.0)
        m.gauge("serving_slo_tpot_attainment").set(0.0)
        m.gauge("serving_goodput_tokens_per_s").set(0.0)


_LEDGER = RequestLedger(
    retired_capacity=int(os.environ.get("FF_LEDGER_RETIRED",
                                        str(DEFAULT_RETIRED))
                         or DEFAULT_RETIRED),
    events_per_request=int(os.environ.get("FF_LEDGER_EVENTS",
                                          str(DEFAULT_EVENTS))
                           or DEFAULT_EVENTS),
    live_capacity=int(os.environ.get("FF_LEDGER_LIVE",
                                     str(DEFAULT_LIVE))
                      or DEFAULT_LIVE),
    enabled=os.environ.get("FF_TELEMETRY", "1") != "0")


def get_ledger() -> RequestLedger:
    """The process-wide request ledger (always allocated; inert when
    FF_TELEMETRY=0)."""
    return _LEDGER
