"""Loader for the native C++ runtime components (csrc/flexflow_native.cc).

The reference keeps its host runtime in C++ (tokenizer gpt_tokenizer.cc,
dataloader dataloader.cc, C API flexflow_c.cc); this module builds and
binds our native equivalents.  Build is on-demand with g++ into a cache
dir (no pybind11 in the image — plain ctypes over an extern "C" surface),
and everything degrades gracefully to the pure-Python paths when a
toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), os.pardir, "csrc",
                    "flexflow_native.cc")
_ABI = 1

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    src = os.path.abspath(_SRC)
    try:
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        cache = os.path.join(os.path.expanduser("~/.cache/flexflow_tpu"),
                             "native")
        os.makedirs(cache, exist_ok=True)
    except OSError:
        return None  # missing source / unwritable HOME: Python fallback
    so = os.path.join(cache, f"libflexflow_native_{digest}.so")
    if not os.path.exists(so):
        # per-process tmp name: concurrent cold builds (pytest-xdist,
        # multi-process launches) must not clobber each other's output
        tmp = f"{so}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
               src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=120)
            os.replace(tmp, so)
        except Exception:
            return None
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    if lib.ff_native_abi_version() != _ABI:
        return None
    lib.ff_bpe_new.restype = ctypes.c_void_p
    lib.ff_bpe_free.argtypes = [ctypes.c_void_p]
    lib.ff_bpe_add_token.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64]
    lib.ff_bpe_add_merge.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_char_p, ctypes.c_int64]
    lib.ff_bpe_encode_token.restype = ctypes.c_int64
    lib.ff_bpe_encode_token.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
    lib.ff_gather_rows.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if the
    toolchain/source is unavailable (callers fall back to Python)."""
    global _lib, _tried
    with _lock:
        if not _tried:
            _tried = True
            _lib = _build_and_load()
        return _lib


def available() -> bool:
    return get_lib() is not None


# ------------------------------------------------------------------ BPE
class NativeBPE:
    """ctypes wrapper over the C++ merge engine (reference
    gpt_tokenizer.cc).  Python keeps the regex pre-tokenization; each
    pre-token's merge loop + vocab lookup runs native."""

    def __init__(self, encoder: dict, bpe_ranks: dict):
        lib = get_lib()
        assert lib is not None, "native library unavailable"
        self._lib = lib
        self._h = ctypes.c_void_p(lib.ff_bpe_new())
        for tok, tid in encoder.items():
            lib.ff_bpe_add_token(self._h, tok.encode("utf-8"), int(tid))
        for (a, b), rank in bpe_ranks.items():
            lib.ff_bpe_add_merge(self._h, a.encode("utf-8"),
                                 b.encode("utf-8"), int(rank))
        self._buf = (ctypes.c_int64 * 4096)()

    def encode_token(self, token: str) -> Optional[List[int]]:
        """ids for one byte-encoded pre-token; None -> caller falls back."""
        n = self._lib.ff_bpe_encode_token(self._h, token.encode("utf-8"),
                                          self._buf, len(self._buf))
        if n < 0:
            return None
        return list(self._buf[:n])

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.ff_bpe_free(h)


# --------------------------------------------------------------- gather
def gather_rows(src: np.ndarray, indices: Sequence[int]) -> np.ndarray:
    """dst[i] = src[indices[i]] over the leading axis, memcpy'd natively
    (falls back to numpy fancy indexing without the library)."""
    lib = get_lib()
    src = np.asarray(src)
    # ascontiguousarray, not asarray: the C loop walks a dense int64
    # buffer, so a strided index view must be compacted first
    idx = np.ascontiguousarray(indices, np.int64)
    # numpy fancy indexing handles everything the memcpy path can't:
    # missing lib, PyObject refcounting, non-contiguous layouts (native
    # would force a full-dataset copy per call), negative/out-of-range
    # indices (end-relative semantics / IndexError)
    if (lib is None or src.dtype.hasobject
            or not src.flags.c_contiguous or len(idx) == 0
            or idx.min() < 0 or idx.max() >= src.shape[0]):
        return src[idx]
    out = np.empty((len(idx),) + src.shape[1:], src.dtype)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    lib.ff_gather_rows(
        src.ctypes.data_as(ctypes.c_char_p),
        out.ctypes.data_as(ctypes.c_char_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(idx), row_bytes)
    return out
