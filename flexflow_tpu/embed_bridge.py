"""Python side of the C embedding API (csrc/flexflow_embed.cc).

The reference exposes ~380 ``extern "C"`` functions
(src/c/flexflow_c.cc) because its control plane is C++ and every
frontend must cross that boundary.  Here the control plane is Python,
so a non-Python host embeds the interpreter and drives THIS bridge
through a handful of C calls (init / create-from-JSON-config /
generate / free) — same capability, one boundary, JSON instead of 380
handle-typed constructors (docs/INTERNALS.md "Why there is no big C
API").

Config JSON accepted by :func:`create`::

    {"family": "llama",            # llama (default) | opt
     "vocab_size": 128, "hidden_size": 64, "intermediate_size": 128,
     "num_hidden_layers": 2, "num_attention_heads": 4,
     "num_key_value_heads": 2,
     "seed": 0,                    # random-init weights
     "weights_npz": "/path.npz",   # optional real weights (npz tree)
     "tensor_parallelism_degree": 1, "sequence_parallelism_degree": 1,
     "pipeline_parallelism_degree": 1,
     "max_requests": 4, "max_seq_length": 256,
     "max_tokens_per_batch": 32}
"""

from __future__ import annotations

import json
from typing import Dict, List

_models: Dict[int, Dict] = {}
_next_handle = 1


def create(config_json: str) -> int:
    """Build + compile a serving model; returns a handle (>0)."""
    global _next_handle

    import jax
    import numpy as np

    from . import FFConfig, Model
    from .fftype import InferenceMode
    from .serving import InferenceManager

    cfg = json.loads(config_json)
    family = cfg.get("family", "llama")
    ffcfg = FFConfig(
        tensor_parallelism_degree=cfg.get("tensor_parallelism_degree", 1),
        sequence_parallelism_degree=cfg.get(
            "sequence_parallelism_degree", 1),
        pipeline_parallelism_degree=cfg.get(
            "pipeline_parallelism_degree", 1))
    max_requests = cfg.get("max_requests", 4)
    if family == "llama":
        from .models.llama import LLAMAConfig, create_llama_model

        mc = LLAMAConfig(**{k: cfg[k] for k in (
            "vocab_size", "hidden_size", "intermediate_size",
            "num_hidden_layers", "num_attention_heads",
            "num_key_value_heads") if k in cfg})
        model = Model(ffcfg, name=f"embed_{_next_handle}")
        create_llama_model(model, mc, mode=InferenceMode.INC_DECODING,
                           max_requests=max_requests)
    elif family == "opt":
        from .models.opt import OPTConfig, create_opt_model

        mc = OPTConfig(**{k: cfg[k] for k in (
            "vocab_size", "hidden_size", "ffn_dim", "num_hidden_layers",
            "num_attention_heads", "max_position_embeddings")
            if k in cfg})
        model = Model(ffcfg, name=f"embed_{_next_handle}")
        create_opt_model(model, mc, mode=InferenceMode.INC_DECODING,
                         max_requests=max_requests)
    else:
        raise ValueError(f"unknown family {family!r}")
    if "weights_npz" in cfg:
        loaded = np.load(cfg["weights_npz"])
        model.params = {}
        for key in loaded.files:        # "layer/param" flat names
            ln, pn = key.split("/", 1)
            model.params.setdefault(ln, {})[pn] = loaded[key]
    else:
        model.params = model.init_params(
            jax.random.PRNGKey(cfg.get("seed", 0)))
    im = InferenceManager(ffcfg)
    mid = im.compile_model_and_allocate_buffer(
        model, max_requests=max_requests,
        max_seq_length=cfg.get("max_seq_length", 256))
    handle = _next_handle
    _next_handle += 1
    _models[handle] = dict(
        im=im, mid=mid,
        max_requests=max_requests,
        max_seq_length=cfg.get("max_seq_length", 256),
        max_tokens_per_batch=cfg.get("max_tokens_per_batch", 32))
    return handle


def generate(handle: int, prompt: List[int], max_new: int) -> List[int]:
    """Greedy-decode ``max_new`` tokens after ``prompt``; returns the
    GENERATED ids (prompt excluded)."""
    from .serving import RequestManager

    rec = _models[handle]
    rm = RequestManager(
        max_requests_per_batch=rec["max_requests"],
        max_tokens_per_batch=rec["max_tokens_per_batch"],
        max_sequence_length=rec["max_seq_length"])
    req = rm.register_new_request(list(prompt), max_new_tokens=max_new)
    rm.generate_incr_decoding(rec["im"], rec["mid"], [req])
    return list(req.tokens[req.prompt_len:])


def destroy(handle: int) -> None:
    rec = _models.pop(handle, None)
    if rec is not None:
        rec["im"].free_model(rec["mid"])
