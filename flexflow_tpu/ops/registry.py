"""Operator registry.

TPU-native re-design of the reference's operator layer (include/flexflow/
operator.h:75 `class Op` with virtual init/forward/backward/inference).  On
TPU there is no per-op task launch: every op is a *pure function* that XLA
traces and fuses, so an operator definition reduces to three pieces:

- ``infer``:   shape/dtype inference at graph-build time (the reference does
               this inside each op's constructor, e.g. linear.cc shape calc);
- ``params``:  declarative parameter specs (the reference creates weight
               ParallelTensors per op);
- ``forward``: the pure computation. ``backward`` is jax.grad — the
               reference's hand-written backward kernels collapse away.

Ops with serving behaviour additionally implement ``inference`` taking a
BatchConfig (mirroring Op::inference, operator.h).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..core.tensor import TensorSpec
from ..fftype import DataType, OpType


@dataclasses.dataclass
class ParamSpec:
    """Declarative weight spec (plays the role of the reference's per-op
    weight ParallelTensor creation)."""

    name: str
    shape: Tuple[int, ...]
    dtype: DataType
    initializer: Any = None  # Initializer or None -> op default
    fans: Any = None  # optional (fan_in, fan_out) for fan-based initializers


@dataclasses.dataclass
class OpContext:
    """Per-call execution context threaded through op forward functions.

    Replaces the reference's OpMeta/FFHandler plumbing (op_meta.h,
    config.h:68-85): no cuDNN handles needed, but training mode, PRNG for
    dropout, and the serving BatchConfig ride here.
    """

    training: bool = False
    rng: Any = None
    batch_config: Any = None  # serving: BatchConfig family
    kv_cache: Any = None      # serving: per-layer KV cache pytree (read)
    kv_cache_out: Dict = None  # serving: updated caches collected here
    # serving: static bound on attended cache length this step (attention
    # reads cache[:, :attend_len] instead of the full padded allocation —
    # at 7B/MHA the full-length read costs more than the weights)
    attend_len: Any = None
    # serving: host's cost decision that this step's depth profile favors
    # the length-tiled flash-decode kernel's per-row pruning over the XLA
    # attend (inference_manager.flash_wins)
    use_flash: bool = False
    mesh: Any = None
    # serving: int8 weights multiply MXU-natively against dynamically
    # int8-quantized activations (FFConfig.int8_native_matmul)
    w8a8: bool = False
    extra_outputs: Dict = None  # side outputs (e.g. beam parent ids)
    state_updates: Dict = None  # non-trainable state written by ops (BN stats)
    aux_losses: Dict = None     # auxiliary losses (MoE load balance) summed
                                # into the training loss by Model.compile


class OpDef:
    """Base operator definition."""

    type: OpType = None

    def infer(self, attrs: dict, in_specs: Sequence[TensorSpec]) -> List[TensorSpec]:
        raise NotImplementedError

    def params(self, attrs: dict, in_specs: Sequence[TensorSpec]) -> List[ParamSpec]:
        return []

    def forward(self, params: dict, inputs: Sequence, attrs: dict, ctx: OpContext):
        raise NotImplementedError

    # serving path; default: same as forward
    def inference(self, params, inputs, attrs, ctx: OpContext):
        return self.forward(params, inputs, attrs, ctx)

    def flops(self, attrs: dict, in_specs: Sequence[TensorSpec]) -> int:
        """Analytic FLOP estimate used by the auto-parallelization cost model
        (stands in for Simulator::measure_operator_cost before real timing,
        simulator.cc:519)."""
        return 0


_REGISTRY: Dict[OpType, OpDef] = {}


def register(op) -> OpDef:
    """Register an OpDef instance (or class — instantiated on the spot, so
    ``@register`` works as a class decorator)."""
    inst = op() if isinstance(op, type) else op
    assert inst.type is not None
    _REGISTRY[inst.type] = inst
    return op


def get_op(op_type: OpType) -> OpDef:
    return _REGISTRY[op_type]


def simple_op(op_type: OpType, infer_fn: Callable, fwd_fn: Callable):
    """Helper for parameterless ops."""

    class _Simple(OpDef):
        type = op_type

        def infer(self, attrs, in_specs):
            return infer_fn(attrs, in_specs)

        def forward(self, params, inputs, attrs, ctx):
            return fwd_fn(inputs, attrs, ctx)

    return register(_Simple())
