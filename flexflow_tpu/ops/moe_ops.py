"""Mixture-of-Experts operators.

TPU-native re-design of the reference's MoE operator family:

- Group_by   (src/ops/group_by.cc:44  — route tokens to per-expert buffers)
- Aggregate  (src/ops/aggregate.cc:40 — gate-weighted combine + load balance)
- AggregateSpec (src/ops/aggregate_spec.cc — speculative-aggregation variant)
- Experts    (src/ops/experts.cc:49   — fused expert-FFN dispatch/compute)
- Cache      (src/ops/cache.cc:57     — dead-coded in the reference; minimal
              working equivalent here)
- composed by ``Model.moe`` (src/ops/moe.cc:19-43).

Architecture: the reference dispatches tokens with hand-written CUDA scatter
kernels (group_by.cu) and the fused Experts op runs cublasGemmBatchedEx per
expert.  On TPU the idiomatic formulation is the Switch-Transformer-style
*dense dispatch einsum*: a one-hot dispatch tensor (tokens x topk x experts x
capacity) turns routing into two MXU matmuls (dispatch and combine), which

- keeps every shape static (XLA requirement),
- is trivially differentiable (no hand-written backward scatter), and
- partitions cleanly over an ``ep`` mesh axis: GSPMD turns the dispatch
  einsum into an all-to-all, which is exactly the expert-parallel exchange
  the reference gets from Legion region movement.

Load balancing: the reference injects a hand-derived gradient of the
load-balance penalty inside Aggregate's backward kernel
(aggregate.cc backward).  Under autodiff we instead *compute* the auxiliary
loss (Switch Transformer eq. 4 form: n * sum_e f_e * P_e) and publish it via
``ctx.aux_losses``; ``Model.compile`` adds it to the training loss, and the
same gradient emerges from jax.grad.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.initializers import (DEFAULT_BIAS_INIT, DEFAULT_WEIGHT_INIT,
                                 ZeroInitializer)
from ..core.tensor import TensorSpec
from ..fftype import ActiMode, DataType, OpType, apply_activation
from .registry import OpContext, OpDef, ParamSpec, register


def moe_capacity(alpha: float, k: int, tokens: int, n_experts: int) -> int:
    """Per-expert buffer size (reference group_by.cc output dims:
    alpha * k * batch / n, the `alpha` overhead factor of moe.h:47)."""
    return max(1, int(math.ceil(alpha * k * tokens / n_experts)))


def dispatch_tensor(assign: jnp.ndarray, n_experts: int, capacity: int,
                    offset: int = 0) -> jnp.ndarray:
    """Build the (tokens, k, experts, capacity) one-hot dispatch tensor.

    Token (t, j) goes to expert assign[t, j] at the next free capacity slot,
    in flat (t*k + j) priority order — matching the reference's sequential
    scatter order in group_by.cu.  Overflowing tokens are dropped (the
    reference likewise truncates when a buffer fills).

    ``offset`` shifts assignments (expert-parallel shards own a contiguous
    expert range, reference experts.cc experts_start_idx).
    """
    T, k = assign.shape
    flat = assign.reshape(T * k) - offset
    oh = jax.nn.one_hot(flat, n_experts, dtype=jnp.int32)  # (T*k, n)
    # position of each (token, slot) within its expert's buffer
    pos = jnp.cumsum(oh, axis=0) * oh - 1                   # (T*k, n)
    keep = (pos >= 0) & (pos < capacity)
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, -1), capacity,
                            dtype=jnp.float32)              # (T*k, n, cap)
    return pos_oh.reshape(T, k, n_experts, capacity)


def _flatten_tokens(x: jnp.ndarray):
    """(..., d) -> (T, d) plus the leading shape for restore."""
    lead = x.shape[:-1]
    return x.reshape(-1, x.shape[-1]), lead


@register
class GroupBy(OpDef):
    """Route tokens into per-expert buffers (reference group_by.cc:44:
    inputs (input, assign), n outputs of shape (capacity, d))."""

    type = OpType.GROUP_BY

    def infer(self, attrs, in_specs):
        x, assign = in_specs
        n, alpha = attrs["n"], attrs.get("alpha", 2.0)
        tokens = int(np.prod(x.shape[:-1]))
        k = assign.shape[-1]
        cap = moe_capacity(alpha, k, tokens, n)
        attrs["_capacity"] = cap
        return [TensorSpec((cap, x.shape[-1]), x.dtype) for _ in range(n)]

    def forward(self, params, inputs, attrs, ctx):
        x, assign = inputs
        n = attrs["n"]
        cap = attrs["_capacity"]
        xf, _ = _flatten_tokens(x)
        af = assign.reshape(-1, assign.shape[-1])
        disp = dispatch_tensor(af, n, cap)                  # (T, k, n, cap)
        # one MXU contraction builds every expert buffer at once
        buf = jnp.einsum("tknc,td->ncd", disp, xf.astype(jnp.float32))
        buf = buf.astype(x.dtype)
        return [buf[e] for e in range(n)]

    def flops(self, attrs, in_specs):
        x, assign = in_specs
        tokens = int(np.prod(x.shape[:-1]))
        return 2 * tokens * assign.shape[-1] * attrs["n"] * x.shape[-1]


def _combine(exp_preds, gate_preds, gate_assign, full_gate_preds, attrs, ctx,
             aux_name):
    """Shared Aggregate/AggregateSpec combine (aggregate.cc forward kernel
    semantics): out[t] = sum_j gate[t,j] * expert_buffer[assign[t,j]][pos]."""
    n = attrs["n"]
    lam = attrs.get("lambda_bal", 0.0)
    cap = exp_preds[0].shape[0]
    gf = gate_preds.reshape(-1, gate_preds.shape[-1])
    af = gate_assign.reshape(-1, gate_assign.shape[-1])
    disp = dispatch_tensor(af, n, cap)                      # (T, k, n, cap)
    stack = jnp.stack(exp_preds).astype(jnp.float32)        # (n, cap, d)
    out = jnp.einsum("tknc,ncd,tk->td", disp, stack,
                     gf.astype(jnp.float32))
    # auxiliary load-balance loss (replaces the reference's hand-written
    # balance gradient in aggregate.cc backward; see module docstring)
    if lam and ctx.aux_losses is not None and full_gate_preds is not None:
        probs = jax.nn.softmax(
            full_gate_preds.reshape(-1, n).astype(jnp.float32), axis=-1)
        counts = jnp.sum(disp, axis=(0, 1, 3))              # per-expert load
        f_e = counts / max(gf.shape[0] * gf.shape[1], 1)    # assignment frac
        p_e = jnp.mean(probs, axis=0)                       # mean router prob
        ctx.aux_losses[aux_name] = lam * n * jnp.sum(f_e * p_e)
    out_shape = gate_preds.shape[:-1] + (exp_preds[0].shape[-1],)
    return out.reshape(out_shape).astype(exp_preds[0].dtype)


class _AggregateBase(OpDef):
    def infer(self, attrs, in_specs):
        gate = in_specs[0]
        exp0 = in_specs[4]
        return [TensorSpec(gate.shape[:-1] + (exp0.shape[-1],), exp0.dtype)]

    def forward(self, params, inputs, attrs, ctx):
        gate_preds, gate_assign, _true_assign, full_gate = inputs[:4]
        exp_preds = inputs[4:]
        out = _combine(exp_preds, gate_preds, gate_assign, full_gate, attrs,
                       ctx, attrs.get("layer_name", self.type.value))
        return [out]

    def flops(self, attrs, in_specs):
        gate = in_specs[0]
        tokens = int(np.prod(gate.shape[:-1]))
        return (2 * tokens * gate.shape[-1] * attrs["n"]
                * in_specs[4].shape[-1])


@register
class Aggregate(_AggregateBase):
    """Gate-weighted combine of expert outputs (aggregate.cc:40; inputs
    [gate_preds, gate_assign, true_gate_assign, full_gate_preds,
    exp_pred_1..n])."""

    type = OpType.AGGREGATE


@register
class AggregateSpec(_AggregateBase):
    """aggregate_spec.cc variant.  In the reference the difference is purely
    in the hand-written backward (it back-propagates through every
    speculatively-computed expert rather than only the selected ones);
    under autodiff the forward is identical and jax.grad derives the
    appropriate gradient, so the op shares the Aggregate implementation."""

    type = OpType.AGG_SPEC


@register
class Experts(OpDef):
    """Fused expert-FFN op for serving (reference experts.cc:49: inputs
    [input, indices, topk_gate_preds]; one or two dense layers per expert,
    relu, bias; experts_start_idx selects this shard's expert range).

    Weights are stored stacked over a leading expert axis so a single
    batched einsum computes all local experts — GSPMD shards that axis over
    ``ep`` (the reference instead round-robins whole Experts ops across
    devices, inference_manager.cc:229 expert_device_index).
    """

    type = OpType.EXPERTS

    def infer(self, attrs, in_specs):
        x, idx, gate = in_specs
        assert idx.shape == gate.shape, (idx.shape, gate.shape)
        out_dim = attrs["experts_output_dim_size"]
        return [TensorSpec(x.shape[:-1] + (out_dim,), x.dtype)]

    def params(self, attrs, in_specs):
        x = in_specs[0]
        n = attrs["num_experts"]
        d = x.shape[-1]
        out = attrs["experts_output_dim_size"]
        layers = attrs.get("experts_num_layers", 1)
        use_bias = attrs.get("use_bias", True)
        dtype = x.dtype
        if layers == 1:
            dims = [(d, out)]
        else:
            hidden = attrs["experts_internal_dim_size"]
            dims = [(d, hidden), (hidden, out)]
        ps = []
        for i, (di, do) in enumerate(dims):
            ps.append(ParamSpec(f"kernel{i}", (n, di, do), dtype,
                                DEFAULT_WEIGHT_INIT, fans=(di, do)))
            if use_bias:
                ps.append(ParamSpec(f"bias{i}", (n, do), dtype,
                                    DEFAULT_BIAS_INIT))
        return ps

    def forward(self, params, inputs, attrs, ctx):
        x, idx, gate = inputs
        n = attrs["num_experts"]
        start = attrs.get("experts_start_idx", 0)
        alpha = attrs.get("alpha", 2.0)
        layers = attrs.get("experts_num_layers", 1)
        use_bias = attrs.get("use_bias", True)
        act = attrs.get("activation", ActiMode.RELU)
        xf, lead = _flatten_tokens(x)
        T = xf.shape[0]
        k = idx.shape[-1]
        cap = moe_capacity(alpha, k, T, n)
        disp = dispatch_tensor(idx.reshape(T, k).astype(jnp.int32), n, cap,
                               offset=start)                # (T, k, n, cap)
        h = jnp.einsum("tknc,td->ncd", disp, xf.astype(jnp.float32))
        for i in range(layers):
            w = params[f"kernel{i}"].astype(jnp.float32)
            h = jnp.einsum("ncd,ndo->nco", h, w)
            if use_bias:
                h = h + params[f"bias{i}"].astype(jnp.float32)[:, None, :]
            if i < layers - 1:
                h = apply_activation(h, act)
        out = jnp.einsum("tknc,nco,tk->to", disp, h,
                         gate.reshape(T, k).astype(jnp.float32))
        out_dim = attrs["experts_output_dim_size"]
        return [out.reshape(lead + (out_dim,)).astype(x.dtype)]

    def flops(self, attrs, in_specs):
        x, idx, _ = in_specs
        tokens = int(np.prod(x.shape[:-1]))
        layers = attrs.get("experts_num_layers", 1)
        d = x.shape[-1]
        out = attrs["experts_output_dim_size"]
        hidden = attrs.get("experts_internal_dim_size", 0)
        per_tok = 2 * d * (hidden if layers == 2 else out)
        if layers == 2:
            per_tok += 2 * hidden * out
        return tokens * idx.shape[-1] * per_tok


@register
class Cache(OpDef):
    """Batch-input cache (reference cache.cc:57 — the op exists in the
    reference API but its builder is dead code behind ``assert(false)``;
    this is a minimal *working* equivalent).

    Keeps the last seen input as non-trainable state and passes the input
    through unchanged; the cached copy is readable via
    ``model.params[name]["cache"]`` for trigger-style reuse (the role the
    reference's score_f/RecompileState machinery plays for MoE
    re-balancing)."""

    type = OpType.CACHE
    NON_TRAINABLE = ("cache",)

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        return [x]

    def params(self, attrs, in_specs):
        (x,) = in_specs
        return [ParamSpec("cache", x.shape, x.dtype, ZeroInitializer())]

    def forward(self, params, inputs, attrs, ctx):
        return [inputs[0]]

    def new_state(self, params, inputs, attrs):
        return {"cache": inputs[0]}
