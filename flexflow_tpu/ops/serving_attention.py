"""Serving attention operators (KV-cached, BatchConfig-driven).

TPU-native re-design of the reference's serving attention family:

- IncMultiHeadSelfAttention   (src/ops/inc_multihead_self_attention.cu:
  qkv GEMM :328-397, in-kernel RoPE :449, KV append :603/:857, prompt-phase
  batched attention :902, single-token generation kernel :46)
- SpecIncMultiHeadSelfAttention (src/ops/spec_inc_multihead_self_attention.cu:
  beam-aware KV cache per sub-request)
- TreeIncMultiHeadSelfAttention (src/ops/tree_inc_multihead_self_attention.cu:
  commit_tokens_kernel :276-330, tree-mask attention :43)

Design notes (why this is NOT a kernel port):

* The reference needs three distinct hand-written CUDA kernels because its
  batches are token-flattened and its cache is indexed per token.  Here the
  batch is row-oriented ``[R, C]`` (see serving/batch_config.py), so all
  three modes share ONE attention path: scatter the chunk's K/V into each
  row's cache slice with a vmapped dynamic_update_slice, then batched
  einsums q@K^T -> mask -> softmax -> @V that XLA tiles onto the MXU.
  The modes differ only in (a) RoPE position source, (b) the attention
  mask, (c) the tree commit step — all data, not code paths.

* GQA/MQA (num_q_heads != num_kv_heads, reference
  inc_multihead_self_attention.cc:694-697) is a reshape of the query heads
  to [KV, G] — no KV duplication in memory.

* TP sharding: q/k/v/o weights and the cache's head dim are sharded over
  the ``tp`` mesh axis by the InferenceManager; the contraction with wo
  produces a partial sum that GSPMD all-reduces (the reference inserts an
  explicit AllReduce op after attention, model.cc:3292).

The cache lives in ``ctx.kv_cache[layer_name] = {"k","v"}: [R, KV, S, D]``
(r4: kv-heads-major so flash-decode tiles arrive pre-transposed — the
layout that made the Pallas kernel beat the XLA attend in BOTH its
regimes; see kernels/flash_decode.py);
updated caches are written to ``ctx.kv_cache_out`` (functional update — the
step fn donates the cache buffers so XLA updates them in place).

PR 10 (physical paged KV): when the batch carries a ``page_table``
(int32 ``[R, max_pages]`` — presence of the key IS the layout switch),
the same dicts hold GLOBAL frame pools ``[num_frames, KV, page_len,
D]`` instead of row slabs; scatters/commits resolve positions to
(frame, in-frame offset) through the table, the flash paths dispatch
the page-table kernels, and the jnp fallback attends a gathered dense
view bucketed in whole pages (docs/INTERNALS.md "Paged KV cache").

Hybrid steps (stall-free mixed batches): this op is deliberately
ROLE-AGNOSTIC.  The fused decode+rider dispatch
(inference_manager.hybrid_step) runs it twice over the same caches —
once at chunk 1 with ``active`` = the decode rows, once at the rider
chunk with ``active`` = the rider rows — so the mixed-row attend is
mask dataflow, not a new code path: inactive rows' scatters redirect
and DROP, their attend lanes mask to zeros (and the flash kernels
prune their tiles), and the two roles share the page-table
indirection untouched.  Everything hybrid-specific lives in the
batch/scheduler layers (docs/INTERNALS.md "Hybrid steps").
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.initializers import DEFAULT_WEIGHT_INIT
from ..core.tensor import TensorSpec
from ..fftype import DataType, OpType
from ..quantization import kv_pack_factor, resolve_weight
from .attention_ops import apply_rotary_embedding
from .registry import OpDef, ParamSpec, register

NEG_INF = -1e30  # large-negative fill; -inf breaks softmax rows that are all masked


def _scatter_chunk(cache, chunk, start, active):
    """cache [R,KV,S,D] <- chunk [R,C,KV,D] at per-row offset start [R].

    One scatter op with sorted unique (row, pos) indices.  r4: the
    previous vmapped dynamic_update_slice lowered to a SERIAL 16-
    iteration XLA while loop costing ~50 us per cache per layer on chip
    (~3.2 ms of a 12 ms 7B decode step — found by XProf); the hinted
    scatter measures ~free.  Inactive rows redirect past the cache end
    and DROP (previously they clamp-wrote into the never-attended slack
    tail; dropping is the same guarantee with no write).

    Advanced-indexing note: the slice between the two index arrays puts
    the advanced dims first, so the update shape is chunk's natural
    [R, C, KV, D]."""
    S = cache.shape[2]
    R, C = chunk.shape[:2]
    safe_start = jnp.where(active, start, S)
    rows = jnp.broadcast_to(jnp.arange(R)[:, None], (R, C))
    pos = safe_start[:, None] + jnp.arange(C)[None, :]
    return cache.at[rows, :, pos].set(chunk.astype(cache.dtype),
                                      mode="drop", unique_indices=True,
                                      indices_are_sorted=True)


def _scatter_chunk_paged(pool, chunk, start, active, table):
    """pool [F,KV,L,D] <- chunk [R,C,KV,D] through the page table at
    per-row offset ``start`` — the paged twin of :func:`_scatter_chunk`.
    Row r's token c lands in frame ``table[r, pos // L]`` at in-frame
    offset ``pos % L``; inactive rows and positions past the table
    redirect to the sentinel frame F and DROP."""
    F, KV, L, D = pool.shape
    R, C = chunk.shape[:2]
    P = table.shape[1]
    pos = start[:, None].astype(jnp.int32) + jnp.arange(C,
                                                       dtype=jnp.int32)
    page = pos // L
    ok = active[:, None].astype(bool) & (pos >= 0) & (page < P)
    fr = jnp.take_along_axis(table, jnp.clip(page, 0, P - 1), axis=1)
    fr = jnp.where(ok, fr, F)
    return pool.at[fr, :, pos % L].set(chunk.astype(pool.dtype),
                                       mode="drop")


def _paged_view(pool, table, pages):
    """Gather a dense logical view of the first ``pages`` table columns:
    pool [F,KV,L,D] + table [R,P] -> [R, KV, pages*L, D] (scale pools
    [F,KV,L] -> [R, KV, pages*L]).  The jnp-fallback read path: XLA
    fuses the gather into the attend's operand stream, and the gather
    width is the host's attend bucket in pages — the paged analogue of
    ``_attend_slice``.  Stale table entries clip to a real frame; the
    attend mask (span <= depth) guards every unleased position."""
    t = jnp.clip(table[:, :pages], 0, pool.shape[0] - 1)
    g = pool[t]                        # [R, pages, KV, L(, D)]
    if g.ndim == 5:
        R, Pg, KV, L, D = g.shape
        return g.transpose(0, 2, 1, 3, 4).reshape(R, KV, Pg * L, D)
    R, Pg, KV, L = g.shape
    return g.transpose(0, 2, 1, 3).reshape(R, KV, Pg * L)


def _attend(q, cache_k, cache_v, mask, scale, alibi=None):
    """q [R,C,H,D] vs cache [R,KV,S,D] with mask [R,C,S] -> [R,C,H,D].

    H = KV * G; queries grouped so each KV head serves G query heads.
    ``alibi``: optional (slopes[H], q_positions[R,C], key_positions[R,S])
    triple adding the MPT position bias slope_h * (k_pos - q_pos).  Key
    positions are explicit because in tree-verify mode a key's cache slot is
    NOT its token depth (siblings share a depth but occupy distinct slots).
    """
    R, C, H, D = q.shape
    KV = cache_k.shape[1]
    G = H // KV
    qg = q.reshape(R, C, KV, G, D)
    logits = jnp.einsum("rckgd,rksd->rckgs", qg, cache_k,
                        preferred_element_type=jnp.float32) * scale
    if alibi is not None:
        slopes, positions, key_pos = alibi
        rel = (key_pos[:, None, :]
               - positions[:, :, None]).astype(jnp.float32)  # [R,C,S]
        bias = slopes.reshape(1, 1, KV, G, 1) * rel[:, :, None, None, :]
        logits = logits + bias
    logits = jnp.where(mask[:, :, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("rckgs,rksd->rckgd", probs.astype(cache_v.dtype), cache_v,
                     preferred_element_type=jnp.float32)
    return out.reshape(R, C, H, D).astype(q.dtype)


def pallas_tpu_available() -> bool:
    """True when Pallas kernels can compile for the local backend."""
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


class _ServingAttentionBase(OpDef):
    """Shared qkv/o projection + cache plumbing for the three modes."""

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        return [TensorSpec(x.shape[:-1] + (attrs["embed_dim"],), x.dtype)]

    def params(self, attrs, in_specs):
        (x,) = in_specs
        e = attrs["embed_dim"]
        h = attrs["num_q_heads"]
        kv = attrs["num_kv_heads"]
        d = attrs.get("head_dim") or e // h
        dt = x.dtype
        init = attrs.get("kernel_initializer") or DEFAULT_WEIGHT_INIT
        ps = [
            ParamSpec("wq", (x.shape[-1], h, d), dt, init, fans=(x.shape[-1], h * d)),
            ParamSpec("wk", (x.shape[-1], kv, d), dt, init, fans=(x.shape[-1], kv * d)),
            ParamSpec("wv", (x.shape[-1], kv, d), dt, init, fans=(x.shape[-1], kv * d)),
            ParamSpec("wo", (h, d, e), dt, init, fans=(h * d, e)),
        ]
        if attrs.get("qkv_bias", False):
            ps += [ParamSpec("bq", (h, d), dt),
                   ParamSpec("bk", (kv, d), dt),
                   ParamSpec("bv", (kv, d), dt)]
        if attrs.get("final_bias", False):
            ps.append(ParamSpec("bo", (e,), dt))
        return ps

    def forward(self, params, inputs, attrs, ctx):
        raise NotImplementedError(
            f"{type(self).__name__} is a serving op: it needs a BatchConfig "
            "and KV cache (use multihead_attention for training)")

    # ------------------------------------------------------------ helpers
    def _project_qkv(self, params, x, attrs, ctx=None):
        if "wqkv" in params:
            # fused projection (InferenceManager.fuse_qkv): one matmul
            # instead of three — decode at small batch is per-kernel
            # floor-bound, so kernel count is throughput.  The reference
            # stores attention weights fused the same way
            # (file_loader.cc:209 loads one qkv tensor).
            h = attrs["num_q_heads"]
            kv = attrs["num_kv_heads"]
            qkv = jnp.einsum("rce,ehd->rchd", x,
                             params["wqkv"].astype(x.dtype))
            if attrs.get("qkv_bias", False):
                qkv = qkv + params["bqkv"].astype(qkv.dtype)
            return (qkv[:, :, :h], qkv[:, :, h:h + kv],
                    qkv[:, :, h + kv:])
        def proj(name):
            w_q = params.get(name + "_q")
            if w_q is not None:
                scale = params[name + "_scale"]
                if scale.ndim == 2:   # int8_nd [E,H,D], scale [H,D]
                    if ctx is not None and getattr(ctx, "w8a8", False):
                        from ..quantization import native_int8_matmul

                        return native_int8_matmul(x, w_q, scale)
                    # convert-dot + post-scale (exact; weights stream
                    # int8, see Linear._quantized_matmul)
                    y = jnp.einsum("rce,ehd->rchd", x,
                                   w_q.astype(x.dtype),
                                   preferred_element_type=jnp.float32)
                    return (y * scale).astype(x.dtype)
            return jnp.einsum("rce,ehd->rchd", x,
                              resolve_weight(params, name, x.dtype))

        q, k, v = proj("wq"), proj("wk"), proj("wv")
        if attrs.get("qkv_bias", False):
            q = q + params["bq"].astype(q.dtype)
            k = k + params["bk"].astype(k.dtype)
            v = v + params["bv"].astype(v.dtype)
        return q, k, v

    def _output(self, params, out, attrs, ctx=None):
        wo_q = params.get("wo_q")
        if wo_q is not None and params["wo_scale"].ndim == 1:
            if ctx is not None and getattr(ctx, "w8a8", False):
                from ..quantization import native_int8_matmul

                y = native_int8_matmul(out, wo_q, params["wo_scale"],
                                       contract_rhs_dims=(0, 1))
            else:
                # int8_nd [H,D,E], scale [E]: convert-dot + post-scale
                y = jnp.einsum("rchd,hde->rce", out, wo_q.astype(out.dtype),
                               preferred_element_type=jnp.float32)
                y = (y * params["wo_scale"]).astype(out.dtype)
        else:
            y = jnp.einsum("rchd,hde->rce", out,
                           resolve_weight(params, "wo", out.dtype))
        if attrs.get("final_bias", False):
            y = y + params["bo"].astype(y.dtype)
        return y

    def _scale(self, attrs):
        """Logit scale (reference inc_multihead_self_attention.cu:718):
        qk_prod_scaling gates the 1/sqrt(d) factor; scaling_query/
        scaling_factor independently pre-scale Q (composed here since both
        are scalar multiplies on the logits)."""
        d = attrs.get("head_dim") or attrs["embed_dim"] // attrs["num_q_heads"]
        scale = 1.0
        if attrs.get("qk_prod_scaling", True):
            scale /= np.sqrt(d)
        if attrs.get("scaling_query", False):
            sf = attrs.get("scaling_factor")
            scale *= sf if sf is not None else 1.0
        return scale

    @staticmethod
    def _alibi_slopes(num_heads: int):
        """ALiBi per-head slopes, MPT convention with alibi_bias_max=8
        (reference apply_position_bias_qkprd,
        inc_multihead_self_attention.cu:304-325: slope_h = 2^-((h+1)*8/H);
        the reference's (k+1-T) offset differs from our (k - q) only by a
        per-row constant, which softmax ignores)."""
        h = np.arange(1, num_heads + 1, dtype=np.float32)
        return 2.0 ** (-h * 8.0 / num_heads)

    def _cache(self, ctx, layer_name):
        """(k, v, k_scale, v_scale) — the scale tensors are None for
        full-precision caches, [R, KV, S] f32 for int8 caches (the
        InferenceManager allocates them beside the K/V rows)."""
        cache = ctx.kv_cache[layer_name]
        return (cache["k"], cache["v"],
                cache.get("k_scale"), cache.get("v_scale"))

    def _store(self, ctx, layer_name, ck, cv, ks=None, vs=None):
        out = {"k": ck, "v": cv}
        if ks is not None:
            out["k_scale"], out["v_scale"] = ks, vs
        ctx.kv_cache_out[layer_name] = out

    @staticmethod
    def _page_table(ctx):
        """The step's page table (int32 [R, max_pages]) when the record
        is paged — the InferenceManager rides it on the batch dict as
        DATA — else None.  Presence of the key IS the layout switch:
        paged pools and dense slabs are both 4-D and otherwise
        indistinguishable inside the trace."""
        bc = ctx.batch_config
        return bc["page_table"] if "page_table" in bc else None

    @staticmethod
    def _paged_attend_pages(ctx, pool, table, pack=1):
        """Table columns this step's attend reads: the host's attend
        bucket rounded up to whole pages (the paged analogue of
        ``_attend_slice`` — fewer gathered frames instead of a shorter
        slice), or the full table without a bucket.  ``pack``: codes
        per carrier byte (int4 pools hold 2 logical positions per
        axis-2 row), so the bucket compares in LOGICAL tokens."""
        L = pool.shape[2] * pack
        P = table.shape[1]
        if ctx.attend_len and ctx.attend_len < P * L:
            return min(P, -(-int(ctx.attend_len) // L))
        return P

    def _paged_gather(self, ctx, ck, cv, ks, vs, table):
        """(ak, av, aks, avs, S): the dense logical view the jnp attend
        reads, gathered frame-by-frame through the table.  ``S`` is the
        LOGICAL length (int4 carriers stay packed in the view; the
        dequant unpacks them)."""
        pack = kv_pack_factor(ck, ks)
        pages = self._paged_attend_pages(ctx, ck, table, pack)
        ak = _paged_view(ck, table, pages)
        av = _paged_view(cv, table, pages)
        aks = _paged_view(ks, table, pages) if ks is not None else None
        avs = _paged_view(vs, table, pages) if vs is not None else None
        return ak, av, aks, avs, pages * ck.shape[2] * pack

    def _scatter_any(self, ck, cv, ks, vs, k, v, start, active,
                     table=None):
        """Chunk commit on either layout: dense slabs scatter rows,
        paged pools scatter through the table; int8 caches quantize
        once (the shared quantizer) and move codes + scales in
        lockstep.  Int4 caches (pack factor 2, recovered from the
        carrier/scale shape ratio) quantize to +-7 codes and merge them
        nibble-wise into the packed carrier — the parity-sequenced RMW
        scatter, so chunk boundaries splitting a byte stay exact."""
        if ks is not None:
            from ..quantization import (quantize_kv, quantize_kv_int4,
                                        scatter_kv_packed,
                                        scatter_kv_packed_paged,
                                        scatter_kv_scales,
                                        scatter_kv_scales_paged)

            if kv_pack_factor(ck, ks) == 2:
                k_q, k_sc = quantize_kv_int4(k)
                v_q, v_sc = quantize_kv_int4(v)
                if table is not None:
                    ck = scatter_kv_packed_paged(ck, k_q, start, active,
                                                 table)
                    cv = scatter_kv_packed_paged(cv, v_q, start, active,
                                                 table)
                    ks = scatter_kv_scales_paged(ks, k_sc, start,
                                                 active, table)
                    vs = scatter_kv_scales_paged(vs, v_sc, start,
                                                 active, table)
                else:
                    ck = scatter_kv_packed(ck, k_q, start, active)
                    cv = scatter_kv_packed(cv, v_q, start, active)
                    ks = scatter_kv_scales(ks, k_sc, start, active)
                    vs = scatter_kv_scales(vs, v_sc, start, active)
                return ck, cv, ks, vs
            k_q, k_sc = quantize_kv(k)
            v_q, v_sc = quantize_kv(v)
            if table is not None:
                ck = _scatter_chunk_paged(ck, k_q, start, active, table)
                cv = _scatter_chunk_paged(cv, v_q, start, active, table)
                ks = scatter_kv_scales_paged(ks, k_sc, start, active,
                                             table)
                vs = scatter_kv_scales_paged(vs, v_sc, start, active,
                                             table)
            else:
                ck = _scatter_chunk(ck, k_q, start, active)
                cv = _scatter_chunk(cv, v_q, start, active)
                ks = scatter_kv_scales(ks, k_sc, start, active)
                vs = scatter_kv_scales(vs, v_sc, start, active)
            return ck, cv, ks, vs
        if table is not None:
            ck = _scatter_chunk_paged(ck, k, start, active, table)
            cv = _scatter_chunk_paged(cv, v, start, active, table)
        else:
            ck = _scatter_chunk(ck, k, start, active)
            cv = _scatter_chunk(cv, v, start, active)
        return ck, cv, ks, vs

    @staticmethod
    def _attend_slice(ctx, ck, cv, ks=None, vs=None):
        """Bound the attended cache prefix: positions past
        ctx.attend_len are provably masked (the host buckets it above
        every active row's depth+chunk), so reading them only burns HBM
        bandwidth — at 7B/MHA the full padded length costs more per step
        than the weights.  Sharded caches skip the slice (it would
        reshard the sp/tp layout mid-step).  Scale tensors (int8/int4
        caches) slice in lockstep with their K/V; int4 carriers slice
        at HALF the logical bucket (2 codes/byte), with the bucket
        rounded down to even so carrier and scale stay aligned.
        Returns the LOGICAL attended length."""
        L = ctx.attend_len
        pack = kv_pack_factor(ck, ks)
        S = ck.shape[2] * pack
        if L:
            L -= L % pack
        if L and L < S and ctx.mesh is None:
            return (ck[:, :, :L // pack], cv[:, :, :L // pack],
                    None if ks is None else ks[:, :, :L],
                    None if vs is None else vs[:, :, :L], L)
        return ck, cv, ks, vs, S

    @staticmethod
    def _dequant_pair(ak, av, aks, avs, dtype):
        """Dequantize attended cache slices to the compute dtype; jnp
        so XLA fuses the int8->float convert into the attend's operand
        load (the HBM stream stays int8 — the ISSUE's bandwidth win on
        the fallback path too).  Int4 carriers additionally unpack via
        shifts/masks in the same fusion, so the stream is 0.5 byte per
        cached value."""
        from ..quantization import dequantize_kv, dequantize_kv_packed

        if kv_pack_factor(ak, aks) == 2:
            return (dequantize_kv_packed(ak, aks, dtype),
                    dequantize_kv_packed(av, avs, dtype))
        return dequantize_kv(ak, aks, dtype), dequantize_kv(av, avs, dtype)


@register
class IncMultiHeadSelfAttention(_ServingAttentionBase):
    """Incremental decoding attention (reference:
    src/ops/inc_multihead_self_attention.{cc,cu}).

    One op handles prompt phase and generation phase: the chunk is the
    prompt slice during prefill (C=chunk bucket) and a single token during
    decode (C=1 bucket).  Token c of row r sits at absolute position
    first_depth[r]+c and attends cache positions s <= that.
    """

    type = OpType.INC_MULTIHEAD_SELF_ATTENTION

    def inference(self, params, inputs, attrs, ctx):
        (x,) = inputs  # [R, C, E]
        bc = ctx.batch_config
        layer = attrs["layer_name"]
        R, C, _ = x.shape
        q, k, v = self._project_qkv(params, x, attrs, ctx)
        positions = bc["first_depth"][:, None] + jnp.arange(C)[None, :]
        if attrs.get("rotary", True):
            theta = attrs.get("rope_theta", 10000.0)
            q = apply_rotary_embedding(q.swapaxes(1, 2), positions[:, None, :],
                                       theta).swapaxes(1, 2)
            k = apply_rotary_embedding(k.swapaxes(1, 2), positions[:, None, :],
                                       theta).swapaxes(1, 2)
        ck, cv, ks, vs = self._cache(ctx, layer)
        quant = ks is not None
        table = self._page_table(ctx)
        slopes = (self._alibi_slopes(attrs["num_q_heads"])
                  if attrs.get("position_bias", False) else None)
        pack = kv_pack_factor(ck, ks)
        flash_mode = self._flash_decode_ok(attrs, ctx, C, ck,
                                           paged=table is not None,
                                           pack=pack)
        if flash_mode:
            interp = flash_mode == "interpret"
            if table is not None:
                from ..kernels.flash_decode import (
                    paged_decode_attention, paged_decode_attention_sharded)

                fn = (paged_decode_attention_sharded
                      if getattr(ctx, "mesh", None) is not None
                      else paged_decode_attention)
                kw = ({"mesh": ctx.mesh}
                      if getattr(ctx, "mesh", None) is not None else {})
                res = fn(q[:, 0], k[:, 0], v[:, 0], ck, cv, table,
                         bc["first_depth"],
                         bc["active"].astype(jnp.int32),
                         self._scale(attrs), interpret=interp,
                         slopes=slopes, s_bound=ctx.attend_len,
                         k_scale=ks, v_scale=vs, **kw)
            elif getattr(ctx, "mesh", None) is not None:
                from ..kernels.flash_decode import (
                    flash_decode_attention_sharded)

                res = flash_decode_attention_sharded(
                    q[:, 0], k[:, 0], v[:, 0], ck, cv,
                    bc["first_depth"], bc["active"].astype(jnp.int32),
                    self._scale(attrs), ctx.mesh, interpret=interp,
                    slopes=slopes, k_scale=ks, v_scale=vs)
            else:
                from ..kernels.flash_decode import flash_decode_attention

                res = flash_decode_attention(
                    q[:, 0], k[:, 0], v[:, 0], ck, cv,
                    bc["first_depth"], bc["active"].astype(jnp.int32),
                    self._scale(attrs), interpret=interp, slopes=slopes,
                    k_scale=ks, v_scale=vs)
            out1, ck, cv = res[:3]
            if quant:
                ks, vs = res[3], res[4]
            self._store(ctx, layer, ck, cv, ks, vs)
            return [self._output(params, out1[:, None], attrs, ctx)]
        flash_pre = self._flash_prefill_ok(attrs, ctx, C, ck,
                                           paged=table is not None,
                                           pack=pack)
        if flash_pre:
            interp = flash_pre == "interpret"
            if table is not None:
                from ..kernels.flash_prefill import (
                    paged_prefill_attention,
                    paged_prefill_attention_sharded)

                fn = (paged_prefill_attention_sharded
                      if getattr(ctx, "mesh", None) is not None
                      else paged_prefill_attention)
                kw = ({"mesh": ctx.mesh}
                      if getattr(ctx, "mesh", None) is not None else {})
                res = fn(q, k, v, ck, cv, table, bc["first_depth"],
                         bc["row_tokens"],
                         bc["active"].astype(jnp.int32),
                         self._scale(attrs), interpret=interp,
                         s_bound=ctx.attend_len, slopes=slopes,
                         k_scale=ks, v_scale=vs, **kw)
            elif getattr(ctx, "mesh", None) is not None:
                from ..kernels.flash_prefill import (
                    flash_prefill_attention_sharded)

                res = flash_prefill_attention_sharded(
                    q, k, v, ck, cv, bc["first_depth"],
                    bc["row_tokens"], bc["active"].astype(jnp.int32),
                    self._scale(attrs), ctx.mesh, interpret=interp,
                    slopes=slopes, s_bound=ctx.attend_len,
                    k_scale=ks, v_scale=vs)
            else:
                from ..kernels.flash_prefill import (
                    flash_prefill_attention)

                res = flash_prefill_attention(
                    q, k, v, ck, cv, bc["first_depth"],
                    bc["row_tokens"], bc["active"].astype(jnp.int32),
                    self._scale(attrs), interpret=interp,
                    s_bound=ctx.attend_len, slopes=slopes,
                    k_scale=ks, v_scale=vs)
            out, ck, cv = res[:3]
            if quant:
                ks, vs = res[3], res[4]
            self._store(ctx, layer, ck, cv, ks, vs)
            return [self._output(params, out, attrs, ctx)]
        ck, cv, ks, vs = self._scatter_any(
            ck, cv, ks, vs, k, v, bc["first_depth"], bc["active"],
            table=table)
        self._store(ctx, layer, ck, cv, ks, vs)
        if table is not None:
            ak, av, aks, avs, S = self._paged_gather(ctx, ck, cv, ks,
                                                     vs, table)
        else:
            ak, av, aks, avs, S = self._attend_slice(ctx, ck, cv, ks,
                                                     vs)
        if quant:
            ak, av = self._dequant_pair(ak, av, aks, avs, q.dtype)
        span = jnp.arange(S)[None, None, :]  # [1,1,S]
        mask = (span <= positions[:, :, None]) & bc["active"][:, None, None]
        alibi = None
        if attrs.get("position_bias", False):
            key_pos = jnp.broadcast_to(jnp.arange(S)[None, :], (R, S))
            alibi = (jnp.asarray(self._alibi_slopes(attrs["num_q_heads"])),
                     positions, key_pos)
        out = _attend(q, ak, av, mask, self._scale(attrs), alibi)
        return [self._output(params, out, attrs, ctx)]

    @staticmethod
    def _flash_decode_ok(attrs, ctx, C, ck, paged=False, pack=1):
        """Gate for the length-tiled flash-decode kernel
        (kernels/flash_decode.py).  The HOST decides per step whether the
        kernel's per-row tile pruning beats the XLA attend for this
        batch's depth profile (inference_manager.flash_wins sets
        ctx.use_flash); this gate checks the shapes the kernel supports
        (single-token decode, lane-aligned head dim, unsharded cache or
        one sharded over tp/sp — r5; ALiBi is in-kernel).  ``paged``
        records gate on the page-table kernel's shapes instead
        (paged_path_ok — PR 10).  ``pack``: codes per carrier byte —
        int4 caches need the wider 64-logical-position alignment (32
        int8 sublanes of carrier).  FF_FLASH_DECODE=interpret runs the
        kernel interpreted regardless of platform (CI coverage of the
        in-model wiring on CPU); =0 disables.  Returns 'interpret',
        True or False."""
        import os

        from ..kernels.flash_decode import flash_path_ok, paged_path_ok

        mode = os.environ.get("FF_FLASH_DECODE", "auto")
        if mode == "0" or not getattr(ctx, "use_flash", False):
            return False
        gate = paged_path_ok if paged else flash_path_ok
        ok = (gate(C, ck, getattr(ctx, "mesh", None), pack=pack)
              and (mode == "interpret" or pallas_tpu_available()))
        return (mode if mode == "interpret" else True) if ok else False

    @staticmethod
    def _flash_prefill_ok(attrs, ctx, C, ck, paged=False, pack=1):
        """Gate for the length-tiled flash-prefill kernel
        (kernels/flash_prefill.py).  The HOST decides per step whether
        the kernel beats the XLA prefill attend for this batch's attend
        bucket (inference_manager.flash_prefill_wins sets
        ctx.use_flash); this checks the shapes the kernel supports
        (16-divisible multi-token chunk, lane-aligned head dim,
        unsharded cache or one sharded over tp/sp — r5; ALiBi is
        in-kernel).  ``paged`` records gate on the page-table kernel's
        shapes instead (paged_prefill_path_ok — PR 10).
        FF_FLASH_PREFILL=interpret runs the kernel interpreted
        regardless of platform; =0 disables."""
        import os

        from ..kernels.flash_prefill import (paged_prefill_path_ok,
                                             prefill_path_ok)

        mode = os.environ.get("FF_FLASH_PREFILL", "auto")
        if mode == "0" or not getattr(ctx, "use_flash", False):
            return False
        gate = paged_prefill_path_ok if paged else prefill_path_ok
        ok = (gate(C, ck, getattr(ctx, "mesh", None), pack=pack)
              and (mode == "interpret" or pallas_tpu_available()))
        return (mode if mode == "interpret" else True) if ok else False

    def flops(self, attrs, in_specs):
        (x,) = in_specs
        e = attrs["embed_dim"]
        toks = int(np.prod(x.shape[:-1]))
        return 2 * toks * x.shape[-1] * e * 4


@register
class SpecIncMultiHeadSelfAttention(IncMultiHeadSelfAttention):
    """Beam-search (SSM-side) attention (reference:
    src/ops/spec_inc_multihead_self_attention.cu).

    Identical compute to the incremental op — the beam dimension is folded
    into the request rows (BeamSearchBatchConfig.row), and beam-parent cache
    shuffles happen once per step in the InferenceManager (gather of cache
    rows by parent id) instead of the reference's per-kernel sub-request
    indexing.
    """

    type = OpType.SPEC_INC_MULTIHEAD_SELF_ATTENTION


@register
class TreeIncMultiHeadSelfAttention(_ServingAttentionBase):
    """Tree-verify attention (reference:
    src/ops/tree_inc_multihead_self_attention.cu).

    Two extra data inputs vs incremental mode:
    - commit lists: before computing, move previously-speculated KV entries
      to their committed positions (commit_tokens_kernel :276-330).  Here
      that is a vmapped gather+scatter inside the same jit.
    - tree mask: token c attends committed prefix (s < first_depth) plus its
      in-batch ancestors (tree_mask[r, c, c']), the tree tokens living at
      cache slots first_depth + c'.
    RoPE uses the per-token tree depth (siblings share positions).
    """

    type = OpType.TREE_INC_MULTIHEAD_SELF_ATTENTION

    @staticmethod
    def _commit(cache, count, src, dst):
        """Move verified speculative KV to committed slots.

        cache [R,KV,S,D]; per row, for i < count:
        cache[:, dst[i]] = cache[:, src[i]].  Non-committed entries
        scatter out of bounds and drop.
        """

        def row(cache_row, n, s_idx, d_idx):       # cache_row [KV, S, D]
            vals = cache_row[:, s_idx]             # [KV, C, D] gather
            # discard sentinel must be out-of-bounds *positive* (negative
            # indices wrap in JAX even under mode='drop')
            S = cache_row.shape[1]
            d_safe = jnp.where(jnp.arange(s_idx.shape[0]) < n, d_idx, S)
            return cache_row.at[:, d_safe].set(vals, mode="drop")

        return jax.vmap(row)(cache, count, src, dst)

    @staticmethod
    def _commit_paged(pool, table, count, src, dst):
        """The page-table commit: per row, for i < count, the KV at
        logical position src[i] moves to logical position dst[i] —
        both resolved to (frame, in-frame offset) through the row's
        table.  Rank-agnostic (4-D K/V pools and 3-D scale pools);
        non-committed entries target the sentinel frame and drop."""
        F = pool.shape[0]
        L = pool.shape[2]
        P = table.shape[1]
        n_slots = src.shape[1]
        src = jnp.clip(src.astype(jnp.int32), 0, P * L - 1)
        fs = jnp.clip(jnp.take_along_axis(table, src // L, axis=1),
                      0, F - 1)
        vals = pool[fs, :, src % L]                # [R, C, KV(, D)]
        live = jnp.arange(n_slots)[None, :] < count[:, None]
        dpage = dst.astype(jnp.int32) // L
        okd = live & (dst >= 0) & (dpage < P)
        fd = jnp.take_along_axis(table, jnp.clip(dpage, 0, P - 1),
                                 axis=1)
        fd = jnp.where(okd, fd, F)
        return pool.at[fd, :, dst % L].set(vals, mode="drop")

    @staticmethod
    def _commit_packed_paged(pool, table, count, src, dst):
        """The page-table commit for int4 CARRIER pools ``[F, KV,
        page_len//2, D]``: logical position ``src[i]`` resolves through
        the table to (frame, carrier byte, nibble); the gather
        sign-extends the selected nibble and the rewrite runs the
        two-pass parity merge at the destination (even logical
        positions first, odd on the pass-A result) so committed
        neighbours sharing a destination byte compose.  Scale pools
        stay logical-length and take :meth:`_commit_paged`."""
        F, KV, L2, D = pool.shape
        L = L2 * 2
        P = table.shape[1]
        n_slots = src.shape[1]
        src = jnp.clip(src.astype(jnp.int32), 0, P * L - 1)
        fs = jnp.clip(jnp.take_along_axis(table, src // L, axis=1),
                      0, F - 1)
        v = pool[fs, :, (src % L) // 2].astype(jnp.int32)  # [R,C,KV,D]
        code = jnp.where((src % 2).astype(bool)[:, :, None, None],
                         v >> 4, (v << 28) >> 28)          # sign-extended
        live = jnp.arange(n_slots)[None, :] < count[:, None]
        dst = dst.astype(jnp.int32)
        dpage = dst // L
        fd = jnp.take_along_axis(table, jnp.clip(dpage, 0, P - 1),
                                 axis=1)
        okd = (live & (dst >= 0) & (dpage < P)
               & (fd >= 0) & (fd < F))
        fd = jnp.where(okd, fd, 0)      # safe gather index; DROP via tgt
        db = (dst % L) // 2
        odd = (dst % 2).astype(bool)
        for parity in (False, True):
            m = okd & (odd == parity)
            old = pool[fd, :, db].astype(jnp.int32)
            c4 = code & 0x0F
            new = jnp.where(odd[:, :, None, None],
                            (old & 0x0F) | (c4 << 4),
                            (old & ~0x0F) | c4).astype(pool.dtype)
            pool = pool.at[jnp.where(m, fd, F), :, db].set(new,
                                                           mode="drop")
        return pool

    def inference(self, params, inputs, attrs, ctx):
        (x,) = inputs  # [R, C, E] — C = flattened tree slots
        bc = ctx.batch_config
        layer = attrs["layer_name"]
        R, C, _ = x.shape
        ck, cv, ks, vs = self._cache(ctx, layer)
        quant = ks is not None
        pack = kv_pack_factor(ck, ks)
        table = self._page_table(ctx)
        # 1) commit verified tokens from the previous verify step
        # (int8/int4 caches move each committed position's SCALE with
        # its codes — a code reinterpreted under another position's
        # scale would silently rescale the whole head slice; int4
        # carriers commit nibble-wise via the packed commit twins)
        if table is not None:
            commit = (lambda c: self._commit_paged(
                c, table, bc["commit_count"], bc["commit_src"],
                bc["commit_dst"]))
            commit_kv = commit if pack == 1 else (
                lambda c: self._commit_packed_paged(
                    c, table, bc["commit_count"], bc["commit_src"],
                    bc["commit_dst"]))
        else:
            commit = (lambda c: self._commit(
                c, bc["commit_count"], bc["commit_src"],
                bc["commit_dst"]))
            if pack == 1:
                commit_kv = commit
            else:
                from ..quantization import commit_kv_packed
                commit_kv = (lambda c: commit_kv_packed(
                    c, bc["commit_count"], bc["commit_src"],
                    bc["commit_dst"]))
        ck = commit_kv(ck)
        cv = commit_kv(cv)
        if quant:
            ks = commit(ks)
            vs = commit(vs)
        # 2) project + RoPE at tree depths
        q, k, v = self._project_qkv(params, x, attrs, ctx)
        depths = bc["token_depth"]  # [R, C]
        if attrs.get("rotary", True):
            theta = attrs.get("rope_theta", 10000.0)
            q = apply_rotary_embedding(q.swapaxes(1, 2), depths[:, None, :],
                                       theta).swapaxes(1, 2)
            k = apply_rotary_embedding(k.swapaxes(1, 2), depths[:, None, :],
                                       theta).swapaxes(1, 2)
        # 3) stash tree K/V flat at [first_depth, first_depth+C)
        ck, cv, ks, vs = self._scatter_any(
            ck, cv, ks, vs, k, v, bc["first_depth"], bc["active"],
            table=table)
        self._store(ctx, layer, ck, cv, ks, vs)
        # 4) mask: committed prefix + in-batch ancestors
        if table is not None:
            ak, av, aks, avs, S = self._paged_gather(ctx, ck, cv, ks,
                                                     vs, table)
        else:
            ak, av, aks, avs, S = self._attend_slice(ctx, ck, cv, ks,
                                                     vs)
        if quant:
            ak, av = self._dequant_pair(ak, av, aks, avs, q.dtype)
        span = jnp.arange(S)[None, None, :]
        committed = span < bc["first_depth"][:, None, None]  # [R,1->C,S]
        # scatter tree_mask [R,C,C] into the S axis at first_depth offset
        def place(tm_row, start):  # tm_row [C, C] -> [C, S]
            full = jnp.zeros((C, S), bool)
            return jax.lax.dynamic_update_slice(full, tm_row, (0, start))

        intree = jax.vmap(place)(bc["tree_mask"], bc["first_depth"])
        mask = (committed | intree) & bc["active"][:, None, None]
        alibi = None
        if attrs.get("position_bias", False):
            # key position = slot index for the committed prefix, token
            # depth for in-tree slots (scattered over the slot range)
            base_pos = jnp.broadcast_to(jnp.arange(S)[None, :], (R, S))

            def place_pos(pos_row, d_row, start):
                return jax.lax.dynamic_update_slice(pos_row, d_row, (start,))

            key_pos = jax.vmap(place_pos)(base_pos, depths, bc["first_depth"])
            alibi = (jnp.asarray(self._alibi_slopes(attrs["num_q_heads"])),
                     depths, key_pos)
        out = _attend(q, ak, av, mask, self._scale(attrs), alibi)
        return [self._output(params, out, attrs, ctx)]
