"""Dense/compute and data-movement operators.

TPU-native equivalents of the reference's core op set (src/ops/*.cc + CUDA
kernels in src/ops/kernels/).  Each op is a pure jnp computation: the cuBLAS
GEMM in linear_kernels.cu:130 becomes one jnp.einsum the MXU executes; the
hand-written broadcast logic of element_binary.cu is jnp broadcasting; all
backward kernels are jax.grad.

Convention: activations are [batch, ..., channels] (row-major outermost
batch), matching the reference's logical shapes (it stores innermost-first).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.initializers import DEFAULT_BIAS_INIT, DEFAULT_WEIGHT_INIT
from ..core.tensor import TensorSpec
from ..fftype import ActiMode, AggrMode, DataType, OpType, apply_activation
from .registry import OpContext, OpDef, ParamSpec, register, simple_op


# --------------------------------------------------------------------- Linear
@register
class Linear(OpDef):
    """Dense layer (reference: src/ops/linear.cc + kernels/linear_kernels.cu).

    weight is stored [in_dim, out_dim] so the forward is a single
    x @ w einsum that XLA maps onto the MXU; fused activation mirrors the
    reference's cublasLt epilogue fusion.
    """

    type = OpType.LINEAR

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        out_dim = attrs["out_dim"]
        dtype = attrs.get("dtype") or x.dtype
        return [TensorSpec(x.shape[:-1] + (out_dim,), dtype)]

    def params(self, attrs, in_specs):
        (x,) = in_specs
        dtype = attrs.get("param_dtype") or attrs.get("dtype") or x.dtype
        ps = [ParamSpec("kernel", (x.shape[-1], attrs["out_dim"]), dtype,
                        attrs.get("kernel_initializer") or DEFAULT_WEIGHT_INIT)]
        if attrs.get("use_bias", True):
            ps.append(ParamSpec("bias", (attrs["out_dim"],), dtype,
                                attrs.get("bias_initializer") or DEFAULT_BIAS_INIT))
        return ps

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        if "kernel_q" in params:
            y = self._quantized_matmul(params, x, ctx)
        else:
            w = params["kernel"].astype(x.dtype)
            y = jnp.einsum("...i,io->...o", x, w,
                           preferred_element_type=jnp.float32).astype(x.dtype)
        if attrs.get("use_bias", True):
            y = y + params["bias"].astype(y.dtype)
        return [apply_activation(y, attrs.get("activation", ActiMode.NONE))]

    @staticmethod
    def _quantized_matmul(params, x, ctx=None):
        """Weight-only-quantized forward.

        int8: XLA convert-dot with the per-channel scale applied AFTER
        the matmul — int8 values are exactly representable in bf16, so
        this is bit-identical to dequantizing the weight first, XLA fuses
        the convert into the dot's operand load (weights stream int8 from
        HBM, measured ≈86% of the weight roofline inside the decode
        scan — the role of the reference's decompress_kernels.cu), and
        post-scaling touches [B, N] instead of [K, N].  A hand-written
        whole-K Pallas kernel was tried in r2/r3 and DELETED: it tied the
        convert-dot in isolation and cost ~2x in-model (the custom call
        blocks XLA's cross-op scheduling).  int4 uses the jnp
        group-dequant path (XLA fuses the unpack into the operand load).
        """
        from ..quantization import dequantize_kernel, native_int8_matmul

        scale = params["kernel_scale"]
        if scale.ndim == 1:  # int8
            if ctx is not None and getattr(ctx, "w8a8", False):
                # MXU-native int8 x int8 (W8A8): the activation rows
                # quantize dynamically, skipping the VPU int8->bf16
                # convert that bounds the convert-dot (~20% faster
                # streaming on v5e; FFConfig.int8_native_matmul)
                return native_int8_matmul(x, params["kernel_q"], scale)
            y = jnp.einsum("...i,io->...o", x,
                           params["kernel_q"].astype(x.dtype),
                           preferred_element_type=jnp.float32)
            return (y * scale).astype(x.dtype)
        w = dequantize_kernel(params, x.dtype)
        return jnp.einsum("...i,io->...o", x, w,
                          preferred_element_type=jnp.float32).astype(x.dtype)

    def flops(self, attrs, in_specs):
        (x,) = in_specs
        return 2 * int(np.prod(x.shape)) * attrs["out_dim"]


# ----------------------------------------------------------------- Embedding
@register
class Embedding(OpDef):
    """Token embedding (reference: src/ops/embedding.cc).

    Supports the reference's SUM/AVG aggregation over a bag-of-ids axis
    (embedding.cc aggr modes) in addition to plain lookup.
    """

    type = OpType.EMBEDDING

    def infer(self, attrs, in_specs):
        (ids,) = in_specs
        out_dim = attrs["out_dim"]
        dtype = attrs.get("dtype", DataType.FLOAT)
        aggr = attrs.get("aggr", AggrMode.NONE)
        if aggr is AggrMode.NONE:
            shape = ids.shape + (out_dim,)
        else:
            shape = ids.shape[:-1] + (out_dim,)
        return [TensorSpec(shape, dtype)]

    def params(self, attrs, in_specs):
        dtype = attrs.get("dtype", DataType.FLOAT)
        return [ParamSpec("embedding", (attrs["num_entries"], attrs["out_dim"]),
                          dtype, attrs.get("kernel_initializer") or DEFAULT_WEIGHT_INIT)]

    def forward(self, params, inputs, attrs, ctx):
        (ids,) = inputs
        table = params["embedding"]
        offset = attrs.get("input_offset", 0)
        if offset:
            ids = ids + offset
        out = jnp.take(table, ids, axis=0)
        aggr = attrs.get("aggr", AggrMode.NONE)
        if aggr is AggrMode.SUM:
            out = out.sum(axis=-2)
        elif aggr is AggrMode.AVG:
            out = out.mean(axis=-2)
        return [out]


# -------------------------------------------------------------- BatchMatmul
@register
class BatchMatmul(OpDef):
    """reference: src/ops/batch_matmul.cc (cublas strided batched gemm)."""

    type = OpType.BATCH_MATMUL

    def infer(self, attrs, in_specs):
        a, b = in_specs
        assert a.shape[:-2] == b.shape[:-2], (a.shape, b.shape)
        assert a.shape[-1] == b.shape[-2]
        return [TensorSpec(a.shape[:-1] + (b.shape[-1],), a.dtype)]

    def forward(self, params, inputs, attrs, ctx):
        a, b = inputs
        return [jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(a.dtype)]

    def flops(self, attrs, in_specs):
        a, b = in_specs
        return 2 * int(np.prod(a.shape)) * b.shape[-1]


# ------------------------------------------------------------- element-wise
_BINARY_FNS = {
    OpType.EW_ADD: jnp.add,
    OpType.EW_SUB: jnp.subtract,
    OpType.EW_MUL: jnp.multiply,
    OpType.EW_DIV: jnp.divide,
    OpType.EW_MAX: jnp.maximum,
    OpType.EW_MIN: jnp.minimum,
    OpType.EW_POW: jnp.power,
}


def _broadcast_infer(attrs, in_specs):
    a, b = in_specs
    shape = np.broadcast_shapes(a.shape, b.shape)
    return [TensorSpec(tuple(shape), a.dtype)]


class ElementBinary(OpDef):
    """reference: src/ops/element_binary.cc (broadcast-aware binary kernels)."""

    def __init__(self, op_type):
        self.type = op_type

    def infer(self, attrs, in_specs):
        return _broadcast_infer(attrs, in_specs)

    def forward(self, params, inputs, attrs, ctx):
        a, b = inputs
        out = _BINARY_FNS[self.type](a, b)
        return [apply_activation(out, attrs.get("activation", ActiMode.NONE))]


for _t in _BINARY_FNS:
    register(ElementBinary(_t))


_UNARY_FNS = {
    OpType.RELU: jax.nn.relu,
    OpType.SIGMOID: jax.nn.sigmoid,
    OpType.TANH: jnp.tanh,
    OpType.ELU: jax.nn.elu,
    OpType.GELU: jax.nn.gelu,
    OpType.SILU: jax.nn.silu,
    OpType.IDENTITY: lambda x: x,
    OpType.RSQRT: jax.lax.rsqrt,
    OpType.EXP: jnp.exp,
    OpType.SIN: jnp.sin,
    OpType.COS: jnp.cos,
}

_SCALAR_FNS = {
    OpType.SCALAR_ADD: lambda x, s: x + s,
    OpType.SCALAR_SUB: lambda x, s: x - s,
    OpType.SCALAR_MUL: lambda x, s: x * s,
    OpType.SCALAR_TRUE_DIV: lambda x, s: x / s,
    OpType.POW: lambda x, s: jnp.power(x, s),
}


class ElementUnary(OpDef):
    """reference: src/ops/element_unary.cc (incl. scalar variants, gelu,
    rsqrt, pow)."""

    def __init__(self, op_type):
        self.type = op_type

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        if self.type in _SCALAR_FNS:
            out = _SCALAR_FNS[self.type](x, attrs["scalar"])
        else:
            out = _UNARY_FNS[self.type](x)
        if attrs.get("inplace"):  # parity no-op: XLA decides buffer reuse
            pass
        return [out]


for _t in list(_UNARY_FNS) + list(_SCALAR_FNS):
    register(ElementUnary(_t))


# ------------------------------------------------------------------ Softmax
@register
class Softmax(OpDef):
    """reference: src/ops/softmax.cc (cuDNN softmax)."""

    type = OpType.SOFTMAX

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        return [jax.nn.softmax(x, axis=attrs.get("axis", -1))]


# ------------------------------------------------------------ data movement
@register
class Reshape(OpDef):
    type = OpType.RESHAPE

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        shape = tuple(attrs["shape"])
        if -1 in shape:
            known = int(np.prod([s for s in shape if s != -1]))
            shape = tuple(int(np.prod(x.shape)) // known if s == -1 else s
                          for s in shape)
        assert np.prod(shape) == np.prod(x.shape), (shape, x.shape)
        return [TensorSpec(shape, x.dtype)]

    def forward(self, params, inputs, attrs, ctx):
        out_shape = self.infer(attrs, [TensorSpec(inputs[0].shape,
                                                  DataType.from_jnp(inputs[0].dtype))])[0].shape
        return [jnp.reshape(inputs[0], out_shape)]


@register
class Transpose(OpDef):
    type = OpType.TRANSPOSE

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        perm = attrs["perm"]
        return [TensorSpec(tuple(x.shape[p] for p in perm), x.dtype)]

    def forward(self, params, inputs, attrs, ctx):
        return [jnp.transpose(inputs[0], attrs["perm"])]


@register
class Concat(OpDef):
    type = OpType.CONCAT

    def infer(self, attrs, in_specs):
        axis = attrs["axis"]
        base = list(in_specs[0].shape)
        base[axis] = sum(s.shape[axis] for s in in_specs)
        return [TensorSpec(tuple(base), in_specs[0].dtype)]

    def forward(self, params, inputs, attrs, ctx):
        return [jnp.concatenate(inputs, axis=attrs["axis"])]


@register
class Split(OpDef):
    type = OpType.SPLIT

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        axis = attrs["axis"]
        sizes = attrs["sizes"]
        assert sum(sizes) == x.shape[axis]
        out = []
        for s in sizes:
            shape = list(x.shape)
            shape[axis] = s
            out.append(TensorSpec(tuple(shape), x.dtype))
        return out

    def forward(self, params, inputs, attrs, ctx):
        splits = np.cumsum(attrs["sizes"])[:-1]
        return list(jnp.split(inputs[0], splits, axis=attrs["axis"]))


@register
class Flat(OpDef):
    """reference: src/ops/flat.cc — flatten all non-batch dims."""

    type = OpType.FLAT

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        return [TensorSpec((x.shape[0], int(np.prod(x.shape[1:]))), x.dtype)]

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        return [jnp.reshape(x, (x.shape[0], -1))]


@register
class Reverse(OpDef):
    type = OpType.REVERSE

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def forward(self, params, inputs, attrs, ctx):
        return [jnp.flip(inputs[0], axis=attrs["axis"])]


@register
class Gather(OpDef):
    """reference: src/ops/gather.cc — torch.gather semantics along a dim."""

    type = OpType.GATHER

    def infer(self, attrs, in_specs):
        x, idx = in_specs
        return [TensorSpec(idx.shape, x.dtype)]

    def forward(self, params, inputs, attrs, ctx):
        x, idx = inputs
        return [jnp.take_along_axis(x, idx, axis=attrs["axis"])]


@register
class Cast(OpDef):
    type = OpType.CAST

    def infer(self, attrs, in_specs):
        return [TensorSpec(in_specs[0].shape, attrs["dtype"])]

    def forward(self, params, inputs, attrs, ctx):
        return [inputs[0].astype(attrs["dtype"].to_jnp())]


# --------------------------------------------------------------- reductions
@register
class ReduceSum(OpDef):
    type = OpType.REDUCE_SUM

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        axes = tuple(a % len(x.shape) for a in attrs["axes"])
        keepdims = attrs.get("keepdims", False)
        shape = tuple(
            (1 if i in axes else s) for i, s in enumerate(x.shape)
            if keepdims or i not in axes
        )
        return [TensorSpec(shape, x.dtype)]

    def forward(self, params, inputs, attrs, ctx):
        return [jnp.sum(inputs[0], axis=tuple(attrs["axes"]),
                        keepdims=attrs.get("keepdims", False))]


@register
class Mean(OpDef):
    type = OpType.MEAN

    def infer(self, attrs, in_specs):
        return ReduceSum().infer(attrs, in_specs)

    def forward(self, params, inputs, attrs, ctx):
        return [jnp.mean(inputs[0], axis=tuple(attrs["axes"]),
                         keepdims=attrs.get("keepdims", False))]


# ------------------------------------------------------------------ Dropout
@register
class Dropout(OpDef):
    """reference: src/ops/dropout.cc (cuDNN RNG); here jax.random inside jit."""

    type = OpType.DROPOUT

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        rate = attrs.get("rate", 0.5)
        if not ctx.training or rate == 0.0:
            return [x]
        assert ctx.rng is not None, "dropout needs an rng in training mode"
        key = jax.random.fold_in(ctx.rng, attrs["seed_offset"])
        if attrs.get("seed"):
            key = jax.random.fold_in(key, attrs["seed"])
        keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
        return [jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)]


# -------------------------------------------------------------------- NoOp
def _identity_infer(attrs, in_specs):
    return [in_specs[0]]


simple_op(OpType.NOOP, _identity_infer, lambda inputs, attrs, ctx: [inputs[0]])


# --------------------------------------------------------------- Constant
@register
class Constant(OpDef):
    """Materialize a host-known constant array in the graph (no inputs).

    Used by the torch.fx importer for traced chains that fold to concrete
    values at the importer's static sequence length — e.g. GPT-2's
    position-id arange feeding its position-embedding lookup.  The value
    rides the op attrs (static, baked into the jitted graph)."""

    type = OpType.CONSTANT

    def infer(self, attrs, in_specs):
        v = np.asarray(attrs["value"])
        return [TensorSpec(tuple(v.shape), DataType.from_jnp(v.dtype))]

    def forward(self, params, inputs, attrs, ctx):
        return [jnp.asarray(attrs["value"])]
