"""Convolution / pooling / batch-norm operators.

TPU-native equivalents of the reference's cuDNN-backed vision ops
(src/ops/conv_2d.cc, pool_2d.cc, batch_norm.cc).  Logical layout is NCHW for
API parity with the reference examples (AlexNet/ResNet, examples/cpp); XLA's
layout assignment re-tiles for the MXU internally, so no manual NHWC
conversion is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.initializers import DEFAULT_BIAS_INIT, DEFAULT_WEIGHT_INIT, ZeroInitializer, ConstantInitializer
from ..core.tensor import TensorSpec
from ..fftype import ActiMode, DataType, OpType, PoolType, apply_activation
from .registry import OpContext, OpDef, ParamSpec, register


def _conv_out(size, kernel, stride, pad):
    return (size + 2 * pad - kernel) // stride + 1


@register
class Conv2D(OpDef):
    """reference: src/ops/conv_2d.cc (cuDNN convolution + fused bias/act)."""

    type = OpType.CONV2D

    def infer(self, attrs, in_specs):
        (x,) = in_specs  # [N, C, H, W]
        n, c, h, w = x.shape
        oh = _conv_out(h, attrs["kernel_h"], attrs["stride_h"], attrs["padding_h"])
        ow = _conv_out(w, attrs["kernel_w"], attrs["stride_w"], attrs["padding_w"])
        return [TensorSpec((n, attrs["out_channels"], oh, ow), x.dtype)]

    def params(self, attrs, in_specs):
        (x,) = in_specs
        c = x.shape[1]
        groups = attrs.get("groups", 1)
        ps = [ParamSpec(
            "kernel",
            (attrs["out_channels"], c // groups, attrs["kernel_h"], attrs["kernel_w"]),
            x.dtype, attrs.get("kernel_initializer") or DEFAULT_WEIGHT_INIT)]
        if attrs.get("use_bias", True):
            ps.append(ParamSpec("bias", (attrs["out_channels"],), x.dtype,
                                attrs.get("bias_initializer") or DEFAULT_BIAS_INIT))
        return ps

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        y = jax.lax.conv_general_dilated(
            x, params["kernel"].astype(x.dtype),
            window_strides=(attrs["stride_h"], attrs["stride_w"]),
            padding=[(attrs["padding_h"], attrs["padding_h"]),
                     (attrs["padding_w"], attrs["padding_w"])],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=attrs.get("groups", 1),
            preferred_element_type=jnp.float32,
        ).astype(x.dtype)
        if attrs.get("use_bias", True):
            y = y + params["bias"].astype(y.dtype)[None, :, None, None]
        return [apply_activation(y, attrs.get("activation", ActiMode.NONE))]

    def flops(self, attrs, in_specs):
        out = self.infer(attrs, in_specs)[0]
        c_in = in_specs[0].shape[1]
        return (2 * int(np.prod(out.shape)) * c_in
                * attrs["kernel_h"] * attrs["kernel_w"]
                // attrs.get("groups", 1))


@register
class Pool2D(OpDef):
    """reference: src/ops/pool_2d.cc (cuDNN pooling)."""

    type = OpType.POOL2D

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        n, c, h, w = x.shape
        oh = _conv_out(h, attrs["kernel_h"], attrs["stride_h"], attrs["padding_h"])
        ow = _conv_out(w, attrs["kernel_w"], attrs["stride_w"], attrs["padding_w"])
        return [TensorSpec((n, c, oh, ow), x.dtype)]

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        pool_type = attrs.get("pool_type", PoolType.MAX)
        window = (1, 1, attrs["kernel_h"], attrs["kernel_w"])
        strides = (1, 1, attrs["stride_h"], attrs["stride_w"])
        padding = [(0, 0), (0, 0),
                   (attrs["padding_h"], attrs["padding_h"]),
                   (attrs["padding_w"], attrs["padding_w"])]
        if pool_type is PoolType.MAX:
            y = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides, padding)
        else:
            summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, padding)
            counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                           window, strides, padding)
            y = summed / counts
        return [apply_activation(y.astype(x.dtype),
                                 attrs.get("activation", ActiMode.NONE))]


@register
class BatchNorm(OpDef):
    """reference: src/ops/batch_norm.cc (cuDNN BN, stored running stats).

    Running stats live as non-trainable state params updated functionally in
    training mode (the reference mutates them in the fwd task).
    """

    type = OpType.BATCHNORM

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def params(self, attrs, in_specs):
        (x,) = in_specs
        c = x.shape[1]
        return [
            ParamSpec("scale", (c,), x.dtype, ConstantInitializer(1.0)),
            ParamSpec("bias", (c,), x.dtype, ZeroInitializer()),
            ParamSpec("running_mean", (c,), x.dtype, ZeroInitializer()),
            ParamSpec("running_var", (c,), x.dtype, ConstantInitializer(1.0)),
        ]

    # running stats are state, not gradient targets
    NON_TRAINABLE = ("running_mean", "running_var")

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        eps = attrs.get("eps", 1e-5)
        xf = x.astype(jnp.float32)  # stats in f32 (bf16-safe)
        if ctx.training:
            axes = (0, 2, 3)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
        else:
            mean = params["running_mean"].astype(jnp.float32)
            var = params["running_var"].astype(jnp.float32)
        inv = jax.lax.rsqrt(var + eps)
        bshape = (1, -1, 1, 1)
        y = (xf - mean.reshape(bshape)) * inv.reshape(bshape)
        if attrs.get("relu", True):
            y = jax.nn.relu(y * params["scale"].reshape(bshape)
                            + params["bias"].reshape(bshape))
        else:
            y = y * params["scale"].reshape(bshape) + params["bias"].reshape(bshape)
        return [y.astype(x.dtype)]

    def new_state(self, params, inputs, attrs, momentum=0.9):
        """Functional running-stat update; applied by the trainer."""
        (x,) = inputs
        axes = (0, 2, 3)
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        rm = params["running_mean"]
        rv = params["running_var"]
        return {
            "running_mean": (momentum * rm.astype(jnp.float32)
                             + (1 - momentum) * mean).astype(rm.dtype),
            "running_var": (momentum * rv.astype(jnp.float32)
                            + (1 - momentum) * var).astype(rv.dtype),
        }
