"""Normalization and fused-residual operators.

TPU-native equivalents of the reference's transformer norm family
(src/ops/layer_norm.cc, residual_layer_norm.cc, add_bias_residual_layer_norm.cc,
rms_norm.cc, residual_rms_norm.cc, sigmoid_silu_multi.cc — each a hand-fused
CUDA kernel).  Here each is a short jnp expression; XLA fuses the
residual-add + normalize + scale chain into one HBM pass, which is exactly
what the reference's hand fusion buys.

Stats are computed in float32 regardless of activation dtype (bfloat16-safe).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.initializers import ConstantInitializer, ZeroInitializer
from ..core.tensor import TensorSpec
from ..fftype import OpType
from .registry import OpDef, ParamSpec, register


def _ln(x, gamma, beta, eps):
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    if gamma is not None:
        y = y * gamma.astype(jnp.float32)
    if beta is not None:
        y = y + beta.astype(jnp.float32)
    return y.astype(x.dtype)


def _rms(x, gamma, eps):
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1, keepdims=True) + eps)
    return (xf * scale * gamma.astype(jnp.float32)).astype(x.dtype)


def _norm_params(attrs, in_specs, elementwise_affine=True, rms=False):
    dim = in_specs[0].shape[-1]
    dtype = in_specs[0].dtype
    ps = []
    if elementwise_affine or rms:
        ps.append(ParamSpec("weight", (dim,), dtype, ConstantInitializer(1.0)))
    # the reference's layer_norm takes use_bias separately from
    # elementwise_affine (model.h layer_norm(..., elementwise_affine, eps,
    # use_bias, ...)); MPT norms are affine-without-bias
    if elementwise_affine and not rms and attrs.get("use_bias", True):
        ps.append(ParamSpec("bias", (dim,), dtype, ZeroInitializer()))
    return ps


@register
class LayerNorm(OpDef):
    """reference: src/ops/layer_norm.cc."""

    type = OpType.LAYERNORM

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def params(self, attrs, in_specs):
        return _norm_params(attrs, in_specs,
                            attrs.get("elementwise_affine", True))

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        gamma = params.get("weight")
        beta = params.get("bias")
        return [_ln(x, gamma, beta, attrs.get("eps", 1e-5))]


@register
class ResidualLayerNorm(OpDef):
    """reference: src/ops/residual_layer_norm.cc — y = LN(x + r1 [+ r2]);
    also returns the pre-norm sum (needed by the next residual hop)."""

    type = OpType.RESIDUAL_LAYERNORM

    def infer(self, attrs, in_specs):
        return [in_specs[0], in_specs[0]]  # (normed, residual_sum)

    def params(self, attrs, in_specs):
        return _norm_params(attrs, [in_specs[0]],
                            attrs.get("elementwise_affine", True))

    def forward(self, params, inputs, attrs, ctx):
        total = inputs[0]
        for r in inputs[1:]:
            total = total + r
        return [_ln(total, params.get("weight"), params.get("bias"),
                    attrs.get("eps", 1e-5)), total]


@register
class AddBiasResidualLayerNorm(OpDef):
    """reference: src/ops/add_bias_residual_layer_norm.cc — fold the
    preceding projection's bias into the residual-add, then LN."""

    type = OpType.ADD_BIAS_RESIDUAL_LAYERNORM

    def infer(self, attrs, in_specs):
        return [in_specs[0], in_specs[0]]

    def params(self, attrs, in_specs):
        dim = in_specs[0].shape[-1]
        dtype = in_specs[0].dtype
        return ([ParamSpec("attn_bias", (dim,), dtype, ZeroInitializer())]
                + _norm_params(attrs, [in_specs[0]],
                               attrs.get("elementwise_affine", True)))

    def forward(self, params, inputs, attrs, ctx):
        x, residual = inputs
        total = x + params["attn_bias"].astype(x.dtype) + residual
        return [_ln(total, params.get("weight"), params.get("bias"),
                    attrs.get("eps", 1e-5)), total]


@register
class RMSNorm(OpDef):
    """reference: src/ops/rms_norm.cc (LLaMA-style)."""

    type = OpType.RMS_NORM

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def params(self, attrs, in_specs):
        return _norm_params(attrs, in_specs, rms=True)

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        return [_rms(x, params["weight"], attrs.get("eps", 1e-6))]


@register
class ResidualRMSNorm(OpDef):
    """reference: src/ops/residual_rms_norm.cc — y = RMS(x + r); returns
    (normed, sum)."""

    type = OpType.RESIDUAL_RMS_NORM

    def infer(self, attrs, in_specs):
        return [in_specs[0], in_specs[0]]

    def params(self, attrs, in_specs):
        return _norm_params(attrs, [in_specs[0]], rms=True)

    def forward(self, params, inputs, attrs, ctx):
        x, residual = inputs
        total = x + residual
        return [_rms(total, params["weight"], attrs.get("eps", 1e-6)), total]


@register
class SigmoidSiluMulti(OpDef):
    """Fused SwiGLU gate: silu(x1) * x2
    (reference: src/ops/sigmoid_silu_multi.cc)."""

    type = OpType.SIGMOID_SILU_MULTI

    def infer(self, attrs, in_specs):
        return [in_specs[0]]

    def forward(self, params, inputs, attrs, ctx):
        x1, x2 = inputs
        return [jax.nn.silu(x1) * x2]
