"""Attention operators (training path).

TPU-native equivalent of the reference's classic multi-head attention for
training (src/ops/attention.cc — cuDNN cudnnMultiHeadAttnForward).  The
serving attention family (IncMultiHeadSelfAttention / Spec / Tree variants,
src/ops/inc_multihead_self_attention.cu etc.) lives in
``flexflow_tpu.ops.serving_attention`` because it is driven by BatchConfig.

The computation is the standard q@k^T softmax v expressed as einsums so XLA
tiles it onto the MXU; flash-style Pallas kernels slot in underneath for long
sequences (see flexflow_tpu/kernels/).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.initializers import DEFAULT_WEIGHT_INIT
from ..core.tensor import TensorSpec
from ..fftype import OpType
from .registry import OpDef, ParamSpec, register


def mha_attention(q, k, v, *, causal=False, mask=None, scale=None,
                  dropout_rate=0.0, dropout_rng=None,
                  sliding_window=None, bias=None):
    """Core attention: q [B, H, Sq, D], k/v [B, KV, Sk, D] ->
    [B, H, Sq, D].  H = KV * G (GQA: query heads grouped per KV head, no
    KV duplication in memory — the layout serving_attention uses).

    ``dropout_rate`` applies to the attention probabilities (matching the
    reference's cuDNN attnDropout, src/ops/attention.cc).
    ``sliding_window``: with ``causal``, restrict each query to the last
    ``sliding_window`` positions (HF Mistral convention:
    0 <= q_pos - k_pos < window).
    ``bias``: additive logits bias [H, Sq, Sk] (T5-style relative
    position bias, applied before the mask)."""
    d = q.shape[-1]
    B, H, Sq, _ = q.shape
    KV = k.shape[1]
    G = H // KV
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    qg = q.reshape(B, KV, G, Sq, d)
    logits = jnp.einsum("bkgqd,bksd->bkgqs", qg, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        # the [H, Sq, Sk] bias's head axis must use the same KV-major
        # grouping as q's reshape above; today T5 relative bias is the
        # only producer and T5 has no GQA — assert rather than silently
        # misassign per-head biases if the two are ever combined
        assert KV == H, (
            "t5_bias with GQA (num_kv_heads < num_heads) needs the bias "
            "head axis laid out KV-major to match the query grouping — "
            f"unverified combination (KV={KV}, H={H})")
        logits = logits + bias.reshape(KV, G, *bias.shape[-2:])[None]
    sk = logits.shape[-1]
    if causal:
        span = jnp.arange(sk)[None, :]
        qpos = (jnp.arange(Sq) + (sk - Sq))[:, None]
        cmask = span <= qpos
        if sliding_window is not None:
            cmask &= (qpos - span) < sliding_window
        logits = jnp.where(cmask[None, None, None], logits, -jnp.inf)
    if mask is not None:
        if mask.ndim == 4:        # [B, H or 1, Sq, Sk] -> group the heads
            mask = (mask.reshape(B, KV, G, Sq, sk)
                    if mask.shape[1] == H else mask[:, :, None])
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    out = jnp.einsum("bkgqs,bksd->bkgqd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    # -1: v's head dim may differ from q's (vdim != kdim)
    return out.reshape(B, H, Sq, -1).astype(v.dtype)


def t5_relative_buckets(rel_pos, num_buckets: int, max_distance: int,
                        bidirectional: bool = True):
    """Bucketize relative positions the T5 way (log-spaced beyond
    num_buckets//4 exact offsets; bidirectional splits the buckets by
    sign).  ``rel_pos`` = key_pos - query_pos.  Mirrors the scheme of
    the T5 paper as implemented by HF T5Attention._relative_position_
    bucket — needed so ported mt5-family checkpoints reproduce exactly
    (the reference aligns an mt5 encoder end-to-end,
    tests/align/mt5_encoder/)."""
    n = num_buckets
    ret = jnp.zeros_like(rel_pos)
    if bidirectional:
        n //= 2
        ret = ret + (rel_pos > 0).astype(rel_pos.dtype) * n
        rel_pos = jnp.abs(rel_pos)
    else:
        rel_pos = -jnp.minimum(rel_pos, 0)
    max_exact = n // 2
    is_small = rel_pos < max_exact
    scaled = (jnp.log(jnp.maximum(rel_pos, 1).astype(jnp.float32)
                      / max_exact)
              / np.log(max_distance / max_exact) * (n - max_exact))
    large = jnp.minimum(max_exact + scaled.astype(rel_pos.dtype), n - 1)
    return ret + jnp.where(is_small, rel_pos, large)


def t5_position_bias(table, sq: int, sk: int, num_buckets: int,
                     max_distance: int, bidirectional: bool = True):
    """Relative position bias [H, Sq, Sk] from a learned bucket table
    [num_buckets, H]."""
    rel = (jnp.arange(sk)[None, :] - jnp.arange(sq)[:, None]).astype(
        jnp.int32)
    buckets = t5_relative_buckets(rel, num_buckets, max_distance,
                                  bidirectional)
    return table[buckets].transpose(2, 0, 1)          # [H, Sq, Sk]


@register
class MultiHeadAttention(OpDef):
    """Training multi-head attention over (query, key, value) inputs
    (reference: src/ops/attention.cc; API model.h multihead_attention)."""

    type = OpType.MULTIHEAD_ATTENTION

    def infer(self, attrs, in_specs):
        q, k, v = in_specs
        return [TensorSpec(q.shape[:-1] + (attrs["embed_dim"],), q.dtype)]

    def params(self, attrs, in_specs):
        q, k, v = in_specs
        e = attrs["embed_dim"]
        h = attrs["num_heads"]
        kv = attrs.get("num_kv_heads") or h        # GQA: fewer KV heads
        kdim = attrs.get("kdim") or e
        vdim = attrs.get("vdim") or e
        d = kdim // h
        dt = q.dtype
        init = attrs.get("kernel_initializer") or DEFAULT_WEIGHT_INIT
        ps = [
            ParamSpec("wq", (q.shape[-1], h, d), dt, init,
                      fans=(q.shape[-1], kdim)),
            ParamSpec("wk", (k.shape[-1], kv, d), dt, init,
                      fans=(k.shape[-1], kv * d)),
            ParamSpec("wv", (v.shape[-1], kv, vdim // h), dt, init,
                      fans=(v.shape[-1], kv * (vdim // h))),
            ParamSpec("wo", (h, vdim // h, e), dt, init, fans=(vdim, e)),
        ]
        # projection biases (reference attention.cc qkv/final bias flags;
        # GPT-2-style checkpoints need them for the torch.fx importer)
        if attrs.get("qkv_bias", False):
            ps += [ParamSpec("bq", (h, d), dt),
                   ParamSpec("bk", (kv, d), dt),
                   ParamSpec("bv", (kv, vdim // h), dt)]
        if attrs.get("final_bias", False):
            ps.append(ParamSpec("bo", (e,), dt))
        t5 = attrs.get("t5_bias")
        if t5:
            ps.append(ParamSpec("rel_bias", (t5["num_buckets"], h), dt))
        return ps

    def forward(self, params, inputs, attrs, ctx):
        xq, xk, xv = inputs  # [B, S, E]
        q = jnp.einsum("bse,ehd->bhsd", xq, params["wq"].astype(xq.dtype))
        k = jnp.einsum("bse,ehd->bhsd", xk, params["wk"].astype(xk.dtype))
        v = jnp.einsum("bse,ehd->bhsd", xv, params["wv"].astype(xv.dtype))
        if attrs.get("qkv_bias", False):
            q = q + params["bq"].astype(q.dtype)[None, :, None, :]
            k = k + params["bk"].astype(k.dtype)[None, :, None, :]
            v = v + params["bv"].astype(v.dtype)[None, :, None, :]
        if attrs.get("rotary", False):
            # full-sequence RoPE at positions 0..S-1 (the torch.fx
            # importer's LLaMA/Mistral-family leaf; serving attention
            # applies the same rotation at cache depths)
            theta = attrs.get("rope_theta", 10000.0)
            pos = jnp.arange(q.shape[2])[None, None, :]
            q = apply_rotary_embedding(q, pos, theta)
            k = apply_rotary_embedding(k, pos, theta)
        rate = attrs.get("dropout", 0.0)
        drop_rng = None
        if ctx.training and rate > 0.0:
            assert ctx.rng is not None, "attention dropout needs ctx.rng"
            drop_rng = jax.random.fold_in(ctx.rng, attrs["seed_offset"])
        bias = None
        t5 = attrs.get("t5_bias")
        if t5:
            bias = t5_position_bias(
                params["rel_bias"].astype(jnp.float32),
                q.shape[2], k.shape[2], t5["num_buckets"],
                t5["max_distance"], t5.get("bidirectional", True))
        # T5 folds the 1/sqrt(d) into init: scale_qk=False means raw QK
        scale = None if attrs.get("scale_qk", True) else 1.0
        out = mha_attention(q, k, v, causal=attrs.get("causal", False),
                            scale=scale, bias=bias,
                            dropout_rate=rate if ctx.training else 0.0,
                            dropout_rng=drop_rng,
                            sliding_window=attrs.get("sliding_window"))
        y = jnp.einsum("bhsd,hde->bse", out, params["wo"].astype(out.dtype))
        if attrs.get("final_bias", False):
            y = y + params["bo"].astype(y.dtype)
        return [y]

    def flops(self, attrs, in_specs):
        q = in_specs[0]
        b, s, e = q.shape
        h = attrs["num_heads"]
        kv = attrs.get("num_kv_heads") or h
        d = (attrs.get("kdim") or e) // h
        # q + o projections at h heads, k/v at kv heads (GQA), plus the
        # two seq^2 attention matmuls
        proj = 2 * b * s * e * (h * d) * 2 + 2 * b * s * e * (kv * d) * 2
        return proj + 4 * b * h * s * s * d


def apply_rotary_embedding(x, positions, theta: float = 10000.0):
    """HF-convention RoPE applied to [..., S, D] given integer positions
    [..., S] (reference: apply_rotary_embedding_hf,
    inc_multihead_self_attention.cu:449 — applied in-kernel during qk
    projection; here it is a fused elementwise stage XLA folds into the
    surrounding einsums).

    Uses the HF pairing (first half / second half split), matching
    transformers' LLaMA implementation so HF checkpoints decode identically.
    """
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., S, half]
    cos = jnp.cos(angles)
    sin = jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    rx1 = x1 * cos - x2 * sin
    rx2 = x2 * cos + x1 * sin
    return jnp.concatenate([rx1, rx2], axis=-1).astype(x.dtype)
