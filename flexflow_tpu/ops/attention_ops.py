"""Attention operators (training path).

TPU-native equivalent of the reference's classic multi-head attention for
training (src/ops/attention.cc — cuDNN cudnnMultiHeadAttnForward).  The
serving attention family (IncMultiHeadSelfAttention / Spec / Tree variants,
src/ops/inc_multihead_self_attention.cu etc.) lives in
``flexflow_tpu.ops.serving_attention`` because it is driven by BatchConfig.

The computation is the standard q@k^T softmax v expressed as einsums so XLA
tiles it onto the MXU; flash-style Pallas kernels slot in underneath for long
sequences (see flexflow_tpu/kernels/).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.initializers import DEFAULT_WEIGHT_INIT
from ..core.tensor import TensorSpec
from ..fftype import OpType
from .registry import OpDef, ParamSpec, register


def mha_attention(q, k, v, *, causal=False, mask=None, scale=None,
                  dropout_rate=0.0, dropout_rng=None):
    """Core attention: q,k,v [B, H, S, D] -> [B, H, Sq, D].

    ``dropout_rate`` applies to the attention probabilities (matching the
    reference's cuDNN attnDropout, src/ops/attention.cc)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cmask, logits, -jnp.inf)
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(v.dtype)


@register
class MultiHeadAttention(OpDef):
    """Training multi-head attention over (query, key, value) inputs
    (reference: src/ops/attention.cc; API model.h multihead_attention)."""

    type = OpType.MULTIHEAD_ATTENTION

    def infer(self, attrs, in_specs):
        q, k, v = in_specs
        return [TensorSpec(q.shape[:-1] + (attrs["embed_dim"],), q.dtype)]

    def params(self, attrs, in_specs):
        q, k, v = in_specs
        e = attrs["embed_dim"]
        h = attrs["num_heads"]
        kdim = attrs.get("kdim") or e
        vdim = attrs.get("vdim") or e
        dt = q.dtype
        init = attrs.get("kernel_initializer") or DEFAULT_WEIGHT_INIT
        ps = [
            ParamSpec("wq", (q.shape[-1], h, kdim // h), dt, init,
                      fans=(q.shape[-1], kdim)),
            ParamSpec("wk", (k.shape[-1], h, kdim // h), dt, init,
                      fans=(k.shape[-1], kdim)),
            ParamSpec("wv", (v.shape[-1], h, vdim // h), dt, init,
                      fans=(v.shape[-1], vdim)),
            ParamSpec("wo", (h, vdim // h, e), dt, init, fans=(vdim, e)),
        ]
        # projection biases (reference attention.cc qkv/final bias flags;
        # GPT-2-style checkpoints need them for the torch.fx importer)
        if attrs.get("qkv_bias", False):
            ps += [ParamSpec("bq", (h, kdim // h), dt),
                   ParamSpec("bk", (h, kdim // h), dt),
                   ParamSpec("bv", (h, vdim // h), dt)]
        if attrs.get("final_bias", False):
            ps.append(ParamSpec("bo", (e,), dt))
        return ps

    def forward(self, params, inputs, attrs, ctx):
        xq, xk, xv = inputs  # [B, S, E]
        q = jnp.einsum("bse,ehd->bhsd", xq, params["wq"].astype(xq.dtype))
        k = jnp.einsum("bse,ehd->bhsd", xk, params["wk"].astype(xk.dtype))
        v = jnp.einsum("bse,ehd->bhsd", xv, params["wv"].astype(xv.dtype))
        if attrs.get("qkv_bias", False):
            q = q + params["bq"].astype(q.dtype)[None, :, None, :]
            k = k + params["bk"].astype(k.dtype)[None, :, None, :]
            v = v + params["bv"].astype(v.dtype)[None, :, None, :]
        rate = attrs.get("dropout", 0.0)
        drop_rng = None
        if ctx.training and rate > 0.0:
            assert ctx.rng is not None, "attention dropout needs ctx.rng"
            drop_rng = jax.random.fold_in(ctx.rng, attrs["seed_offset"])
        out = mha_attention(q, k, v, causal=attrs.get("causal", False),
                            dropout_rate=rate if ctx.training else 0.0,
                            dropout_rng=drop_rng)
        y = jnp.einsum("bhsd,hde->bse", out, params["wo"].astype(out.dtype))
        if attrs.get("final_bias", False):
            y = y + params["bo"].astype(y.dtype)
        return [y]

    def flops(self, attrs, in_specs):
        q = in_specs[0]
        b, s, e = q.shape
        h = attrs["num_heads"]
        return 2 * b * s * e * e * 4 + 4 * b * h * s * s * (e // h)


def apply_rotary_embedding(x, positions, theta: float = 10000.0):
    """HF-convention RoPE applied to [..., S, D] given integer positions
    [..., S] (reference: apply_rotary_embedding_hf,
    inc_multihead_self_attention.cu:449 — applied in-kernel during qk
    projection; here it is a fused elementwise stage XLA folds into the
    surrounding einsums).

    Uses the HF pairing (first half / second half split), matching
    transformers' LLaMA implementation so HF checkpoints decode identically.
    """
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., S, half]
    cos = jnp.cos(angles)
    sin = jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    rx1 = x1 * cos - x2 * sin
    rx2 = x2 * cos + x1 * sin
    return jnp.concatenate([rx1, rx2], axis=-1).astype(x.dtype)
