"""Ring attention: sequence/context parallelism over ICI.

The reference has NO sequence parallelism — the sequence dim is never a
sharded dim anywhere in its PCG (SURVEY.md §5: no ring attention, no
Ulysses; KV caches are dense per-shard).  Long-context support is therefore
designed fresh here, TPU-first, as a first-class parallel dim alongside
dp/tp/pp/ep:

- q, k, v are sharded on the sequence dim over the `sp` mesh axis: each
  device holds a T/S block.
- Attention runs blockwise with the online-softmax (flash) recurrence:
  each device computes its q-block against the kv-block it currently
  holds, then the kv-block rotates one step around the `sp` ring via
  `lax.ppermute`.  After S steps every q-block has seen every kv-block
  while HBM only ever holds one kv-block per device, and the ppermute
  overlaps with the block matmuls (XLA schedules the collective-permute
  concurrently with compute on TPU).
- Causal masking uses *global* positions (shard_index * block + offset),
  so fully-future blocks contribute zero mass.

Reverse-mode AD through the scan+ppermute yields the backward ring
automatically (ppermute's transpose is the inverted ring).

The math follows the blockwise-parallel-transformer / ring-attention
formulation (PAPERS.md); the implementation is original and jit/GSPMD
native: `sp` is the only manual axis, so dp sharding of the batch dim and
tp sharding of the heads dim compose with it unchanged inside the same
shard_map.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec

from ..config import AXIS_SEQ

P = PartitionSpec

_NEG_BIG = -0.7 * float(np.finfo(np.float32).max)  # finite "-inf" (nan-safe)


def _block_scores(q, k, scale):
    """[b,t,h,d] x [b,s,kv,d] -> [b,h,t,s] with GQA grouping."""
    b, t, h, d = q.shape
    kv = k.shape[2]
    g = h // kv
    qg = q.reshape(b, t, kv, g, d)
    s = jnp.einsum("btkgd,bskd->bkgts", qg.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    return s.reshape(b, kv * g, t, s.shape[-1])


def _block_context(p, v):
    """[b,h,t,s] x [b,s,kv,d] -> [b,t,h,d] with GQA grouping."""
    b, h, t, s = p.shape
    kv = v.shape[2]
    g = h // kv
    pg = p.reshape(b, kv, g, t, s)
    o = jnp.einsum("bkgts,bskd->btkgd", pg, v.astype(jnp.float32))
    return o.reshape(b, t, h, v.shape[3])


def _ring_attention_sharded(q, k, v, *, axis: str, causal: bool,
                            scale: float):
    """Body run per-`sp`-shard inside shard_map; q [b, tl, h, d],
    k/v [b, tl, kv, d] (tl = local sequence block)."""
    num_shards = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    b, tl, h, d = q.shape
    q_pos = my * tl + jnp.arange(tl)

    o0 = jnp.zeros((b, tl, h, d), jnp.float32)
    m0 = jnp.full((b, h, tl), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, tl), jnp.float32)
    ring = [(i, (i + 1) % num_shards) for i in range(num_shards)]

    def step(carry, i):
        o, m, l, k, v = carry
        src = (my - i) % num_shards  # owner of the kv block we hold now
        kv_pos = src * tl + jnp.arange(tl)
        s = _block_scores(q, k, scale)  # [b,h,tl,tl] f32
        if causal:
            mask = kv_pos[None, :] <= q_pos[:, None]  # [tq, tk]
            s = jnp.where(mask[None, None], s, _NEG_BIG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)  # [b,h,tl]
        p = jnp.exp(s - m_new[..., None])
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr.transpose(0, 2, 1)[..., None] + _block_context(p, v)
        k = jax.lax.ppermute(k, axis, ring)
        v = jax.lax.ppermute(v, axis, ring)
        return (o, m_new, l, k, v), None

    (o, m, l, k, v), _ = jax.lax.scan(step, (o0, m0, l0, k, v),
                                      jnp.arange(num_shards))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def manual_axis_active(axis: str) -> bool:
    """True when tracing inside a shard_map that already binds `axis` as
    manual (e.g. the pp pipeline binding sp for the ring)."""
    m = jax.sharding.get_abstract_mesh()
    return (not m.empty) and axis in getattr(m, "manual_axes", ())


def ring_attention(q, k, v, *, mesh: Optional[Mesh] = None,
                   axis: str = AXIS_SEQ, causal: bool = True,
                   scale: Optional[float] = None):
    """Sequence-parallel attention.

    q: [b, T, h, d], k/v: [b, T, kv, d] with T sharded over `axis`
    (kv may be < h for GQA/MQA; h % kv == 0).  Returns [b, T, h, d] with
    the same sequence sharding.  When the mesh axis has size 1 (or no mesh)
    this reduces to one local flash block — same code path, no collectives.

    Composable two ways: called from auto-mode code it opens its own
    shard_map over `axis`; called where `axis` is already manual (inside the
    pp pipeline, which binds sp for it) it runs the ring body directly —
    shardy forbids re-binding a parent's manual axis.
    """
    assert q.shape[2] % k.shape[2] == 0, (q.shape, k.shape)
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    body = functools.partial(_ring_attention_sharded, axis=axis,
                             causal=causal, scale=scale)
    if manual_axis_active(axis):
        return body(q, k, v)
    # inside jit with a context mesh, shard_map must use the context's
    # AbstractMesh (mesh=None), not the concrete mesh
    ctx_mesh = jax.sharding.get_abstract_mesh()
    if not ctx_mesh.empty and axis in ctx_mesh.axis_names:
        mesh = None
    spec = P(None, axis, None, None)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names=frozenset({axis}),
                         check_vma=False)(q, k, v)
