"""Sampling-head operators.

TPU-native equivalents of the reference's serving heads: ArgMax
(src/ops/argmax.cc — greedy + beam variants), ArgTopK (src/ops/arg_topk.cc),
BeamTopK (src/ops/beam_topk.cc), Sampling (src/ops/sampling.cc — top-p via
cub radix sort + prefix sum), TopK (src/ops/topk.cc).

On TPU, sort/top_k are single XLA ops; top-p sampling is a sort + cumulative
sum + masked categorical draw, fully inside jit (the reference needs a
multi-kernel cub pipeline for the same thing).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import TensorSpec
from ..fftype import DataType, OpType
from .registry import OpDef, register


@register
class ArgMax(OpDef):
    """Greedy token selection (reference: src/ops/argmax.cc).  The beam
    variant also returns the parent slot id and log-prob of the winner."""

    type = OpType.ARG_MAX

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        out = [TensorSpec(x.shape[:-1], DataType.INT32)]
        if attrs.get("beam_search", False):
            out.append(TensorSpec(x.shape[:-1], DataType.FLOAT))  # log-probs
        return out

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        idx = jnp.argmax(x, axis=-1).astype(jnp.int32)
        if attrs.get("beam_search", False):
            logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
            return [idx, jnp.take_along_axis(logp, idx[..., None], axis=-1)[..., 0]]
        return [idx]


@register
class ArgTopK(OpDef):
    """reference: src/ops/arg_topk.cc — indices (and optionally probs) of the
    top-k logits; used to propose speculative branches."""

    type = OpType.ARG_TOPK

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        k = attrs["k"]
        out = [TensorSpec(x.shape[:-1] + (k,), DataType.INT32)]
        if attrs.get("speculative_decoding", False):
            out.append(TensorSpec(x.shape[:-1] + (k,), DataType.FLOAT))
        return out

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        vals, idx = jax.lax.top_k(x, attrs["k"])
        idx = idx.astype(jnp.int32)
        if attrs.get("speculative_decoding", False):
            logp = jax.nn.log_softmax(vals.astype(jnp.float32), axis=-1)
            return [idx, logp]
        return [idx]


@register
class TopK(OpDef):
    """reference: src/ops/topk.cc — returns (values, indices)."""

    type = OpType.TOPK

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        k = attrs["k"]
        return [TensorSpec(x.shape[:-1] + (k,), x.dtype),
                TensorSpec(x.shape[:-1] + (k,), DataType.INT32)]

    def forward(self, params, inputs, attrs, ctx):
        vals, idx = jax.lax.top_k(inputs[0], attrs["k"])
        return [vals, idx.astype(jnp.int32)]


@register
class BeamTopK(OpDef):
    """reference: src/ops/beam_topk.cc — per-request top-k over the joint
    (beam slot x vocab) distribution, emitting token ids, parent beam slots
    and cumulative log-probs for BeamSearchBatchConfig."""

    type = OpType.BEAM_TOPK

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        k = attrs["max_beam_width"]
        return [TensorSpec(x.shape[:-1] + (k,), DataType.INT32),   # token ids
                TensorSpec(x.shape[:-1] + (k,), DataType.INT32),   # parent ids
                TensorSpec(x.shape[:-1] + (k,), DataType.FLOAT)]   # log-probs

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs  # [..., vocab] PROBABILITIES (builders place a softmax
        # before this head, matching reference llama.cc)
        k = attrs["max_beam_width"]
        vals, idx = jax.lax.top_k(x.astype(jnp.float32), k)
        logp = jnp.log(vals + 1e-20)
        parents = jnp.zeros(idx.shape, jnp.int32)  # parent = own slot; RM remaps
        return [idx.astype(jnp.int32), parents, logp]


@register
class Sampling(OpDef):
    """Top-p (nucleus) sampling (reference: src/ops/sampling.cc).

    Sort-descending + cumsum + renormalised categorical, all in one jitted
    graph.  Matches the reference semantics: keep the smallest prefix with
    cumulative prob >= top_p (always keeping the first token).

    ``top_k > 0`` additionally restricts candidates to the k highest
    logits before the top-p cut (the GenerationConfig.topk knob the
    reference declares, serve.py:44, but never consumes; 0 = disabled).
    """

    type = OpType.SAMPLING

    def infer(self, attrs, in_specs):
        (x,) = in_specs
        return [TensorSpec(x.shape[:-1], DataType.INT32)]

    def forward(self, params, inputs, attrs, ctx):
        (x,) = inputs
        top_p = attrs.get("top_p", 1.0)
        probs = jax.nn.softmax(x.astype(jnp.float32), axis=-1)
        sort_idx = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
        csum = jnp.cumsum(sorted_p, axis=-1)
        # keep tokens whose *preceding* mass < top_p (first token always kept)
        keep = (csum - sorted_p) < top_p
        top_k = attrs.get("top_k", 0)
        if top_k > 0 and top_k < x.shape[-1]:  # <=0 disabled (no NaN mask)
            keep = keep & (jnp.arange(x.shape[-1]) < top_k)
        masked = jnp.where(keep, sorted_p, 0.0)
        masked = masked / masked.sum(axis=-1, keepdims=True)
        assert ctx.rng is not None, "Sampling op needs ctx.rng"
        key = jax.random.fold_in(ctx.rng, attrs.get("seed_offset", 0))
        draw = jax.random.categorical(key, jnp.log(masked + 1e-20), axis=-1)
        out = jnp.take_along_axis(sort_idx, draw[..., None], axis=-1)[..., 0]
        return [out.astype(jnp.int32)]
