"""ONNX frontend (reference: python/flexflow/onnx/model.py, 375 LoC).

The ``onnx`` package is not part of this image, so the importer is gated:
constructing :class:`ONNXModel` raises a clear ImportError without it.
The replay logic itself is implemented and mirrors the reference's
node-type dispatch (onnx/model.py handle_* methods).
"""

from .model import ONNXModel, UnsupportedOnnxOp

__all__ = ["ONNXModel", "UnsupportedOnnxOp"]
