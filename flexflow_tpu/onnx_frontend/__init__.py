"""ONNX frontend (reference: python/flexflow/onnx/model.py, 375 LoC).

The ``onnx`` package is not part of this image, so proto access goes
through the vendored minimal wire-format codec (:mod:`.minionnx`) — the
importer runs (and is CI-tested) without it; with the real package
installed its protos are used instead.  The replay mirrors the
reference's node-type dispatch (onnx/model.py handle_* methods) and
additionally ports initializer weights exactly.
"""

from . import minionnx
from .model import ONNXModel, UnsupportedOnnxOp

__all__ = ["ONNXModel", "UnsupportedOnnxOp", "minionnx"]
