"""ONNX → Model importer (reference python/flexflow/onnx/model.py).

Dispatches on ONNX node op_type the way the reference's ``ONNXModel``
dispatches via ``handle_<op>`` methods, replaying onto the core Model layer
API.  Gated on the ``onnx`` package (not in this image — the environment
policy is to gate, not install).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from ..core.model import Model
from ..core.tensor import Tensor
from ..fftype import ActiMode, PoolType


class UnsupportedOnnxOp(NotImplementedError):
    pass


def _attrs(node) -> Dict[str, Any]:
    import onnx

    out = {}
    for a in node.attribute:
        out[a.name] = onnx.helper.get_attribute_value(a)
    return out


class ONNXModel:
    """reference: class ONNXModel (onnx/model.py) with ``apply``."""

    def __init__(self, path_or_proto):
        try:
            import onnx  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "the `onnx` package is required for the ONNX frontend; it "
                "is not bundled in this environment — install it or export "
                "the model via the torch.fx frontend instead") from e
        import onnx

        self.proto = (onnx.load(path_or_proto)
                      if isinstance(path_or_proto, str) else path_or_proto)

    def apply(self, ffmodel: Model, inputs: Sequence[Tensor]) -> List[Tensor]:
        g = self.proto.graph
        env: Dict[str, Any] = {}
        init_names = {i.name for i in g.initializer}
        graph_inputs = [i for i in g.input if i.name not in init_names]
        assert len(graph_inputs) == len(inputs), \
            f"model wants {len(graph_inputs)} inputs, got {len(inputs)}"
        for gi, t in zip(graph_inputs, inputs):
            env[gi.name] = t
        for node in g.node:
            handler = getattr(self, f"_handle_{node.op_type.lower()}", None)
            if handler is None:
                raise UnsupportedOnnxOp(node.op_type)
            env[node.output[0]] = handler(ffmodel, node, env)
        return [env[o.name] for o in g.output]

    # ------------------------------------------------------------ handlers
    def _handle_gemm(self, ff, node, env):
        a = _attrs(node)
        x = env[node.input[0]]
        # weight initializer gives out_dim
        w = next(i for i in self.proto.graph.initializer
                 if i.name == node.input[1])
        out_dim = w.dims[0] if not a.get("transB", 0) == 0 else w.dims[1]
        return ff.dense(x, int(out_dim), use_bias=len(node.input) > 2)

    def _handle_matmul(self, ff, node, env):
        return ff.batch_matmul(env[node.input[0]], env[node.input[1]])

    def _handle_relu(self, ff, node, env):
        return ff.relu(env[node.input[0]])

    def _handle_sigmoid(self, ff, node, env):
        return ff.sigmoid(env[node.input[0]])

    def _handle_tanh(self, ff, node, env):
        return ff.tanh(env[node.input[0]])

    def _handle_softmax(self, ff, node, env):
        return ff.softmax(env[node.input[0]],
                          axis=_attrs(node).get("axis", -1))

    def _handle_flatten(self, ff, node, env):
        return ff.flat(env[node.input[0]])

    def _handle_add(self, ff, node, env):
        return ff.add(env[node.input[0]], env[node.input[1]])

    def _handle_sub(self, ff, node, env):
        return ff.subtract(env[node.input[0]], env[node.input[1]])

    def _handle_mul(self, ff, node, env):
        return ff.multiply(env[node.input[0]], env[node.input[1]])

    def _handle_concat(self, ff, node, env):
        return ff.concat([env[i] for i in node.input],
                         axis=_attrs(node).get("axis", 0))

    def _handle_conv(self, ff, node, env):
        a = _attrs(node)
        w = next(i for i in self.proto.graph.initializer
                 if i.name == node.input[1])
        kh, kw = a.get("kernel_shape", [w.dims[2], w.dims[3]])
        sh, sw = a.get("strides", [1, 1])
        pads = a.get("pads", [0, 0, 0, 0])
        return ff.conv2d(env[node.input[0]], int(w.dims[0]), kh, kw, sh, sw,
                         pads[0], pads[1], groups=a.get("group", 1),
                         use_bias=len(node.input) > 2)

    def _handle_maxpool(self, ff, node, env):
        return self._pool(ff, node, env, PoolType.MAX)

    def _handle_averagepool(self, ff, node, env):
        return self._pool(ff, node, env, PoolType.AVG)

    def _pool(self, ff, node, env, pt):
        a = _attrs(node)
        kh, kw = a["kernel_shape"]
        sh, sw = a.get("strides", [kh, kw])
        pads = a.get("pads", [0, 0, 0, 0])
        return ff.pool2d(env[node.input[0]], kh, kw, sh, sw,
                         pads[0], pads[1], pool_type=pt)

    def _handle_dropout(self, ff, node, env):
        a = _attrs(node)
        return ff.dropout(env[node.input[0]], rate=a.get("ratio", 0.5))

    def _handle_identity(self, ff, node, env):
        return env[node.input[0]]

    def _handle_reshape(self, ff, node, env):
        raise UnsupportedOnnxOp(
            "Reshape with runtime shape tensor; export static shapes")
