"""ONNX → Model importer (reference python/flexflow/onnx/model.py).

Dispatches on ONNX node op_type the way the reference's ``ONNXModel``
dispatches via ``handle_<op>`` methods, replaying onto the core Model
layer API, then ports the graph's initializer weights into the framework
param tree (the reference leaves weights to FlexFlow initializers; we
port exactly, like the torch frontend).

Proto access goes through the vendored minimal codec
(:mod:`.minionnx`) when the ``onnx`` package is absent (it is not
bundled in this image), so the frontend is exercised in CI either way;
with the real package installed its protos are used directly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.model import Model
from ..core.tensor import Tensor
from ..fftype import ActiMode, PoolType
from . import minionnx


def _onnx_api():
    """(load, get_attribute_value, numpy_from_tensor) — real onnx package
    if importable, vendored codec otherwise."""
    try:
        import onnx
        from onnx import numpy_helper

        def _load(src):
            # onnx.load takes a path; serialized bytes need the
            # from-string entry point
            if isinstance(src, (bytes, bytearray)):
                return onnx.load_model_from_string(bytes(src))
            return onnx.load(src)

        return _load, onnx.helper.get_attribute_value, \
            numpy_helper.to_array
    except ImportError:
        return (minionnx.load, minionnx.get_attribute_value,
                minionnx.numpy_from_tensor)


class UnsupportedOnnxOp(NotImplementedError):
    pass


class ONNXModel:
    """reference: class ONNXModel (onnx/model.py) with ``apply``."""

    def __init__(self, path_or_proto):
        load, self._attr_value, self._to_array = _onnx_api()
        self.proto = (load(path_or_proto)
                      if isinstance(path_or_proto, (str, bytes, bytearray))
                      else path_or_proto)
        # fx-importer-style porting map: framework layer name ->
        # (weight initializer name, bias initializer name, transpose)
        self.param_layers: Dict[str, tuple] = {}

    def _attrs(self, node) -> Dict[str, Any]:
        return {a.name: self._attr_value(a) for a in node.attribute}

    def _init(self, name: str):
        return next(i for i in self.proto.graph.initializer
                    if i.name == name)

    def apply(self, ffmodel: Model, inputs: Sequence[Tensor]) -> List[Tensor]:
        g = self.proto.graph
        env: Dict[str, Any] = {}
        init_names = {i.name for i in g.initializer}
        graph_inputs = [i for i in g.input if i.name not in init_names]
        assert len(graph_inputs) == len(inputs), \
            f"model wants {len(graph_inputs)} inputs, got {len(inputs)}"
        for gi, t in zip(graph_inputs, inputs):
            env[gi.name] = t
        for node in g.node:
            handler = getattr(self, f"_handle_{node.op_type.lower()}", None)
            if handler is None:
                raise UnsupportedOnnxOp(node.op_type)
            env[node.output[0]] = handler(ffmodel, node, env)
        return [env[o.name] for o in g.output]

    def port_parameters(self, ffmodel: Model) -> None:
        """Copy initializer weights into ``ffmodel.params`` for every
        layer created by :meth:`apply`."""
        assert ffmodel.params is not None, "init params first"
        for lname, (w_name, b_name, transpose) in self.param_layers.items():
            p = ffmodel.params.get(lname)
            if p is None:
                continue
            w = np.asarray(self._to_array(self._init(w_name)))
            p["kernel"] = (w.T if transpose else w).copy()
            if b_name is not None:
                p["bias"] = np.asarray(
                    self._to_array(self._init(b_name))).copy()

    # ------------------------------------------------------------ handlers
    def _handle_gemm(self, ff, node, env):
        a = self._attrs(node)
        x = env[node.input[0]]
        w = self._init(node.input[1])
        trans_b = bool(a.get("transB", 0))
        out_dim = w.dims[0] if trans_b else w.dims[1]
        use_bias = len(node.input) > 2
        y = ff.dense(x, int(out_dim), use_bias=use_bias)
        # framework kernel is [in, out]: transB weights are [out, in]
        self.param_layers[y.owner_layer.name] = (
            node.input[1], node.input[2] if use_bias else None, trans_b)
        return y

    def _handle_matmul(self, ff, node, env):
        return ff.batch_matmul(env[node.input[0]], env[node.input[1]])

    def _handle_relu(self, ff, node, env):
        return ff.relu(env[node.input[0]])

    def _handle_sigmoid(self, ff, node, env):
        return ff.sigmoid(env[node.input[0]])

    def _handle_tanh(self, ff, node, env):
        return ff.tanh(env[node.input[0]])

    def _handle_softmax(self, ff, node, env):
        return ff.softmax(env[node.input[0]],
                          axis=self._attrs(node).get("axis", -1))

    def _handle_flatten(self, ff, node, env):
        return ff.flat(env[node.input[0]])

    def _handle_add(self, ff, node, env):
        return ff.add(env[node.input[0]], env[node.input[1]])

    def _handle_sub(self, ff, node, env):
        return ff.subtract(env[node.input[0]], env[node.input[1]])

    def _handle_mul(self, ff, node, env):
        return ff.multiply(env[node.input[0]], env[node.input[1]])

    def _handle_concat(self, ff, node, env):
        return ff.concat([env[i] for i in node.input],
                         axis=self._attrs(node).get("axis", 0))

    def _handle_conv(self, ff, node, env):
        a = self._attrs(node)
        w = self._init(node.input[1])
        kh, kw = a.get("kernel_shape", [w.dims[2], w.dims[3]])
        sh, sw = a.get("strides", [1, 1])
        pads = a.get("pads", [0, 0, 0, 0])
        use_bias = len(node.input) > 2
        y = ff.conv2d(env[node.input[0]], int(w.dims[0]), kh, kw, sh, sw,
                      pads[0], pads[1], groups=a.get("group", 1),
                      use_bias=use_bias)
        # ONNX conv weights are OIHW — the framework layout, no transpose
        self.param_layers[y.owner_layer.name] = (
            node.input[1], node.input[2] if use_bias else None, False)
        return y

    def _handle_maxpool(self, ff, node, env):
        return self._pool(ff, node, env, PoolType.MAX)

    def _handle_averagepool(self, ff, node, env):
        return self._pool(ff, node, env, PoolType.AVG)

    def _pool(self, ff, node, env, pt):
        a = self._attrs(node)
        kh, kw = a["kernel_shape"]
        sh, sw = a.get("strides", [kh, kw])
        pads = a.get("pads", [0, 0, 0, 0])
        return ff.pool2d(env[node.input[0]], kh, kw, sh, sw,
                         pads[0], pads[1], pool_type=pt)

    def _handle_dropout(self, ff, node, env):
        a = self._attrs(node)
        return ff.dropout(env[node.input[0]], rate=a.get("ratio", 0.5))

    def _handle_identity(self, ff, node, env):
        return env[node.input[0]]

    def _handle_reshape(self, ff, node, env):
        raise UnsupportedOnnxOp(
            "Reshape with runtime shape tensor; export static shapes")
