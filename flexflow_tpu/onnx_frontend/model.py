"""ONNX → Model importer (reference python/flexflow/onnx/model.py).

Dispatches on ONNX node op_type the way the reference's ``ONNXModel``
dispatches via ``handle_<op>`` methods, replaying onto the core Model
layer API, then ports the graph's initializer weights into the framework
param tree (the reference leaves weights to FlexFlow initializers; we
port exactly, like the torch frontend).

Proto access goes through the vendored minimal codec
(:mod:`.minionnx`) when the ``onnx`` package is absent (it is not
bundled in this image), so the frontend is exercised in CI either way;
with the real package installed its protos are used directly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.model import Model
from ..core.tensor import Tensor
from ..fftype import ActiMode, PoolType
from . import minionnx


def _onnx_api():
    """(load, get_attribute_value, numpy_from_tensor) — real onnx package
    if importable, vendored codec otherwise."""
    try:
        import onnx
        from onnx import numpy_helper

        def _load(src):
            # onnx.load takes a path; serialized bytes need the
            # from-string entry point
            if isinstance(src, (bytes, bytearray)):
                return onnx.load_model_from_string(bytes(src))
            return onnx.load(src)

        return _load, onnx.helper.get_attribute_value, \
            numpy_helper.to_array
    except ImportError:
        return (minionnx.load, minionnx.get_attribute_value,
                minionnx.numpy_from_tensor)


class UnsupportedOnnxOp(NotImplementedError):
    pass


class ONNXModel:
    """reference: class ONNXModel (onnx/model.py) with ``apply``."""

    def __init__(self, path_or_proto):
        load, self._attr_value, self._to_array = _onnx_api()
        self.proto = (load(path_or_proto)
                      if isinstance(path_or_proto, (str, bytes, bytearray))
                      else path_or_proto)
        # fx-importer-style porting map: framework layer name ->
        # (weight initializer name, bias initializer name, transpose)
        self.param_layers: Dict[str, tuple] = {}
        # r5 (transformer-block graphs): direct numpy ports — framework
        # layer name -> {param name: ndarray}; used where the value may
        # come from a Constant/Identity chain instead of an initializer
        self.param_arrays: Dict[str, Dict[str, np.ndarray]] = {}
        # Add nodes folded into a preceding biasless MatMul-dense
        self._folded_adds: set = set()

    def _attrs(self, node) -> Dict[str, Any]:
        return {a.name: self._attr_value(a) for a in node.attribute}

    def _init(self, name: str):
        return next(i for i in self.proto.graph.initializer
                    if i.name == name)

    def _is_const(self, name: str, env) -> bool:
        """True when ``name`` resolves to host data: an initializer, a
        Constant/Identity product already in env, or the output of a
        Constant node anywhere in the graph (lookahead — a bias
        Constant may legally be ordered AFTER the MatMul that wants to
        fold it)."""
        if isinstance(env.get(name), np.ndarray):
            return True
        if any(i.name == name for i in self.proto.graph.initializer):
            return True
        return any(n.op_type == "Constant" and name in n.output
                   for n in self.proto.graph.node)

    def _const(self, name: str, env) -> np.ndarray:
        v = env.get(name)
        if isinstance(v, np.ndarray):
            return v
        for n in self.proto.graph.node:
            if n.op_type == "Constant" and name in n.output:
                return self._handle_constant(None, n, env)
        return np.asarray(self._to_array(self._init(name)))

    def _consumers(self, out_name: str):
        return [n for n in self.proto.graph.node if out_name in n.input]

    def apply(self, ffmodel: Model, inputs: Sequence[Tensor]) -> List[Tensor]:
        g = self.proto.graph
        env: Dict[str, Any] = {}
        init_names = {i.name for i in g.initializer}
        graph_inputs = [i for i in g.input if i.name not in init_names]
        assert len(graph_inputs) == len(inputs), \
            f"model wants {len(graph_inputs)} inputs, got {len(inputs)}"
        for gi, t in zip(graph_inputs, inputs):
            env[gi.name] = t
        for node in g.node:
            handler = getattr(self, f"_handle_{node.op_type.lower()}", None)
            if handler is None:
                raise UnsupportedOnnxOp(node.op_type)
            out = handler(ffmodel, node, env)
            outs = out if isinstance(out, (list, tuple)) else [out]
            for name, val in zip(node.output, outs):
                env[name] = val
        return [env[o.name] for o in g.output]

    def port_parameters(self, ffmodel: Model) -> None:
        """Copy initializer weights into ``ffmodel.params`` for every
        layer created by :meth:`apply`."""
        assert ffmodel.params is not None, "init params first"
        for lname, (w_name, b_name, transpose) in self.param_layers.items():
            p = ffmodel.params.get(lname)
            if p is None:
                continue
            w = np.asarray(self._to_array(self._init(w_name)))
            p["kernel"] = (w.T if transpose else w).copy()
            if b_name is not None:
                p["bias"] = np.asarray(
                    self._to_array(self._init(b_name))).copy()
        for lname, arrays in self.param_arrays.items():
            p = ffmodel.params.get(lname)
            if p is None:
                continue
            for pn, arr in arrays.items():
                p[pn] = np.asarray(arr).copy()

    # ------------------------------------------------------------ handlers
    def _handle_gemm(self, ff, node, env):
        a = self._attrs(node)
        x = env[node.input[0]]
        w = self._init(node.input[1])
        trans_b = bool(a.get("transB", 0))
        out_dim = w.dims[0] if trans_b else w.dims[1]
        use_bias = len(node.input) > 2
        y = ff.dense(x, int(out_dim), use_bias=use_bias)
        # framework kernel is [in, out]: transB weights are [out, in]
        self.param_layers[y.owner_layer.name] = (
            node.input[1], node.input[2] if use_bias else None, trans_b)
        return y

    def _handle_matmul(self, ff, node, env):
        """x @ W with a host-side weight becomes a Dense layer (the
        TorchScript exporter emits Linear as MatMul [+ Add bias], weight
        pre-transposed to [in, out]); a following Add whose other operand
        is host data is folded in as the dense bias.  Tensor x tensor
        MatMul (attention q@k^T, att@v) stays a batched matmul."""
        a_name, b_name = node.input[0], node.input[1]
        if not self._is_const(b_name, env):
            return ff.batch_matmul(env[a_name], env[b_name])
        w = self._const(b_name, env)                 # [in, out]
        assert w.ndim == 2, w.shape
        bias_arr = None
        consumers = self._consumers(node.output[0])
        graph_outs = {o.name for o in self.proto.graph.output}
        if (len(consumers) == 1 and consumers[0].op_type == "Add"
                # folding rewrites env[matmul_out] to the biased value,
                # so a matmul output that is ALSO a graph output (or an
                # Add using it for both operands) must not fold
                and node.output[0] not in graph_outs):
            addn = consumers[0]
            others = [i for i in addn.input if i != node.output[0]]
            if others and self._is_const(others[0], env):
                b = self._const(others[0], env)
                if b.ndim == 1 and b.shape[0] == w.shape[1]:
                    bias_arr = b
                    self._folded_adds.add(id(addn))
        y = ff.dense(env[a_name], int(w.shape[1]),
                     use_bias=bias_arr is not None)
        port = {"kernel": w}
        if bias_arr is not None:
            port["bias"] = bias_arr
        self.param_arrays[y.owner_layer.name] = port
        return y

    def _handle_relu(self, ff, node, env):
        return ff.relu(env[node.input[0]])

    def _handle_sigmoid(self, ff, node, env):
        return ff.sigmoid(env[node.input[0]])

    def _handle_tanh(self, ff, node, env):
        return ff.tanh(env[node.input[0]])

    def _handle_softmax(self, ff, node, env):
        return ff.softmax(env[node.input[0]],
                          axis=self._attrs(node).get("axis", -1))

    def _handle_flatten(self, ff, node, env):
        return ff.flat(env[node.input[0]])

    def _handle_add(self, ff, node, env):
        if id(node) in self._folded_adds:        # dense-bias add: folded
            tensor_in = next(i for i in node.input
                             if not self._is_const(i, env))
            return env[tensor_in]
        x, y = env[node.input[0]], env[node.input[1]]
        if isinstance(x, np.ndarray):
            x, y = y, x
        if isinstance(y, np.ndarray):
            if y.ndim == 0 or y.size == 1:
                return ff.scalar_add(x, float(np.reshape(y, ())))
            raise UnsupportedOnnxOp(
                "Add with non-scalar constant operand (unfolded bias)")
        return ff.add(x, y)

    def _handle_sub(self, ff, node, env):
        x, y = env[node.input[0]], env[node.input[1]]
        if isinstance(y, np.ndarray):
            if y.ndim == 0 or y.size == 1:
                return ff.scalar_sub(x, float(np.reshape(y, ())))
            raise UnsupportedOnnxOp("Sub with non-scalar constant operand")
        if isinstance(x, np.ndarray):
            raise UnsupportedOnnxOp("Sub with constant minuend")
        return ff.subtract(x, y)

    def _handle_mul(self, ff, node, env):
        x, y = env[node.input[0]], env[node.input[1]]
        if isinstance(x, np.ndarray):
            x, y = y, x
        if isinstance(y, np.ndarray):
            if y.ndim == 0 or y.size == 1:
                return ff.scalar_multiply(x, float(np.reshape(y, ())))
            raise UnsupportedOnnxOp("Mul with non-scalar constant operand")
        return ff.multiply(x, y)

    def _handle_concat(self, ff, node, env):
        return ff.concat([env[i] for i in node.input],
                         axis=self._attrs(node).get("axis", 0))

    def _handle_conv(self, ff, node, env):
        a = self._attrs(node)
        w = self._init(node.input[1])
        kh, kw = a.get("kernel_shape", [w.dims[2], w.dims[3]])
        sh, sw = a.get("strides", [1, 1])
        pads = a.get("pads", [0, 0, 0, 0])
        use_bias = len(node.input) > 2
        y = ff.conv2d(env[node.input[0]], int(w.dims[0]), kh, kw, sh, sw,
                      pads[0], pads[1], groups=a.get("group", 1),
                      use_bias=use_bias)
        # ONNX conv weights are OIHW — the framework layout, no transpose
        self.param_layers[y.owner_layer.name] = (
            node.input[1], node.input[2] if use_bias else None, False)
        return y

    def _handle_maxpool(self, ff, node, env):
        return self._pool(ff, node, env, PoolType.MAX)

    def _handle_averagepool(self, ff, node, env):
        return self._pool(ff, node, env, PoolType.AVG)

    def _pool(self, ff, node, env, pt):
        a = self._attrs(node)
        kh, kw = a["kernel_shape"]
        sh, sw = a.get("strides", [kh, kw])
        pads = a.get("pads", [0, 0, 0, 0])
        return ff.pool2d(env[node.input[0]], kh, kw, sh, sw,
                         pads[0], pads[1], pool_type=pt)

    def _handle_dropout(self, ff, node, env):
        a = self._attrs(node)
        return ff.dropout(env[node.input[0]], rate=a.get("ratio", 0.5))

    def _handle_identity(self, ff, node, env):
        name = node.input[0]
        if self._is_const(name, env) and name not in env:
            return self._const(name, env)   # initializer alias (tied LN)
        return env[name]

    def _handle_constant(self, ff, node, env):
        a = self._attrs(node)
        for key in ("value", "value_float", "value_int", "value_floats",
                    "value_ints"):
            if key in a:
                v = a[key]
                if key == "value":
                    v = self._to_array(v)
                return np.asarray(v)
        raise UnsupportedOnnxOp(f"Constant with attrs {sorted(a)}")

    def _handle_reshape(self, ff, node, env):
        """Static-shape reshape (the TorchScript exporter emits the
        target shape as a Constant when the traced model used concrete
        dims).  Runtime shape tensors stay unsupported — export with
        static shapes."""
        if not self._is_const(node.input[1], env):
            raise UnsupportedOnnxOp(
                "Reshape with runtime shape tensor; export static shapes")
        shape = [int(d) for d in self._const(node.input[1], env)]
        x = env[node.input[0]]
        if any(d in (0, -1) for d in shape):
            # resolve 0 (copy input dim) and a single -1 against the
            # known element count
            in_shape = list(x.spec.shape)
            shape = [in_shape[i] if d == 0 else d
                     for i, d in enumerate(shape)]
            if shape.count(-1) == 1:
                known = int(np.prod([d for d in shape if d != -1]))
                shape[shape.index(-1)] = int(np.prod(in_shape)) // known
        return ff.reshape(x, tuple(shape))

    def _handle_transpose(self, ff, node, env):
        perm = self._attrs(node).get("perm")
        x = env[node.input[0]]
        if perm is None:
            perm = list(range(len(x.spec.shape)))[::-1]
        return ff.transpose(x, [int(p) for p in perm])

    def _handle_div(self, ff, node, env):
        if self._is_const(node.input[0], env):
            raise UnsupportedOnnxOp("Div with constant numerator")
        x = env[node.input[0]]
        if self._is_const(node.input[1], env):
            d = self._const(node.input[1], env)
            if d.ndim == 0 or d.size == 1:
                return ff.scalar_true_divide(x, float(d.reshape(())))
            raise UnsupportedOnnxOp("Div with non-scalar constant "
                                    "denominator")
        return ff.divide(x, env[node.input[1]])

    def _handle_layernormalization(self, ff, node, env):
        """Opset-17 fused LayerNormalization (x, scale, bias) — the
        torch exporter's nn.LayerNorm; scale/bias may arrive through an
        Identity alias of another layer's initializers (torch ties
        them), so resolve through env."""
        a = self._attrs(node)
        assert a.get("axis", -1) in (-1, None) or \
            a["axis"] == len(env[node.input[0]].spec.shape) - 1, a
        y = ff.layer_norm(env[node.input[0]], eps=a.get("epsilon", 1e-5),
                          elementwise_affine=True)
        port = {"weight": self._const(node.input[1], env)}
        if len(node.input) > 2:
            port["bias"] = self._const(node.input[2], env)
        self.param_arrays[y.owner_layer.name] = port
        return y
