"""Minimal self-contained ONNX protobuf codec.

The environment does not bundle the ``onnx`` package (and the policy is
to gate, not install), which left the ONNX frontend permanently
unexecuted.  ONNX models are ordinary protobufs, and the subset the
importer needs — ModelProto/GraphProto/NodeProto/AttributeProto/
TensorProto/ValueInfoProto — decodes with a ~hundred-line wire-format
reader, so this module implements exactly that (plus the tiny encoder
the tests use to synthesize models).  Field numbers follow the public
onnx.proto3 schema; unknown fields are skipped, like any proto reader.

API mirrors the pieces of the onnx package the frontend touches:
``load(path_or_bytes)``, ``numpy_from_tensor(TensorProto)``,
``get_attribute_value(AttributeProto)``, and ``make_*`` helpers.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# TensorProto.DataType (onnx.proto3)
DT_FLOAT, DT_UINT8, DT_INT8, DT_UINT16, DT_INT16, DT_INT32, DT_INT64 = \
    1, 2, 3, 4, 5, 6, 7
DT_BOOL, DT_FLOAT16, DT_DOUBLE = 9, 10, 11
_NP_OF = {DT_FLOAT: np.float32, DT_UINT8: np.uint8, DT_INT8: np.int8,
          DT_UINT16: np.uint16, DT_INT16: np.int16, DT_INT32: np.int32,
          DT_INT64: np.int64, DT_BOOL: np.bool_, DT_FLOAT16: np.float16,
          DT_DOUBLE: np.float64}

# AttributeProto.AttributeType
AT_FLOAT, AT_INT, AT_STRING, AT_TENSOR, AT_GRAPH = 1, 2, 3, 4, 5
AT_FLOATS, AT_INTS, AT_STRINGS = 6, 7, 8


# ------------------------------------------------------------ wire reader
def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message buffer.
    wire 0 -> varint int, 1 -> 8 bytes, 2 -> bytes, 5 -> 4 bytes."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        fn, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 1:
            v, i = buf[i:i + 8], i + 8
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v, i = buf[i:i + ln], i + ln
        elif wt == 5:
            v, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fn, wt, v


def _packed_varints(buf: bytes) -> List[int]:
    out, i = [], 0
    while i < len(buf):
        v, i = _read_varint(buf, i)
        out.append(v)
    return out


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


# ------------------------------------------------------------- messages
@dataclasses.dataclass
class TensorProto:
    name: str = ""
    dims: List[int] = dataclasses.field(default_factory=list)
    data_type: int = DT_FLOAT
    raw_data: bytes = b""
    float_data: List[float] = dataclasses.field(default_factory=list)
    int64_data: List[int] = dataclasses.field(default_factory=list)
    int32_data: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class AttributeProto:
    name: str = ""
    type: int = 0
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: Optional[TensorProto] = None
    floats: List[float] = dataclasses.field(default_factory=list)
    ints: List[int] = dataclasses.field(default_factory=list)
    strings: List[bytes] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class NodeProto:
    op_type: str = ""
    name: str = ""
    input: List[str] = dataclasses.field(default_factory=list)
    output: List[str] = dataclasses.field(default_factory=list)
    attribute: List[AttributeProto] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ValueInfoProto:
    name: str = ""
    elem_type: int = DT_FLOAT
    shape: List[Optional[int]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class GraphProto:
    name: str = ""
    node: List[NodeProto] = dataclasses.field(default_factory=list)
    initializer: List[TensorProto] = dataclasses.field(default_factory=list)
    input: List[ValueInfoProto] = dataclasses.field(default_factory=list)
    output: List[ValueInfoProto] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ModelProto:
    ir_version: int = 8
    graph: GraphProto = dataclasses.field(default_factory=GraphProto)


def _parse_tensor(buf: bytes) -> TensorProto:
    t = TensorProto()
    for fn, wt, v in _fields(buf):
        if fn == 1:
            t.dims.extend(_packed_varints(v) if wt == 2
                          else [_signed64(v)])
        elif fn == 2:
            t.data_type = v
        elif fn == 4:
            t.float_data.extend(
                struct.unpack(f"<{len(v) // 4}f", v) if wt == 2
                else [struct.unpack("<f", v)[0]])
        elif fn == 5:
            # negative int32 values ride the varint as 64-bit two's
            # complement — recover the sign like the int64 branch
            t.int32_data.extend(
                [_signed64(x) for x in _packed_varints(v)] if wt == 2
                else [_signed64(v)])
        elif fn == 7:
            t.int64_data.extend(
                [_signed64(x) for x in _packed_varints(v)] if wt == 2
                else [_signed64(v)])
        elif fn == 8:
            t.name = v.decode()
        elif fn == 9:
            t.raw_data = v
    return t


def _parse_attribute(buf: bytes) -> AttributeProto:
    a = AttributeProto()
    for fn, wt, v in _fields(buf):
        if fn == 1:
            a.name = v.decode()
        elif fn == 2:
            a.f = struct.unpack("<f", v)[0]
        elif fn == 3:
            a.i = _signed64(v)
        elif fn == 4:
            a.s = v
        elif fn == 5:
            a.t = _parse_tensor(v)
        elif fn == 7:
            a.floats.extend(struct.unpack(f"<{len(v) // 4}f", v)
                            if wt == 2 else [struct.unpack("<f", v)[0]])
        elif fn == 8:
            a.ints.extend([_signed64(x) for x in _packed_varints(v)]
                          if wt == 2 else [_signed64(v)])
        elif fn == 9:
            a.strings.append(v)
        elif fn == 20:
            a.type = v
    return a


def _parse_node(buf: bytes) -> NodeProto:
    n = NodeProto()
    for fn, _, v in _fields(buf):
        if fn == 1:
            n.input.append(v.decode())
        elif fn == 2:
            n.output.append(v.decode())
        elif fn == 3:
            n.name = v.decode()
        elif fn == 4:
            n.op_type = v.decode()
        elif fn == 5:
            n.attribute.append(_parse_attribute(v))
    return n


def _parse_value_info(buf: bytes) -> ValueInfoProto:
    vi = ValueInfoProto()
    for fn, _, v in _fields(buf):
        if fn == 1:
            vi.name = v.decode()
        elif fn == 2:  # TypeProto
            for fn2, _, v2 in _fields(v):
                if fn2 == 1:  # tensor_type
                    for fn3, _, v3 in _fields(v2):
                        if fn3 == 1:
                            vi.elem_type = v3
                        elif fn3 == 2:  # shape
                            for fn4, _, v4 in _fields(v3):
                                if fn4 == 1:  # dim
                                    dim = None
                                    for fn5, _, v5 in _fields(v4):
                                        if fn5 == 1:
                                            dim = _signed64(v5)
                                    vi.shape.append(dim)
    return vi


def _parse_graph(buf: bytes) -> GraphProto:
    g = GraphProto()
    for fn, _, v in _fields(buf):
        if fn == 1:
            g.node.append(_parse_node(v))
        elif fn == 2:
            g.name = v.decode()
        elif fn == 5:
            g.initializer.append(_parse_tensor(v))
        elif fn == 11:
            g.input.append(_parse_value_info(v))
        elif fn == 12:
            g.output.append(_parse_value_info(v))
    return g


def parse_model(buf: bytes) -> ModelProto:
    m = ModelProto()
    for fn, _, v in _fields(buf):
        if fn == 1:
            m.ir_version = v
        elif fn == 7:
            m.graph = _parse_graph(v)
    return m


def load(path_or_bytes) -> ModelProto:
    if isinstance(path_or_bytes, (bytes, bytearray)):
        return parse_model(bytes(path_or_bytes))
    with open(path_or_bytes, "rb") as f:
        return parse_model(f.read())


def numpy_from_tensor(t: TensorProto) -> np.ndarray:
    dt = _NP_OF[t.data_type]
    if t.raw_data:
        arr = np.frombuffer(t.raw_data, dtype=dt)
    elif t.float_data:
        arr = np.asarray(t.float_data, dt)
    elif t.int64_data:
        arr = np.asarray(t.int64_data, dt)
    elif t.int32_data:
        if t.data_type == DT_FLOAT16:
            # fp16 payloads in int32_data are raw uint16 BIT PATTERNS
            # (onnx.proto3), not numeric values
            arr = np.asarray(t.int32_data,
                             np.uint16).view(np.float16)
        else:
            arr = np.asarray(t.int32_data, dt)
    else:
        arr = np.zeros(0, dt)
    return arr.reshape(t.dims) if t.dims else arr


def get_attribute_value(a: AttributeProto) -> Any:
    getters = {AT_FLOAT: lambda: a.f, AT_INT: lambda: a.i,
               AT_STRING: lambda: a.s, AT_TENSOR: lambda: a.t,
               AT_FLOATS: lambda: list(a.floats),
               AT_INTS: lambda: list(a.ints),
               AT_STRINGS: lambda: list(a.strings)}
    if a.type not in getters:
        # AT_GRAPH (If/Loop bodies), sparse tensors, or an attribute type
        # from a newer exporter: surface a diagnosable error instead of a
        # bare KeyError
        raise ValueError(
            f"unsupported ONNX attribute type {a.type} ({a.name!r})")
    return getters[a.type]()


# ------------------------------------------------------------ wire writer
def _varint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tag(fn: int, wt: int) -> bytes:
    return _varint((fn << 3) | wt)


def _ld(fn: int, payload: bytes) -> bytes:
    return _tag(fn, 2) + _varint(len(payload)) + payload


def _encode_tensor(t: TensorProto) -> bytes:
    out = b""
    for d in t.dims:
        out += _tag(1, 0) + _varint(d)
    out += _tag(2, 0) + _varint(t.data_type)
    if t.name:
        out += _ld(8, t.name.encode())
    if t.raw_data:
        out += _ld(9, t.raw_data)
    return out


def _encode_attribute(a: AttributeProto) -> bytes:
    out = _ld(1, a.name.encode())
    if a.type == AT_FLOAT:
        out += _tag(2, 5) + struct.pack("<f", a.f)
    elif a.type == AT_INT:
        out += _tag(3, 0) + _varint(a.i & ((1 << 64) - 1))
    elif a.type == AT_STRING:
        out += _ld(4, a.s)
    elif a.type == AT_TENSOR:
        out += _ld(5, _encode_tensor(a.t))
    elif a.type == AT_FLOATS:
        out += _ld(7, b"".join(struct.pack("<f", f) for f in a.floats))
    elif a.type == AT_INTS:
        out += _ld(8, b"".join(_varint(i & ((1 << 64) - 1))
                               for i in a.ints))
    elif a.type == AT_STRINGS:
        for s in a.strings:
            out += _ld(9, s if isinstance(s, bytes) else s.encode())
    else:
        raise ValueError(
            f"cannot encode ONNX attribute type {a.type} ({a.name!r})")
    out += _tag(20, 0) + _varint(a.type)
    return out


def _encode_node(n: NodeProto) -> bytes:
    out = b""
    for s in n.input:
        out += _ld(1, s.encode())
    for s in n.output:
        out += _ld(2, s.encode())
    if n.name:
        out += _ld(3, n.name.encode())
    out += _ld(4, n.op_type.encode())
    for a in n.attribute:
        out += _ld(5, _encode_attribute(a))
    return out


def _encode_value_info(vi: ValueInfoProto) -> bytes:
    dims = b""
    for d in vi.shape:
        dims += _ld(1, (_tag(1, 0) + _varint(d)) if d is not None else b"")
    tensor_type = _tag(1, 0) + _varint(vi.elem_type) + _ld(2, dims)
    return _ld(1, vi.name.encode()) + _ld(2, _ld(1, tensor_type))


def _encode_graph(g: GraphProto) -> bytes:
    out = b""
    for n in g.node:
        out += _ld(1, _encode_node(n))
    if g.name:
        out += _ld(2, g.name.encode())
    for t in g.initializer:
        out += _ld(5, _encode_tensor(t))
    for vi in g.input:
        out += _ld(11, _encode_value_info(vi))
    for vi in g.output:
        out += _ld(12, _encode_value_info(vi))
    return out


def serialize_model(m: ModelProto) -> bytes:
    return (_tag(1, 0) + _varint(m.ir_version)
            + _ld(7, _encode_graph(m.graph)))


# ------------------------------------------------------- make_* helpers
def make_tensor(name: str, arr: np.ndarray) -> TensorProto:
    arr = np.asarray(arr)
    dt = next(k for k, v in _NP_OF.items() if v == arr.dtype.type)
    return TensorProto(name=name, dims=list(arr.shape), data_type=dt,
                       raw_data=arr.tobytes())


def make_node(op_type: str, inputs, outputs, **attrs) -> NodeProto:
    node = NodeProto(op_type=op_type, input=list(inputs),
                     output=list(outputs))
    for k, v in attrs.items():
        if isinstance(v, float):
            node.attribute.append(AttributeProto(name=k, type=AT_FLOAT,
                                                 f=v))
        elif isinstance(v, int):
            node.attribute.append(AttributeProto(name=k, type=AT_INT, i=v))
        elif isinstance(v, (list, tuple)) and all(
                isinstance(x, int) for x in v):
            node.attribute.append(AttributeProto(name=k, type=AT_INTS,
                                                 ints=list(v)))
        elif isinstance(v, str):
            node.attribute.append(AttributeProto(name=k, type=AT_STRING,
                                                 s=v.encode()))
        else:
            raise TypeError(f"attribute {k}: {type(v)}")
    return node


def make_value_info(name: str, shape, elem_type: int = DT_FLOAT
                    ) -> ValueInfoProto:
    return ValueInfoProto(name=name, elem_type=elem_type,
                          shape=list(shape))


def make_model(nodes, inputs, outputs, initializers=(),
               name: str = "graph") -> ModelProto:
    return ModelProto(graph=GraphProto(
        name=name, node=list(nodes), initializer=list(initializers),
        input=list(inputs), output=list(outputs)))
