"""Core enums and type utilities.

TPU-native re-design of the reference's constant/type layer
(reference: include/flexflow/ffconst.h, src/runtime/fftype.cc).  We keep the
same *semantic* vocabulary (activation modes, aggregation modes, loss/metrics
types, inference modes) but map data types onto JAX dtypes instead of the
reference's cuDNN descriptors.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    """Tensor element types (reference: ffconst.h DT_* values)."""

    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    BFLOAT16 = "bfloat16"
    # the reference's DT_HALF is CUDA fp16; the TPU-native half precision
    # is bfloat16 (fp16 is not MXU-native and XLA upcasts it), so HALF
    # aliases BFLOAT16 (declared after it, so BFLOAT16 stays the canonical
    # member name).  FLOAT16 exists for ingesting fp16 arrays from
    # frontends; compute should use BFLOAT16.
    HALF = "bfloat16"
    FLOAT16 = "float16"
    FLOAT = "float32"
    DOUBLE = "float64"
    INT4 = "int4"
    INT8 = "int8"
    NONE = "none"

    def to_jnp(self):
        if self is DataType.NONE:
            raise ValueError("DT_NONE has no jnp dtype")
        if self is DataType.INT4:
            return jnp.int4
        return jnp.dtype(self.value)

    @property
    def size_bytes(self) -> float:
        if self is DataType.INT4:
            return 0.5
        return np.dtype(self.value).itemsize

    @staticmethod
    def from_jnp(dtype) -> "DataType":
        name = jnp.dtype(dtype).name
        for dt in DataType:
            if dt.value == name:
                return dt
        raise ValueError(f"unsupported dtype {dtype}")


class ActiMode(enum.Enum):
    """Fused-activation modes (reference: ffconst.h AC_MODE_*)."""

    NONE = "none"
    RELU = "relu"
    SIGMOID = "sigmoid"
    TANH = "tanh"
    GELU = "gelu"


class AggrMode(enum.Enum):
    """Embedding aggregation (reference: ffconst.h AGGR_MODE_*)."""

    NONE = "none"
    SUM = "sum"
    AVG = "avg"


class PoolType(enum.Enum):
    MAX = "max"
    AVG = "avg"


class LossType(enum.Enum):
    """Loss functions (reference: ffconst.h:41-47)."""

    CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
    SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
    MEAN_SQUARED_ERROR_AVG_REDUCE = "mean_squared_error_avg_reduce"
    MEAN_SQUARED_ERROR_SUM_REDUCE = "mean_squared_error_sum_reduce"
    IDENTITY = "identity"


class MetricsType(enum.Enum):
    """Metrics (reference: ffconst.h:60-68)."""

    ACCURACY = "accuracy"
    CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
    SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
    MEAN_SQUARED_ERROR = "mean_squared_error"
    ROOT_MEAN_SQUARED_ERROR = "root_mean_squared_error"
    MEAN_ABSOLUTE_ERROR = "mean_absolute_error"


class InferenceMode(enum.Enum):
    """Serving mode per model (reference: ffconst.h INC_DECODING_MODE etc.)."""

    INC_DECODING = "inc_decoding"
    BEAM_SEARCH = "beam_search"
    TREE_VERIFY = "tree_verify"


class ParameterSyncType(enum.Enum):
    """Gradient sync strategy (reference: ffconst.h ParameterSyncType)."""

    NONE = "none"
    PS = "ps"
    NCCL = "allreduce"  # the reference's NCCL path == our ICI allreduce path


class OpType(enum.Enum):
    """Operator vocabulary (reference: ffconst.h OperatorType OP_*).

    One entry per operator the reference supports; serving ops included.
    """

    INPUT = "input"
    WEIGHT = "weight"
    NOOP = "noop"
    CONSTANT = "constant"
    LINEAR = "linear"
    CONV2D = "conv2d"
    POOL2D = "pool2d"
    BATCHNORM = "batchnorm"
    BATCH_MATMUL = "batch_matmul"
    EMBEDDING = "embedding"
    DROPOUT = "dropout"
    FLAT = "flat"
    SOFTMAX = "softmax"
    CONCAT = "concat"
    SPLIT = "split"
    RESHAPE = "reshape"
    TRANSPOSE = "transpose"
    REVERSE = "reverse"
    GATHER = "gather"
    CAST = "cast"
    REDUCE_SUM = "reduce_sum"
    MEAN = "mean"
    EW_ADD = "ew_add"
    EW_SUB = "ew_sub"
    EW_MUL = "ew_mul"
    EW_DIV = "ew_div"
    EW_MAX = "ew_max"
    EW_MIN = "ew_min"
    EW_POW = "ew_pow"
    SCALAR_ADD = "scalar_add"
    SCALAR_SUB = "scalar_sub"
    SCALAR_MUL = "scalar_mul"
    SCALAR_TRUE_DIV = "scalar_true_div"
    RELU = "relu"
    SIGMOID = "sigmoid"
    TANH = "tanh"
    ELU = "elu"
    GELU = "gelu"
    SILU = "silu"
    IDENTITY = "identity"
    RSQRT = "rsqrt"
    POW = "pow"
    EXP = "exp"
    SIN = "sin"
    COS = "cos"
    MULTIHEAD_ATTENTION = "multihead_attention"
    INC_MULTIHEAD_SELF_ATTENTION = "inc_multihead_self_attention"
    SPEC_INC_MULTIHEAD_SELF_ATTENTION = "spec_inc_multihead_self_attention"
    TREE_INC_MULTIHEAD_SELF_ATTENTION = "tree_inc_multihead_self_attention"
    LAYERNORM = "layernorm"
    RESIDUAL_LAYERNORM = "residual_layernorm"
    ADD_BIAS_RESIDUAL_LAYERNORM = "add_bias_residual_layernorm"
    RMS_NORM = "rms_norm"
    RESIDUAL_RMS_NORM = "residual_rms_norm"
    SIGMOID_SILU_MULTI = "sigmoid_silu_multi"
    ARG_MAX = "arg_max"
    ARG_TOPK = "arg_topk"
    BEAM_TOPK = "beam_topk"
    SAMPLING = "sampling"
    TOPK = "topk"
    GROUP_BY = "group_by"
    AGGREGATE = "aggregate"
    AGG_SPEC = "agg_spec"
    EXPERTS = "experts"
    CACHE = "cache"
    FUSED = "fused"
    # parallel ops (first-class parallelism IR, reference src/parallel_ops/)
    REPARTITION = "repartition"
    COMBINE = "combine"
    REPLICATE = "replicate"
    REDUCTION = "reduction"
    ALLREDUCE = "allreduce"
    FUSED_PARALLEL = "fused_parallel"


# Activation helpers -------------------------------------------------------

def apply_activation(x, act: ActiMode):
    import jax.nn as jnn

    if act is ActiMode.NONE:
        return x
    if act is ActiMode.RELU:
        return jnn.relu(x)
    if act is ActiMode.SIGMOID:
        return jnn.sigmoid(x)
    if act is ActiMode.TANH:
        return jnp.tanh(x)
    if act is ActiMode.GELU:
        return jnn.gelu(x)
    raise ValueError(f"unknown activation {act}")
