"""PyTorch frontend: torch.fx-traced modules replayed onto the Model API.

TPU-native re-design of the reference's ``python/flexflow/torch/model.py``
(2,607 LoC): ``PyTorchModel.apply`` (reference :2408) replays a traced op
list onto an FFModel; tracing uses torch.fx ``symbolic_trace``
(reference :2424-2444).
"""

from .model import PyTorchModel, UnsupportedTorchOp

__all__ = ["PyTorchModel", "UnsupportedTorchOp"]
