"""torch.fx → Model importer.

reference: python/flexflow/torch/model.py — its flow is
``torch.fx.symbolic_trace`` (:2424-2444) → serialized op list → replay onto
FFModel (:2408 ``PyTorchModel.apply``).  Here the fx graph replays directly
(no intermediate file format needed inside one process; ``to_op_list`` /
``from_op_list`` provide the serialized exchange for parity), and
``port_parameters`` copies the torch module's weights into the framework
param tree (transposing torch's [out,in] linear layout to our [in,out]).
"""

from __future__ import annotations

import json
import operator
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.model import Model
from ..core.tensor import Tensor
from ..fftype import ActiMode, DataType, PoolType


class UnsupportedTorchOp(NotImplementedError):
    pass


class _ParamRef:
    """Marker for a get_attr parameter/buffer reference; resolved by the
    consuming op (inline addmm/matmul) and recorded for weight porting."""

    def __init__(self, target: str):
        self.target = target

    def __repr__(self):
        return f"_ParamRef({self.target})"


def _is_hf_attention(m) -> bool:
    """Duck-typed GPT-2-family attention leaf: fused c_attn qkv Conv1D +
    c_proj output Conv1D (transformers.models.gpt2.modeling_gpt2
    GPT2Attention and friends)."""
    return hasattr(m, "c_attn") and hasattr(m, "c_proj") \
        and hasattr(m, "num_heads")


def _is_llama_attention(m) -> bool:
    """Duck-typed LLaMA/Mistral/Qwen2-family attention leaf: separate
    q/k/v/o Linear projections + a config carrying head counts
    (transformers.models.mistral.modeling_mistral MistralAttention and
    friends — the GQA + RoPE + optional sliding-window decoders)."""
    return all(hasattr(m, a) for a in
               ("q_proj", "k_proj", "v_proj", "o_proj")) \
        and hasattr(m, "config")


def _is_t5_attention(m) -> bool:
    """Duck-typed T5/mt5-family attention leaf: q/k/v/o Linear
    projections (no _proj suffix), bucketed relative position bias
    (transformers.models.t5.modeling_t5 T5Attention / MT5Attention —
    the family the reference aligns end-to-end,
    tests/align/mt5_encoder/)."""
    return all(hasattr(m, a) for a in ("q", "k", "v", "o")) \
        and hasattr(m, "relative_attention_num_buckets")


def _is_hf_rmsnorm(m) -> bool:
    """Duck-typed transformers RMS norm: a single ``weight`` and a
    ``variance_epsilon`` (MistralRMSNorm etc.; T5LayerNorm is the same
    computation under a LayerNorm name — nn.LayerNorm carries ``eps``,
    not ``variance_epsilon``, so the duck-type cannot misfire)."""
    return (type(m).__name__.endswith(("RMSNorm", "LayerNorm"))
            and hasattr(m, "weight") and hasattr(m, "variance_epsilon"))


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _np_params(m) -> Dict[str, np.ndarray]:
    import torch

    with torch.no_grad():
        return {k: v.detach().cpu().numpy().copy()
                for k, v in m.named_parameters()}


class PyTorchModel:
    """Wraps a ``torch.nn.Module`` for replay onto a :class:`Model`
    (reference PyTorchModel, torch/model.py:2408)."""

    def __init__(self, module, trace: Optional[Any] = None):
        import torch.fx

        self.module = module
        self.graph_module = trace or torch.fx.symbolic_trace(module)
        # fx node name -> framework layer name (for weight porting)
        self.node_to_layer: Dict[str, str] = {}
        # layers created from inline call_function params (HF Conv1D
        # traces as addmm): layer name -> (weight get_attr target,
        # bias get_attr target or None, transpose_weight)
        self.param_layers: Dict[str, tuple] = {}

    # ---------------------------------------------------------------- apply
    def apply(self, ffmodel: Model, inputs: Sequence[Tensor]) -> List[Tensor]:
        """Replay the traced graph onto ``ffmodel`` (reference
        torch/model.py:2408)."""
        import torch

        env: Dict[str, Any] = {}
        input_iter = iter(inputs)
        out: List[Tensor] = []
        mods = dict(self.graph_module.named_modules())

        for node in self.graph_module.graph.nodes:
            if node.op == "placeholder":
                env[node.name] = next(input_iter)
            elif node.op == "get_attr":
                # parameter/buffer reference: consumed by inline matmuls
                # (addmm); the marker defers the torch lookup to use sites
                env[node.name] = _ParamRef(node.target)
            elif node.op == "call_module":
                m = mods[node.target]
                # modern transformers invokes submodules keyword-only
                # (self_attn(hidden_states=..., ...)): the primary input
                # is the first positional arg or the hidden_states kwarg
                if node.args:
                    first = node.args[0]
                else:
                    first = node.kwargs.get(
                        "hidden_states",
                        next(iter(node.kwargs.values()), None))
                x = env[first.name] if hasattr(first, "name") else first
                kw = {k: (env[v.name] if hasattr(v, "name") else v)
                      for k, v in node.kwargs.items()}
                if len(node.args) > 1:
                    kw["__positional_extras__"] = [
                        env[a.name] if hasattr(a, "name") else a
                        for a in node.args[1:]]
                y = self._call_module(ffmodel, node, m, x, kw)
                env[node.name] = y
                lead = y[0] if isinstance(y, tuple) else y
                if isinstance(lead, Tensor) and lead.owner_layer is not None:
                    self.node_to_layer[node.name] = lead.owner_layer.name
            elif node.op in ("call_function", "call_method"):
                env[node.name] = self._call_function(ffmodel, node, env)
            elif node.op == "output":
                args = node.args[0]
                if isinstance(args, dict):      # HF ModelOutput dict
                    out = [env[v.name] for v in args.values()
                           if hasattr(v, "name")]
                elif isinstance(args, (tuple, list)):
                    out = [env[a.name] for a in args]
                else:
                    out = [env[args.name]]
        return out

    # ------------------------------------------------------------- modules
    def _call_module(self, ff: Model, node, m, x, kw=None):
        import torch
        import torch.nn as nn

        kw = kw or {}

        if _is_hf_attention(m):
            # attention leaf -> the framework's fused causal MHA op (the
            # reference importer's MultiheadAttentionNode analogue); HF
            # attention returns (output, weights) — mirror the tuple so
            # downstream getitem(…, 0) works
            e = m.c_attn.weight.shape[0]
            y = ff.multihead_attention(
                x, x, x, embed_dim=e, num_heads=int(m.num_heads),
                causal=True, qkv_bias=m.c_attn.bias is not None,
                final_bias=m.c_proj.bias is not None)
            return (y, None)
        if isinstance(m, nn.Embedding) and not isinstance(x, Tensor):
            # concrete indices (e.g. GPT-2's traced position-id arange):
            # land them as a constant node feeding a normal embedding
            # lookup so the table still ports from the checkpoint
            idx = ff.constant(np.asarray(
                x.detach().cpu().numpy() if torch.is_tensor(x) else x,
                np.int32))
            return ff.embedding(idx, m.num_embeddings, m.embedding_dim)
        if _is_t5_attention(m):
            # T5/mt5-family attention leaf: unscaled QK (the 1/sqrt(d)
            # is folded into init), bucketed relative position bias
            # shared from the stack's first block, no projection biases.
            # Three modes by leaf role: encoder self-attention
            # (bidirectional bias), decoder self-attention (causal,
            # unidirectional bias), cross-attention (key_value_states
            # from the encoder, no bias — HF computes zeros there).
            # The traced mask inputs are ignored: causal masking replays
            # natively and the no-padding extended mask is identically
            # zero.  Returns enough tuple slots for any getitem.
            h = int(m.n_heads)
            d = int(m.key_value_proj_dim)
            is_dec = bool(getattr(m, "is_decoder", False))
            kv_states = kw.get("key_value_states")
            cross = isinstance(kv_states, Tensor)
            if not cross:
                # drift guard: if a transformers version passes
                # key_value_states POSITIONALLY, silently replaying as
                # self-attention would produce wrong logits.  Only a
                # graph-valued extra can be kv_states; positional masks
                # (None / concrete torch tensors) are ignored exactly
                # like keyword masks are.
                graph_extras = [e for e in
                                kw.get("__positional_extras__", [])
                                if isinstance(e, Tensor)]
                if graph_extras:
                    raise UnsupportedTorchOp(
                        "T5 attention leaf got a graph-valued positional "
                        "arg beyond hidden_states — cannot distinguish a "
                        "traced mask from key_value_states; pass "
                        f"key_value_states as a keyword ({node.args!r})")
            kv_in = kv_states if cross else x
            y = ff.multihead_attention(
                x, kv_in, kv_in, embed_dim=int(m.d_model), num_heads=h,
                kdim=h * d, vdim=h * d,
                causal=is_dec and not cross, scale_qk=False,
                t5_bias=None if cross else dict(
                    num_buckets=int(m.relative_attention_num_buckets),
                    max_distance=int(m.relative_attention_max_distance),
                    bidirectional=not is_dec))
            return (y, None, None, None)
        if _is_llama_attention(m):
            # LLaMA/Mistral-family leaf -> the framework op with GQA +
            # in-op RoPE + sliding window; the traced (cos, sin)
            # position_embeddings arg is ignored (the op re-derives RoPE
            # at positions 0..S-1, which full-sequence replay means)
            c = m.config
            scaling = getattr(c, "rope_scaling", None)
            if scaling and (scaling.get("rope_type", scaling.get("type"))
                            not in (None, "default")):
                # Llama-3-style scaled RoPE would silently diverge
                raise UnsupportedTorchOp(
                    f"rope_scaling {scaling!r} (plain RoPE only)")
            # sliding-window resolution, most-specific first:
            # 1. Qwen2-style modules carry the PER-LAYER resolved window
            #    (self.sliding_window set from config.layer_types)
            # 2. configs with layer_types gate by the leaf's layer_idx
            # 3. Mistral-style: one config-level window for every layer
            if hasattr(m, "sliding_window"):
                window = m.sliding_window
            elif getattr(c, "layer_types", None) is not None:
                li = getattr(m, "layer_idx", None)
                if li is None:
                    raise UnsupportedTorchOp(
                        "per-layer sliding-window gating (layer_types) "
                        "needs the attention leaf's layer_idx")
                window = (getattr(c, "sliding_window", None)
                          if c.layer_types[li] == "sliding_attention"
                          else None)
            else:
                # Mistral-style: one config-level window for every
                # layer.  Older-transformers Qwen2 lands here too (no
                # module attr, no layer_types) with the RAW config value
                # — honor its gating flags instead of silently windowing
                # every layer
                window = getattr(c, "sliding_window", None)
                if window is not None and hasattr(c, "use_sliding_window"):
                    if not c.use_sliding_window:
                        window = None
                    elif getattr(c, "max_window_layers", None):
                        raise UnsupportedTorchOp(
                            "per-layer sliding-window gating "
                            "(max_window_layers) without module-resolved "
                            "windows — upgrade transformers")
            h = int(c.num_attention_heads)
            kv = int(getattr(c, "num_key_value_heads", h) or h)
            d = int(getattr(m, "head_dim", None)
                    or c.hidden_size // h)
            y = ff.multihead_attention(
                x, x, x, embed_dim=int(c.hidden_size), num_heads=h,
                kdim=h * d, vdim=h * d, num_kv_heads=kv, causal=True,
                rotary=True,
                rope_theta=float(getattr(c, "rope_theta", 10000.0)),
                sliding_window=window,
                qkv_bias=m.q_proj.bias is not None,
                final_bias=m.o_proj.bias is not None)
            return (y, None)
        if type(m).__name__.endswith("RotaryEmbedding"):
            # traced as a leaf only so its inv_freq buffer stays out of
            # the graph; its (cos, sin) output feeds attention leaves
            # that re-derive RoPE natively
            return None
        if _is_hf_rmsnorm(m):
            return ff.rms_norm(x, eps=float(m.variance_epsilon))
        if type(m).__name__ in ("NewGELUActivation", "GELUActivation",
                                "FastGELUActivation", "QuickGELUActivation"):
            return ff.gelu(x)
        if type(m).__name__ in ("SiLUActivation",) or isinstance(m, nn.SiLU):
            return ff.silu(x)
        if isinstance(m, nn.Linear):
            return ff.dense(x, m.out_features, use_bias=m.bias is not None)
        if isinstance(m, nn.Conv2d):
            kh, kw = _pair(m.kernel_size)
            sh, sw = _pair(m.stride)
            ph, pw = _pair(m.padding)
            return ff.conv2d(x, m.out_channels, kh, kw, sh, sw, ph, pw,
                             groups=m.groups, use_bias=m.bias is not None)
        if isinstance(m, (nn.MaxPool2d, nn.AvgPool2d)):
            kh, kw = _pair(m.kernel_size)
            sh, sw = _pair(m.stride or m.kernel_size)
            ph, pw = _pair(m.padding)
            pt = (PoolType.MAX if isinstance(m, nn.MaxPool2d)
                  else PoolType.AVG)
            return ff.pool2d(x, kh, kw, sh, sw, ph, pw, pool_type=pt)
        if isinstance(m, nn.Embedding):
            return ff.embedding(x, m.num_embeddings, m.embedding_dim)
        if isinstance(m, nn.LayerNorm):
            return ff.layer_norm(x, eps=m.eps,
                                 elementwise_affine=m.elementwise_affine,
                                 use_bias=m.bias is not None)
        if isinstance(m, nn.Dropout):
            return ff.dropout(x, rate=m.p)
        if isinstance(m, nn.Flatten):
            return ff.flat(x)
        if isinstance(m, nn.ReLU):
            return ff.relu(x)
        if isinstance(m, nn.GELU):
            return ff.gelu(x)
        if isinstance(m, nn.Sigmoid):
            return ff.sigmoid(x)
        if isinstance(m, nn.Tanh):
            return ff.tanh(x)
        if isinstance(m, nn.Softmax):
            return ff.softmax(x, axis=m.dim if m.dim is not None else -1)
        if isinstance(m, nn.Identity):
            return x
        raise UnsupportedTorchOp(f"module {type(m).__name__}")

    # ----------------------------------------------------------- functions
    def _call_function(self, ff: Model, node, env):
        import torch
        import torch.nn.functional as F

        def val(a):
            if isinstance(a, (list, tuple)):
                return type(a)(val(x) for x in a)
            return env[a.name] if hasattr(a, "name") else a

        args = [val(a) for a in node.args]
        kwargs = {k: val(v) for k, v in node.kwargs.items()}
        tgt = node.target
        name = tgt if isinstance(tgt, str) else getattr(tgt, "__name__", "")

        def leaves(v):
            if isinstance(v, (list, tuple)):
                for x in v:
                    yield from leaves(x)
            elif isinstance(v, dict):
                for x in v.values():
                    yield from leaves(x)
            else:
                yield v

        def has_tensor(v):
            return any(isinstance(x, (Tensor, _ParamRef)) for x in leaves(v))

        # ---- constant folding: traced chains whose inputs are all
        # concrete at the importer's static shapes (size arithmetic,
        # position-id aranges) evaluate eagerly with torch
        if not has_tensor(args) and not has_tensor(kwargs):
            if node.op == "call_method":
                return getattr(args[0], tgt)(*args[1:], **kwargs)
            return tgt(*args, **kwargs)

        # ---- shape/device plumbing on framework tensors
        if name == "size":
            shape = tuple(int(s) for s in args[0].spec.shape)
            if len(args) > 1:
                return shape[int(args[1])]
            return shape
        if name in ("to", "type_as", "contiguous"):
            return args[0]
        if tgt is getattr:
            if args[1] == "dtype" and isinstance(args[0], Tensor):
                # resolve to the real torch dtype so downstream folded
                # chains (T5Stack's `finfo(embeds.dtype).min` mask
                # arithmetic) evaluate concretely
                # DataType.HALF aliases BFLOAT16 (fftype.py: TPU half
                # precision is bf16) — map it to torch.bfloat16, with
                # FLOAT16 carrying true fp16
                return {DataType.FLOAT: torch.float32,
                        DataType.BFLOAT16: torch.bfloat16,
                        DataType.FLOAT16: torch.float16,
                        DataType.DOUBLE: torch.float64,
                        DataType.INT32: torch.int32,
                        DataType.INT64: torch.int64,
                        DataType.BOOL: torch.bool}.get(
                            args[0].spec.dtype, torch.float32)
            if args[1] in ("device", "dtype"):
                return None     # placeholder; only feeds folded calls
            raise UnsupportedTorchOp(f"getattr {args[1]}")
        if name == "getitem":
            seq, idx = args[0], args[1]
            if isinstance(seq, (tuple, list)):
                return seq[idx]
            if isinstance(seq, Tensor):
                sl = idx if isinstance(idx, tuple) else (idx,)
                if all(isinstance(s, slice)
                       and (s.start in (None, 0)) and s.stop is None
                       and s.step in (None, 1) for s in sl):
                    return seq   # full slice = identity
                raise UnsupportedTorchOp(f"tensor getitem {idx}")
        if tgt is torch.addmm or name == "addmm":
            # HF Conv1D body: addmm(bias, x2d, weight[in, out]) — a dense
            # layer whose weight ports WITHOUT the nn.Linear transpose
            bias_ref, x2, w_ref = args
            assert isinstance(w_ref, _ParamRef) and isinstance(x2, Tensor)
            params = dict(self.module.named_parameters())
            w = params[w_ref.target]
            y = ff.dense(x2, int(w.shape[1]),
                         use_bias=isinstance(bias_ref, _ParamRef))
            lname = y.owner_layer.name
            self.node_to_layer[node.name] = lname
            self.param_layers[lname] = (
                w_ref.target,
                bias_ref.target if isinstance(bias_ref, _ParamRef) else None,
                False)
            return y
        # past addmm, a parameter/buffer reference has no resolver: fail
        # loudly at the consuming node instead of leaking the marker into
        # the generic dispatch (where it would take the scalar branch or
        # die with an opaque downstream error)
        leaked = next((x for x in leaves((args, kwargs))
                       if isinstance(x, _ParamRef)), None)
        if leaked is not None:
            raise UnsupportedTorchOp(
                f"get_attr {leaked.target} consumed by {name}")

        if tgt is torch.pow or name == "pow":
            return ff.pow(args[0], float(args[1]))

        binary = {operator.add: (ff.add, ff.scalar_add),
                  "add": (ff.add, ff.scalar_add),
                  operator.sub: (ff.subtract, ff.scalar_sub),
                  "sub": (ff.subtract, ff.scalar_sub),
                  operator.mul: (ff.multiply, ff.scalar_multiply),
                  "mul": (ff.multiply, ff.scalar_multiply),
                  operator.truediv: (ff.divide, ff.scalar_true_divide),
                  "div": (ff.divide, ff.scalar_true_divide)}
        if tgt in binary or (isinstance(tgt, str) and tgt in binary):
            key = tgt if tgt in binary else name
            tensor_fn, scalar_fn = binary[key]
            a, b = args[0], args[1]
            if (isinstance(a, (tuple, list))
                    and isinstance(b, (tuple, list))):
                # python sequence concatenation (HF blocks build output
                # tuples with `(hidden,) + attention_outputs`)
                return tuple(a) + tuple(b)
            if isinstance(b, Tensor) and isinstance(a, Tensor):
                return tensor_fn(a, b)
            if isinstance(a, Tensor):
                return scalar_fn(a, float(b))
            # scalar on the left: add/mul commute; c - x composes; c / x
            # has no stable elementwise inverse in the op set
            if tensor_fn in (ff.add, ff.multiply):  # == on bound methods
                return scalar_fn(b, float(a))
            if tensor_fn == ff.subtract:   # c - x == -(x - c)
                return ff.scalar_multiply(ff.scalar_sub(b, float(a)), -1.0)
            raise UnsupportedTorchOp(f"scalar-over-tensor {name} "
                                     f"({a!r} {name} tensor)")

        if tgt in (torch.relu, F.relu) or name == "relu":
            return ff.relu(args[0])
        if tgt is F.gelu or name == "gelu":
            return ff.gelu(args[0])
        if tgt is F.silu or name == "silu":
            return ff.silu(args[0])
        if tgt in (torch.sigmoid, F.sigmoid) or name == "sigmoid":
            return ff.sigmoid(args[0])
        if tgt in (torch.tanh, F.tanh) or name == "tanh":
            return ff.tanh(args[0])
        if tgt is F.softmax or name == "softmax":
            axis = node.kwargs.get("dim", args[1] if len(args) > 1 else -1)
            return ff.softmax(args[0], axis=-1 if axis is None else axis)
        if tgt in (torch.flatten,) or name == "flatten":
            return ff.flat(args[0])
        if name in ("view", "reshape"):
            shape = (list(args[1]) if isinstance(args[1], (tuple, list))
                     else [int(s) for s in args[1:]])
            shape = [int(s) for s in shape]
            if -1 in shape:
                total = int(np.prod(args[0].spec.shape))
                known = int(np.prod([s for s in shape if s != -1]))
                shape[shape.index(-1)] = total // known
            return ff.reshape(args[0], shape)
        if name == "transpose":
            d0, d1 = int(args[1]), int(args[2])
            ndim = args[0].spec.ndim
            perm = list(range(ndim))
            perm[d0], perm[d1] = perm[d1], perm[d0]
            return ff.transpose(args[0], perm)
        if tgt is torch.cat or name == "cat":
            axis = node.kwargs.get("dim", args[1] if len(args) > 1 else 0)
            return ff.concat(args[0], axis=axis)
        if tgt is torch.matmul or name == "matmul":
            return ff.batch_matmul(args[0], args[1])
        if name == "contiguous":
            return args[0]
        if name == "size":
            raise UnsupportedTorchOp("dynamic .size() in traced graph")
        raise UnsupportedTorchOp(f"function {tgt}")

    # ------------------------------------------------------------- weights
    def port_parameters(self, ffmodel: Model) -> Dict[str, Dict[str, Any]]:
        """Copy torch weights into the framework param tree for every layer
        created by :meth:`apply` (reference: the fx importer relies on
        FlexFlow-side initializers; we do better and port exactly)."""
        import torch.nn as nn

        assert ffmodel.params is not None, "compile or init params first"
        mods = dict(self.graph_module.named_modules())
        fx_nodes = {n.name: n for n in self.graph_module.graph.nodes}
        # .copy(): .numpy() views alias live torch parameter storage
        all_params = {k: v.detach().cpu().numpy().copy()
                      for k, v in self.module.named_parameters()}
        for node_name, layer_name in self.node_to_layer.items():
            p = ffmodel.params.get(layer_name)
            if p is None:
                continue
            if layer_name in self.param_layers:
                # inline addmm (HF Conv1D): weight already [in, out]
                w_t, b_t, transpose = self.param_layers[layer_name]
                w = all_params[w_t]
                p["kernel"] = (w.T if transpose else w).copy()
                if b_t is not None:
                    p["bias"] = all_params[b_t]
                continue
            if fx_nodes[node_name].op != "call_module":
                continue
            m = mods[fx_nodes[node_name].target]
            with_no_grad = _np_params(m)
            if _is_hf_attention(m):
                # fused c_attn [E, 3E] -> wq/wk/wv [E, H, d]; c_proj
                # [E, E] -> wo [H, d, E] (same head-split convention as
                # torch's .view(..., H, d))
                e = with_no_grad["c_attn.weight"].shape[0]
                h = int(m.num_heads)
                d = e // h
                W = with_no_grad["c_attn.weight"]
                p["wq"] = W[:, :e].reshape(e, h, d).copy()
                p["wk"] = W[:, e:2 * e].reshape(e, h, d).copy()
                p["wv"] = W[:, 2 * e:].reshape(e, h, d).copy()
                p["wo"] = with_no_grad["c_proj.weight"].reshape(h, d, e).copy()
                if "c_attn.bias" in with_no_grad:
                    b = with_no_grad["c_attn.bias"]
                    p["bq"] = b[:e].reshape(h, d).copy()
                    p["bk"] = b[e:2 * e].reshape(h, d).copy()
                    p["bv"] = b[2 * e:].reshape(h, d).copy()
                if "c_proj.bias" in with_no_grad:
                    p["bo"] = with_no_grad["c_proj.bias"]
                continue
            if _is_t5_attention(m):
                # q/k/v/o Linears ([out=H*D, in=E] torch layout, no
                # biases) -> wq/wk/wv [E, H, D] / wo [H, D, E]; the
                # relative-bias bucket table [num_buckets, H] comes from
                # this leaf if it owns one, else from the stack's first
                # block (HF computes it there once and threads the bias
                # tensor down — replaying it per layer is the same bias)
                h = int(m.n_heads)
                e = int(m.d_model)
                d = int(m.key_value_proj_dim)
                # cross-attention k/v project from the ENCODER stream
                # (kdim may differ when d_model != encoder width; same
                # here), weights still [H*D, E_kv]
                ekv = with_no_grad["k.weight"].shape[1]
                p["wq"] = with_no_grad["q.weight"].T.reshape(e, h, d).copy()
                p["wk"] = with_no_grad["k.weight"].T.reshape(ekv, h, d).copy()
                p["wv"] = with_no_grad["v.weight"].T.reshape(ekv, h, d).copy()
                p["wo"] = with_no_grad["o.weight"].T.reshape(h, d, e).copy()
                if "rel_bias" in p:     # cross-attn layers carry none
                    if "relative_attention_bias.weight" in with_no_grad:
                        p["rel_bias"] = with_no_grad[
                            "relative_attention_bias.weight"]
                    else:
                        # the stack's first block owns the table; pick
                        # the owner on the same side (encoder/decoder)
                        side = bool(getattr(m, "is_decoder", False))
                        owners = [
                            mm for mm in self.module.modules()
                            if getattr(mm, "has_relative_attention_bias",
                                       False)
                            and bool(getattr(mm, "is_decoder",
                                             False)) == side]
                        assert owners, "no relative_attention_bias table"
                        p["rel_bias"] = (
                            owners[0].relative_attention_bias.weight
                            .detach().cpu().numpy().copy())
                continue
            if _is_llama_attention(m):
                # separate q/k/v/o Linears ([out, in] torch layout) ->
                # wq [E, H, D] / wk,wv [E, KV, D] / wo [H, D, E]; same
                # head-split convention as models/llama.py
                # convert_hf_state_dict
                c = m.config
                h = int(c.num_attention_heads)
                kv = int(getattr(c, "num_key_value_heads", h) or h)
                e = int(c.hidden_size)
                d = int(getattr(m, "head_dim", None) or e // h)
                p["wq"] = with_no_grad["q_proj.weight"].T.reshape(e, h, d).copy()
                p["wk"] = with_no_grad["k_proj.weight"].T.reshape(e, kv, d).copy()
                p["wv"] = with_no_grad["v_proj.weight"].T.reshape(e, kv, d).copy()
                p["wo"] = with_no_grad["o_proj.weight"].T.reshape(h, d, e).copy()
                if "q_proj.bias" in with_no_grad:
                    p["bq"] = with_no_grad["q_proj.bias"].reshape(h, d).copy()
                    p["bk"] = with_no_grad["k_proj.bias"].reshape(kv, d).copy()
                    p["bv"] = with_no_grad["v_proj.bias"].reshape(kv, d).copy()
                if "o_proj.bias" in with_no_grad:
                    p["bo"] = with_no_grad["o_proj.bias"]
                continue
            if _is_hf_rmsnorm(m):
                p["weight"] = with_no_grad["weight"]
                continue
            if isinstance(m, nn.Linear):
                p["kernel"] = with_no_grad["weight"].T.copy()
                if "bias" in with_no_grad:
                    p["bias"] = with_no_grad["bias"]
            elif isinstance(m, nn.Conv2d):
                p["kernel"] = with_no_grad["weight"]  # OIHW both sides
                if "bias" in with_no_grad:
                    p["bias"] = with_no_grad["bias"]
            elif isinstance(m, nn.Embedding):
                p["embedding"] = with_no_grad["weight"]
            elif isinstance(m, nn.LayerNorm):
                if "weight" in with_no_grad:
                    p["weight"] = with_no_grad["weight"]
                if "bias" in with_no_grad:
                    p["bias"] = with_no_grad["bias"]
        import jax.numpy as jnp

        ffmodel.params = {ln: {pn: jnp.asarray(pv) for pn, pv in lp.items()}
                          for ln, lp in ffmodel.params.items()}
        return ffmodel.params

    # -------------------------------------------------- serialized op list
    def to_op_list(self) -> str:
        """Serialize the traced graph (reference: the importer's file
        format written by ``torch_to_flexflow``, torch/model.py)."""
        ops = []
        for node in self.graph_module.graph.nodes:
            ops.append({
                "name": node.name, "op": node.op,
                "target": str(node.target),
                "args": [a.name if hasattr(a, "name") else a
                         for a in node.args
                         if not isinstance(a, (dict, slice))],
            })
        return json.dumps(ops, default=str, indent=2)
