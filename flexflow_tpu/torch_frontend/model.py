"""torch.fx → Model importer.

reference: python/flexflow/torch/model.py — its flow is
``torch.fx.symbolic_trace`` (:2424-2444) → serialized op list → replay onto
FFModel (:2408 ``PyTorchModel.apply``).  Here the fx graph replays directly
(no intermediate file format needed inside one process; ``to_op_list`` /
``from_op_list`` provide the serialized exchange for parity), and
``port_parameters`` copies the torch module's weights into the framework
param tree (transposing torch's [out,in] linear layout to our [in,out]).
"""

from __future__ import annotations

import json
import operator
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.model import Model
from ..core.tensor import Tensor
from ..fftype import ActiMode, DataType, PoolType


class UnsupportedTorchOp(NotImplementedError):
    pass


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _np_params(m) -> Dict[str, np.ndarray]:
    import torch

    with torch.no_grad():
        return {k: v.detach().cpu().numpy().copy()
                for k, v in m.named_parameters()}


class PyTorchModel:
    """Wraps a ``torch.nn.Module`` for replay onto a :class:`Model`
    (reference PyTorchModel, torch/model.py:2408)."""

    def __init__(self, module, trace: Optional[Any] = None):
        import torch.fx

        self.module = module
        self.graph_module = trace or torch.fx.symbolic_trace(module)
        # fx node name -> framework layer name (for weight porting)
        self.node_to_layer: Dict[str, str] = {}

    # ---------------------------------------------------------------- apply
    def apply(self, ffmodel: Model, inputs: Sequence[Tensor]) -> List[Tensor]:
        """Replay the traced graph onto ``ffmodel`` (reference
        torch/model.py:2408)."""
        import torch

        env: Dict[str, Any] = {}
        input_iter = iter(inputs)
        out: List[Tensor] = []
        mods = dict(self.graph_module.named_modules())

        for node in self.graph_module.graph.nodes:
            if node.op == "placeholder":
                env[node.name] = next(input_iter)
            elif node.op == "get_attr":
                raise UnsupportedTorchOp(
                    f"get_attr {node.target} (constants not supported)")
            elif node.op == "call_module":
                m = mods[node.target]
                x = env[node.args[0].name]
                y = self._call_module(ffmodel, node, m, x)
                env[node.name] = y
                if isinstance(y, Tensor) and y.owner_layer is not None:
                    self.node_to_layer[node.name] = y.owner_layer.name
            elif node.op in ("call_function", "call_method"):
                env[node.name] = self._call_function(ffmodel, node, env)
            elif node.op == "output":
                args = node.args[0]
                if isinstance(args, (tuple, list)):
                    out = [env[a.name] for a in args]
                else:
                    out = [env[args.name]]
        return out

    # ------------------------------------------------------------- modules
    def _call_module(self, ff: Model, node, m, x):
        import torch.nn as nn

        if isinstance(m, nn.Linear):
            return ff.dense(x, m.out_features, use_bias=m.bias is not None)
        if isinstance(m, nn.Conv2d):
            kh, kw = _pair(m.kernel_size)
            sh, sw = _pair(m.stride)
            ph, pw = _pair(m.padding)
            return ff.conv2d(x, m.out_channels, kh, kw, sh, sw, ph, pw,
                             groups=m.groups, use_bias=m.bias is not None)
        if isinstance(m, (nn.MaxPool2d, nn.AvgPool2d)):
            kh, kw = _pair(m.kernel_size)
            sh, sw = _pair(m.stride or m.kernel_size)
            ph, pw = _pair(m.padding)
            pt = (PoolType.MAX if isinstance(m, nn.MaxPool2d)
                  else PoolType.AVG)
            return ff.pool2d(x, kh, kw, sh, sw, ph, pw, pool_type=pt)
        if isinstance(m, nn.Embedding):
            return ff.embedding(x, m.num_embeddings, m.embedding_dim)
        if isinstance(m, nn.LayerNorm):
            return ff.layer_norm(x, eps=m.eps,
                                 elementwise_affine=m.elementwise_affine,
                                 use_bias=m.bias is not None)
        if isinstance(m, nn.Dropout):
            return ff.dropout(x, rate=m.p)
        if isinstance(m, nn.Flatten):
            return ff.flat(x)
        if isinstance(m, nn.ReLU):
            return ff.relu(x)
        if isinstance(m, nn.GELU):
            return ff.gelu(x)
        if isinstance(m, nn.Sigmoid):
            return ff.sigmoid(x)
        if isinstance(m, nn.Tanh):
            return ff.tanh(x)
        if isinstance(m, nn.Softmax):
            return ff.softmax(x, axis=m.dim if m.dim is not None else -1)
        if isinstance(m, nn.Identity):
            return x
        raise UnsupportedTorchOp(f"module {type(m).__name__}")

    # ----------------------------------------------------------- functions
    def _call_function(self, ff: Model, node, env):
        import torch
        import torch.nn.functional as F

        def val(a):
            if isinstance(a, (list, tuple)):
                return type(a)(val(x) for x in a)
            return env[a.name] if hasattr(a, "name") else a

        args = [val(a) for a in node.args]
        tgt = node.target
        name = tgt if isinstance(tgt, str) else getattr(tgt, "__name__", "")

        binary = {operator.add: (ff.add, ff.scalar_add),
                  "add": (ff.add, ff.scalar_add),
                  operator.sub: (ff.subtract, ff.scalar_sub),
                  "sub": (ff.subtract, ff.scalar_sub),
                  operator.mul: (ff.multiply, ff.scalar_multiply),
                  "mul": (ff.multiply, ff.scalar_multiply),
                  operator.truediv: (ff.divide, ff.scalar_true_divide),
                  "div": (ff.divide, ff.scalar_true_divide)}
        if tgt in binary or (isinstance(tgt, str) and tgt in binary):
            key = tgt if tgt in binary else name
            tensor_fn, scalar_fn = binary[key]
            a, b = args[0], args[1]
            if isinstance(b, Tensor) and isinstance(a, Tensor):
                return tensor_fn(a, b)
            if isinstance(a, Tensor):
                return scalar_fn(a, float(b))
            # scalar on the left: add/mul commute; c - x composes; c / x
            # has no stable elementwise inverse in the op set
            if tensor_fn in (ff.add, ff.multiply):  # == on bound methods
                return scalar_fn(b, float(a))
            if tensor_fn == ff.subtract:   # c - x == -(x - c)
                return ff.scalar_multiply(ff.scalar_sub(b, float(a)), -1.0)
            raise UnsupportedTorchOp(f"scalar-over-tensor {name} "
                                     f"({a!r} {name} tensor)")

        if tgt in (torch.relu, F.relu) or name == "relu":
            return ff.relu(args[0])
        if tgt is F.gelu or name == "gelu":
            return ff.gelu(args[0])
        if tgt in (torch.sigmoid, F.sigmoid) or name == "sigmoid":
            return ff.sigmoid(args[0])
        if tgt in (torch.tanh, F.tanh) or name == "tanh":
            return ff.tanh(args[0])
        if tgt is F.softmax or name == "softmax":
            axis = node.kwargs.get("dim", args[1] if len(args) > 1 else -1)
            return ff.softmax(args[0], axis=-1 if axis is None else axis)
        if tgt in (torch.flatten,) or name == "flatten":
            return ff.flat(args[0])
        if name in ("view", "reshape"):
            shape = (list(args[1]) if isinstance(args[1], (tuple, list))
                     else [int(s) for s in args[1:]])
            shape = [int(s) for s in shape]
            if -1 in shape:
                total = int(np.prod(args[0].spec.shape))
                known = int(np.prod([s for s in shape if s != -1]))
                shape[shape.index(-1)] = total // known
            return ff.reshape(args[0], shape)
        if name == "transpose":
            d0, d1 = int(args[1]), int(args[2])
            ndim = args[0].spec.ndim
            perm = list(range(ndim))
            perm[d0], perm[d1] = perm[d1], perm[d0]
            return ff.transpose(args[0], perm)
        if tgt is torch.cat or name == "cat":
            axis = node.kwargs.get("dim", args[1] if len(args) > 1 else 0)
            return ff.concat(args[0], axis=axis)
        if tgt is torch.matmul or name == "matmul":
            return ff.batch_matmul(args[0], args[1])
        if name == "contiguous":
            return args[0]
        if name == "size":
            raise UnsupportedTorchOp("dynamic .size() in traced graph")
        raise UnsupportedTorchOp(f"function {tgt}")

    # ------------------------------------------------------------- weights
    def port_parameters(self, ffmodel: Model) -> Dict[str, Dict[str, Any]]:
        """Copy torch weights into the framework param tree for every layer
        created by :meth:`apply` (reference: the fx importer relies on
        FlexFlow-side initializers; we do better and port exactly)."""
        import torch.nn as nn

        assert ffmodel.params is not None, "compile or init params first"
        mods = dict(self.graph_module.named_modules())
        fx_nodes = {n.name: n for n in self.graph_module.graph.nodes}
        for node_name, layer_name in self.node_to_layer.items():
            m = mods[fx_nodes[node_name].target]
            p = ffmodel.params.get(layer_name)
            if p is None:
                continue
            with_no_grad = _np_params(m)
            if isinstance(m, nn.Linear):
                p["kernel"] = with_no_grad["weight"].T.copy()
                if "bias" in with_no_grad:
                    p["bias"] = with_no_grad["bias"]
            elif isinstance(m, nn.Conv2d):
                p["kernel"] = with_no_grad["weight"]  # OIHW both sides
                if "bias" in with_no_grad:
                    p["bias"] = with_no_grad["bias"]
            elif isinstance(m, nn.Embedding):
                p["embedding"] = with_no_grad["weight"]
            elif isinstance(m, nn.LayerNorm):
                if "weight" in with_no_grad:
                    p["weight"] = with_no_grad["weight"]
                if "bias" in with_no_grad:
                    p["bias"] = with_no_grad["bias"]
        import jax.numpy as jnp

        ffmodel.params = {ln: {pn: jnp.asarray(pv) for pn, pv in lp.items()}
                          for ln, lp in ffmodel.params.items()}
        return ffmodel.params

    # -------------------------------------------------- serialized op list
    def to_op_list(self) -> str:
        """Serialize the traced graph (reference: the importer's file
        format written by ``torch_to_flexflow``, torch/model.py)."""
        ops = []
        for node in self.graph_module.graph.nodes:
            ops.append({
                "name": node.name, "op": node.op,
                "target": str(node.target),
                "args": [a.name if hasattr(a, "name") else a
                         for a in node.args
                         if not isinstance(a, (dict, slice))],
            })
        return json.dumps(ops, default=str, indent=2)
