"""HF-aware torch.fx tracing.

reference: python/flexflow/torch/model.py:2424-2444 traces HF models with
torch.fx and replays them onto FFModel (tests/align/mt5_encoder/ pins a
real checkpoint end-to-end).  The TPU-native importer does the same
through ``transformers.utils.fx`` with two adjustments that make modern
HF checkpoints traceable and the replay TPU-idiomatic:

1. **Attention modules trace as leaves.**  Replaying HF attention's
   dozen-view/permute/matmul dance op-by-op would hand XLA a worse graph
   than the framework's fused ``multihead_attention`` op (which the
   replay maps the leaf to, exactly like the reference importer
   recognizes ``torch.nn.MultiheadAttention``, torch/model.py).
2. **Mask construction is stubbed during tracing.**  transformers'
   ``create_causal_mask`` vmaps over proxies (untraceable by HF's own fx
   machinery in this version); its output only feeds the attention leaf,
   which the replay masks natively (causal=True), so the trace patches it
   to return None.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Sequence


@contextlib.contextmanager
def _patched_masks(module):
    """Stub transformers' mask builders inside the model's modeling
    module for the duration of the trace (the symbol is imported into
    each modeling namespace, so the patch must land there)."""
    import sys

    mod_cls = type(module)
    modeling = sys.modules[mod_cls.__module__]
    patched = []
    for name in ("create_causal_mask", "create_sliding_window_causal_mask"):
        if hasattr(modeling, name):
            patched.append((name, getattr(modeling, name)))
            setattr(modeling, name, lambda *a, **k: None)
    try:
        yield
    finally:
        for name, orig in patched:
            setattr(modeling, name, orig)


@contextlib.contextmanager
def _narrowed_forward(module, input_names: Sequence[str]):
    """Modern transformers forwards end in ``**kwargs: Unpack[...]``,
    which torch.fx's bytecode patching cannot rebuild (co_varnames too
    small).  For the duration of the trace, swap in a forward whose
    signature is exactly ``input_names`` — the original still runs
    underneath with those kwargs."""
    import inspect

    cls = type(module)
    orig = cls.forward
    has_var_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD
                     for p in inspect.signature(orig).parameters.values())
    if not has_var_kw:
        yield
        return
    args = ", ".join(f"{n}=None" for n in input_names)
    calls = ", ".join(f"{n}={n}" for n in input_names)
    ns = {"_orig": orig}
    exec(f"def forward(self, {args}):\n    return _orig(self, {calls})\n",
         ns)
    cls.forward = ns["forward"]
    try:
        yield
    finally:
        cls.forward = orig


def hf_symbolic_trace(module, input_names: Sequence[str] = ("input_ids",),
                      extra_leaf_suffixes: Sequence[str] = (
                          "Attention", "RotaryEmbedding", "RMSNorm")):
    """Trace an HF transformers model into a GraphModule suitable for
    :class:`flexflow_tpu.torch_frontend.PyTorchModel` replay: attention
    modules stay leaves, mask construction is stubbed."""
    from transformers.utils import fx as hffx

    suffixes = tuple(extra_leaf_suffixes)

    class _Tracer(hffx.HFTracer):
        def is_leaf_module(self, mod, name):
            if type(mod).__name__.endswith(suffixes):
                return True
            return super().is_leaf_module(mod, name)

    with _patched_masks(module), _narrowed_forward(module, input_names):
        return hffx.symbolic_trace(module, input_names=list(input_names),
                                   tracer_cls=_Tracer)
