"""HF-aware torch.fx tracing.

reference: python/flexflow/torch/model.py:2424-2444 traces HF models with
torch.fx and replays them onto FFModel (tests/align/mt5_encoder/ pins a
real checkpoint end-to-end).  The TPU-native importer does the same
through ``transformers.utils.fx`` with two adjustments that make modern
HF checkpoints traceable and the replay TPU-idiomatic:

1. **Attention modules trace as leaves.**  Replaying HF attention's
   dozen-view/permute/matmul dance op-by-op would hand XLA a worse graph
   than the framework's fused ``multihead_attention`` op (which the
   replay maps the leaf to, exactly like the reference importer
   recognizes ``torch.nn.MultiheadAttention``, torch/model.py).
2. **Mask construction is stubbed during tracing.**  transformers'
   ``create_causal_mask`` vmaps over proxies (untraceable by HF's own fx
   machinery in this version); its output only feeds the attention leaf,
   which the replay masks natively (causal=True), so the trace patches it
   to return None.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Sequence


@contextlib.contextmanager
def _patched_masks(module):
    """Stub transformers' mask builders inside the model's modeling
    module for the duration of the trace (the symbol is imported into
    each modeling namespace, so the patch must land there)."""
    import sys

    mod_cls = type(module)
    modeling = sys.modules[mod_cls.__module__]
    patched = []
    for name in ("create_causal_mask", "create_sliding_window_causal_mask",
                 "make_flex_block_causal_mask"):
        if hasattr(modeling, name):
            patched.append((name, getattr(modeling, name)))
            setattr(modeling, name, lambda *a, **k: None)
    # stack-level mask METHODS (T5Stack._update_causal_mask, copied from
    # GPTJ): control flow over proxied masks; the output only feeds
    # attention leaves, which replay their masks natively
    meth_patched = []
    seen = set()
    for mm in module.modules():
        cls = type(mm)
        if cls in seen:
            continue
        seen.add(cls)
        if "_update_causal_mask" in cls.__dict__:
            meth_patched.append((cls, cls._update_causal_mask))
            cls._update_causal_mask = lambda self, *a, **k: None
    try:
        yield
    finally:
        for name, orig in patched:
            setattr(modeling, name, orig)
        for cls, orig in meth_patched:
            cls._update_causal_mask = orig


@contextlib.contextmanager
def _t5_leaf_metas(module):
    """Register fx meta overrides for T5/mt5-style leaves.

    HFTracer infers each proxy's dtype/shape by running the module on
    meta tensors; T5Attention's forward throws under meta execution
    (cache/position plumbing) and T5LayerNorm's throws on its real cpu
    ``weight`` times a meta input.  The tracer swallows the errors, the
    proxies carry no metadata, and the first
    ``hidden_states.dtype == float16`` check downstream dies with a
    control-flow TraceError.  The overrides declare what each leaf
    returns: the attention leaf yields hidden states of the input shape
    plus None slots, the norm leaf is shape/dtype-identity."""
    from transformers.utils import fx as hffx

    def attn_meta(mod, hidden_states, *a, **k):
        return (hidden_states, None, None)

    def identity_meta(mod, hidden_states, *a, **k):
        return hidden_states

    from .model import _is_hf_rmsnorm, _is_t5_attention

    added = []
    for mm in module.modules():
        cls = type(mm)
        if cls in hffx._MANUAL_META_OVERRIDES or cls in (
                c for c, _ in added):
            continue
        if _is_t5_attention(mm):
            added.append((cls, attn_meta))
        elif _is_hf_rmsnorm(mm):
            added.append((cls, identity_meta))
    for cls, fn in added:
        hffx._MANUAL_META_OVERRIDES[cls] = fn
    try:
        yield
    finally:
        for cls, _ in added:
            hffx._MANUAL_META_OVERRIDES.pop(cls, None)


@contextlib.contextmanager
def _narrowed_forward(module, input_names: Sequence[str]):
    """Modern transformers forwards end in ``**kwargs: Unpack[...]``,
    which torch.fx's bytecode patching cannot rebuild (co_varnames too
    small).  For the duration of the trace, swap in a forward whose
    signature is exactly ``input_names`` — the original still runs
    underneath with those kwargs."""
    import inspect

    cls = type(module)
    orig = cls.forward
    has_var_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD
                     for p in inspect.signature(orig).parameters.values())
    if not has_var_kw:
        yield
        return
    args = ", ".join(f"{n}=None" for n in input_names)
    calls = ", ".join(f"{n}={n}" for n in input_names)
    ns = {"_orig": orig}
    exec(f"def forward(self, {args}):\n    return _orig(self, {calls})\n",
         ns)
    cls.forward = ns["forward"]
    try:
        yield
    finally:
        cls.forward = orig


def hf_symbolic_trace(module, input_names: Sequence[str] = ("input_ids",),
                      extra_leaf_suffixes: Sequence[str] = (
                          "Attention", "RotaryEmbedding", "RMSNorm",
                          "LayerNorm")):
    """Trace an HF transformers model into a GraphModule suitable for
    :class:`flexflow_tpu.torch_frontend.PyTorchModel` replay: attention
    modules stay leaves, mask construction is stubbed.  T5-style
    WRAPPER blocks (T5LayerSelfAttention / T5LayerFF — norm + inner op +
    residual) must trace THROUGH so the residual adds replay op-by-op;
    only the inner T5Attention / T5LayerNorm are leaves."""
    from transformers.utils import fx as hffx

    suffixes = tuple(extra_leaf_suffixes)
    wrappers = ("LayerSelfAttention", "LayerCrossAttention", "LayerFF")

    class _Tracer(hffx.HFTracer):
        def is_leaf_module(self, mod, name):
            cls = type(mod).__name__
            if cls.endswith(wrappers):
                return False
            if cls.endswith(suffixes):
                return True
            return super().is_leaf_module(mod, name)

    with _patched_masks(module), _narrowed_forward(module, input_names), \
            _t5_leaf_metas(module):
        # disable_check: the whitelist omits some traceable classes
        # (e.g. T5EncoderModel while T5Model is listed); unsupported
        # graphs still fail loudly at replay via UnsupportedTorchOp
        return hffx.symbolic_trace(module, input_names=list(input_names),
                                   tracer_cls=_Tracer, disable_check=True)
