"""Runtime configuration.

TPU-native equivalent of the reference's ``FFConfig`` (reference:
include/flexflow/config.h:102, defaults src/runtime/model.cc:3974-4008, arg
parsing model.cc:4085+).  Where the reference configures Legion processors and
framebuffer sizes, we configure a `jax.sharding.Mesh` over the available
devices plus the parallelism degrees (dp/tp/pp + the new sequence-parallel
axis the reference lacks, SURVEY.md §5).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np


# Mesh axis names used across the framework.  Collectives ride ICI along
# these axes; the GSPMD partitioner inserts them from NamedSharding
# annotations (replaces the reference's NCCL-comm-per-MachineView scheme,
# model.cc:3637-3673).
AXIS_DATA = "dp"
AXIS_MODEL = "tp"
AXIS_PIPE = "pp"
AXIS_SEQ = "sp"
AXIS_EXPERT = "ep"


@dataclasses.dataclass
class FFConfig:
    """Global runtime config (reference FFConfig, config.h:102).

    The reference's per-GPU memory knobs (``-ll:fsize``, ``-ll:zsize``) have
    no TPU analogue — XLA owns HBM — so they are accepted but unused.
    """

    batch_size: int = 64
    epochs: int = 1
    iterations: int = -1  # -1: derive from dataset size
    # parallelism degrees (reference: -tensor-parallelism-degree etc.)
    data_parallelism_degree: int = 1
    tensor_parallelism_degree: int = 1
    pipeline_parallelism_degree: int = 1
    sequence_parallelism_degree: int = 1  # NEW vs reference (SURVEY.md §5)
    expert_parallelism_degree: int = 1
    # training knobs
    only_data_parallel: bool = True  # reference DefaultConfig model.cc:3995
    search_budget: int = -1
    search_alpha: float = 1.2
    enable_fusion: bool = True  # XLA fuses by default; kept for parity
    profiling: bool = False
    inference_debugging: bool = False
    seed: int = 0
    # numerics
    computation_dtype: str = "float32"
    # memory knobs (accepted for CLI parity; unused on TPU)
    memory_per_device_mb: int = 0
    zero_copy_memory_mb: int = 0
    offload: bool = False
    offload_reserve_space_size: int = 0
    quantization: Optional[str] = None  # "int8" | "int4" | None
    # KV-cache storage dtype for serving: "bf16" (= the computation
    # dtype — the pre-existing behavior, bit-identical default),
    # "int8" (per-row-per-position-per-head scales beside int8 K/V —
    # halves decode cache HBM reads and doubles resident rows x context)
    # or "int4" (two codes per int8 carrier byte along the sequence
    # axis — quarter-bandwidth decode attend, ~4x resident context;
    # see docs/INTERNALS.md "KV cache memory layout & dtype")
    kv_cache_dtype: Optional[str] = None  # "bf16" | "int8" | "int4" | None
    # int8 serving matmuls run MXU-NATIVE (int8 x int8 -> int32) with
    # dynamic per-row activation quantization (W8A8) instead of the
    # exact convert-dot (W8A16).  ~20% faster weight streaming on v5e
    # (the convert-dot is VPU-convert-bound, not HBM-bound) at a small,
    # documented numerics change; see docs/INTERNALS.md
    int8_native_matmul: bool = False
    # device selection
    num_devices: int = 0  # 0: all visible
    devices: Optional[Sequence[jax.Device]] = None

    def __post_init__(self):
        if self.devices is None:
            devs = jax.devices()
            if self.num_devices:
                devs = devs[: self.num_devices]
            self.devices = tuple(devs)
        self.num_devices = len(self.devices)

    # ---------------------------------------------------------------- mesh
    def total_parallel_degree(self) -> int:
        return (
            self.data_parallelism_degree
            * self.tensor_parallelism_degree
            * self.pipeline_parallelism_degree
            * self.sequence_parallelism_degree
            * self.expert_parallelism_degree
        )

    def validate(self):
        """dp*tp*pp(*sp*ep) must cover the devices (reference:
        inference_manager.cc:31-56)."""
        if self.total_parallel_degree() > self.num_devices:
            raise ValueError(
                f"dp({self.data_parallelism_degree}) x "
                f"tp({self.tensor_parallelism_degree}) x "
                f"pp({self.pipeline_parallelism_degree}) x "
                f"sp({self.sequence_parallelism_degree}) x "
                f"ep({self.expert_parallelism_degree}) = "
                f"{self.total_parallel_degree()} > num_devices "
                f"({self.num_devices})"
            )

    def make_mesh(self, axes: Optional[Sequence[str]] = None,
                  sizes: Optional[Sequence[int]] = None) -> jax.sharding.Mesh:
        """Build the device mesh.

        Replaces the reference's MachineView device assignment
        (machine_view.h:18-39) + FFMapper placement (mapper.cc:376-560):
        device placement on TPU is mesh construction, and op placement is
        sharding annotation.  ``sizes`` overrides the per-axis extents for
        axes the config degrees don't describe (factorized tp sub-axes).
        """
        self.validate()
        degrees = {
            AXIS_DATA: self.data_parallelism_degree,
            AXIS_SEQ: self.sequence_parallelism_degree,
            AXIS_PIPE: self.pipeline_parallelism_degree,
            AXIS_EXPERT: self.expert_parallelism_degree,
            AXIS_MODEL: self.tensor_parallelism_degree,
        }
        if axes is None:
            axes = [a for a, d in degrees.items() if d > 1] or [AXIS_DATA]
        shape = (list(sizes) if sizes is not None
                 else [degrees.get(a, 1) for a in axes])
        n = int(np.prod(shape))
        devs = np.array(self.devices[:n]).reshape(shape)
        return jax.sharding.Mesh(devs, tuple(axes))
