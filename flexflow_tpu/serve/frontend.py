"""Async serving front-end: continuous admission, streaming, deadlines,
backpressure and graceful shedding over the blocking driver loops.

Everything below this module is a *batch* engine: the driver loops
(``RequestManager.generate_incr_decoding``, ``generate_spec_infer``)
block the calling thread until every queued request retires — the shape
the reference exposes through its ``inference/incr_decoding`` /
``inference/spec_infer`` entry points and the prototype ``triton/``
backend wraps for live traffic.  This module is our live-traffic
equivalent, built the way the reference splits Legion runtime threads
from the request queue:

- **One dedicated driver thread** owns the blocking step loop.  It
  re-enters the generate loop whenever the pending deque is non-empty,
  so admission is CONTINUOUS (Orca-style: new arrivals join the running
  batch at the next ``prepare_next_batch`` boundary, they never wait
  for a batch to finish).  JAX dispatch stays on one thread — the event
  loop never touches the device.
- **The asyncio event loop** owns intake, per-token streaming,
  deadlines, backpressure and shedding.  The thread boundary is
  explicit and narrow: driver→loop via ``call_soon_threadsafe`` (the
  ``on_commit``/``on_finish`` hooks), loop→driver via
  ``RequestManager.request_cancel`` (a locked mailbox the driver drains
  at the ``admit_pending`` boundary, where no driver-local row state is
  in flight).
- **Streaming** is a bounded per-request ``asyncio.Queue``: tokens are
  delivered as the driver commits them (per fold — a K-step decode
  block arrives as one K-token burst, which is what the device actually
  produced between host syncs).  A consumer that stops draining fills
  its queue and is cancelled as a slow client rather than growing
  unbounded host memory; the final-status sentinel always has a
  reserved slot, so no await ever hangs.
- **Deadlines** derive from the installed
  :class:`~flexflow_tpu.observability.SLOPolicy` when the caller gives
  none: a request that would blow ``deadline_factor * (ttft_s +
  max_new_tokens * tpot_s)`` is cancelled mid-stream — its pager
  pages, pool donations and ledger timeline released exactly like a
  retirement (``RequestManager.cancel_request``).
- **Backpressure**: intake REJECTS (``Overloaded`` with a
  ``retry_after_s`` hint, ``serving_rejected_total{reason=
  backpressure}``) when the pending deque reaches the watermark —
  bounded queues instead of unbounded growth, the vLLM admission-
  control stance.
- **Shedding**: under overload the :class:`ShedPolicy` reads the
  request ledger's in-flight timelines and the KV pager's page
  pressure and drops the pending requests LEAST likely to attain
  their SLO (hopeless deadlines first, then newest arrivals), counted
  under ``serving_shed_total{reason}``.

See docs/SERVING.md for the architecture walkthrough and
``tools/ffload.py`` for the fault-injecting load harness that
exercises every path above.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..observability import get_flight_recorder, get_ledger, get_registry
from ..serving.request_manager import Request, RequestManager

__all__ = ["AsyncServeFrontend", "TokenStream", "ShedPolicy",
           "Overloaded", "RequestAborted", "FrontendClosed"]


class Overloaded(Exception):
    """Intake rejected: the pending deque is at the backpressure
    watermark.  ``retry_after_s`` is the estimated drain time of one
    queue slot — the HTTP-429-Retry-After hint."""

    def __init__(self, retry_after_s: float, pending: int, limit: int):
        super().__init__(
            f"serving queue full ({pending}/{limit} pending); "
            f"retry after {retry_after_s:.3f}s")
        self.retry_after_s = retry_after_s
        self.pending = pending
        self.limit = limit


class RequestAborted(Exception):
    """The stream ended before natural retirement (deadline, shed,
    disconnect, slow client, driver stall).  ``tokens`` carries what
    was streamed before the abort."""

    def __init__(self, guid: int, reason: str,
                 tokens: Optional[List[int]] = None):
        super().__init__(f"request {guid} aborted: {reason}")
        self.guid = guid
        self.reason = reason
        self.tokens = list(tokens or [])


class FrontendClosed(Exception):
    """Submission refused: the front-end is shut down or its driver
    failed/stalled (the bundle path, when a watchdog dumped one)."""


#: queue sentinel carrying the final status (its slot is reserved so a
#: full token queue can never block stream termination)
_FINAL = object()


class TokenStream:
    """One client's handle on an in-flight request.

    Async-iterate for per-token streaming, or :meth:`result` to drain
    to completion.  All state lives on the event-loop thread; the
    driver reaches it only through ``call_soon_threadsafe``.
    """

    def __init__(self, frontend: "AsyncServeFrontend", req: Request,
                 queue_tokens: int, deadline_mono: Optional[float]):
        self._fe = frontend
        self.request = req
        self.guid = req.guid
        self.deadline_mono = deadline_mono
        # +1: the _FINAL sentinel's reserved slot (delivery never
        # exceeds maxsize-1 tokens — see _deliver)
        self._q: "asyncio.Queue" = asyncio.Queue(maxsize=queue_tokens + 1)
        #: (status, reason, exc) once the request left the engine
        self._final: Optional[Tuple[str, Optional[str],
                                    Optional[BaseException]]] = None
        self.tokens: List[int] = []     # streamed so far (consumer side)

    # ------------------------------------------------------------- client
    def __aiter__(self) -> "TokenStream":
        return self

    async def __anext__(self) -> int:
        item = await self._q.get()
        if item is _FINAL:
            # re-arm: repeated iteration keeps terminating
            self._q.put_nowait(_FINAL)
            status, reason, exc = self._final
            if exc is not None:
                raise exc
            if status != "retired":
                raise RequestAborted(self.guid, reason or status,
                                     self.tokens)
            raise StopAsyncIteration
        self.tokens.append(item)
        return item

    async def result(self) -> List[int]:
        """Drain the stream; returns all generated token ids.  Raises
        :class:`RequestAborted` (carrying the partial tokens) when the
        request was cancelled."""
        async for _ in self:
            pass
        return self.tokens

    @property
    def finished(self) -> bool:
        return self._final is not None

    @property
    def status(self) -> Optional[str]:
        """None while streaming; "retired" | "cancelled" | "failed"."""
        return self._final[0] if self._final is not None else None

    def disconnect(self) -> None:
        """The client goes away mid-stream: the front-end cancels the
        request so its row/pages free immediately instead of decoding
        for a dead socket (``serving_cancellations_total{reason=
        disconnect}``)."""
        if self._final is None:
            self._fe._note_disconnect(self)


class ShedPolicy:
    """WHEN the front-end sheds pending requests and WHOM.

    - ``overloaded()``: the trigger — the pending deque over the shed
      watermark, or the KV pager's page budget exhausted under a
      non-empty queue (``pager_pressure``).
    - ``victims()``: the selection — requests LEAST likely to attain
      their SLO.  Hopeless deadlines first: with a service-time
      estimate from the ledger's recent retired window and the
      request's queue position against the in-flight batch, a pending
      request whose deadline lands before its estimated completion is
      shed for free (it was going to miss anyway).  Then, while still
      over the watermark, newest arrivals (LIFO — preserving the FCFS
      order of earlier arrivals, the same fairness stance as the
      pager's admission preemption).
    """

    def __init__(self, max_pending: int = 64,
                 shed_watermark: Optional[int] = None,
                 estimate_ttl_s: float = 0.25):
        self.max_pending = max(1, int(max_pending))
        self.shed_watermark = (int(shed_watermark)
                               if shed_watermark is not None
                               else max(1, self.max_pending // 2))
        # service-estimate cache: the median scan copies the ledger's
        # whole retired window under its lock, and victims() runs
        # every reap tick (50x/s default) — cap the scan rate instead
        self.estimate_ttl_s = float(estimate_ttl_s)
        self._est: Optional[float] = None
        self._est_mono: float = 0.0

    # ------------------------------------------------------------ intake
    def reject_now(self, rm: RequestManager) -> bool:
        return len(rm.pending) >= self.max_pending

    def retry_after_s(self, rm: RequestManager, ledger) -> float:
        """One queue slot's estimated drain time (the Overloaded
        hint): recent per-request service time over the batch width,
        floored at 10 ms so clients never busy-spin."""
        est = self._service_estimate(ledger)
        if est is None:
            return 0.05
        return max(0.01, est / max(1, rm.max_requests_per_batch))

    # ---------------------------------------------------------- shedding
    def overloaded(self, rm: RequestManager, pager) -> Optional[str]:
        if len(rm.pending) > self.shed_watermark:
            return "overload"
        if (pager is not None and rm.pending
                and pager.free_pages == 0):
            return "pager_pressure"
        return None

    def victims(self, rm: RequestManager, ledger, pager, now: float,
                deadlines: Dict[int, Optional[float]]
                ) -> List[Tuple[int, str]]:
        """(guid, reason) per shed victim this tick.  ``deadlines``
        maps guid -> absolute monotonic deadline (None = none)."""
        out: List[Tuple[int, str]] = []
        trigger = self.overloaded(rm, pager)
        if not rm.pending or (trigger is None and not any(
                d is not None for d in deadlines.values())):
            # idle/healthy fast path: nothing to shed and no deadline
            # to price — skip the ledger-window scan entirely (this
            # runs every reap tick on the event loop)
            return out
        try:
            pending = list(rm.pending)
        except RuntimeError:
            # the driver thread mutated the deque mid-iteration; this
            # tick's view is gone — shed on the next one
            return out
        est = self._service_estimate(ledger)
        if est is not None:
            # per-slot start estimate: position in the queue over the
            # batch width rounds of the estimated service time
            width = max(1, rm.max_requests_per_batch)
            survivors = []
            for i, req in enumerate(pending):
                dl = deadlines.get(req.guid)
                if dl is not None and now + (i // width + 1) * est > dl:
                    out.append((req.guid, "hopeless"))
                else:
                    survivors.append(req)
            pending = survivors
        if trigger is not None:
            keep = self.shed_watermark
            for req in pending[keep:][::-1]:        # newest first
                out.append((req.guid, trigger))
        return out

    def _service_estimate(self, ledger) -> Optional[float]:
        """Median admitted-span of the recent retired window (the
        ledger feed the shed decision reads) — None before any
        retirement, which disables hopeless-shedding (never guess).
        Cached for ``estimate_ttl_s`` so reap ticks don't rescan the
        window 50x/s."""
        now = time.monotonic()
        if (self._est_mono
                and now - self._est_mono < self.estimate_ttl_s):
            return self._est
        # admitted span only: latency_s includes queue wait (its
        # docstring says so), and pricing a queue-positioned start
        # estimate with queue-inflated service times would double-count
        # the wait and shed attainable requests as hopeless
        lats = sorted(
            t["latency_s"] - (t.get("queue_s") or 0.0)
            for t in ledger.timelines(include_live=False)
            if t.get("latency_s") is not None and not t.get("cancelled"))
        self._est = lats[len(lats) // 2] if lats else None
        self._est_mono = now
        return self._est


class AsyncServeFrontend:
    """The asyncio front-end (module docstring).  Use as an async
    context manager::

        async with AsyncServeFrontend(im, model_id, rm) as fe:
            stream = await fe.submit([1, 2, 3], max_new_tokens=32)
            async for tok in stream:
                ...

    or build one from a compiled :class:`~flexflow_tpu.serve.LLM` via
    ``llm.frontend()``.
    """

    def __init__(self, im, model_id: int, rm: RequestManager,
                 shed_policy: Optional[ShedPolicy] = None,
                 stream_queue_tokens: int = 256,
                 deadline_factor: float = 2.0,
                 reap_interval_s: float = 0.02):
        self.im = im
        self.model_id = model_id
        self.rm = rm
        self.shed_policy = shed_policy or ShedPolicy()
        self.stream_queue_tokens = max(1, int(stream_queue_tokens))
        self.deadline_factor = float(deadline_factor)
        self.reap_interval_s = float(reap_interval_s)
        self.ledger = get_ledger()
        self.recorder = get_flight_recorder()
        m = get_registry()
        self._m_shed = m.counter("serving_shed_total")
        self._m_rejected = m.counter("serving_rejected_total")
        # event-loop-owned state (every touch happens on the loop
        # thread; the driver reaches it only via call_soon_threadsafe)
        self._handles: Dict[int, TokenStream] = {}
        # guids with an abort already requested but not yet enacted
        # (the cancel mailbox drains at driver boundaries, so a shed
        # victim stays visible in rm.pending for up to a decode block
        # — without this guard the reaper would re-count it each tick)
        self._abort_requested: set = set()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._reaper_task: Optional[asyncio.Task] = None
        # driver-thread plumbing
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._failed: Optional[BaseException] = None
        self.last_bundle: Optional[str] = None

    # ----------------------------------------------------------- lifecycle
    async def start(self) -> "AsyncServeFrontend":
        if self._thread is not None:
            return self
        self._loop = asyncio.get_running_loop()
        self.rm.on_commit = self._driver_on_commit
        self.rm.on_finish = self._driver_on_finish
        self._stop.clear()
        self._thread = threading.Thread(target=self._driver_main,
                                        name="ff-serve-driver",
                                        daemon=True)
        self._thread.start()
        self._reaper_task = self._loop.create_task(self._reaper())
        return self

    async def close(self, timeout: float = 10.0) -> None:
        """Shut down behind a DRAIN BARRIER: stop intake, flush (fail)
        every live stream and box cancels for their engine-side
        requests, then join the driver thread.

        The ordering is the fix for the teardown re-entry bug: when
        streams were failed only *after* the join, their boxed cancels
        were never drained, so requests that arrived during teardown
        left ``rm.pending`` non-empty and a driver mid-pass would
        re-enter the generate loop for clients that no longer existed —
        the join then timed out and leaked the thread.  With the
        barrier, the driver's next ``admit_pending`` boundary drains
        the cancels, the engine empties, and the pass returns promptly;
        whatever the dead driver never drained is enacted here after
        the join (``drain_cancels`` is driver-safe once the thread is
        gone).  The wire server's SIGTERM path
        (:meth:`~flexflow_tpu.serve.net.server.ServeNetServer.begin_drain`)
        depends on this barrier for its bounded shutdown."""
        if self._failed is None:
            self._failed = FrontendClosed("front-end closed")
        if self._reaper_task is not None:
            self._reaper_task.cancel()
            self._reaper_task = None
        # barrier step 1+2: intake is refused (_failed above), live
        # streams flush with FrontendClosed and their engine-side
        # requests are cancel-boxed so the driver exits its pass at the
        # next admission boundary instead of decoding for dead clients
        self._fail_live(FrontendClosed("front-end closed"),
                        reason="closed")
        # barrier step 3: join the driver
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self._thread.join, timeout)
            if not self._thread.is_alive():
                self._thread = None
        # catch streams submitted in the closing race (after the flush
        # above but before intake saw _failed), then enact every cancel
        # the dead driver never reached so the engine queue is empty
        # for whoever owns this rm next.  ONLY when the join actually
        # succeeded: drain_cancels is driver-safe solely with no driver
        # in flight — a wedged thread that outlived the join timeout
        # still owns the boundary and will drain the box itself
        self._fail_live(FrontendClosed("front-end closed"),
                        reason="closed")
        if self._thread is None:
            # fflint: disable=ffrace-thread-affinity  guarded by the
            # join above: _thread is None only after the driver thread
            # exited, so the loop IS the sole thread touching the rm
            self.rm.drain_cancels()
        self.rm.on_commit = None
        self.rm.on_finish = None

    async def __aenter__(self) -> "AsyncServeFrontend":
        return await self.start()

    async def __aexit__(self, *exc) -> bool:
        await self.close()
        return False

    # -------------------------------------------------------------- intake
    async def submit(self, prompt, max_new_tokens: int = 128,
                     deadline_s: Optional[float] = None,
                     stream_queue_tokens: Optional[int] = None,
                     trace=None,
                     trace_source: Optional[str] = None) -> TokenStream:
        """Enqueue one request; returns its :class:`TokenStream`.

        Raises :class:`Overloaded` (with ``retry_after_s``) at the
        backpressure watermark and :class:`FrontendClosed` after
        shutdown/failure.  ``deadline_s`` is a wall budget from NOW
        (submission); None derives one from the installed SLOPolicy
        (``deadline_factor * (ttft_s + max_new_tokens * tpot_s)``) and
        stays None when no policy is installed.  ``trace`` is an
        adopted :class:`~flexflow_tpu.observability.TraceContext` (the
        wire server passes the X-FFServe-Trace header's): it is
        stamped onto the request's ledger timeline so cross-process
        trace assembly can join this hop.  ``trace_source`` labels
        ``serving_trace_hops_total`` — "wire" when the context arrived
        in an inbound header, "minted" when this process created it;
        None infers from the hop (hop>0 must have been forwarded)."""
        if self._failed is not None:
            self._m_rejected.inc(reason="closed")
            raise FrontendClosed(str(self._failed))
        if self.shed_policy.reject_now(self.rm):
            self._m_rejected.inc(reason="backpressure")
            raise Overloaded(
                self.shed_policy.retry_after_s(self.rm, self.ledger),
                len(self.rm.pending), self.shed_policy.max_pending)
        if deadline_s is None:
            deadline_s = self._policy_deadline_s(max_new_tokens)
        req = self.rm.register_new_request(prompt, max_new_tokens,
                                           trace=trace,
                                           trace_source=trace_source)
        stream = TokenStream(
            self, req,
            stream_queue_tokens or self.stream_queue_tokens,
            time.monotonic() + deadline_s
            if deadline_s is not None else None)
        self._handles[req.guid] = stream
        self._wake.set()
        return stream

    def _policy_deadline_s(self, max_new_tokens: int) -> Optional[float]:
        pol = self.ledger.slo_policy()
        if pol is None:
            return None
        base = (pol.ttft_s or 0.0) + max_new_tokens * (pol.tpot_s or 0.0)
        return self.deadline_factor * base if base > 0 else None

    # ------------------------------------------------------- cancellation
    def cancel(self, guid: int, reason: str = "client") -> None:
        """Cancel a submitted request from the event loop (boxed to the
        driver; the stream terminates when the cancel lands).  A no-op
        for already-finished streams (the natural race: a client
        cancel scheduled behind a completion)."""
        h = self._handles.get(guid)
        if h is not None and h._final is not None:
            return
        # the abort is now spoken for: the shed policy must not pick
        # this guid while its cancel waits in the mailbox (a shed tick
        # then would inflate serving_shed_total with no matching
        # shed:* cancellation — the reasons are first-wins)
        self._abort_requested.add(guid)
        self.rm.request_cancel(guid, reason)
        self._wake.set()

    def _note_disconnect(self, stream: TokenStream) -> None:
        self.recorder.record_event("disconnect", guid=stream.guid,
                                   streamed=len(stream.tokens))
        self.ledger.note_event("disconnect", guid=stream.guid,
                               streamed=len(stream.tokens))
        self.cancel(stream.guid, "disconnect")

    # ------------------------------------------------------ reaper/shedder
    async def _reaper(self) -> None:
        """Deadline enforcement + shed policy, on the event loop."""
        while True:
            await asyncio.sleep(self.reap_interval_s)
            try:
                self._reap_tick(time.monotonic())
            except asyncio.CancelledError:
                raise
            except Exception:       # the reaper must outlive one bad tick
                import traceback

                traceback.print_exc()

    def _reap_tick(self, now: float) -> None:
        for h in list(self._handles.values()):
            if (h._final is None and h.deadline_mono is not None
                    and now > h.deadline_mono
                    and h.guid not in self._abort_requested):
                self._abort_requested.add(h.guid)
                self.cancel(h.guid, "deadline")
        deadlines = {h.guid: h.deadline_mono
                     for h in self._handles.values()
                     if h._final is None}
        for guid, why in self.shed_policy.victims(
                self.rm, self.ledger, self.rm.kv_pager, now, deadlines):
            if guid in self._abort_requested:
                continue
            # the shed COUNTER/EVENT is emitted at enactment
            # (_driver_on_finish), not here: a victim that retires
            # naturally before the mailbox drains must not read as a
            # shed with no matching cancellation
            self.cancel(guid, f"shed:{why}")
        # prune abort marks whose request is gone without a handle
        # finish (cancel-of-finished races): neither side will ever
        # discard them, and a long-lived server must not leak guids
        if self._abort_requested:
            try:
                alive = {h.guid for h in self._handles.values()}
                alive |= {r.guid for r in list(self.rm.pending)}
                alive |= {r.guid
                          for r in list(self.rm.running.values())}
            except RuntimeError:
                return               # driver mutated mid-scan; next tick
            self._abort_requested &= alive

    # ------------------------------------------------------ driver thread
    # ffrace: root=driver  (the blocking driver loop: Thread(target=
    # _driver_main) in start() carries the engine's affinity, so the
    # rm mutations below are its own, not a foreign thread's)
    def _driver_main(self) -> None:
        rm = self.rm
        while not self._stop.is_set():
            if rm.pending or rm.running:
                try:
                    self._generate_once()
                except BaseException as e:  # noqa: BLE001 - fail streams
                    self._failed = e
                    self._call_loop(self._fail_live, e)
                    return
            else:
                rm.drain_cancels()       # idle-time cancels (stale-safe)
                self._wake.wait(0.05)
                self._wake.clear()

    def _generate_once(self) -> None:
        """One blocking generate pass over everything queued (the
        driver loop admits continuously, so arrivals during the pass
        join it; the pass returns when the engine drains)."""
        if self.rm.ssm_model_ids:
            from ..serving.spec_infer import generate_spec_infer

            generate_spec_infer(self.rm, self.im, self.model_id, ())
        else:
            self.rm.generate_incr_decoding(self.im, self.model_id, ())

    # --------------------------------------------- driver->loop delivery
    def _call_loop(self, fn, *args) -> None:
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        try:
            loop.call_soon_threadsafe(fn, *args)
        except RuntimeError:            # loop shut down mid-call
            pass

    def _driver_on_commit(self, req: Request, toks: Sequence[int]) -> None:
        self._call_loop(self._deliver, req.guid,
                        [int(t) for t in toks])

    def _driver_on_finish(self, req: Request, status: str,
                          reason: Optional[str]) -> None:
        if (status == "cancelled" and reason
                and reason.startswith("shed:")):
            # shed accounting lands when the cancel is ENACTED — the
            # counter/event can never outnumber actual cancellations
            # (registry + recorder are thread-safe; the timeline
            # already carries cancel_reason="shed:<why>")
            why = reason[5:]
            self._m_shed.inc(reason=why)
            self.recorder.record_event("shed", guid=req.guid,
                                       reason=why)
        self._call_loop(self._finish, req.guid, status, reason, None)

    def _deliver(self, guid: int, toks: List[int]) -> None:
        h = self._handles.get(guid)
        if h is None or h._final is not None:
            return
        for t in toks:
            if h._q.qsize() >= h._q.maxsize - 1:
                # bounded stream: a consumer this far behind is treated
                # as gone — cancel rather than buffer unboundedly (the
                # sentinel slot stays reserved, so termination is still
                # deliverable)
                self.cancel(guid, "slow_client")
                return
            h._q.put_nowait(t)

    def _finish(self, guid: int, status: str, reason: Optional[str],
                exc: Optional[BaseException]) -> None:
        self._abort_requested.discard(guid)
        h = self._handles.pop(guid, None)
        if h is None or h._final is not None:
            return
        h._final = (status, reason, exc)
        h._q.put_nowait(_FINAL)         # reserved slot — never raises

    def _fail_live(self, exc: BaseException,
                   reason: str = "driver_failed") -> None:
        """Terminate every live stream with ``exc`` (driver death,
        watchdog stall, shutdown) — no hung awaits, ever.  The
        engine-side requests are cancelled too (boxed; enacted when the
        driver unwedges or next idles): their clients are gone, so
        decoding on for them would burn batch rows on dead sockets.
        ``reason`` labels those cancellations (stall | closed |
        driver_failed) so a post-mortem never misreads server-side
        failure as a burst of client disconnects."""
        for guid in list(self._handles):
            self._finish(guid, "failed", None,
                         exc if isinstance(exc, Exception)
                         else RuntimeError(repr(exc)))
            self.rm.request_cancel(guid, reason)
        self._wake.set()

    # ------------------------------------------------------ observability
    def live_guids(self) -> List[int]:
        return [g for g, h in self._handles.items() if h._final is None]

    def watchdog(self, stall_timeout: float = 120.0,
                 bundle_dir: Optional[str] = None, **kwargs):
        """A stall :class:`~flexflow_tpu.observability.Watchdog` wired
        to this front-end: when the driver loop stops committing steps
        for ``stall_timeout`` seconds, the bundle dumps (ledger names
        the in-flight GUIDs) AND every connected client stream
        terminates with :class:`RequestAborted` — a stalled chip must
        never strand clients on hung awaits."""
        from ..observability import Watchdog

        def on_bundle(path: str, reason: str) -> None:
            self.last_bundle = path
            if reason.startswith("stall"):
                self._failed = FrontendClosed(
                    f"driver stalled ({reason}); bundle: {path}")
                self._call_loop(
                    self._fail_live,
                    RequestAborted(-1, f"driver-stall:{path}"),
                    "stall")

        return Watchdog(stall_timeout=stall_timeout,
                        bundle_dir=bundle_dir, on_bundle=on_bundle,
                        **kwargs)

    def stats(self) -> Dict[str, Any]:
        return {
            "live_streams": len(self._handles),
            "pending": len(self.rm.pending),
            "running": len(self.rm.running),
            "failed": repr(self._failed) if self._failed else None,
            "last_bundle": self.last_bundle,
        }
