"""CLI for the network serving surface.

``--replica``: run one wire server over a tiny CPU engine (the
N-CPU-procs replica shape ``spawn_replica`` launches for tests and the
bench ``net`` mode; production replicas wrap their own compiled model
the same way).  Prints ``FFSERVE_READY <host> <port>`` once bound and
serves until SIGTERM (graceful drain).

``--selftest``: the run_tier1.sh CI smoke —

1. **loopback wire parity**: an in-process tiny engine serves over a
   real loopback socket; streamed greedy tokens must be byte-identical
   to the same engine's in-process streams, a mid-stream socket abort
   must land as ``serving_cancellations_total{reason=disconnect}`` with
   the engine drained, and health/metrics must answer;
2. **2-replica router smoke**: two spawned replica processes behind a
   :class:`ReplicaRouter` — tenant traffic must produce affinity hits,
   and killing the bound replica mid-stream must fail over with a
   deterministic resume (the relayed stream equals the surviving
   replica's own answer, token for token).

Every fault is injected deterministically; the gate never flakes.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))))


def _build_engine(rows: int, decode_block: int, seed: int,
                  prefix_cache: bool = False, paged: bool = False):
    from tools.ffload import build_tiny_engine

    return build_tiny_engine(max_requests=rows,
                             decode_block=decode_block, seed=seed,
                             prefix_cache=prefix_cache, paged=paged)


# --------------------------------------------------------------- replica
def replica_main(args) -> int:
    from flexflow_tpu.observability import SLOPolicy, get_ledger
    from flexflow_tpu.serve.frontend import AsyncServeFrontend, ShedPolicy
    from flexflow_tpu.serve.net.server import ServeNetServer

    im, mid, rm = _build_engine(args.rows, args.decode_block, args.seed,
                                prefix_cache=args.prefix_cache,
                                paged=args.paged)
    if get_ledger().slo_policy() is None:
        # a policy must be installed for the goodput gauge the router
        # scores on; generous CPU-feasible targets by default.  The
        # flags exist so a test can spawn one replica with an
        # unattainably tight budget — deterministic SLO degradation
        # (attainment pins to 0, goodput to 0) without touching the
        # token stream, the fleet-alert smoke's fault profile.
        get_ledger().set_slo_policy(SLOPolicy(ttft_s=args.slo_ttft,
                                              tpot_s=args.slo_tpot))

    async def amain() -> None:
        # watermark == max_pending: replicas queue under oversubscription
        # instead of shedding (the router is the admission layer here)
        fe = AsyncServeFrontend(
            im, mid, rm, reap_interval_s=0.005,
            shed_policy=ShedPolicy(max_pending=args.max_pending,
                                   shed_watermark=args.max_pending))
        async with fe:
            srv = ServeNetServer(fe, host=args.host, port=args.port)
            await srv.start()
            srv.install_signal_handlers()
            print(f"FFSERVE_READY {srv.host} {srv.port}", flush=True)
            await srv.wait_closed()

    asyncio.run(amain())
    return 0


# -------------------------------------------------------------- selftest
def selftest() -> int:
    import numpy as np

    from flexflow_tpu.observability import (SLOPolicy, get_ledger,
                                            get_registry)
    from flexflow_tpu.serve.frontend import AsyncServeFrontend
    from flexflow_tpu.serve.net.client import NetClient
    from flexflow_tpu.serve.net.router import (ReplicaRouter,
                                               spawn_replica)
    from flexflow_tpu.serve.net.server import ServeNetServer

    ok = True

    def check(cond, msg):
        nonlocal ok
        if not cond:
            ok = False
            print(f"serve.net selftest FAILED: {msg}")

    def labels(name):
        v = (get_registry().snapshot().get("counters") or {}).get(name,
                                                                  {})
        return dict(v.get("labels", {})) if isinstance(v, dict) else {}

    # ---- part 1: loopback wire parity + disconnect ------------------
    rng = np.random.default_rng(3)
    prompts: List[List[int]] = [rng.integers(4, 120, n).tolist()
                                for n in (8, 12, 16)]
    im, mid, rm = _build_engine(rows=2, decode_block=4, seed=0)
    get_ledger().clear()
    get_ledger().set_slo_policy(SLOPolicy(ttft_s=30.0, tpot_s=5.0))

    async def part1() -> None:
        fe = AsyncServeFrontend(im, mid, rm, reap_interval_s=0.005)
        async with fe:
            ref = []
            for p in prompts:
                s = await fe.submit(p, max_new_tokens=12)
                ref.append(await s.result())
            async with ServeNetServer(fe) as srv:
                cl = NetClient(srv.url)
                hel = await cl.health()
                check(hel.get("ok") and hel.get("state") == "serving",
                      f"health not serving: {hel}")
                got = []
                for p in prompts:
                    ws = await cl.generate(p, max_new_tokens=12)
                    got.append(await ws.result())
                check(got == ref,
                      f"wire tokens != in-process tokens: "
                      f"{got} vs {ref}")
                # deterministic disconnect: abort the socket after two
                # streamed tokens; the engine-side request must cancel
                ws = await cl.generate(prompts[0], max_new_tokens=64)
                async for _ in ws:
                    if len(ws.tokens) >= 2:
                        break
                ws.disconnect()
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    lab = labels("serving_cancellations_total")
                    if any("disconnect" in k for k in lab):
                        break
                    await asyncio.sleep(0.02)
                lab = labels("serving_cancellations_total")
                check(any("disconnect" in k for k in lab),
                      f"socket abort did not cancel: {sorted(lab)}")
                text = await cl.metrics_text()
                check("serving_net_requests_total" in text
                      and "serving_net_disconnects_total" in text,
                      "metrics page missing serving_net_* series")
        check(not rm.pending and not rm.running,
              "engine did not drain after wire load")

    asyncio.run(part1())

    # ---- part 2: 2-replica router smoke -----------------------------
    # IDENTICAL seeds: replicas of one model are identical by
    # definition, which is what makes failover-resume deterministic
    reps = [spawn_replica(rows=2, decode_block=4, seed=0)
            for _ in range(2)]
    try:
        async def part2() -> None:
            router = ReplicaRouter([r.url for r in reps],
                                   scrape_interval_s=0.1,
                                   circuit_cooldown_s=0.5)
            async with router:
                # two rounds of tenant traffic: round 2 must hit the
                # affinity map (same tenants, same replicas)
                for rnd in range(2):
                    for tenant in ("acme", "globex"):
                        rs = await router.generate(
                            prompts[0], max_new_tokens=8, tenant=tenant)
                        toks = await rs.result()
                        check(len(toks) == 8,
                              f"router stream short: {len(toks)}")
                hits = labels("router_affinity_total")
                check(any("hit" in k for k in hits),
                      f"no affinity hits after repeat tenants: {hits}")
                # failover: kill the bound replica mid-stream; the
                # relayed stream must keep going and match what the
                # SURVIVOR answers for the same prompt
                rs = await router.generate(prompts[1],
                                           max_new_tokens=24)
                async for _ in rs:
                    if len(rs.tokens) >= 4:
                        break
                bound = rs._replica.url
                victim = next(r for r in reps if r.url == bound)
                survivor = next(r for r in reps if r.url != bound)
                victim.kill()
                rest = await rs.result()
                check(len(rest) == 24,
                      f"failover lost tokens: {len(rest)}/24")
                check(rs.failovers >= 1, "kill did not trigger failover")
                ref = await (await NetClient(survivor.url).generate(
                    prompts[1], max_new_tokens=24)).result()
                check(rest == ref,
                      f"failover resume not byte-identical: {rest} "
                      f"vs {ref}")
        asyncio.run(part2())
    finally:
        for r in reps:
            r.close()

    if ok:
        print("serve.net selftest OK (wire parity, disconnect-cancel, "
              "2-replica affinity + failover resume)")
    return 0 if ok else 1


# ---------------------------------------------------- fleet-KV smoke
def selftest_fleetkv() -> int:
    """run_tier1.sh fleet-KV loopback smoke (deterministic, 2 spawned
    CPU replicas): serve a prompt cold on replica A (the retire
    donates its prefix into A's pool), wait for A to advertise the
    prefix digest in ``/v1/stats``, export the frames over
    ``/v1/kv/export``, import the bundle into replica B over
    ``/v1/kv/import``, then serve the SAME prompt on B — B must score
    a prefix-pool match (``serving_prefix_hits_total`` > 0, zero
    before) and stream byte-identical greedy tokens to A's cold
    answer."""
    import numpy as np

    from flexflow_tpu.serve.net.client import NetClient
    from flexflow_tpu.serve.net.router import spawn_replica

    ok = True

    def check(cond, msg):
        nonlocal ok
        if not cond:
            ok = False
            print(f"serve.net fleetkv selftest FAILED: {msg}")

    rng = np.random.default_rng(7)
    prompt = rng.integers(4, 120, 48).tolist()
    reps = [spawn_replica(rows=2, decode_block=4, seed=0,
                          prefix_cache=True) for _ in range(2)]
    try:
        async def run() -> None:
            a = NetClient(reps[0].url)
            b = NetClient(reps[1].url)
            # cold reference on A — the same serve warms A's pool
            ref = await (await a.generate(prompt,
                                          max_new_tokens=12)).result()
            check(len(ref) == 12, f"cold serve short: {len(ref)}")
            deadline = time.monotonic() + 10.0
            digests: List[str] = []
            while time.monotonic() < deadline and not digests:
                kv = (await a.stats()).get("kv") or {}
                digests = list(kv.get("digests") or ())
                if not digests:
                    await asyncio.sleep(0.05)
            check(digests, "donor never advertised a prefix digest")
            before = await b.metrics_values()
            check(before.get("serving_prefix_hits_total", 0.0) == 0.0,
                  "importer pool warm before import (bad baseline)")
            bundle = await a.kv_export(prompt)
            check(bundle is not None, "kv_export found no usable match")
            res = await b.kv_import(bundle)
            check(res.get("imported"),
                  f"kv_import did not adopt the bundle: {res}")
            got = await (await b.generate(prompt,
                                          max_new_tokens=12)).result()
            check(got == ref,
                  f"imported-prefix serve not byte-identical: "
                  f"{got} vs {ref}")
            vals = await b.metrics_values()
            check(vals.get("serving_prefix_hits_total", 0.0) > 0,
                  "importer served without a prefix-pool match")
            check(vals.get("serving_kv_wire_import_bytes_total", 0.0)
                  >= len(bundle),
                  "import byte counter did not account the bundle")
            avals = await a.metrics_values()
            check(avals.get("serving_kv_wire_export_bytes_total", 0.0)
                  >= len(bundle),
                  "export byte counter did not account the bundle")

        asyncio.run(run())
    finally:
        for r in reps:
            r.close()

    if ok:
        print("serve.net fleetkv selftest OK (cross-replica export/"
              "import, prefix match on importer, byte-identical "
              "greedy tokens)")
    return 0 if ok else 1


# ------------------------------------------------- fleet-health smoke
def selftest_fleet() -> int:
    """run_tier1.sh fleet-health federation smoke (deterministic, 2
    spawned CPU replicas behind a router): one replica spawns with an
    unattainably tight SLO budget, so its attainment gauge pins to 0
    while its token stream stays byte-identical to the healthy
    replica's.  The router's burn-rate engine must fire
    ``replica-slo-burn`` against that replica ONLY, auto-capture its
    ``/v1/debug/bundle`` to disk, and ``/v1/fleet/health`` over the
    wire must mark it the outlier — then, once killed, ``stale``."""
    import json as _json
    import shutil
    import tempfile

    from flexflow_tpu.serve.net.client import NetClient
    from flexflow_tpu.serve.net.router import (ReplicaRouter,
                                               RouterServer,
                                               spawn_replica)

    ok = True

    def check(cond, msg):
        nonlocal ok
        if not cond:
            ok = False
            print(f"serve.net fleet selftest FAILED: {msg}")

    prompt = [(7 * i) % 120 + 4 for i in range(32)]
    cap_dir = tempfile.mkdtemp(prefix="ff_fleet_caps_")
    healthy = spawn_replica(rows=2, decode_block=4, seed=0)
    degraded = spawn_replica(rows=2, decode_block=4, seed=0,
                             slo_ttft_s=1e-4)
    try:
        async def run() -> None:
            # sub-second windows keep the smoke fast; the semantics
            # (both windows must burn) are identical at any scale
            rules = [{"name": "replica-slo-burn",
                      "metric": "serving_slo_attainment",
                      "scope": "replica", "kind": "below",
                      "threshold": 0.9, "fast_window_s": 0.5,
                      "slow_window_s": 1.0, "rearm_margin": 0.02,
                      "capture": True}]
            router = ReplicaRouter([healthy.url, degraded.url],
                                   scrape_interval_s=0.1,
                                   alert_rules=rules,
                                   capture_dir=cap_dir)
            async with router:
                srv = RouterServer(router)
                await srv.start()
                rc = NetClient(srv.url)
                # the degraded replica SERVES identically — only its
                # SLO accounting is broken
                ref = await (await NetClient(healthy.url).generate(
                    prompt, max_new_tokens=10)).result()
                got = await (await NetClient(degraded.url).generate(
                    prompt, max_new_tokens=10)).result()
                check(got == ref,
                      f"degraded replica stream diverged: {got} "
                      f"vs {ref}")
                # scrapes pick the pinned gauge up; both burn windows
                # breach; the alert fires and the capture lands
                deadline = time.monotonic() + 20.0
                while time.monotonic() < deadline:
                    if any(c["ok"] for c in router.captures):
                        break
                    await asyncio.sleep(0.1)
                active = router.alerts.active()
                check(any(a["rule"] == "replica-slo-burn"
                          and a["scope"] == degraded.url
                          for a in active),
                      f"no replica-slo-burn against the degraded "
                      f"replica: {active}")
                check(not any(a["scope"] == healthy.url
                              for a in active),
                      f"healthy replica alarmed: {active}")
                caps = [c for c in router.captures if c["ok"]]
                check(caps, "alert fired but no bundle captured")
                if caps:
                    check(caps[0]["replica"] == degraded.url,
                          f"captured the wrong replica: {caps[0]}")
                    with open(caps[0]["path"]) as f:
                        bundle = _json.load(f)
                    check(bundle.get("reason") == "on-demand"
                          and "flight_record" in bundle
                          and "ledger" in bundle,
                          f"capture is not a watchdog-shaped bundle: "
                          f"{sorted(bundle)}")
                # the wire view: outlier table + alerts + fleet series
                fh = await rc.fleet_health()
                reps = fh.get("replicas") or {}
                check((reps.get(degraded.url) or {}).get("outlier")
                      is True,
                      f"degraded replica not the outlier: {reps}")
                check((reps.get(healthy.url) or {}).get("outlier")
                      is False,
                      f"healthy replica flagged outlier: {reps}")
                check((fh.get("alerts") or {}).get("active"),
                      "wire payload lost the active alerts")
                series = (fh.get("fleet") or {}).get("series") or {}
                check("fleet_slo_attainment" in series
                      and "fleet_goodput_tokens_per_s" in series,
                      f"fleet series missing: {sorted(series)}")
                # staleness: kill the degraded replica; its ring stops
                # refreshing and the table must flip to stale
                degraded.kill()
                deadline = time.monotonic() + 10.0
                stale = False
                while time.monotonic() < deadline and not stale:
                    fh = await rc.fleet_health()
                    stale = ((fh["replicas"].get(degraded.url) or {})
                             .get("stale") is True)
                    if not stale:
                        await asyncio.sleep(0.2)
                check(stale, "killed replica never flagged stale")
                srv._server.close()

        asyncio.run(run())
    finally:
        for r in (healthy, degraded):
            r.close()
        shutil.rmtree(cap_dir, ignore_errors=True)

    if ok:
        print("serve.net fleet selftest OK (burn-rate alert on the "
              "degraded replica only, auto bundle capture, wire "
              "outlier + staleness, byte-identical streams)")
    return 0 if ok else 1


# ------------------------------------------------------------------ CLI
def main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flexflow_tpu.serve.net", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--replica", action="store_true",
                    help="run one replica wire server over a tiny CPU "
                         "engine until SIGTERM")
    ap.add_argument("--selftest", action="store_true")
    ap.add_argument("--selftest-fleetkv", action="store_true",
                    help="2-process cross-replica KV export/import "
                         "smoke (run_tier1.sh)")
    ap.add_argument("--selftest-fleet", action="store_true",
                    help="2-replica fleet-health federation smoke: "
                         "SLO burn-rate alert on the degraded replica, "
                         "auto bundle capture, /v1/fleet/health outlier "
                         "+ staleness (run_tier1.sh)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--rows", type=int, default=2)
    ap.add_argument("--decode-block", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-pending", type=int, default=64)
    ap.add_argument("--prefix-cache", action="store_true",
                    help="replica: enable the prefix pool (fleet-KV "
                         "donors/importers need it)")
    ap.add_argument("--paged", action="store_true",
                    help="replica: physical paged KV + frame-backed "
                         "pager instead of dense rows")
    ap.add_argument("--slo-ttft", type=float, default=30.0,
                    help="replica: SLO TTFT budget in seconds (set "
                         "unattainably tight to degrade one replica's "
                         "attainment deterministically)")
    ap.add_argument("--slo-tpot", type=float, default=5.0,
                    help="replica: SLO per-token budget in seconds")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if args.selftest_fleetkv:
        return selftest_fleetkv()
    if args.selftest_fleet:
        return selftest_fleet()
    if args.replica:
        return replica_main(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
